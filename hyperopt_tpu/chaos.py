"""Deterministic fault-injection plane (the chaos half of ISSUE 8).

The repo's reliability story — leased work shards (``parallel/membership``),
retry/backoff (``retry.py``), stale reclaim (``filestore.py``) — is only
trustworthy if failure paths are *exercised on purpose*.  This module turns
selected code sites into seeded failure points, armed by one environment
variable::

    HYPEROPT_TPU_CHAOS="<seed>:<rule>[;<rule>...]"

Rule grammar (whitespace-free; a malformed spec WARNS ONCE and disarms —
the same fail-open convention as every observability env var)::

    kill@<site>:<n>         SIGKILL this process on the n-th hit of <site>
    term@<site>:<n>         SIGTERM on the n-th hit (flight recorder dumps)
    ioerr@<site>:<p>        raise OSError with probability p per hit
    stall@<site>:<p>:<sec>  sleep <sec> seconds with probability p per hit
    enospc@<site>:<p>       raise OSError(ENOSPC) with probability p per
                            hit (io sites only — the disk-full analog of
                            ioerr; ISSUE 15's backpressure gate drives it)
    corrupt@<site>:<p>      flip ONE seeded bit in the just-written record
                            with probability p (``corrupt_bytes`` sites —
                            the WAL append; the write SUCCEEDS, the medium
                            lies: what the checksum/quarantine plane must
                            catch at the next replay or scrub)

Sites are plain strings named by the instrumented call sites:

==============  ============================================================
``gen``         driver generation start (collective AND fleet loops)
``allgather``   before each cross-controller collective (driver.py)
``checkpoint``  before the checkpoint file write (driver/fleet)
``claim``       before a fleet shard-lease claim (parallel/fleet.py)
``publish``     before a fleet shard-result publish (parallel/fleet.py)
``trial``       before each objective evaluation (worker.py / fleet eval)
``io``          inside ``filestore._atomic_write`` (``ioerr`` rules only)
``admit``       service study admission (service/scheduler.create_study)
``ask``         service ask ingress (service/scheduler.ask)
``tell``        service tell ingress (service/scheduler.tell)
``wal``         service journal append/compact (``ioerr`` raises as a
                JournalError — the failed request errors, state holds)
``tick``        before each cohort-tick device dispatch (``ioerr`` here is
                the OOM-shaped fault the degrade ladder absorbs; ``kill``
                is the mid-wave crash the WAL resume gate exercises)
==============  ============================================================

Determinism: every probabilistic rule owns a ``random.Random`` seeded from
``(seed, rule text)`` and advances it once per hit, and count-triggered
rules fire on exact hit counts — two runs of the same program under the
same spec inject identically.  **Disarmed runs are bit-identical and start
no threads**: the module keeps no state beyond a ``None`` plan, draws no
random numbers, and every ``point()`` call is a single attribute check
(the invariant every obs plane in this repo pins by test).

Kills are synchronous ``os.kill(os.getpid(), ...)`` at the site — SIGTERM
walks the flight recorder's handler chain (the dump lands in the store's
attachments when ``FileStore.arm_flight`` armed it), SIGKILL is the
unsurvivable spot-preemption analog.  Injections are counted in the
metrics registry the call site passes (so they land in the run's snapshot
and the ``obs.report`` fleet/chaos section) and recorded in the flight
ring, so a killed process's dump names the injection that killed it.
"""

from __future__ import annotations

import logging
import os
import random
import signal
import time

__all__ = ["ChaosPlan", "parse_spec", "get_plan", "configure", "armed",
           "point", "io_point", "corrupt_bytes", "corrupt_floats"]

logger = logging.getLogger(__name__)

_ACTIONS = ("kill", "term", "ioerr", "stall", "enospc", "corrupt")

_UNSET = object()
_plan = _UNSET  # lazily resolved from the environment on first use

_warned = False


def _warn_once(raw, why):
    global _warned
    if not _warned:
        _warned = True
        logger.warning("HYPEROPT_TPU_CHAOS=%r is not %s; disarming (chaos "
                       "spec errors warn-and-disable, never raise)", raw, why)


class _Rule:
    __slots__ = ("action", "site", "count", "prob", "sec", "rng", "text")

    def __init__(self, action, site, count=None, prob=None, sec=None,
                 seed=0, text=""):
        self.action = action
        self.site = site
        self.count = count
        self.prob = prob
        self.sec = sec
        self.text = text
        # per-rule generator: deterministic in (seed, rule text), advanced
        # once per hit — schedules replay exactly across runs
        self.rng = random.Random(f"{seed}:{text}")

    def fires(self, hits):
        """Decide for hit number ``hits`` (1-based).  Probabilistic rules
        draw exactly one number per hit, fired or not."""
        if self.count is not None:
            return hits == self.count
        return self.rng.random() < self.prob


class ChaosPlan:
    """A parsed, armed schedule: rules + per-site hit counters."""

    def __init__(self, seed, rules):
        self.seed = seed
        self.rules = rules
        self.hits = {}

    def check(self, site, io=False):
        """Advance ``site``'s hit counter and return the actions due at
        this hit: ``[("kill",), ("term",), ("ioerr",), ("stall", sec)]``.
        ``io=True`` sites additionally evaluate ``ioerr`` rules; plain
        sites never do (an OSError can only escape where the caller
        expects filesystem failure)."""
        due = []
        # corrupt rules never fire at point()/io_point(): they mutate a
        # payload, not control flow — corrupt_bytes() owns them (its own
        # hit counter, so mixed rules at one site stay deterministic)
        matched = [r for r in self.rules
                   if r.site == site and r.action != "corrupt"]
        if not matched:
            return due
        n = self.hits.get(site, 0) + 1
        self.hits[site] = n
        for r in matched:
            if r.action in ("ioerr", "enospc") and not io:
                continue
            if r.fires(n):
                due.append((r.action,) if r.sec is None else (r.action, r.sec))
        return due

    def mutate_rule(self, site):
        """The corrupt rule due at this ``corrupt_bytes`` hit, or None.
        Separate hit counter (``<site>!corrupt``): the mutate probe runs
        on a different cadence than point()/io_point() at the same
        site, and sharing one counter would skew both schedules."""
        matched = [r for r in self.rules
                   if r.site == site and r.action == "corrupt"]
        if not matched:
            return None
        key = f"{site}!corrupt"
        n = self.hits.get(key, 0) + 1
        self.hits[key] = n
        for r in matched:
            if r.fires(n):
                return r
        return None


def parse_spec(raw):
    """``"<seed>:<rule>[;<rule>...]"`` → :class:`ChaosPlan`, or None when
    empty/disabled/malformed (warn-and-disable)."""
    raw = (raw or "").strip()
    if raw.lower() in ("", "0", "off", "false", "no"):
        return None
    seed_s, sep, body = raw.partition(":")
    if not sep or not body.strip():
        _warn_once(raw, "of the form <seed>:<rule>[;<rule>...]")
        return None
    try:
        seed = int(seed_s)
    except ValueError:
        _warn_once(raw, "led by an integer seed")
        return None
    rules = []
    for part in body.split(";"):
        part = part.strip()
        if not part:
            continue
        action, sep, rest = part.partition("@")
        if action not in _ACTIONS or not sep:
            _warn_once(raw, f"using actions {_ACTIONS} as <action>@<site>")
            return None
        bits = rest.split(":")
        site = bits[0]
        args = bits[1:]
        try:
            if action in ("kill", "term"):
                if len(args) != 1:
                    raise ValueError
                rules.append(_Rule(action, site, count=int(args[0]),
                                   seed=seed, text=part))
            elif action in ("ioerr", "enospc", "corrupt"):
                if len(args) != 1:
                    raise ValueError
                rules.append(_Rule(action, site, prob=float(args[0]),
                                   seed=seed, text=part))
            else:  # stall
                if len(args) != 2:
                    raise ValueError
                rules.append(_Rule(action, site, prob=float(args[0]),
                                   sec=float(args[1]), seed=seed, text=part))
        except ValueError:
            _warn_once(raw, f"well-formed in rule {part!r}")
            return None
    if not rules:
        _warn_once(raw, "carrying at least one rule")
        return None
    return ChaosPlan(seed, rules)


def get_plan():
    """The process's armed plan (lazy env resolution), or None."""
    global _plan
    if _plan is _UNSET:
        _plan = parse_spec(os.environ.get("HYPEROPT_TPU_CHAOS", ""))
        if _plan is not None:
            logger.warning("CHAOS ARMED: %s",
                           "; ".join(r.text for r in _plan.rules))
    return _plan


def configure(spec=None):
    """Explicitly (re)arm — tests use this instead of the environment.
    ``None`` disarms; a spec string parses as the env var would; a
    :class:`ChaosPlan` installs directly.  Returns the active plan."""
    global _plan, _warned
    _warned = False
    if spec is None or isinstance(spec, ChaosPlan):
        _plan = spec
    else:
        _plan = parse_spec(spec)
    return _plan


def reset():
    """Forget any explicit configuration; the next use re-reads the env."""
    global _plan, _warned
    _plan = _UNSET
    _warned = False


def armed():
    return get_plan() is not None


def _execute(site, actions, metrics):
    for act in actions:
        name = act[0]
        if metrics is not None:
            metrics.counter(f"chaos.{name}.{site}").inc()
        # the flight ring survives a SIGTERM (the dump names the injection
        # that killed the process) — recorded BEFORE the action executes
        try:
            from .obs.flight import get_flight

            get_flight().record({"kind": "chaos", "ts": time.time(),
                                 "action": name, "site": site,
                                 "pid": os.getpid()})
        except Exception:
            pass
        if name == "kill":
            logger.warning("chaos: SIGKILL at %s", site)
            os.kill(os.getpid(), signal.SIGKILL)
        elif name == "term":
            logger.warning("chaos: SIGTERM at %s", site)
            os.kill(os.getpid(), signal.SIGTERM)
        elif name == "stall":
            logger.warning("chaos: stalling %.3fs at %s", act[1], site)
            time.sleep(act[1])
        elif name == "ioerr":
            logger.warning("chaos: injected I/O error at %s", site)
            raise OSError(f"chaos: injected I/O error at {site}")
        elif name == "enospc":
            import errno

            logger.warning("chaos: injected ENOSPC at %s", site)
            raise OSError(errno.ENOSPC,
                          f"chaos: injected ENOSPC at {site}")


def point(site, metrics=None):
    """A plain chaos site.  Disarmed cost: one attribute check + one
    ``is None``.  Never raises (``ioerr`` rules are ignored here — see
    :func:`io_point`)."""
    plan = _plan if _plan is not _UNSET else get_plan()
    if plan is None:
        return
    _execute(site, plan.check(site, io=False), metrics)


def io_point(site="io", metrics=None):
    """A filesystem chaos site: like :func:`point`, but ``ioerr`` and
    ``enospc`` rules RAISE ``OSError`` here — callers are the store
    paths whose error handling the chaos gate exists to exercise."""
    plan = _plan if _plan is not _UNSET else get_plan()
    if plan is None:
        return
    _execute(site, plan.check(site, io=True), metrics)


def corrupt_bytes(site, data, metrics=None):
    """A payload-mutation chaos site (ISSUE 15): when a ``corrupt`` rule
    is due, flip ONE seeded bit in ``data`` (never the trailing
    newline — the line framing must survive so the corruption lands
    MID-file, the case the torn-tail reader cannot excuse) and return
    the mutated copy; otherwise ``data`` unchanged.  Disarmed cost: one
    attribute check.  Deterministic: the flip position draws from the
    rule's own seeded stream, one draw per fired hit."""
    plan = _plan if _plan is not _UNSET else get_plan()
    if plan is None:
        return data
    rule = plan.mutate_rule(site)
    if rule is None:
        return data
    n = len(data) - (1 if data.endswith(b"\n") else 0)
    if n <= 0:
        return data
    pos = rule.rng.randrange(n * 8)
    out = bytearray(data)
    out[pos // 8] ^= 1 << (pos % 8)
    if metrics is not None:
        metrics.counter(f"chaos.corrupt.{site}").inc()
    try:
        from .obs.flight import get_flight

        get_flight().record({"kind": "chaos", "ts": time.time(),
                             "action": "corrupt", "site": site,
                             "bit": pos, "pid": os.getpid()})
    except Exception:
        pass
    logger.warning("chaos: flipped bit %d in a %s record", pos, site)
    return bytes(out)


def corrupt_floats(site, arr, metrics=None):
    """A proposal-mutation chaos site (ISSUE 18): when a ``corrupt``
    rule is due, perturb ONE seeded element per row of the float array
    ``arr`` (a copy — device buffers are never mutated) and return it;
    otherwise ``arr`` unchanged.  The perturbation is finite, small and
    SILENT — no flag, no exception, values still in-range-ish — i.e.
    exactly the wrong-answer class that slips past the non-finite guard
    and every checksum, and that only the blackbox prober's golden
    stream digest can catch.  Per-ROW so every study slot served by a
    corrupted tick is affected (a single global flip could land in
    masked padding and detect as nothing).  Disarmed cost: one
    attribute check.  Deterministic: positions draw from the rule's own
    seeded stream, one draw per row per fired hit."""
    plan = _plan if _plan is not _UNSET else get_plan()
    if plan is None:
        return arr
    rule = plan.mutate_rule(site)
    if rule is None:
        return arr
    import numpy as _np

    out = _np.array(arr, copy=True)
    flat = out.reshape(-1) if out.ndim <= 1 \
        else out.reshape(out.shape[0], -1)
    rows = flat.reshape(1, -1) if flat.ndim == 1 else flat
    if rows.shape[-1] == 0:
        return arr
    for i in range(rows.shape[0]):
        j = rule.rng.randrange(rows.shape[-1])
        rows[i, j] = rows[i, j] * 1.03125 + 0.03125
    if metrics is not None:
        metrics.counter(f"chaos.corrupt.{site}").inc()
    try:
        from .obs.flight import get_flight

        get_flight().record({"kind": "chaos", "ts": time.time(),
                             "action": "corrupt", "site": site,
                             "rows": int(rows.shape[0]),
                             "pid": os.getpid()})
    except Exception:
        pass
    logger.warning("chaos: silently perturbed %d proposal row(s) at %s",
                   int(rows.shape[0]), site)
    return out
