"""The optimization driver: ``fmin`` and the ask→tell loop.

Parity target: ``hyperopt/fmin.py`` (sym: fmin, FMinIter, space_eval,
generate_trials_to_calculate, fmin_pass_expr_memo_ctrl), including timeout,
loss_threshold, early_stop_fn, points_to_evaluate, trials_save_file and the
``HYPEROPT_FMIN_SEED`` environment default.

The loop itself is host-side control (as in the reference); all numeric work
happens inside the suggester's jitted kernels.  For fully JAX-traceable
objectives, ``device_fmin.fmin_device`` runs the entire loop on-device under
``lax.scan`` instead.
"""

from __future__ import annotations

import contextlib
import logging
import os
import pickle
import time

import numpy as np

from . import obs as obs_mod
from . import progress as progress_mod
from .base import (
    Ctrl,
    coarse_utcnow,
    Domain,
    JOB_STATE_DONE,
    JOB_STATE_ERROR,
    JOB_STATE_NEW,
    JOB_STATE_RUNNING,
    STATUS_OK,
    Trials,
    spec_from_misc,
    trials_from_docs,
)
from .exceptions import AllTrialsFailed, InvalidTrial
from .spaces import space_eval  # re-export (hyperopt/fmin.py sym: space_eval)

__all__ = [
    "fmin",
    "FMinIter",
    "PhaseTimings",
    "space_eval",
    "fmin_pass_expr_memo_ctrl",
    "generate_trials_to_calculate",
    "partial",
]

logger = logging.getLogger(__name__)

# PhaseTimings moved into the obs layer (obs/trace.py): the tracer now owns
# the measurement and this dict is its aggregate view.  Re-exported here so
# ``from hyperopt_tpu.fmin import PhaseTimings`` and pickled Trials carrying
# one keep working unchanged.
PhaseTimings = obs_mod.PhaseTimings


def fmin_pass_expr_memo_ctrl(f):
    """Decorator: objective wants (expr, memo, ctrl) instead of a sampled point
    (hyperopt/fmin.py sym: fmin_pass_expr_memo_ctrl)."""
    f.fmin_pass_expr_memo_ctrl = True
    return f


def generate_trial(tid, space_points):
    """One NEW trial doc pinning explicit hyperparameter values
    (hyperopt/fmin.py sym: generate_trial)."""
    variables = space_points.keys()
    idxs = {v: [tid] for v in variables}
    vals = {v: [space_points[v]] for v in variables}
    return {
        "state": JOB_STATE_NEW,
        "tid": tid,
        "spec": None,
        "result": {"status": "new"},
        "misc": {
            "tid": tid,
            "cmd": ("domain_attachment", "FMinIter_Domain"),
            "idxs": idxs,
            "vals": vals,
        },
        "exp_key": None,
        "owner": None,
        "version": 0,
        "book_time": None,
        "refresh_time": None,
    }


def generate_trials_to_calculate(points):
    """Trials pre-loaded with explicit points (hyperopt/fmin.py sym:
    generate_trials_to_calculate) — implements ``points_to_evaluate``."""
    return trials_from_docs([generate_trial(tid, x) for tid, x in enumerate(points)])


class FMinIter:
    """The ask→tell loop (hyperopt/fmin.py sym: FMinIter).

    ``run(N)``: refresh → ask suggester for new trials → insert → evaluate
    (serially in-process, or poll an asynchronous Trials backend) → check
    stop conditions → optionally persist.
    """

    catch_eval_exceptions = False
    pickle_protocol = -1

    def __init__(
        self,
        algo,
        domain,
        trials,
        rstate,
        asynchronous=None,
        max_queue_len=None,
        poll_interval_secs=None,
        max_evals=float("inf"),
        timeout=None,
        loss_threshold=None,
        verbose=False,
        show_progressbar=True,
        early_stop_fn=None,
        trials_save_file="",
        device_loop=False,
        obs=None,
        obs_http=None,
        profile=None,
        lookahead=0,
        compile_cache=None,
    ):
        from ._env import enable_persistent_compilation_cache

        enable_persistent_compilation_cache(compile_cache)
        self.device_loop = device_loop
        self.algo = algo
        self.domain = domain
        self.trials = trials
        self.asynchronous = trials.asynchronous if asynchronous is None else asynchronous
        self.rstate = rstate
        # precedence: explicit argument > backend attribute > 1 — mirroring
        # poll_interval_secs below.  An async backend knows how many trials it
        # can usefully run at once (the SparkTrials-parallelism pattern), but
        # an explicit request (e.g. queue depth 1 for fresh-posterior
        # reference semantics) must never be silently widened.
        if max_queue_len is None:
            max_queue_len = getattr(trials, "default_max_queue_len", 1)
        self.max_queue_len = max_queue_len
        # seed the suggesters' sticky id-bucket floor (rand.pad_ids_sticky)
        # from the queue depth: the first ramp-up batch then compiles the
        # steady-state kernel shape, and queue-drain tails reuse it instead
        # of compiling a narrower copy of the same program.  Capped at 64:
        # an async backend advertising a huge queue (SparkTrials-style
        # parallelism) but asking in small batches would otherwise pad EVERY
        # suggest call to full bucket width — pure wasted device work per
        # call; past the cap, pad_ids_sticky grows the floor organically
        # from observed batch sizes (ADVICE.md round 5).
        if max_queue_len != float("inf"):
            from .algos.rand import pad_ids_pow2

            b = len(pad_ids_pow2([0], min_bucket=min(int(max_queue_len), 64)))
            domain._ids_bucket = max(getattr(domain, "_ids_bucket", 1), b)
        # precedence: explicit argument > backend attribute > 1.0s default.
        # An async Trials backend may dictate its own polling cadence (the
        # SparkTrials pattern); in-process pools poll much faster than a DB.
        if poll_interval_secs is None:
            poll_interval_secs = getattr(trials, "poll_interval_secs", 1.0)
        self.poll_interval_secs = poll_interval_secs
        self.max_evals = max_evals
        # surface the eval budget to budget-aware suggesters (aTPE reads it
        # via featurize_trials; the reference's suggest protocol has no
        # budget channel, so it rides the trials object)
        if max_evals != float("inf"):
            trials.max_evals_hint = int(max_evals)
        self.timeout = timeout
        self.loss_threshold = loss_threshold
        self.start_time = time.time()
        self.early_stop_fn = early_stop_fn
        self.trials_save_file = trials_save_file
        self.verbose = verbose
        self.show_progressbar = show_progressbar
        self.early_stop_args = []
        self.is_cancelled = False
        # pipelined ask→tell (hyperopt's standard async-evaluation
        # semantics: in-flight trials simply don't contribute losses to the
        # posterior).  lookahead=N keeps up to N speculative asks in flight
        # — dispatched before the evaluate phase so their device programs
        # (and readbacks) overlap with objective evaluation.  lookahead=0
        # (default) is the synchronous loop, proposal-for-proposal
        # identical to the unpipelined driver (pinned by golden test).
        self.lookahead = int(lookahead)
        if self.lookahead < 0:
            raise ValueError(f"lookahead must be >= 0, got {lookahead}")
        self._algo_async = self._resolve_async_algo()
        self._ask_inflight = []  # speculative AskHandles, FIFO
        if self.lookahead > 0:
            if self.asynchronous:
                raise ValueError(
                    "lookahead > 0 applies to the serial in-process loop "
                    "only — an asynchronous Trials backend already "
                    "overlaps evaluation with asks via max_queue_len")
            if self._algo_async is None:
                raise ValueError(
                    "lookahead > 0 requires a suggester with an async "
                    "dispatch/readback split (tpe.suggest or rand.suggest, "
                    "optionally functools.partial-tuned)")
        # per-phase timing counters, shared with (and surfaced on) the trials
        # object; accumulates across fmin calls that reuse one Trials
        if not hasattr(trials, "phase_timings"):
            trials.phase_timings = PhaseTimings()
        self.phase_timings = trials.phase_timings
        # the run-telemetry bundle (obs/): the tracer aggregates into
        # phase_timings (back-compat view), and an armed config additionally
        # streams spans/events/metrics as JSONL.  One flag arms everything,
        # including the jax.profiler hook (HYPEROPT_TPU_OBS / obs= kwarg).
        # obs_http=<port|"host:port"> arms the live scrape server on top of
        # whatever the obs config says (0 = ephemeral port; see
        # obs/serve.py — validation happens there, fail-open)
        if obs_http is not None or profile is not None:
            if isinstance(obs, obs_mod.RunObs):
                # a pre-built bundle already decided its server/profiler
                # config — rebuilding it here would double-arm; say so
                # instead of silently dropping the kwargs
                logger.warning(
                    "obs_http=%r / profile=%r ignored: obs= is a pre-built "
                    "RunObs (set http_port/profile_dir on its ObsConfig "
                    "instead)", obs_http, profile)
            else:
                import dataclasses as _dc

                overrides = {}
                if obs_http is not None:
                    overrides["http_port"] = obs_http
                if profile is not None:
                    # profile=<dir> arms the bounded-capture plane
                    # (obs/profiler.py); "full:<dir>" keeps the legacy
                    # whole-run trace, same grammar as the env var
                    from .obs.profiler import split_profile_mode

                    cap_dir, full_dir = split_profile_mode(str(profile))
                    overrides["profile_dir"] = cap_dir
                    overrides["profile_full"] = full_dir
                obs = _dc.replace(obs_mod.ObsConfig.resolve(obs),
                                  **overrides)
        self.obs = obs_mod.RunObs.resolve(obs, totals=trials.phase_timings)
        trials.obs_run_id = self.obs.run_id
        trials.obs_metrics = self.obs.metrics  # direct post-run handle
        # where the live endpoints landed (None when the server is
        # disarmed or failed open) — the ephemeral-port discovery handle
        trials.obs_http_url = (self.obs.http.url
                               if self.obs.http is not None else None)
        # the bounded device-capture plane (None when profile= is
        # disarmed): the advertised programmatic trigger is
        # ``trials.obs_profiler.capture(sec)``.  Dropped on pickle (holds
        # a lock); re-set here on every resume.
        trials.obs_profiler = self.obs.profiler
        # armed runs hand the bundle to the suggesters through the trials
        # object (the suggest plugin signature has no obs channel): tpe
        # switches to its health-instrumented kernel, rand/anneal record
        # the cheap dup-rate/spread subset.  None when disarmed — the hot
        # path then pays exactly one getattr per suggest call.  Dropped on
        # pickle (base.Trials.__getstate__); re-set here on every resume.
        trials.obs_health = self.obs if self.obs.sink is not None else None

        if self.asynchronous:
            if "FMinIter_Domain" not in trials.attachments:
                import cloudpickle

                trials.attachments["FMinIter_Domain"] = cloudpickle.dumps(domain)
        else:
            trials.attachments["FMinIter_Domain"] = domain

    def _resolve_async_algo(self):
        """An ``(ids, domain, trials, seed) -> AskHandle`` dispatcher when
        the configured algo has a dispatch/readback split (tpe.suggest or
        rand.suggest, possibly ``functools.partial``-tuned), else None.
        Used for the suggest.dispatch/suggest.readback span split and —
        with ``lookahead > 0`` — the speculative-ask pipeline."""
        import functools as _ft

        from .algos import rand as _rand

        try:
            from .algos import tpe as _tpe
        except ModuleNotFoundError:  # partial checkout only
            _tpe = None
        algo, kwargs = self.algo, {}
        while isinstance(algo, _ft.partial):
            if algo.args:  # positional partial args: leave the plain path
                return None
            for k, v in (algo.keywords or {}).items():
                kwargs.setdefault(k, v)
            algo = algo.func
        if _tpe is not None and algo is _tpe.suggest:
            return lambda ids, dom, tr, s: _tpe.suggest_async(
                ids, dom, tr, s, **kwargs)
        if algo is _rand.suggest and not kwargs:
            return _rand.suggest_async
        return None

    def serial_evaluate(self, N=-1):
        """Evaluate queued NEW trials in-process
        (hyperopt/fmin.py sym: FMinIter.serial_evaluate)."""
        for trial in self.trials._dynamic_trials:
            if trial["state"] != JOB_STATE_NEW:
                continue
            trial["state"] = JOB_STATE_RUNNING
            trial["book_time"] = coarse_utcnow()
            self.obs.trial_event(obs_mod.events_mod.TRIAL_CLAIMED,
                                 trial["tid"], owner="serial")
            # a hang past this beat is the objective itself: the watchdog's
            # stall report names the trial that wedged the loop
            self.obs.heartbeat("fmin.evaluate", tid=trial["tid"])
            spec = spec_from_misc(trial["misc"])
            ctrl = Ctrl(self.trials, current_trial=trial)
            t0 = time.perf_counter()
            try:
                result = self.domain.evaluate(spec, ctrl)
            except Exception as e:
                logger.error("job exception: %s", e)
                trial["state"] = JOB_STATE_ERROR
                trial["misc"]["error"] = (str(type(e)), str(e))
                trial["refresh_time"] = coarse_utcnow()
                self.obs.trial_event(obs_mod.events_mod.TRIAL_FINISHED,
                                     trial["tid"], status="error",
                                     sec=time.perf_counter() - t0)
                self.obs.counter("trials.errors").inc()
                if not self.catch_eval_exceptions:
                    self.trials.refresh()
                    raise
            else:
                trial["state"] = JOB_STATE_DONE
                trial["result"] = result
                trial["refresh_time"] = coarse_utcnow()
                self.obs.trial_event(obs_mod.events_mod.TRIAL_FINISHED,
                                     trial["tid"],
                                     status=result.get("status", "ok"),
                                     sec=time.perf_counter() - t0)
                self.obs.counter("trials.completed").inc()
            N -= 1
            if N == 0:
                break
        self.trials.refresh()

    def block_until_done(self):
        """Poll an asynchronous backend until no NEW/RUNNING trials remain
        (hyperopt/fmin.py sym: FMinIter.block_until_done).

        When the fmin-level ``timeout`` has expired, in-flight trials are
        cancelled (backends that support it set JOB_STATE_CANCEL) instead of
        waited on — a hung objective must never wedge the driver
        (hyperopt/spark.py: job-group cancellation on timeout)."""
        already_printed = False
        if self.asynchronous:
            unfinished_states = [JOB_STATE_NEW, JOB_STATE_RUNNING]

            def timed_out():
                return (
                    self.timeout is not None
                    and time.time() - self.start_time >= self.timeout
                )

            def get_queue_len():
                return self.trials.count_by_state_unsynced(unfinished_states)

            cancel = getattr(self.trials, "cancel_unfinished", None)
            if timed_out() and cancel is not None:
                cancel()
            qlen = get_queue_len()
            while qlen > 0:
                if not already_printed and self.verbose:
                    logger.info("Waiting for %d jobs to finish ...", qlen)
                    already_printed = True
                self.obs.heartbeat("fmin.drain", qlen=qlen)
                time.sleep(self.poll_interval_secs)
                if timed_out() and cancel is not None:
                    cancel()
                qlen = get_queue_len()
            self.trials.refresh()
        else:
            self.serial_evaluate()

    def _timed(self, phase):
        """A tracer span for one loop phase: accumulates wall time into
        ``phase_timings`` (the historical contract) and, when the obs config
        is armed, streams the span — with nesting and CPU time — to the
        run's JSONL sink."""
        return self.obs.span(phase)

    def _profiler_ctx(self):
        """Optional ``jax.profiler`` trace over the whole loop, armed by the
        obs config (``HYPEROPT_TPU_PROFILE=<dir>`` or
        ``ObsConfig(profile_dir=...)``): a TensorBoard-viewable device+host
        trace of every suggest kernel and readback."""
        return self.obs.profiler_ctx()

    def run(self, N, block_until_done=True):
        # iterator-protocol re-entry after a finish(): re-adopt this run's
        # metrics namespace so resumed runs don't drop their counters
        self.obs.rearm()
        with self._profiler_ctx():
            with self.obs.span("run", aggregate=False,
                               N=N if N != float("inf") else "inf",
                               device_loop=bool(self.device_loop)):
                try:
                    self._run(N, block_until_done)
                finally:
                    # flush a metrics snapshot record per run() so a killed
                    # stream still ends with the latest full picture
                    self.obs.finish()

    def _device_loop_plan(self):
        """Resolve ``device_loop`` eligibility.  Returns ``(plan, reasons)``
        where plan is ``(tpe_cfg, n_startup)`` or None with the blocking
        reasons.  Eligible = queue-1 synchronous fresh run, a tpe/rand
        suggester (possibly ``functools.partial``-tuned), and an objective
        that traces to a scalar float."""
        import functools as _ft

        from .algos import rand as _rand
        from .algos import tpe as _tpe
        from .device_fmin import objective_is_traceable

        reasons = []
        if self.asynchronous:
            reasons.append("asynchronous trials backend")
        if self.max_queue_len != 1:
            reasons.append("max_queue_len != 1 (host loop already amortizes)")
        if self.max_evals == float("inf"):
            reasons.append("unbounded max_evals")
        if self.lookahead:
            # the device loop already pipelines the whole ask→tell chain on
            # device; silently swallowing lookahead would be inconsistent
            # with the strict validation the host loop applies
            reasons.append("lookahead > 0 (host-loop speculation; the "
                           "device loop pipelines on device already)")
        # trials this iter's own device loop populated are resumable (the
        # device-side history is retained on self); foreign history is not
        if len(self.trials) != getattr(self, "_device_n_done", 0):
            reasons.append("non-empty trials (resume is host-loop only)")
        algo, kwargs = self.algo, {}
        while isinstance(algo, _ft.partial):
            for k, v in (algo.keywords or {}).items():
                kwargs.setdefault(k, v)
            algo = algo.func
        if algo not in (_tpe.suggest, _rand.suggest):
            reasons.append("algo is not tpe.suggest / rand.suggest")
        allowed = {"prior_weight", "n_startup_jobs", "n_EI_candidates",
                   "gamma", "linear_forgetting", "ei_select", "ei_tau",
                   "prior_eps"}
        unknown = set(kwargs) - allowed
        if unknown:
            reasons.append(f"unsupported algo kwargs {sorted(unknown)}")
        if not reasons and not objective_is_traceable(self.domain):
            reasons.append("objective does not trace to a scalar float")
        if reasons:
            return None, reasons
        # tpe's own defaults, so host and device loops stay one optimizer
        cfg = {
            "prior_weight": float(
                kwargs.get("prior_weight", _tpe._default_prior_weight)),
            "n_EI_candidates": int(
                kwargs.get("n_EI_candidates", _tpe._default_n_EI_candidates)),
            "gamma": float(kwargs.get("gamma", _tpe._default_gamma)),
            "LF": int(kwargs.get("linear_forgetting",
                                 _tpe._default_linear_forgetting)),
        }
        for k in ("ei_select", "ei_tau", "prior_eps"):
            if k in kwargs:
                cfg[k] = kwargs[k]
        n_startup = (int(self.max_evals) if algo is _rand.suggest
                     else int(kwargs.get("n_startup_jobs",
                                         _tpe._default_n_startup_jobs)))
        return (cfg, n_startup), []

    def _run_device(self, N, plan):
        """The device-stepped queue-1 loop: CHUNK fresh-posterior trials per
        dispatch, one readback each (see ``device_fmin.DeviceLoopRunner``).
        Reference-shaped docs, chunk-granular timeout / early_stop /
        loss_threshold / checkpointing."""
        from .device_fmin import DeviceLoopRunner

        cfg, n_startup = plan
        trials = self.trials
        cs = self.domain.cs
        L = len(cs.labels)
        cap = int(self.max_evals)
        runner = DeviceLoopRunner(self.domain, cfg, n_startup, cap,
                                  obs=self.obs)
        # incremental runs (iterator protocol / repeated run()) continue from
        # the device-side history this iter accumulated; _device_loop_plan
        # guarantees len(trials) == _device_n_done when we get here
        n_done = getattr(self, "_device_n_done", 0)
        state = (self._device_state if n_done
                 else runner.init_state())
        target = min(cap, n_done + int(N))
        stopped = False
        prior = [l for l in trials.losses() if l is not None] if n_done else []
        best_loss = min(prior) if prior else float("inf")
        with progress_mod.get_progress_callback(self.show_progressbar)(
            initial=n_done, total=self.max_evals
        ) as progress_ctx:
            while n_done < target and not stopped:
                self.obs.heartbeat("fmin.device_chunk", n_done=n_done)
                self.obs.devmem_sample()  # chunk-boundary HBM watermark
                limit = min(n_done + runner.CHUNK, target)
                seed = (self.rstate.integers(2**31 - 1)
                        if hasattr(self.rstate, "integers")
                        else self.rstate.randint(2**31 - 1))
                try:
                    with self._timed("suggest"):
                        state, rows = runner.run_chunk(state, n_done, limit,
                                                       seed)
                except BaseException:
                    # the donated state tuple is consumed by the dispatch:
                    # drop the resume handle so a later run() re-checks
                    # eligibility instead of feeding freed buffers back in
                    # (the device-loop analog of PaddedHistory's
                    # stale-handle guard / abandon_device)
                    self._device_state = None
                    self._device_n_done = 0
                    raise
                k = limit - n_done
                new_ids = trials.new_trial_ids(k)
                now = coarse_utcnow()
                # reference-shaped docs via the one doc builder every
                # suggester uses (rand.flat_to_new_trial_docs recomputes the
                # active masks from the full flat sample — same math the
                # kernel applied in-trace), then mark them completed
                from .algos import rand as _rand

                flats = _rand.unpack_flats(cs, rows[:, :L], k)
                docs = _rand.flat_to_new_trial_docs(
                    self.domain, trials, new_ids, flats)
                for j, doc in enumerate(docs):
                    loss = float(rows[j][2 * L])
                    if np.isfinite(loss):
                        best_loss = min(best_loss, loss)
                        doc["result"] = {"loss": loss, "status": STATUS_OK}
                    else:
                        doc["result"] = {"status": "fail"}
                    doc["state"] = JOB_STATE_DONE
                    doc["book_time"] = now
                    doc["refresh_time"] = now
                    self.obs.trial_event(
                        obs_mod.events_mod.TRIAL_FINISHED, doc["tid"],
                        status=doc["result"].get("status", "ok"),
                        source="device_loop")
                self.obs.counter("trials.completed").inc(len(docs))
                trials.insert_trial_docs(docs)
                with self._timed("refresh"):
                    trials.refresh()
                n_done = limit
                if self.trials_save_file != "":
                    with self._timed("save"):
                        self._save_trials()
                if self.early_stop_fn is not None:
                    stop, kw = self.early_stop_fn(trials, *self.early_stop_args)
                    self.early_stop_args = kw
                    if stop:
                        logger.info("Early stop triggered")
                        stopped = True
                if np.isfinite(best_loss):
                    self.obs.gauge("best_loss").set(float(best_loss))
                    progress_ctx.postfix = progress_mod.format_postfix(
                        best_loss, self.obs)
                progress_ctx.update(k)
                if (self.timeout is not None
                        and time.time() - self.start_time >= self.timeout):
                    stopped = True
                if (self.loss_threshold is not None
                        and best_loss <= self.loss_threshold):
                    stopped = True
                self._device_state = state
                self._device_n_done = n_done

    def _run(self, N, block_until_done=True):
        if self.device_loop:
            plan, reasons = self._device_loop_plan()
            if plan is not None:
                return self._run_device(N, plan)
            if self.device_loop is True:
                raise ValueError(
                    "device_loop=True requested but the run is ineligible: "
                    + "; ".join(reasons))
            logger.info("device_loop='auto': using host loop (%s)",
                        "; ".join(reasons))
        trials = self.trials
        algo = self.algo
        async_algo = self._algo_async
        # speculative asks are scoped to ONE run(): handles left by an
        # earlier interrupted/stopped run are dropped here, because their
        # batch size was budgeted against that run's N and landing them
        # wholesale could overshoot this run's budget (their reserved ids
        # simply go unused — id gaps are legal in the doc schema)
        self._ask_inflight = inflight = []
        n_queued = 0

        def get_queue_len():
            return self.trials.count_by_state_unsynced(JOB_STATE_NEW)

        def get_n_done():
            return self.trials.count_by_state_unsynced(JOB_STATE_DONE)

        def get_n_unfinished():
            unfinished_states = [JOB_STATE_NEW, JOB_STATE_RUNNING]
            return self.trials.count_by_state_unsynced(unfinished_states)

        def inflight_n():
            return sum(len(h.new_ids) for h in inflight)

        def next_seed():
            return (self.rstate.integers(2**31 - 1)
                    if hasattr(self.rstate, "integers")
                    else self.rstate.randint(2**31 - 1))

        stopped = False
        initial_n_done = get_n_done()
        n_reported = initial_n_done
        tick = 0  # ask→tell tick ordinal: the device-timeline step id
        with progress_mod.get_progress_callback(self.show_progressbar)(
            initial=initial_n_done, total=self.max_evals
        ) as progress_ctx:
            all_trials_complete = False
            best_loss = float("inf")

            def land(new_trials):
                """Insert freshly-asked docs; False = suggester is done."""
                nonlocal n_queued, qlen, stopped
                self.obs.counter("suggest.calls").inc()
                if not len(new_trials):
                    stopped = True
                    return False
                for doc in new_trials:
                    self.obs.trial_event(
                        obs_mod.events_mod.TRIAL_NEW, doc["tid"])
                self.obs.counter("trials.suggested").inc(len(new_trials))
                self.trials.insert_trial_docs(new_trials)
                self.trials.refresh()
                n_queued += len(new_trials)
                qlen = get_queue_len()
                self.obs.gauge("queue_depth").set(qlen)
                return True

            while n_queued < N or (block_until_done and not all_trials_complete):
                # one beat per ask→tell tick: the stall watchdog's quiet
                # period measures from here when the host loop wedges
                tick += 1
                self.obs.heartbeat("fmin.tick", n_queued=n_queued)
                self.obs.devmem_sample()  # tick-boundary HBM watermark
                qlen = get_queue_len()
                # land speculative asks first: their device programs ran
                # while the previous tick's trials evaluated, so only the
                # readback is paid here
                while (inflight and qlen < self.max_queue_len
                       and n_queued < N and not self.is_cancelled):
                    handle = inflight.pop(0)
                    self.obs.gauge("suggest.inflight").set(len(inflight))
                    t_ask = time.perf_counter()
                    with self._timed("suggest"):
                        with self._timed("suggest.readback"):
                            new_trials = handle.result()
                    self.obs.histogram("ask.blocked_sec").observe(
                        time.perf_counter() - t_ask)
                    if not land(new_trials):
                        break
                while (
                    qlen < self.max_queue_len and n_queued < N
                    and not self.is_cancelled and not stopped
                ):
                    n_to_enqueue = min(self.max_queue_len - qlen, N - n_queued)
                    new_ids = trials.new_trial_ids(n_to_enqueue)
                    self.trials.refresh()
                    t_ask = time.perf_counter()
                    # step annotation (obs/profiler.py): a device capture
                    # overlapping this ask shows its kernels attributed to
                    # the tick ordinal and the trial ids it proposed
                    with self.obs.annotate(
                            "fmin.tick", step=tick,
                            tid=new_ids[0] if len(new_ids) else -1,
                            n=len(new_ids)), self._timed("suggest"):
                        if async_algo is not None:
                            # same computation as the plain call, but the
                            # dispatch/readback split is visible as child
                            # spans (and in phase_timings)
                            with self._timed("suggest.dispatch"):
                                handle = async_algo(
                                    new_ids, self.domain, trials, next_seed())
                            with self._timed("suggest.readback"):
                                new_trials = handle.result()
                        else:
                            new_trials = algo(
                                new_ids, self.domain, trials, next_seed())
                    self.obs.histogram("ask.blocked_sec").observe(
                        time.perf_counter() - t_ask)
                    assert len(new_ids) >= len(new_trials)
                    if not land(new_trials):
                        break

                # speculative dispatch: ask for the NEXT batch(es) before
                # this tick's trials evaluate — the fused tell+ask program
                # computes on device while the objective runs on host, and
                # the pending trials are simply absent from its posterior
                if (self.lookahead and async_algo is not None and not stopped
                        and not self.is_cancelled):
                    while len(inflight) < self.lookahead:
                        k = min(self.max_queue_len, N - n_queued - inflight_n())
                        if not (k >= 1 and k != float("inf")):
                            break
                        new_ids = trials.new_trial_ids(int(k))
                        self.trials.refresh()
                        # dispatch-only span, NOT nested under "suggest":
                        # the landing readback next tick carries the one
                        # "suggest" span for this ask, so phase counts stay
                        # one-per-ask in both pipelined and sync modes
                        with self.obs.annotate(
                                "fmin.tick.speculative", step=tick,
                                tid=new_ids[0] if len(new_ids) else -1,
                                n=len(new_ids)), \
                                self._timed("suggest.dispatch"):
                            inflight.append(async_algo(
                                new_ids, self.domain, trials, next_seed()))
                        self.obs.counter("suggest.speculative").inc()
                        self.obs.gauge("suggest.inflight").set(len(inflight))

                if self.asynchronous:
                    # wait for workers to fill in the trials
                    with self._timed("poll"):
                        time.sleep(self.poll_interval_secs)
                else:
                    with self._timed("evaluate"):
                        self.serial_evaluate()

                with self._timed("refresh"):
                    self.trials.refresh()
                if self.trials_save_file != "":
                    with self._timed("save"):
                        self._save_trials()

                if self.early_stop_fn is not None:
                    stop, kwargs = self.early_stop_fn(
                        self.trials, *self.early_stop_args
                    )
                    self.early_stop_args = kwargs
                    if stop:
                        logger.info("Early stop triggered")
                        stopped = True

                ok_losses = [
                    r["loss"]
                    for r in self.trials.results
                    if r.get("status") == STATUS_OK and r.get("loss") is not None
                ]
                if ok_losses:
                    new_best = min(ok_losses)
                    if new_best < best_loss:
                        best_loss = new_best
                    # the live scrape server and obs.top read best loss
                    # from this gauge (a gauge set is a dict store)
                    self.obs.gauge("best_loss").set(float(best_loss))
                    # armed runs append live search health (EI p50, dup
                    # rate) next to the best loss
                    progress_ctx.postfix = progress_mod.format_postfix(
                        best_loss, self.obs)
                n_done_now = get_n_done()
                progress_ctx.update(n_done_now - n_reported)
                n_reported = n_done_now

                if self.timeout is not None and time.time() - self.start_time >= self.timeout:
                    stopped = True
                if self.loss_threshold is not None and best_loss <= self.loss_threshold:
                    stopped = True

                all_trials_complete = get_n_unfinished() == 0
                if stopped and (not block_until_done or all_trials_complete):
                    break
                if stopped and block_until_done:
                    self.block_until_done()
                    all_trials_complete = True
                    break

    def _save_trials(self):
        """Checkpoint trials atomically: write a temp file, then rename, so a
        crash mid-dump never truncates an existing checkpoint (round-1 bug:
        a failed dump left a 0-byte file and EOFError on resume).

        An asynchronous backend's workers mutate trial docs concurrently;
        pickling a doc whose dict changes mid-dump raises RuntimeError or
        tears the checkpoint, so serialize under the backend's lock when it
        has one.
        """
        lock = getattr(self.trials, "_lock", None)
        with lock if lock is not None else contextlib.nullcontext():
            payload = pickle.dumps(self.trials, protocol=self.pickle_protocol)
        tmp = self.trials_save_file + ".tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, self.trials_save_file)

    def __iter__(self):
        return self

    def __next__(self):
        self.run(1, block_until_done=self.asynchronous)
        if len(self.trials) >= self.max_evals:
            raise StopIteration()
        return self.trials

    def exhaust(self):
        n_done = len(self.trials)
        self.run(self.max_evals - n_done, block_until_done=self.asynchronous)
        self.trials.refresh()
        return self


def fmin(
    fn,
    space,
    algo=None,
    max_evals=None,
    timeout=None,
    loss_threshold=None,
    trials=None,
    rstate=None,
    allow_trials_fmin=True,
    pass_expr_memo_ctrl=None,
    catch_eval_exceptions=False,
    verbose=False,
    return_argmin=True,
    points_to_evaluate=None,
    max_queue_len=None,
    show_progressbar=True,
    early_stop_fn=None,
    trials_save_file="",
    device_loop=False,
    obs=None,
    obs_http=None,
    profile=None,
    lookahead=0,
    compile_cache=None,
):
    """Minimize ``fn`` over ``space`` (hyperopt/fmin.py sym: fmin).

    Full keyword parity with the reference; seed defaults to the
    ``HYPEROPT_FMIN_SEED`` environment variable when set.

    ``device_loop`` (TPU extension, no reference analog): ``True`` or
    ``"auto"`` runs the queue-1 loop as chunked device programs when the
    objective is JAX-traceable — identical fresh-posterior-per-trial
    semantics, but ~one accelerator round trip per 10 trials instead of
    per trial (the high-latency-link mitigation; see
    ``device_fmin.DeviceLoopRunner``).  ``"auto"`` silently falls back to
    the host loop when ineligible; ``True`` raises with the reasons.

    ``obs`` (TPU extension): run-telemetry config — ``None`` reads the
    environment (``HYPEROPT_TPU_OBS``/``HYPEROPT_TPU_PROFILE``), a path
    streams spans + trial events + a metrics snapshot to that JSONL file
    (render with ``python -m hyperopt_tpu.obs.report``), or pass an
    :class:`hyperopt_tpu.obs.ObsConfig` directly.

    ``obs_http`` (TPU extension): port for the in-process live scrape
    server (``/metrics`` Prometheus, ``/snapshot`` JSON, ``/events`` SSE —
    see ``hyperopt_tpu/obs/serve.py``); ``0`` binds an ephemeral port,
    read back from ``trials.obs_http_url``.  Defaults to
    ``HYPEROPT_TPU_OBS_HTTP``.  Watch live with
    ``python -m hyperopt_tpu.obs.top <url>``.  Fail-open: an occupied
    port logs one warning and disables the server, never the run.

    ``profile`` (TPU extension): directory arming the bounded
    device-capture plane (``hyperopt_tpu/obs/profiler.py``) — on-demand
    ``GET /profile?sec=N`` captures on the scrape server, programmatic
    ``trials.obs_profiler.capture(sec)``, one automatic bounded capture
    on a watchdog stall, and ``TraceAnnotation`` trial/generation ids on
    the device timeline.  ``"full:<dir>"`` keeps the legacy whole-run
    ``jax.profiler.trace`` wrapper instead.  Defaults to
    ``HYPEROPT_TPU_PROFILE`` (same grammar).

    ``lookahead`` (TPU extension): keep up to N speculative asks in flight
    — the next batch's fused tell+ask program dispatches before the
    current trials evaluate, so device compute and readback overlap with
    the objective.  This is hyperopt's standard asynchronous-evaluation
    semantics (a pending trial contributes no loss to the posterior);
    ``lookahead=0`` (default) stays proposal-for-proposal identical to the
    synchronous loop.  Requires a tpe/rand suggester (possibly
    ``functools.partial``-tuned) and a serial (non-async) Trials backend.

    ``compile_cache`` (TPU extension): directory for the persistent XLA
    compilation cache — repeat runs skip the one-time compile that
    dominates short-run wall clock.  Defaults to
    ``HYPEROPT_TPU_COMPILE_CACHE`` (or an automatic per-machine dir);
    ``HYPEROPT_TPU_NO_CACHE=1`` disables.
    """
    if algo is None:
        try:
            from .algos import tpe

            algo = tpe.suggest
        except ModuleNotFoundError as e:  # partial checkout only
            if e.name not in ("hyperopt_tpu.algos.tpe",):
                raise
            from .algos import rand

            logger.warning("tpe module not present; fmin defaulting to random search")
            algo = rand.suggest

    if rstate is None:
        env_rseed = os.environ.get("HYPEROPT_FMIN_SEED", "")
        if env_rseed:
            rstate = np.random.default_rng(int(env_rseed))
        else:
            rstate = np.random.default_rng()
    elif isinstance(rstate, (int, np.integer)):
        rstate = np.random.default_rng(int(rstate))

    validate_timeout(timeout)
    validate_loss_threshold(loss_threshold)

    if trials_save_file != "" and trials is None and os.path.exists(trials_save_file):
        with open(trials_save_file, "rb") as f:
            trials = pickle.load(f)

    if trials is None:
        if points_to_evaluate is None:
            trials = Trials()
        else:
            assert isinstance(points_to_evaluate, list)
            trials = generate_trials_to_calculate(points_to_evaluate)

    if allow_trials_fmin and hasattr(trials, "fmin") and type(trials) is not Trials:
        return trials.fmin(
            fn,
            space,
            algo=algo,
            max_evals=max_evals,
            timeout=timeout,
            loss_threshold=loss_threshold,
            max_queue_len=max_queue_len,
            rstate=rstate,
            pass_expr_memo_ctrl=pass_expr_memo_ctrl,
            verbose=verbose,
            catch_eval_exceptions=catch_eval_exceptions,
            return_argmin=return_argmin,
            show_progressbar=show_progressbar,
            early_stop_fn=early_stop_fn,
            trials_save_file=trials_save_file,
            device_loop=device_loop,
            obs=obs,
            obs_http=obs_http,
            profile=profile,
            lookahead=lookahead,
            compile_cache=compile_cache,
        )

    domain = Domain(fn, space, pass_expr_memo_ctrl=pass_expr_memo_ctrl)

    rval = FMinIter(
        algo,
        domain,
        trials,
        max_evals=max_evals if max_evals is not None else float("inf"),
        timeout=timeout,
        loss_threshold=loss_threshold,
        rstate=rstate,
        verbose=verbose,
        max_queue_len=max_queue_len,
        show_progressbar=show_progressbar,
        early_stop_fn=early_stop_fn,
        trials_save_file=trials_save_file,
        device_loop=device_loop,
        obs=obs,
        obs_http=obs_http,
        profile=profile,
        lookahead=lookahead,
        compile_cache=compile_cache,
    )
    rval.catch_eval_exceptions = catch_eval_exceptions
    rval.exhaust()

    if return_argmin:
        if len(trials.trials) == 0:
            raise AllTrialsFailed(
                "There are no evaluation tasks, cannot return argmin of task losses."
            )
        return trials.argmin
    return None


def validate_timeout(timeout):
    if timeout is not None and (timeout <= 0 or isinstance(timeout, bool)):
        raise Exception(f"The timeout argument should be None or a positive value. Given value: {timeout}")


def validate_loss_threshold(loss_threshold):
    if loss_threshold is not None and not isinstance(loss_threshold, (int, float)):
        raise Exception(
            f"The loss_threshold argument should be None or a numeric value. Given value: {loss_threshold}"
        )


# convenience re-export so ``from hyperopt_tpu.fmin import partial`` idioms work
from functools import partial  # noqa: E402
