"""Device-memory (HBM) telemetry: watermarks, live-array census, OOM
narrative.

The span/metric pillars answer "where did the time go"; this module answers
"where did the *memory* go" — the question a multi-hour sweep asks the
moment XLA raises ``RESOURCE_EXHAUSTED``.  Three pieces:

* :func:`memory_stats` — per-device ``device.memory_stats()``
  (``bytes_in_use`` / ``peak_bytes_in_use`` / ``bytes_limit``), guarded for
  backends that return None (CPU) or raise — the sample then records which
  devices reported nothing instead of failing.
* :func:`live_array_census` — a ``jax.live_arrays()`` walk bucketed by
  shape-owner: the donated history pytree (cap-sized buffers registered by
  ``PaddedHistory`` / ``DeviceLoopRunner`` via :func:`register_owner`),
  proposal/candidate buffers, and everything else.  This is how an OOM dump
  says "the history held 1.9 GiB, your objective leaked the rest".
* :class:`DevMemSampler` — the per-run collector: emits ``devmem.*`` gauges
  into the run's metrics namespace and ``kind="devmem"`` JSONL records,
  keeps a bounded tail ring that the flight recorder attaches to crash
  dumps (``FlightRecorder.devmem``), and optionally runs a low-rate daemon
  sampler thread.  Span-boundary call sites (``fmin`` tick, device-loop
  chunk, driver generation) go through :meth:`maybe_sample`, which
  rate-limits to the configured period — armed sampling adds no per-trial
  host work beyond a clock read.

Arming: ``HYPEROPT_TPU_DEVMEM=<seconds>`` (sample period; ``1``/``on`` →
the 10 s default) or ``ObsConfig(devmem_period=...)``.  Disarmed runs
construct nothing: no thread, no gauges, no census walks.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque

from .._env import DEFAULT_DEVMEM_PERIOD_SEC

__all__ = [
    "DEFAULT_PERIOD_SEC",
    "memory_stats",
    "live_array_census",
    "register_owner",
    "roll_up",
    "DevMemSampler",
]

logger = logging.getLogger(__name__)

DEFAULT_PERIOD_SEC = DEFAULT_DEVMEM_PERIOD_SEC

# shape-owner registry for the census: owner name -> set of array shapes.
# Registration happens at history-allocation sites (PaddedHistory uploads,
# DeviceLoopRunner.init_state, suggest readback buffers) — rare, host-side,
# a set-add each; the census classifies by exact shape match at walk time.
_OWNER_SHAPES: dict = {}
_OWNER_LOCK = threading.Lock()


def register_owner(name, shape):
    """Tag arrays of ``shape`` as belonging to ``name`` ("history",
    "candidates") in the live-array census.  Idempotent and cheap."""
    shape = tuple(int(d) for d in shape)
    with _OWNER_LOCK:
        _OWNER_SHAPES.setdefault(str(name), set()).add(shape)


def _owner_of(shape):
    with _OWNER_LOCK:
        for name, shapes in _OWNER_SHAPES.items():
            if shape in shapes:
                return name
    return "other"


def memory_stats():
    """Per-device memory stats: ``[{device, platform, bytes_in_use,
    peak_bytes_in_use, bytes_limit}, ...]``.  Backends without the API (CPU
    often returns None, some PJRT plugins raise) yield entries whose byte
    fields are None — the caller decides how to render "unavailable"."""
    import jax

    out = []
    for d in jax.devices():
        entry = {"device": str(d), "platform": d.platform,
                 "bytes_in_use": None, "peak_bytes_in_use": None,
                 "bytes_limit": None}
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if isinstance(stats, dict):
            for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
                v = stats.get(key)
                if v is not None:
                    entry[key] = int(v)
        out.append(entry)
    return out


def live_array_census(per_device=False):
    """Bucket every live jax array by shape-owner:
    ``{owner: {"count", "bytes"}}`` plus a ``"total"`` roll-up.  Only
    arrays a Python reference keeps alive are visible — which is exactly
    the leak surface (in-trace temporaries free themselves).

    ``per_device=True`` returns ``(buckets, per_device)`` where the second
    element attributes each array's ADDRESSABLE shard bytes to the device
    holding them (``{device: {owner: {"count", "bytes"}}}``) — on a
    sharded suggest mesh this is the breakdown that shows where the
    candidate/history axes actually landed (a replicated leaf charges
    every device its full size; a sharded one charges ``1/n_shards``)."""
    import jax

    buckets = {}
    by_dev = {}
    total_n = total_b = 0
    for a in jax.live_arrays():
        try:
            shape, nbytes = tuple(a.shape), int(a.nbytes)
        except Exception:  # deleted/donated handle mid-walk
            continue
        owner = _owner_of(shape)
        b = buckets.setdefault(owner, {"count": 0, "bytes": 0})
        b["count"] += 1
        b["bytes"] += nbytes
        total_n += 1
        total_b += nbytes
        if per_device:
            try:
                shards = a.addressable_shards
            except Exception:
                continue
            for s in shards:
                try:
                    dev, sb = str(s.device), int(s.data.nbytes)
                except Exception:
                    continue
                d = by_dev.setdefault(dev, {})
                e = d.setdefault(owner, {"count": 0, "bytes": 0})
                e["count"] += 1
                e["bytes"] += sb
    buckets["total"] = {"count": total_n, "bytes": total_b}
    if per_device:
        return buckets, by_dev
    return buckets


def roll_up(devices):
    """Max-watermark roll-up across one sample's per-device entries (the
    number a progressbar, report line or dashboard row wants):
    ``(in_use, peak, limit, frac)`` with None where no device reported.
    THE one implementation — report/top/bench all read through here."""
    # .get: parsed-JSONL consumers may hand in records whose entries were
    # written by an older/trimmed producer
    in_use = [d.get("bytes_in_use") for d in devices]
    in_use = [v for v in in_use if v is not None]
    peaks = [d.get("peak_bytes_in_use") for d in devices]
    peaks = [v for v in peaks if v is not None]
    limits = [d.get("bytes_limit") for d in devices]
    limits = [v for v in limits if v is not None]
    mx_use = max(in_use) if in_use else None
    mx_peak = max(peaks) if peaks else None
    mx_lim = max(limits) if limits else None
    frac = (mx_use / mx_lim) if (mx_use is not None and mx_lim) else None
    return mx_use, mx_peak, mx_lim, frac


class DevMemSampler:
    """Per-run device-memory collector (see module docstring).

    ``sample()`` does the work: read per-device stats, walk the census, set
    ``devmem.*`` gauges on the run's registry, stream a ``kind="devmem"``
    JSONL record when the run is armed, and remember the record in a
    bounded tail ring for crash dumps.  ``maybe_sample()`` is the
    span-boundary entry: a monotonic-clock read, then ``sample()`` at most
    once per ``period``.
    """

    def __init__(self, obs, period=DEFAULT_PERIOD_SEC, keep=32):
        self.obs = obs
        self.period = float(period)
        self._tail = deque(maxlen=int(keep))
        self._last_mono = None
        self._lock = threading.Lock()
        self._thread = None
        self._stop = threading.Event()
        self._dead = False

    # -- sampling ----------------------------------------------------------

    def maybe_sample(self, reason="span"):
        """Rate-limited sample — the span-boundary hot-path entry.  Costs
        one clock read between samples."""
        now = time.monotonic()
        last = self._last_mono
        if last is not None and now - last < self.period:
            return None
        return self.sample(reason=reason)

    def sample(self, reason="tick"):
        """Take one sample now; returns the record (or None after a
        permanent failure — telemetry never raises into the run)."""
        if self._dead:
            return None
        try:
            return self._sample(reason)
        except Exception as e:
            self._dead = True
            logger.warning("devmem sampling failed (%s); disabling the "
                           "sampler — the run continues without HBM "
                           "telemetry", e)
            return None

    def _sample(self, reason):
        self._last_mono = time.monotonic()
        devices = memory_stats()
        # per-device owner attribution only when there is more than one
        # device to attribute to (the sharded-suggest breakdown); the
        # single-chip walk stays exactly as cheap as before
        if len(devices) > 1:
            census, per_device = live_array_census(per_device=True)
        else:
            census, per_device = live_array_census(), None
        mx_use, mx_peak, mx_lim, frac = roll_up(devices)
        obs = self.obs
        m = obs.metrics
        m.counter("devmem.samples").inc()
        if mx_use is not None:
            m.gauge("devmem.bytes_in_use").set(mx_use)
        if mx_peak is not None:
            m.gauge("devmem.peak_bytes_in_use").set(mx_peak)
        if mx_lim is not None:
            m.gauge("devmem.bytes_limit").set(mx_lim)
        if frac is not None:
            m.gauge("devmem.watermark_frac").set(frac)
        hist_b = census.get("history", {}).get("bytes", 0)
        m.gauge("devmem.history_bytes").set(hist_b)
        m.gauge("devmem.live_arrays").set(census["total"]["count"])
        m.gauge("devmem.live_bytes").set(census["total"]["bytes"])
        rec = {"kind": "devmem", "ts": time.time(), "reason": reason,
               "run_id": obs.run_id, "devices": devices, "census": census}
        if per_device:
            rec["per_device"] = per_device
        with self._lock:
            self._tail.append(rec)
        sink = getattr(obs, "sink", None)
        if sink is not None:
            sink.write(rec)
        return rec

    # -- crash-dump providers (FlightRecorder.devmem) ----------------------

    def tail(self):
        """Recent samples, oldest first — attached to flight dumps."""
        with self._lock:
            return list(self._tail)

    def census_record(self):
        """A fresh census as a JSONL record (taken AT dump time: the tail
        shows the ramp, this shows the end state)."""
        return {"kind": "devmem_census", "ts": time.time(),
                "census": live_array_census()}

    def watermark(self):
        """``(frac, peak_bytes)`` from the last sample's roll-up, or
        ``(None, None)`` before the first — the progressbar's HBM line.
        ``frac`` is CURRENT in-use/limit (what a live surface wants);
        the report's "peak watermark" is peak/limit — a different number
        on runs whose allocation spiked and settled."""
        with self._lock:
            if not self._tail:
                return None, None
            devices = self._tail[-1]["devices"]
        _, mx_peak, _, frac = roll_up(devices)
        return frac, mx_peak

    # -- sampler-thread lifecycle ------------------------------------------

    def start(self):
        """Start the low-rate daemon sampler (idempotent).  Span-boundary
        ``maybe_sample`` calls cover the busy phases; the thread covers the
        quiet ones (a wedged readback still advances the HBM tail).
        ``period <= 0`` means explicit-sample-only (bench mode): no thread
        at all — a zero wait would busy-spin."""
        if (self.period > 0
                and (self._thread is None or not self._thread.is_alive())):
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="hyperopt-obs-devmem", daemon=True)
            self._thread.start()
        from .flight import get_flight

        fl = get_flight()
        if fl.devmem is None:
            fl.devmem = self  # crash dumps attach the memory narrative
        return self

    def _run(self):
        while not self._stop.wait(self.period):
            self.maybe_sample(reason="sampler")

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        from .flight import get_flight

        fl = get_flight()
        if fl.devmem is self:
            fl.devmem = None
