"""Metrics registry: counters, gauges and bounded histograms.

Second pillar of the run-telemetry layer.  Registries are process-global
and namespaced (``get_metrics("device")`` is the same object everywhere in
the process — the natural scope for process-global caches like
``device_fmin._RUN_CACHE``), while per-run consumers create their own
namespace so two concurrent runs don't mix counters.

All metric objects are deliberately lock-free: increments are single
bytecode-level dict/int operations (safe enough under the GIL for
telemetry), and keeping them lock-free means they survive the pickle
boundaries the Trials backends cross (``ExecutorTrials`` checkpoints,
``FileTrials`` resume).
"""

from __future__ import annotations

import json
import threading
from collections import deque

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "get_metrics",
    "reset_metrics",
    "adopt_metrics",
    "all_namespaces",
]


class Counter:
    """Monotonically increasing count."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def inc(self, n=1):
        self.value += n

    def snapshot(self):
        return self.value


class Gauge:
    """Last-set value (queue depth, busy workers, cache size)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, v):
        self.value = v

    def snapshot(self):
        return self.value


class Histogram:
    """Bounded-memory duration/size distribution.

    Running ``count/sum/min/max`` are exact over the full stream; the
    percentile estimates come from a bounded ring of the most recent
    ``maxlen`` observations, so a week-long run cannot grow the registry
    without bound (the "bounded" in the tentpole spec).
    """

    __slots__ = ("count", "total", "min", "max", "_ring")

    def __init__(self, maxlen=512):
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = None
        self._ring = deque(maxlen=maxlen)

    def observe(self, v):
        v = float(v)
        self.count += 1
        self.total += v
        if self.min is None or v < self.min:
            self.min = v
        if self.max is None or v > self.max:
            self.max = v
        self._ring.append(v)

    def snapshot(self):
        if not self.count:
            return {"count": 0}
        ring = sorted(self._ring)

        def pct(p):
            return ring[min(len(ring) - 1, int(p * (len(ring) - 1) + 0.5))]

        return {
            "count": self.count,
            "sum": self.total,
            "mean": self.total / self.count,
            "min": self.min,
            "max": self.max,
            "p50": pct(0.50),
            "p90": pct(0.90),
            "p99": pct(0.99),
        }


class MetricsRegistry:
    """Named metrics under one namespace; ``snapshot()`` is deterministic
    (sorted keys, pure data) so two identically-fed registries serialize
    byte-identically — the property the test suite pins."""

    def __init__(self, namespace="default"):
        self.namespace = namespace
        self._metrics = {}

    def _get(self, name, cls, *args):
        m = self._metrics.get(name)
        if m is None:
            # setdefault: two racing creators converge on one instance
            m = self._metrics.setdefault(name, cls(*args))
        return m

    def counter(self, name) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name, maxlen=512) -> Histogram:
        return self._get(name, Histogram, maxlen)

    def iter_metrics(self):
        """Sorted ``(name, metric object)`` pairs — the typed view the
        Prometheus exposition needs (a snapshot can't distinguish a counter
        from an integer-valued gauge).  ``dict()`` first: the scrape
        thread iterates while the run thread creates metrics."""
        return sorted(dict(self._metrics).items())

    def snapshot(self):
        return {
            "namespace": self.namespace,
            "metrics": {
                name: m.snapshot()
                for name, m in sorted(dict(self._metrics).items())
            },
        }

    def to_json(self, indent=None):
        return json.dumps(self.snapshot(), indent=indent, sort_keys=True)


_REGISTRIES: dict = {}
_REG_LOCK = threading.Lock()


def get_metrics(namespace="default") -> MetricsRegistry:
    """The process-global registry for ``namespace`` (created on first
    use)."""
    reg = _REGISTRIES.get(namespace)
    if reg is None:
        with _REG_LOCK:
            reg = _REGISTRIES.setdefault(namespace, MetricsRegistry(namespace))
    return reg


def reset_metrics(namespace=None):
    """Drop one namespace (or all) — test/bench isolation."""
    with _REG_LOCK:
        if namespace is None:
            _REGISTRIES.clear()
        else:
            _REGISTRIES.pop(namespace, None)


def adopt_metrics(namespace, registry):
    """(Re-)install ``registry`` as the process-global registry for
    ``namespace``, replacing any registry created in the meantime.  This is
    how ``RunObs.rearm()`` re-enters a finished run: ``finish()`` released
    the namespace from the table, but the run's own registry object — with
    its accumulated counters — stays alive on the bundle, and a resumed run
    must keep counting into IT, not into a fresh empty namespace that
    happens to share the run id."""
    with _REG_LOCK:
        _REGISTRIES[namespace] = registry
    return registry


def all_namespaces():
    return sorted(_REGISTRIES)
