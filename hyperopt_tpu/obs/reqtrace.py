"""Request-scoped trace context for the serving plane (ISSUE 11).

Every observability layer before this PR was *run*-scoped: spans,
metrics, the flight ring, the profiler and the trajectory all describe
what one process did, never which *request* it did it for.  This module
is the missing identity: a W3C-``traceparent``-style context — a 128-bit
trace id naming one logical client request and a 64-bit span id naming
one hop of it — carried across threads on a ``contextvars.ContextVar``
so the HTTP handler, the wave ticker and the WAL writer all see the same
ids without plumbing an argument through every signature.

Wire format (the ``traceparent`` request header, W3C Trace Context)::

    00-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7-01
    ^v ^ trace-id (32 lowercase hex)    ^ span-id (16)    ^ flags

Parsing is *strict but never fatal*: a malformed header — wrong version,
short/non-hex ids, all-zero ids, oversized value, control bytes — makes
:func:`parse` return ``None`` and the server degrades to a freshly
minted trace (the request is still served; hostile headers must never
4xx/5xx a request that is otherwise fine).  That contract is pinned by
the tests/test_reqtrace.py fuzz corpus.

Determinism contract: trace ids are pure metadata.  They are minted
from a module-private per-thread generator seeded from ``os.urandom``
(never from any RNG a proposal depends on), never fed
into a seed, and never change what the optimizer proposes — armed
tracing produces byte-identical proposals to disarmed (pinned).
Disarmed (``HYPEROPT_TPU_REQTRACE=0``), nothing here runs at all: no
context is minted, no header sent, no WAL field stamped, zero threads
either way.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import random
import string
import threading

__all__ = [
    "TraceContext",
    "TRACEPARENT",
    "mint",
    "parse",
    "child",
    "extract_or_mint",
    "current",
    "current_trace_id",
    "use",
    "sanitize_request_id",
]

#: the request/response header name (lower-cased — the server's header
#: mapping is lower-cased at ingress)
TRACEPARENT = "traceparent"

#: hard bound on header values we even look at: a multi-KB "traceparent"
#: is an attack or a bug, not a trace
_MAX_HEADER = 256

#: X-Request-Id values are opaque client tokens; the server echoes them
#: back and logs them, so they must be printable and bounded
_MAX_REQUEST_ID = 128
_REQUEST_ID_OK = set(string.ascii_letters + string.digits + "-_.:+/=")

_HEX = set("0123456789abcdef")


class TraceContext:
    """One hop of one logical request: ``trace_id`` (32 lowercase hex)
    names the request end to end, ``span_id`` (16 hex) names this hop,
    ``parent_id`` the hop that caused it (None at the root)."""

    __slots__ = ("trace_id", "span_id", "parent_id")

    def __init__(self, trace_id, span_id, parent_id=None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id

    def traceparent(self):
        """The wire form (version 00, sampled flag set)."""
        return f"00-{self.trace_id}-{self.span_id}-01"

    def __repr__(self):
        return (f"TraceContext({self.trace_id[:8]}../{self.span_id}"
                + (f" <- {self.parent_id}" if self.parent_id else "") + ")")

    def __eq__(self, other):
        return (isinstance(other, TraceContext)
                and self.trace_id == other.trace_id
                and self.span_id == other.span_id)

    def __hash__(self):
        return hash((self.trace_id, self.span_id))


_local = threading.local()


def _rng():
    """Per-thread id generator, seeded once from ``os.urandom`` (and
    re-seeded after ``fork`` — the pid check — so worker processes never
    clone a parent's id stream).  Trace ids need global *uniqueness*,
    not cryptographic secrecy, and ``os.urandom`` is a syscall that
    costs tens of microseconds on older kernels — far too slow to pay
    twice per served request.  This generator is PRIVATE to the module:
    it never touches (and is never touched by) any RNG a proposal
    depends on."""
    pid = os.getpid()
    rng = getattr(_local, "rng", None)
    if rng is None or getattr(_local, "pid", None) != pid:
        rng = _local.rng = random.Random(
            (int.from_bytes(os.urandom(16), "big") << 64)
            ^ (pid << 32) ^ threading.get_ident())
        _local.pid = pid
    return rng


def mint():
    """A fresh root context.  All-zero ids are invalid on the wire, and
    128/64 random bits make one astronomically unlikely; re-draw anyway
    so the invariant is unconditional."""
    rng = _rng()
    tid = "%032x" % rng.getrandbits(128)
    while tid == "0" * 32:  # pragma: no cover - 2^-128
        tid = "%032x" % rng.getrandbits(128)
    return TraceContext(tid, _new_span_id())


def _new_span_id():
    rng = _rng()
    sid = "%016x" % rng.getrandbits(64)
    while sid == "0" * 16:  # pragma: no cover - 2^-64
        sid = "%016x" % rng.getrandbits(64)
    return sid


def child(ctx):
    """Same trace, fresh span, parented on ``ctx``'s span — one retry
    attempt, one handler hop."""
    return TraceContext(ctx.trace_id, _new_span_id(),
                        parent_id=ctx.span_id)


def _is_hex(s):
    return all(c in _HEX for c in s)


def parse(header):
    """Strict ``traceparent`` parse → :class:`TraceContext`, or ``None``
    on ANY malformation (the caller degrades to a fresh trace — a
    hostile header must never fail the request it rides on).

    Accepted: ``vv-<32 hex>-<16 hex>-<2 hex>`` where ``vv`` is two hex
    digits and not ``ff`` (the W3C invalid version); versions above 00
    may carry a ``-``-prefixed suffix (forward compat), which is
    ignored.  Hex must be lowercase (the spec's wire form); all-zero
    trace or span ids are invalid."""
    if not isinstance(header, str):
        return None
    if not header or len(header) > _MAX_HEADER:
        return None
    if any(ord(c) < 0x20 or ord(c) > 0x7E for c in header):
        return None  # control bytes / non-ASCII: hostile, not a trace
    parts = header.split("-")
    if len(parts) < 4:
        return None
    version, trace_id, span_id, flags = parts[0], parts[1], parts[2], parts[3]
    if len(version) != 2 or not _is_hex(version) or version == "ff":
        return None
    if len(parts) > 4 and version == "00":
        return None  # version 00 has exactly four fields
    if len(trace_id) != 32 or not _is_hex(trace_id):
        return None
    if len(span_id) != 16 or not _is_hex(span_id):
        return None
    if len(flags) != 2 or not _is_hex(flags):
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return TraceContext(trace_id, span_id)


def extract_or_mint(header):
    """The server's ingress rule: a valid inbound ``traceparent``
    continues the caller's trace (fresh span, parented on theirs); a
    missing or malformed one degrades to a fresh root trace.  Never
    raises, never refuses the request."""
    ctx = parse(header)
    if ctx is not None:
        return child(ctx)
    return mint()


def sanitize_request_id(value):
    """``X-Request-Id`` is an opaque client token we echo and log — but
    only when it is bounded and printable-safe.  Returns the value or
    ``None`` (hostile/oversized ids are dropped, never an error)."""
    if not isinstance(value, str) or not value:
        return None
    if len(value) > _MAX_REQUEST_ID:
        return None
    if any(c not in _REQUEST_ID_OK for c in value):
        return None
    return value


# ---------------------------------------------------------------------------
# ambient context (contextvar — correct across the threaded HTTP server
# AND the scheduler's wave handoff, where explicit fields take over)
# ---------------------------------------------------------------------------

_current: contextvars.ContextVar = contextvars.ContextVar(
    "hyperopt_tpu_reqtrace", default=None)


def current():
    """The active :class:`TraceContext`, or ``None`` (tracing disarmed,
    or not inside a traced request)."""
    return _current.get()


def current_trace_id():
    ctx = _current.get()
    return ctx.trace_id if ctx is not None else None


@contextlib.contextmanager
def use(ctx):
    """Install ``ctx`` as the ambient context for the block.  ``None``
    is allowed and makes the block a no-op — callers never need to
    branch on whether tracing is armed."""
    if ctx is None:
        yield None
        return
    token = _current.set(ctx)
    try:
        yield ctx
    finally:
        _current.reset(token)
