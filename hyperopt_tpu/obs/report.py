"""Render a captured run's JSONL telemetry stream into a human report.

Usage::

    python -m hyperopt_tpu.obs.report run.jsonl [--top 5]

Three sections, matching the three pillars:

1. **Phase-time breakdown** — spans aggregated by name: where the run's
   wall clock (and host CPU) actually went, with a share bar.
2. **Trial-state waterfall** — lifecycle events rolled into per-trial
   timelines: counts per transition, queue latency (new→claimed) and run
   latency (claimed→finished) distributions.
3. **Top-k slowest trials** — the individual post-mortem targets.

Plus the final metrics snapshot(s) embedded in the stream (compile vs
execute split, cache hit rates, queue gauges).
"""

from __future__ import annotations

import argparse
import json
import sys

from .events import (
    TRIAL_CANCELLED,
    TRIAL_CLAIMED,
    TRIAL_FINISHED,
    TRIAL_NEW,
    TRIAL_RECLAIMED,
)
from .trace import read_jsonl

__all__ = ["main", "render"]

_BAR_W = 30


def _bar(frac, width=_BAR_W):
    n = int(round(frac * width))
    return "#" * n + "." * (width - n)


def _fmt_sec(s):
    if s is None:
        return "-"
    if s < 1e-3:
        return f"{s * 1e6:.0f}us"
    if s < 1.0:
        return f"{s * 1e3:.1f}ms"
    return f"{s:.2f}s"


def _phase_section(spans, out):
    # shares are SELF time (wall minus direct children) so an umbrella span
    # like fmin's "run" doesn't double-count its phases into the breakdown
    child_wall = {}
    for s in spans:
        pid = s.get("parent_id")
        if pid is not None:
            child_wall[pid] = child_wall.get(pid, 0.0) + s.get("wall_sec", 0.0)
    agg = {}
    for s in spans:
        e = agg.setdefault(s["name"],
                           {"sec": 0.0, "self": 0.0, "cpu": 0.0, "count": 0})
        wall = s.get("wall_sec", 0.0)
        e["sec"] += wall
        e["self"] += max(0.0, wall - child_wall.get(s.get("span_id"), 0.0))
        e["cpu"] += s.get("cpu_sec", 0.0)
        e["count"] += 1
    if not agg:
        out.append("  (no spans in stream)")
        return
    total = sum(e["self"] for e in agg.values()) or 1.0
    width = max(len(n) for n in agg)
    for name, e in sorted(agg.items(), key=lambda kv: -kv[1]["self"]):
        frac = e["self"] / total
        out.append(
            f"  {name:<{width}}  {_bar(frac)} {frac * 100:5.1f}%  "
            f"self {_fmt_sec(e['self']):>8}  wall {_fmt_sec(e['sec']):>8}  "
            f"cpu {_fmt_sec(e['cpu']):>8}  x{e['count']}"
        )


def _trial_timelines(trial_events):
    """Per-tid {event: first ts} plus terminal info."""
    timelines = {}
    for r in trial_events:
        t = timelines.setdefault(r["tid"], {})
        t.setdefault(r["event"], r["ts"])  # first occurrence wins
        if r["event"] == TRIAL_FINISHED:
            t["_status"] = r.get("status", "ok")
    return timelines


def _quantiles(xs):
    if not xs:
        return None
    xs = sorted(xs)

    def q(p):
        return xs[min(len(xs) - 1, int(p * (len(xs) - 1) + 0.5))]

    return {"p50": q(0.5), "p90": q(0.9), "max": xs[-1]}


def _waterfall_section(trial_events, out):
    if not trial_events:
        out.append("  (no trial events in stream)")
        return
    counts = {}
    for r in trial_events:
        counts[r["event"]] = counts.get(r["event"], 0) + 1
    out.append("  transitions: " + "  ".join(
        f"{k}={v}" for k, v in sorted(counts.items())))
    timelines = _trial_timelines(trial_events)
    queue_lat = [
        t[TRIAL_CLAIMED] - t[TRIAL_NEW]
        for t in timelines.values()
        if TRIAL_NEW in t and TRIAL_CLAIMED in t
    ]
    run_lat = [
        t[TRIAL_FINISHED] - t[TRIAL_CLAIMED]
        for t in timelines.values()
        if TRIAL_CLAIMED in t and TRIAL_FINISHED in t
    ]
    for label, lat in (("queue (new->claimed)", queue_lat),
                       ("run (claimed->finished)", run_lat)):
        q = _quantiles(lat)
        if q:
            out.append(
                f"  {label:<24} n={len(lat)}  p50 {_fmt_sec(q['p50'])}  "
                f"p90 {_fmt_sec(q['p90'])}  max {_fmt_sec(q['max'])}")
    n_reclaimed = counts.get(TRIAL_RECLAIMED, 0)
    n_cancelled = counts.get(TRIAL_CANCELLED, 0)
    if n_reclaimed or n_cancelled:
        out.append(f"  anomalies: reclaimed={n_reclaimed} "
                   f"cancelled={n_cancelled}")


def _slowest_section(trial_events, out, top=5):
    timelines = _trial_timelines(trial_events)
    durations = []
    for tid, t in timelines.items():
        start = t.get(TRIAL_CLAIMED, t.get(TRIAL_NEW))
        end = t.get(TRIAL_FINISHED, t.get(TRIAL_CANCELLED))
        if start is not None and end is not None:
            durations.append((end - start, tid, t.get("_status", "?")))
    if not durations:
        out.append("  (no completed trials in stream)")
        return
    durations.sort(reverse=True)
    for sec, tid, status in durations[:top]:
        out.append(f"  tid {tid:>6}  {_fmt_sec(sec):>9}  status={status}")


def _metrics_section(metric_recs, out):
    if not metric_recs:
        out.append("  (no metrics snapshot in stream)")
        return
    for rec in metric_recs:
        snap = rec.get("snapshot", {})
        out.append(f"  run_id={rec.get('run_id', '?')}")
        out.append("  " + json.dumps(snap, indent=2, sort_keys=True,
                                     default=str).replace("\n", "\n  "))


def render(records, top=5):
    """Build the report text from parsed JSONL records."""
    spans = [r for r in records if r.get("kind") == "span"]
    trial_events = [r for r in records if r.get("kind") == "trial_event"]
    metric_recs = [r for r in records if r.get("kind") == "metrics"]
    events = [r for r in records if r.get("kind") == "event"]

    out = []
    out.append("== phase-time breakdown " + "=" * 40)
    _phase_section(spans, out)
    out.append("")
    out.append("== trial-state waterfall " + "=" * 39)
    _waterfall_section(trial_events, out)
    out.append("")
    out.append(f"== top-{top} slowest trials " + "=" * 38)
    _slowest_section(trial_events, out, top=top)
    out.append("")
    out.append("== metrics snapshot " + "=" * 44)
    _metrics_section(metric_recs, out)
    if events:
        out.append("")
        out.append("== events " + "=" * 54)
        for r in events:
            attrs = r.get("attrs", {})
            out.append(f"  {r['name']}  " + json.dumps(attrs, default=str))
    return "\n".join(out) + "\n"


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m hyperopt_tpu.obs.report",
        description="Render a hyperopt_tpu obs JSONL stream.")
    p.add_argument("jsonl", help="telemetry stream written by an armed run")
    p.add_argument("--top", type=int, default=5,
                   help="how many slowest trials to list")
    args = p.parse_args(argv)
    try:
        records = read_jsonl(args.jsonl)
    except OSError as e:
        print(f"error: cannot read {args.jsonl}: {e}", file=sys.stderr)
        return 2
    if not records:
        print(f"error: {args.jsonl} holds no telemetry records",
              file=sys.stderr)
        return 1
    sys.stdout.write(render(records, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
