"""Render a captured run's JSONL telemetry stream into a human report.

Usage::

    python -m hyperopt_tpu.obs.report run.jsonl [--top 5]
    python -m hyperopt_tpu.obs.report --merge run.p0.jsonl run.p1.jsonl ...
    python -m hyperopt_tpu.obs.report --postmortem run.flight.jsonl
    python -m hyperopt_tpu.obs.report --export-trace out.json run.jsonl ...
    python -m hyperopt_tpu.obs.report --trend [.obs/trajectory.jsonl]
    python -m hyperopt_tpu.obs.report --study <id> <store-or-wal> [more...]

Single-stream sections, matching the telemetry pillars:

1. **Phase-time breakdown** — spans aggregated by name: where the run's
   wall clock (and host CPU) actually went, with a share bar.
2. **Search health** — the optimizer's own vitals from the armed TPE /
   rand / anneal suggest paths (obs/health.py): EI-quantile and dup-rate
   trends, prior-fallback sparkline, below/above split, per-param
   posterior shape.
3. **Trial-state waterfall** — lifecycle events rolled into per-trial
   timelines: counts per transition, queue latency (new→claimed) and run
   latency (claimed→finished) distributions.
4. **Top-k slowest trials** — the individual post-mortem targets.

Plus the final metrics snapshot(s) embedded in the stream (compile vs
execute split, cache hit rates, queue gauges, device FLOP/byte costs).

``--merge`` treats the inputs as the per-controller streams one
``fmin_multihost`` run wrote (``parallel/driver.py`` names them
``<path>.p<i>.jsonl``) and renders the cross-controller view instead:
per-controller summary + phase breakdown, allgather-latency skew, and
correlated divergence context.

``--postmortem`` renders a flight-recorder dump (``<run>.flight.jsonl``,
written when a process dies — ``obs/flight.py``) as a last-moments
narrative: why/when the process died, the spans still open at death, the
last heartbeat per component (which collective each controller reached),
stall reports, in-flight trials, and the tail of the record ring.

``--export-trace OUT`` converts the input stream(s) to Chrome/Perfetto
trace-event JSON (``obs/export.py``; one process track group per stream)
instead of rendering ASCII — load OUT in https://ui.perfetto.dev.  Any
``kind="profile"`` record in the inputs whose device-capture artifact
(``*.trace.json.gz``, written by obs/profiler.py) still exists is merged
in automatically as additional ``device:`` track groups, wall-clock
aligned with the host spans.

``--trend`` renders the append-only bench trajectory store
(``.obs/trajectory.jsonl``, obs/trajectory.py) as per-key sparkline
history — the answer to "did ``ask_p50_ms`` creep up over the last six
PRs" from the committed artifacts alone.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .events import (
    TRIAL_CANCELLED,
    TRIAL_CLAIMED,
    TRIAL_FINISHED,
    TRIAL_NEW,
    TRIAL_RECLAIMED,
)
from .trace import iter_jsonl, read_jsonl  # noqa: F401  (read_jsonl re-export)

__all__ = ["main", "render", "render_merged", "render_postmortem",
           "render_trend", "headline_sections", "json_report",
           "render_study_timeline", "study_timeline_events",
           "render_probes"]

_BAR_W = 30


def _bar(frac, width=_BAR_W):
    n = int(round(frac * width))
    return "#" * n + "." * (width - n)


def _fmt_sec(s):
    if s is None:
        return "-"
    if s < 1e-3:
        return f"{s * 1e6:.0f}us"
    if s < 1.0:
        return f"{s * 1e3:.1f}ms"
    return f"{s:.2f}s"


_SPARK_BLOCKS = "▁▂▃▄▅▆▇█"


def _spark(values, width=24):
    """ASCII-art trend line; downsamples evenly to ``width`` points."""
    import math

    vals = [v for v in values if v is not None and math.isfinite(v)]
    if not vals:
        return ""
    if len(vals) > width:
        step = (len(vals) - 1) / (width - 1)
        vals = [vals[int(round(i * step))] for i in range(width)]
    lo, hi = min(vals), max(vals)
    rng = (hi - lo) or 1.0
    return "".join(
        _SPARK_BLOCKS[int((v - lo) / rng * (len(_SPARK_BLOCKS) - 1) + 0.5)]
        for v in vals
    )


def _phase_section(spans, out):
    # shares are SELF time (wall minus direct children) so an umbrella span
    # like fmin's "run" doesn't double-count its phases into the breakdown
    child_wall = {}
    for s in spans:
        pid = s.get("parent_id")
        if pid is not None:
            child_wall[pid] = child_wall.get(pid, 0.0) + s.get("wall_sec", 0.0)
    agg = {}
    for s in spans:
        e = agg.setdefault(s["name"],
                           {"sec": 0.0, "self": 0.0, "cpu": 0.0, "count": 0})
        wall = s.get("wall_sec", 0.0)
        e["sec"] += wall
        e["self"] += max(0.0, wall - child_wall.get(s.get("span_id"), 0.0))
        e["cpu"] += s.get("cpu_sec", 0.0)
        e["count"] += 1
    if not agg:
        out.append("  (no spans in stream)")
        return
    total = sum(e["self"] for e in agg.values()) or 1.0
    width = max(len(n) for n in agg)
    for name, e in sorted(agg.items(), key=lambda kv: -kv[1]["self"]):
        frac = e["self"] / total
        out.append(
            f"  {name:<{width}}  {_bar(frac)} {frac * 100:5.1f}%  "
            f"self {_fmt_sec(e['self']):>8}  wall {_fmt_sec(e['sec']):>8}  "
            f"cpu {_fmt_sec(e['cpu']):>8}  x{e['count']}"
        )


def _trial_timelines(trial_events):
    """Per-tid {event: first ts} plus terminal info."""
    timelines = {}
    for r in trial_events:
        t = timelines.setdefault(r["tid"], {})
        t.setdefault(r["event"], r["ts"])  # first occurrence wins
        if r["event"] == TRIAL_FINISHED:
            t["_status"] = r.get("status", "ok")
    return timelines


def _quantiles(xs):
    if not xs:
        return None
    xs = sorted(xs)

    def q(p):
        return xs[min(len(xs) - 1, int(p * (len(xs) - 1) + 0.5))]

    return {"p50": q(0.5), "p90": q(0.9), "max": xs[-1]}


def _waterfall_section(trial_events, out):
    if not trial_events:
        out.append("  (no trial events in stream)")
        return
    counts = {}
    for r in trial_events:
        counts[r["event"]] = counts.get(r["event"], 0) + 1
    out.append("  transitions: " + "  ".join(
        f"{k}={v}" for k, v in sorted(counts.items())))
    timelines = _trial_timelines(trial_events)
    queue_lat = [
        t[TRIAL_CLAIMED] - t[TRIAL_NEW]
        for t in timelines.values()
        if TRIAL_NEW in t and TRIAL_CLAIMED in t
    ]
    run_lat = [
        t[TRIAL_FINISHED] - t[TRIAL_CLAIMED]
        for t in timelines.values()
        if TRIAL_CLAIMED in t and TRIAL_FINISHED in t
    ]
    for label, lat in (("queue (new->claimed)", queue_lat),
                       ("run (claimed->finished)", run_lat)):
        q = _quantiles(lat)
        if q:
            out.append(
                f"  {label:<24} n={len(lat)}  p50 {_fmt_sec(q['p50'])}  "
                f"p90 {_fmt_sec(q['p90'])}  max {_fmt_sec(q['max'])}")
    n_reclaimed = counts.get(TRIAL_RECLAIMED, 0)
    n_cancelled = counts.get(TRIAL_CANCELLED, 0)
    if n_reclaimed or n_cancelled:
        out.append(f"  anomalies: reclaimed={n_reclaimed} "
                   f"cancelled={n_cancelled}")


def _slowest_section(trial_events, out, top=5):
    timelines = _trial_timelines(trial_events)
    durations = []
    for tid, t in timelines.items():
        start = t.get(TRIAL_CLAIMED, t.get(TRIAL_NEW))
        end = t.get(TRIAL_FINISHED, t.get(TRIAL_CANCELLED))
        if start is not None and end is not None:
            durations.append((end - start, tid, t.get("_status", "?")))
    if not durations:
        out.append("  (no completed trials in stream)")
        return
    durations.sort(reverse=True)
    for sec, tid, status in durations[:top]:
        out.append(f"  tid {tid:>6}  {_fmt_sec(sec):>9}  status={status}")


def _health_section(health_recs, out):
    """Search-health vitals (obs/health.py record schema): trends over the
    run's asks, last-ask posterior shape per param."""
    if not health_recs:
        out.append("  (no health records — arm the run with obs=<path> and "
                   "a tpe/rand/anneal suggester)")
        return
    by_algo = {}
    for r in health_recs:
        by_algo[r.get("algo", "?")] = by_algo.get(r.get("algo", "?"), 0) + 1
    out.append("  asks: " + "  ".join(
        f"{a}={n}" for a, n in sorted(by_algo.items())))
    tpe = [r for r in health_recs if "ei_p50" in r]
    if tpe:
        ei = [r["ei_p50"] for r in tpe]
        out.append(f"  EI p50        first {ei[0]:+.3g}  last {ei[-1]:+.3g}"
                   f"  {_spark(ei)}")
        sel = [r.get("sel_rank", 0.0) for r in tpe]
        out.append(f"  EI sel rank   mean {sum(sel) / len(sel):.2f}"
                   "  (0 = pure argmax)")
    dups = [r["dup_rate"] for r in health_recs if "dup_rate" in r]
    if dups:
        out.append(f"  dup rate      first {dups[0]:.1%}  last {dups[-1]:.1%}"
                   f"  {_spark(dups)}")
    spreads = [r["spread"] for r in health_recs if "spread" in r]
    if spreads:
        out.append(f"  spread        last {spreads[-1]:.3g}  {_spark(spreads)}"
                   "  (rand/anneal proposal std)")
    if tpe:
        takes = [r.get("prior_takes", 0) for r in tpe]
        total = sum(r.get("n_label_proposals", 0) for r in tpe)
        out.append(f"  prior fallback  {sum(takes)}/{total} label-proposals"
                   f"  {_spark(takes)}")
        last = tpe[-1]
        out.append(f"  below/above split (last ask): "
                   f"{last.get('n_below', '?')}/{last.get('n_above', '?')}")
        labels = last.get("labels") or {}
        if labels:
            w = max(len(l) for l in labels)
            out.append("  per-param (last ask):")
            for l, st in sorted(labels.items()):
                out.append(
                    f"    {l:<{w}}  eff_comp {st.get('eff_components', 0):.1f}"
                    f"  prior_mass {st.get('prior_mass_frac', 0):.2f}"
                    f"  dup {st.get('dup_rate', 0):.1%}")


def _metrics_section(metric_recs, out):
    if not metric_recs:
        out.append("  (no metrics snapshot in stream)")
        return
    for rec in metric_recs:
        snap = rec.get("snapshot", {})
        out.append(f"  run_id={rec.get('run_id', '?')}")
        out.append("  " + json.dumps(snap, indent=2, sort_keys=True,
                                     default=str).replace("\n", "\n  "))


def _pipeline_section(spans, metrics, out):
    """Ask-pipeline summary (ISSUE 4): dispatch vs readback wall time and
    the speculative-ask overlap, when the run recorded the split."""
    agg = {}
    for s in spans:
        if s["name"] in ("suggest", "suggest.dispatch", "suggest.readback"):
            e = agg.setdefault(s["name"], [0.0, 0])
            e[0] += s.get("wall_sec", 0.0)
            e[1] += 1
    if "suggest.dispatch" not in agg and "suggest.readback" not in agg:
        return
    out.append("")
    out.append("== ask pipeline " + "=" * 48)
    for name in ("suggest", "suggest.dispatch", "suggest.readback"):
        if name in agg:
            sec, count = agg[name]
            out.append(f"  {name:<18} wall {_fmt_sec(sec):>8}  x{count}")
    shards = metrics.get("suggest.shards")
    if shards:
        line = f"  sharded over {int(shards)} device(s)"
        cps = metrics.get("suggest.cand_per_shard")
        if cps:
            line += f"  cand/shard {int(cps)}"
        line += ("  history axis: sharded"
                 if metrics.get("suggest.hist_sharded")
                 else "  history axis: replicated")
        out.append(line)
    spec = metrics.get("suggest.speculative", 0)
    blocked = metrics.get("ask.blocked_sec") or {}
    if blocked.get("count"):
        out.append(
            f"  blocked per ask    p50 {_fmt_sec(blocked.get('p50', 0)):>8}"
            f"  p99 {_fmt_sec(blocked.get('p99', 0)):>8}"
            f"  x{blocked['count']}  (speculative asks: {spec})")
    if spec:
        out.append("  overlap: speculative dispatches ran while trials "
                   "evaluated — readback p50 above is the residual wait")
    else:
        out.append("  no speculative asks recorded (lookahead=0: "
                   "synchronous dispatch+readback)")


def _resilience_section(metrics, out):
    """Fleet & chaos summary (ISSUE 8): shard-lease traffic, injected
    faults, retry/backoff pressure — rendered only when the run recorded
    any of it (a non-fleet, chaos-free run keeps its report unchanged)."""
    lease_keys = [k for k in metrics if k.startswith("lease.")]
    chaos_keys = [k for k in metrics if k.startswith("chaos.")]
    retry_n = metrics.get("trials.retries", 0)
    backoff = metrics.get("retry.backoff_sec") or {}
    res_backoff = metrics.get("reserve.backoff_sec") or {}
    ag_timeouts = metrics.get("allgather.timeouts", 0)
    if not (lease_keys or chaos_keys or retry_n or backoff.get("count")
            or res_backoff.get("count") or ag_timeouts):
        return
    out.append("")
    out.append("== fleet & chaos " + "=" * 47)
    if lease_keys or metrics.get("fleet.members") is not None:
        out.append(
            f"  leases   claims {int(metrics.get('lease.claims', 0))}"
            f"  reclaims {int(metrics.get('lease.reclaims', 0))}"
            f"  contention {int(metrics.get('lease.contention', 0))}"
            f"  heartbeats {int(metrics.get('lease.heartbeats', 0))}")
        members = metrics.get("fleet.members")
        pub = metrics.get("shard.published", 0)
        if members is not None or pub:
            out.append(f"  fleet    members {int(members or 0)}"
                       f"  shards published {int(pub)}"
                       f"  joins {int(metrics.get('fleet.joins', 0))}")
    if chaos_keys:
        inj = "  ".join(f"{k[len('chaos.'):]} x{int(metrics[k])}"
                        for k in sorted(chaos_keys))
        out.append(f"  chaos    {inj}")
    if retry_n or backoff.get("count"):
        line = f"  retries  {int(retry_n)} re-attempts"
        if backoff.get("count"):
            line += (f"  backoff p50 {_fmt_sec(backoff.get('p50', 0))}"
                     f"  max {_fmt_sec(backoff.get('max', 0))}")
        out.append(line)
    if res_backoff.get("count"):
        out.append(
            f"  reserve  backoff x{int(res_backoff['count'])}"
            f"  p50 {_fmt_sec(res_backoff.get('p50', 0))}"
            f"  total {_fmt_sec(res_backoff.get('sum', 0))}")
    if ag_timeouts:
        out.append(f"  DEGRADED: {int(ag_timeouts)} collective timeout(s) — "
                   "checkpoint-and-shrink path taken")


def _service_section(metrics, out):
    """Serving-plane health (ISSUE 10): traffic, shed/backpressure,
    degrade-ladder state, WAL durability and HTTP error classes —
    rendered only when the stream recorded ``service.*`` metrics (a
    non-serving run keeps its report unchanged)."""
    svc = {k: v for k, v in metrics.items() if k.startswith("service.")}
    if not svc:
        return
    out.append("")
    out.append("== service health " + "=" * 46)
    asks = int(svc.get("service.asks", 0))
    tells = int(svc.get("service.tells", 0))
    ticks = int(svc.get("service.ticks", 0))
    if asks or tells:
        wave = svc.get("service.wave_sec") or {}
        line = (f"  traffic  asks {asks}  tells {tells}  ticks {ticks}"
                f"  studies {int(svc.get('service.studies_created', 0))}")
        if wave.get("count"):
            line += (f"  wave p50 {_fmt_sec(wave.get('p50', 0))}"
                     f"  p99 {_fmt_sec(wave.get('p99', 0))}")
        out.append(line)
    shed_ask = int(svc.get("service.shed.ask", 0))
    shed_tell = int(svc.get("service.shed.tell", 0))
    shed_ddl = int(svc.get("service.shed.deadline", 0))
    if shed_ask or shed_tell:
        frac = shed_ask / max(1, shed_ask + asks)
        out.append(f"  shed     asks {shed_ask} ({100 * frac:.1f}% of "
                   f"offered)  tells {shed_tell}"
                   f"  deadline-unservable {shed_ddl}")
    level = svc.get("service.degraded")
    downs = int(svc.get("service.degrade.down", 0))
    if level or downs:
        out.append(
            f"  degrade  level {int(level or 0)}"
            f"  faults {int(svc.get('service.degrade.faults', 0))}"
            f"  down x{downs}"
            f"  up x{int(svc.get('service.degrade.up', 0))}"
            f"  rand-served asks "
            f"{int(svc.get('service.degraded_asks', 0))}")
        if level:
            out.append("  DEGRADED: serving below full quality — see "
                       "service.degrade.* transitions")
    comp_keys = [k for k in svc if k.startswith("service.compile.")]
    if comp_keys:
        # cold-start compile plane (ISSUE 14): warming traffic, the
        # background queue, and the kernel bank's reuse
        cc_h = int(svc.get("service.compile.cohort_cache.hits", 0))
        cc_m = int(svc.get("service.compile.cohort_cache.misses", 0))
        out.append(
            f"  compile  warming studies "
            f"{int(svc.get('service.compile.warming_studies', 0))}"
            f"  warming asks "
            f"{int(svc.get('service.compile.warming_asks', 0))}"
            f"  promotions "
            f"{int(svc.get('service.compile.promotions', 0))}"
            f"  queue {int(svc.get('service.compile.queue_depth', 0))}"
            f"  compiled "
            f"{int(svc.get('service.compile.compiled_total', 0))}")
        bank_keys = int(svc.get("service.compile.bank.keys", 0))
        if bank_keys or cc_h or cc_m:
            line = (f"  kernels  cohort cache {cc_h}h/{cc_m}m"
                    f"  bank keys {bank_keys}"
                    f"  bank hits "
                    f"{int(svc.get('service.compile.bank.hits', 0))}")
            errs = int(svc.get("service.compile.errors", 0))
            if errs:
                line += f"  COMPILE ERRORS {errs}"
            out.append(line)
    wal_keys = [k for k in svc if k.startswith("service.wal.")]
    if wal_keys:
        out.append(
            f"  wal      replayed studies "
            f"{int(svc.get('service.wal.replay_studies', 0))}"
            f"  asks {int(svc.get('service.wal.replay_asks', 0))}"
            f" ({int(svc.get('service.wal.replay_regenerated', 0))} "
            f"regenerated)"
            f"  dup tells "
            f"{int(svc.get('service.wal.replay_duplicate_tells', 0))}"
            f"  compactions "
            f"{int(svc.get('service.wal.compactions', 0))}")
        sync_errs = int(svc.get("service.wal.sync_errors", 0))
        if sync_errs or svc.get("service.wal.replay_errors"):
            out.append(
                f"  WAL TROUBLE: sync errors {sync_errs}  replay errors "
                f"{int(svc.get('service.wal.replay_errors', 0))}")
    http = {}
    for k, v in svc.items():
        if k.startswith("service.http."):
            _, _, rest = k.partition("service.http.")
            ep, _, cls = rest.rpartition(".")
            http.setdefault(cls, {})[ep] = int(v)
    for cls in sorted(http):
        if cls in ("4xx", "5xx") or cls == "2xx":
            total = sum(http[cls].values())
            detail = "  ".join(f"{ep} {n}" for ep, n
                               in sorted(http[cls].items()))
            out.append(f"  http     {cls} x{total}  ({detail})")
    _slo_lines(metrics, out)


def _quality_section(metrics, events, out):
    """Search-quality roll-up (ISSUE 16): the ``quality.*`` gauges per
    (algo, space-signature) cohort — studies/stagnant/solved counts and
    best regret — plus a best-so-far sparkline per cohort mined from the
    streamed ``quality.improvement`` events.  Rendered only when the
    stream recorded the quality plane (a disarmed run keeps its report
    unchanged)."""
    qual = {k: v for k, v in metrics.items() if k.startswith("quality.")}
    imps = [e for e in events
            if e.get("name") == "quality.improvement"
            and (e.get("attrs") or {}).get("best") is not None]
    if not qual and not imps:
        return
    out.append("")
    out.append("== search quality " + "=" * 46)
    n = int(qual.get("quality.studies", 0))
    if n or qual:
        line = (f"  studies  {n}"
                f"  stagnant {int(qual.get('quality.stagnant', 0))}"
                f" ({float(qual.get('quality.stagnant_frac', 0.0)):.0%})"
                f"  solved {int(qual.get('quality.solved', 0))}")
        imp_n = qual.get("quality.improvements")
        stag_n = qual.get("quality.stagnations")
        if imp_n is not None or stag_n is not None:
            line += (f"  improvements {int(imp_n or 0)}"
                     f"  stagnations {int(stag_n or 0)}")
        out.append(line)
    # per-cohort table from the quality.cohort.<key>.* gauges
    cohorts = sorted({k.split(".")[2] for k in qual
                      if k.startswith("quality.cohort.")
                      and k.count(".") >= 3})
    # best-so-far trajectory per cohort: each improvement event carries
    # the new best — in stream order that IS the convergence curve
    curves = {}
    for e in imps:
        a = e.get("attrs") or {}
        curves.setdefault(a.get("cohort") or "?", []).append(
            float(a["best"]))
    for c in cohorts:
        base = f"quality.cohort.{c}"
        line = (f"  cohort   {c:<28}"
                f" studies {int(qual.get(f'{base}.studies', 0))}"
                f"  stagnant {int(qual.get(f'{base}.stagnant', 0))}"
                f"  solved {int(qual.get(f'{base}.solved', 0))}")
        regret = qual.get(f"{base}.best_regret")
        if regret is not None:
            line += f"  regret {float(regret):.4g}"
        spark = _spark(curves.get(c, []))
        if spark:
            line += f"  best {spark}"
        out.append(line)
    # cohorts seen only in the event stream (gauges not snapshotted)
    for c in sorted(set(curves) - set(cohorts)):
        out.append(f"  cohort   {c:<28} best {_spark(curves[c])}"
                   f" -> {min(curves[c]):.4g}")
    if qual.get("quality.stagnant_frac", 0.0) and n and (
            float(qual.get("quality.stagnant_frac", 0.0)) >= 0.5):
        out.append("  STAGNATION: over half the live studies have "
                   "plateaued — check budgets/targets (quality.* gauges, "
                   "per-study timelines)")


def _storage_section(metrics, out):
    """Storage integrity (ISSUE 15): checksum verification traffic,
    quarantines with reasons, disk watermarks, GC reclaim and the
    ENOSPC shed state — rendered only when the stream recorded any
    integrity/store metric (a healthy in-memory run keeps its report
    unchanged)."""
    keys = {k: v for k, v in metrics.items()
            if k.startswith(("service.integrity.", "store.",
                             "service.shed.store_full",
                             "scrub."))}
    if not keys:
        return
    out.append("")
    out.append("== storage integrity " + "=" * 43)
    verified = int(keys.get("service.integrity.verified", 0))
    unchecked = int(keys.get("service.integrity.unchecked", 0))
    corrupt = int(keys.get("service.integrity.corrupt_records", 0))
    torn = int(keys.get("service.integrity.torn", 0))
    if verified or unchecked or corrupt or torn:
        out.append(f"  checksums  verified {verified}"
                   f"  unchecked(pre-15) {unchecked}"
                   f"  torn-tail {torn}  corrupt {corrupt}")
    quarantines = int(keys.get("service.integrity.quarantines", 0))
    if quarantines or corrupt:
        out.append(
            f"  quarantine studies {quarantines}"
            f"  records-skipped "
            f"{int(keys.get('service.integrity.quarantine_skipped', 0))}"
            f"  snapshot-recovered "
            f"{int(keys.get('service.integrity.snapshot_recovered', 0))}"
            f"  unattributed "
            f"{int(keys.get('service.integrity.corrupt_unattributed', 0))}")
        if quarantines:
            out.append("  QUARANTINED: corrupt studies answer 410 — "
                       "run `python -m hyperopt_tpu.service.scrub "
                       "<root> --repair`")
    free = keys.get("store.free_bytes")
    if free is not None:
        used = float(keys.get("store.used_frac", 0.0) or 0.0)
        line = (f"  disk       free {_fmt_bytes(float(free))}"
                f"  used {used:.1%}")
        if keys.get("store.full"):
            line += "  STORE-FULL (shedding 507)"
        out.append(line)
    shed = int(keys.get("service.shed.store_full", 0))
    enospc = int(keys.get("store.enospc_errors", 0))
    if shed or enospc:
        out.append(f"  enospc     sheds {shed}  append-errors {enospc}")
    gc_bytes = keys.get("store.gc.reclaimed_bytes")
    if gc_bytes is not None:
        out.append(
            f"  gc         runs {int(keys.get('store.gc.runs', 0))}"
            f"  reclaimed {_fmt_bytes(float(gc_bytes))}")
    scrub_recs = keys.get("scrub.records")
    if scrub_recs is not None:
        out.append(
            f"  scrub      records {int(scrub_recs)}"
            f"  corrupt {int(keys.get('scrub.corrupt', 0))}"
            f"  repaired {int(keys.get('scrub.repaired', 0))}")


def _probe_section(metrics, out):
    """Blackbox probes (ISSUE 18): the synthetic-canary audit plane —
    cycle count, newest verdict, golden-match streak and the measured
    green→red detection latency — from the ``probe.*`` gauges a
    prober-armed server snapshots.  Rendered only when the stream
    recorded the prober (a disarmed run keeps its report unchanged)."""
    pr = {k: v for k, v in metrics.items() if k.startswith("probe.")}
    if not pr:
        return
    verdict_names = ("ok", "degraded", "contract", "mismatch", "error")
    out.append("")
    out.append("== blackbox probes " + "=" * 45)
    code = int(pr.get("probe.last_verdict_code", -1))
    verdict = verdict_names[code] if 0 <= code < len(verdict_names) \
        else "?"
    out.append(
        f"  cycles   {int(pr.get('probe.cycles', 0))}"
        f"  targets {int(pr.get('probe.targets', 0))}"
        f"  last verdict {verdict}"
        f"  golden-match streak "
        f"{int(pr.get('probe.golden_match_streak', 0))}")
    counts = "  ".join(
        f"{v} {int(pr[f'probe.verdict.{v}'])}" for v in verdict_names
        if pr.get(f"probe.verdict.{v}"))
    if counts:
        out.append(f"  verdicts {counts}")
    lat = pr.get("probe.detection_latency_sec")
    if lat is not None:
        out.append(f"  detection latency {float(lat):.2f}s "
                   "(last green->red edge, client-view)")
    esc = int(pr.get("probe.escalations", 0))
    if esc or verdict == "mismatch":
        out.append(
            f"  GOLDEN MISMATCH: escalations {esc} — the canary's "
            "proposal stream diverged from the committed golden digest "
            "(evidence bundles under fleet/probes/, flight ring has "
            "probe_mismatch records)")


def _megakernel_section(metrics, spans, out):
    """Fused-suggest megakernel plane (ISSUE 19): arming state, quantized
    history encode/dispatch span time, and the two warn-once fallback
    counters (kernel lowering failure, quantizer refusal).  Rendered only
    when the run ever armed the megakernel or tripped a fallback — a
    plain bf16/jnp run keeps its report unchanged."""
    armed = metrics.get("suggest.megakernel")
    kfall = int(metrics.get("suggest.megakernel.fallback", 0))
    qfall = int(metrics.get("suggest.quant.fallback", 0))
    span_tot = {}
    for s in spans:
        n = s.get("name", "")
        if n.startswith("suggest.megakernel."):
            e = span_tot.setdefault(n, {"sec": 0.0, "count": 0})
            e["sec"] += s.get("wall_sec", 0.0)
            e["count"] += 1
    if armed is None and not (kfall or qfall or span_tot):
        return
    out.append("")
    out.append("== megakernel " + "=" * 50)
    state = "armed" if armed else "disarmed"
    out.append(f"  fused    {state}"
               f"  lowering fallbacks {kfall}"
               f"  quant fallbacks {qfall}")
    for name in sorted(span_tot):
        e = span_tot[name]
        short = name[len("suggest.megakernel."):]
        out.append(f"  {short:<8} x{e['count']:<6} "
                   f"total {_fmt_sec(e['sec']):>8}")
    if kfall:
        out.append("  FALLBACK: Pallas lowering failed at least once — "
                   "cohort(s) rebuilt on the jnp path (warn-once log has "
                   "the first error)")
    if qfall:
        out.append("  FALLBACK: quantizer refused the space/dtype — "
                   "history stored bf16 instead (asks unaffected)")


def render_probes(path):
    """The blackbox-probe verdict view (ISSUE 18) from the durable
    CRC-sealed ledgers: give one ``<replica>.jsonl`` ledger, a
    ``fleet/probes`` dir, or a store root — per replica the verdict
    census, current/newest verdict, golden digest provenance and the
    measured detection-latency stats over every green→red edge.
    Corrupt ledger lines are counted, not fatal (the census read
    discipline)."""
    from .prober import PROBES_DIR, detection_stats, read_probes

    if os.path.isdir(path):
        probes_dir = os.path.join(path, PROBES_DIR)
        if not os.path.isdir(probes_dir):
            probes_dir = path
        ledgers = sorted(
            os.path.join(probes_dir, f) for f in os.listdir(probes_dir)
            if f.endswith(".jsonl"))
    else:
        ledgers = [path]
    out = []
    out.append("== blackbox probes " + "=" * 45)
    if not ledgers:
        out.append(f"  (no probe ledgers under {path} — is any replica "
                   "running with --probe on / HYPEROPT_TPU_PROBE=1?)")
        return "\n".join(out) + "\n"
    verdict_names = ("ok", "degraded", "contract", "mismatch", "error")
    glyph = {"ok": ".", "degraded": "d", "contract": "c",
             "mismatch": "X", "error": "!"}
    for ledger in ledgers:
        recs, corrupt, torn = read_probes(ledger)
        name = os.path.basename(ledger)[: -len(".jsonl")]
        line = f"  {name:<24} verdicts {len(recs)}"
        if corrupt:
            line += f"  CORRUPT {corrupt}"
        if torn:
            line += f"  torn {torn}"
        out.append(line)
        if not recs:
            continue
        recs = sorted(recs, key=lambda r: (r.get("ts") or 0.0,
                                           r.get("cycle") or 0))
        counts = {}
        for r in recs:
            counts[r.get("verdict") or "?"] = (
                counts.get(r.get("verdict") or "?", 0) + 1)
        census = "  ".join(f"{v} {counts[v]}" for v in verdict_names
                           if v in counts)
        extra = sum(n for v, n in counts.items()
                    if v not in verdict_names)
        if extra:
            census += f"  other {extra}"
        last = recs[-1]
        out.append(f"    census   {census}")
        out.append(
            f"    newest   cycle {int(last.get('cycle') or 0)}"
            f"  verdict {last.get('verdict')}"
            + (f"  ({last.get('why')})" if last.get("why") else ""))
        golden = last.get("golden")
        if golden:
            out.append(
                f"    golden   {golden} [{last.get('golden_source')}]"
                f"  canary {last.get('canary')}"
                f"  backend {last.get('backend')}")
        strip = "".join(glyph.get(r.get("verdict"), "?")
                        for r in recs[-48:])
        out.append(f"    verdicts [{strip}]  (newest right)")
        stats = detection_stats(recs)
        if stats["episodes"]:
            out.append(
                f"    detect   {stats['episodes']} episode(s)  "
                f"latency min {stats['min_sec']:.2f}s  "
                f"mean {stats['mean_sec']:.2f}s  "
                f"max {stats['max_sec']:.2f}s (client-view "
                "green->red)")
        evidence = [r.get("evidence") for r in recs if r.get("evidence")]
        if evidence:
            out.append(f"    evidence {evidence[-1]}")
    return "\n".join(out) + "\n"


def _slo_lines(metrics, out):
    """SLO error-budget lines (ISSUE 11): one row per objective from the
    ``slo.*`` gauges, budget bar + fast/slow burn rates, with the
    ERROR-BUDGET-EXHAUSTED banner when any objective's budget is gone.
    Rendered only when the stream recorded the SLO plane."""
    objectives = sorted({k.split(".")[1] for k in metrics
                         if k.startswith("slo.") and k.count(".") >= 2})
    if not objectives:
        return
    exhausted = []
    for name in objectives:
        rem = metrics.get(f"slo.{name}.budget_remaining_frac")
        if rem is None:
            continue
        burn_f = metrics.get(f"slo.{name}.burn_fast", 0.0)
        burn_s = metrics.get(f"slo.{name}.burn_slow", 0.0)
        frac = max(0.0, min(1.0, float(rem)))
        line = (f"  slo      {name:<14} budget [{_bar(frac, 16)}] "
                f"{float(rem) * 100:6.1f}%  burn fast {float(burn_f):5.1f}x"
                f"  slow {float(burn_s):5.1f}x")
        if metrics.get(f"slo.{name}.fast_alerting"):
            line += "  FAST-BURN"
        out.append(line)
        if metrics.get(f"slo.{name}.exhausted"):
            exhausted.append(name)
    if exhausted:
        out.append("  ERROR-BUDGET-EXHAUSTED: " + ", ".join(exhausted)
                   + " — the service is out of SLO; see slo.* gauges and "
                     "the escalation capture (slo.escalations)")


def _devmem_section(devmem_recs, out):
    """HBM watermark over the run's devmem samples (obs/devmem.py) + the
    last live-array census, so "how much memory did it hold" is answerable
    from the report alone."""
    if not devmem_recs:
        return
    from .devmem import roll_up

    out.append("")
    out.append("== device memory (HBM) " + "=" * 41)
    rolls = [roll_up(r.get("devices", [])) for r in devmem_recs]
    in_use = [r[0] for r in rolls]
    limit = next((r[2] for r in reversed(rolls) if r[2] is not None), None)
    peak = max((r[1] for r in rolls if r[1] is not None), default=None)
    if peak is None and not any(v is not None for v in in_use):
        out.append(f"  {len(devmem_recs)} sample(s); backend reports no "
                   "memory_stats (CPU?) — census only")
    else:
        line = f"  samples {len(devmem_recs)}"
        if peak is not None:
            line += f"  peak {_fmt_bytes(peak)}"
        if limit:
            line += f"  limit {_fmt_bytes(limit)}"
            if peak is not None:
                # explicitly the PEAK fraction — the live "hbm N%"
                # progressbar/top figure is current in-use, a different
                # (and for a live surface, more useful) number
                line += f"  peak watermark {peak / limit:.0%}"
        out.append(line)
        spark = _spark([v for v in in_use if v is not None])
        if spark:
            out.append(f"  in-use trend  {spark}")
    census = devmem_recs[-1].get("census") or {}
    if census:
        parts = []
        for owner in sorted(census):
            if owner == "total":
                continue
            b = census[owner]
            parts.append(f"{owner} {_fmt_bytes(b['bytes'])} "
                         f"(x{b['count']})")
        tot = census.get("total", {})
        out.append("  live arrays (last census): " + "  ".join(parts)
                   + (f"  | total {_fmt_bytes(tot.get('bytes', 0))} "
                      f"(x{tot.get('count', 0)})" if tot else ""))
    per_device = devmem_recs[-1].get("per_device") or {}
    if per_device:
        # the sharded-suggest breakdown: where each owner's bytes actually
        # landed, device by device (a sharded axis shows up as 1/n-sized
        # slices; a replicated leaf charges every device in full)
        out.append("  per-shard breakdown (last census):")
        for dev in sorted(per_device):
            owners = per_device[dev]
            parts = [f"{o} {_fmt_bytes(owners[o]['bytes'])}"
                     for o in sorted(owners) if o != "total"]
            out.append(f"    {dev}: " + "  ".join(parts))


def _fmt_bytes(n):
    if n is None:
        return "-"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return (f"{n:.0f}{unit}" if unit == "B" else f"{n:.2f}{unit}")
        n /= 1024


def _fmt_flops(v):
    if v is None:
        return "-"
    v = float(v)
    for unit in ("", "K", "M", "G", "T", "P"):
        if abs(v) < 1000 or unit == "P":
            return f"{v:.1f}{unit}F/s"
        v /= 1000


def _roofline_section(records, spans, out):
    """Per-program roofline (kernel attribution): the captured
    cost_analysis() costs joined with measured execute spans, plus each
    program's share of the suggest phase — from the final embedded
    metrics snapshot, same join the live ``/snapshot`` serves."""
    from .health import roofline_table

    metric_recs = [r for r in records if r.get("kind") == "metrics"]
    if not metric_recs:
        return
    snap = metric_recs[-1].get("snapshot") or {}
    dev = ((snap.get("shared") or {}).get("device") or {}).get("metrics", {})
    phases = {}
    for s in spans:
        if s.get("aggregate") is False:
            continue
        e = phases.setdefault(s["name"], {"sec": 0.0, "count": 0})
        e["sec"] += s.get("wall_sec", 0.0)
        e["count"] += 1
    rows = roofline_table(dev, phases=phases)
    if not rows:
        return
    out.append("")
    out.append("== kernel roofline " + "=" * 45)
    w = max(len(n) for n in rows)
    for st, r in sorted(rows.items()):
        ai = r.get("arithmetic_intensity")
        if r.get("dispatches"):
            line = (f"  {st:<{w}}  x{r['dispatches']:<6} "
                    f"exec {_fmt_sec(r['execute_sec_total']):>8}  "
                    f"achieved "
                    f"{_fmt_flops(r.get('achieved_flops_per_sec')):>10}")
            if ai is not None:
                line += f"  AI {ai:.1f} F/B"
            if r.get("pct_of_ask") is not None:
                line += f"  {r['pct_of_ask'] * 100:.0f}% of ask"
        else:
            line = f"  {st:<{w}}  static cost captured"
            if ai is not None:
                line += f"  AI {ai:.1f} F/B"
            line += "  (no execute spans yet)"
        out.append(line)


def render_trend(records, width=24):
    """The bench trajectory store as per-key sparkline history.

    ``records`` is the oldest-first output of
    :func:`hyperopt_tpu.obs.trajectory.load`.  Every key any run ever
    reported gets a row — gated keys (obs/trajectory.py
    ``KEY_DIRECTIONS``) first, with their regression direction named, so
    the reader knows which way "up" is before trusting a slope; keys the
    gate doesn't know render too (marked ungated).  Runs missing a key
    are skipped in that key's sparkline (the run count says how many
    contributed).  Mixed-backend histories segment per backend (one
    ``key [backend]`` row each): a tpu→cpu switch is a hardware change,
    not a 1000x regression — the same reason the windowed gate
    backend-matches its history."""
    from .trajectory import KEY_DIRECTIONS

    out = []
    out.append("== bench trajectory " + "=" * 44)
    if not records:
        out.append("  (store is empty — run bench.py or "
                   "`python -m hyperopt_tpu.obs.trajectory backfill`)")
        return "\n".join(out) + "\n"
    for r in records:
        rd = r.get("round")
        out.append(
            f"  {('r%s' % rd) if rd is not None else 'live':<5} "
            f"{r.get('source', '?'):<18} "
            f"rev {r.get('git_rev') or '-':<9} "
            f"backend {r.get('backend') or '?'}")
    out.append("")
    keys = []
    for r in records:
        for k in (r.get("keys") or {}):
            if k not in keys:
                keys.append(k)
    ordered = ([k for k in KEY_DIRECTIONS if k in keys]
               + [k for k in keys if k not in KEY_DIRECTIONS])
    if not ordered:
        out.append("  (no numeric keys recorded yet)")
        return "\n".join(out) + "\n"
    backends = []
    for r in records:
        b = r.get("backend") or "?"
        if b not in backends:
            backends.append(b)
    multi = len(backends) > 1
    w = max(len(k) for k in ordered)
    if multi:
        w += 3 + max(len(b) for b in backends)
    for k in ordered:
        meta = KEY_DIRECTIONS.get(k)
        direction = {"higher": "higher=better",
                     "lower": "lower=better"}.get(
            (meta or {}).get("direction"), "ungated")
        for b in backends:
            recs = [r for r in records
                    if (r.get("backend") or "?") == b] if multi else records
            series = [(r.get("keys") or {}).get(k) for r in recs]
            vals = [v for v in series if isinstance(v, (int, float))]
            if not vals:
                continue
            label = f"{k} [{b}]" if multi else k
            runs = f"{len(vals)}/{len(recs)} {b} runs" if multi else \
                f"{len(vals)}/{len(recs)} runs"
            out.append(
                f"  {label:<{w}}  {_spark(series, width=width):<{width}}  "
                f"{vals[0]:.6g} -> {vals[-1]:.6g}  ({direction}, {runs})")
            if not multi:
                break
    return "\n".join(out) + "\n"


def render_fleet_load(store_root, width=24):
    """The fleet-wide heat view (ISSUE 17) from every replica's durable
    heat ledger under ``<store_root>/fleet/heat/``: one row per shard —
    cumulative heat (the MAX across all replicas' cumulative snapshots,
    so restarts and ownership moves never reset it), the latest owner,
    and a sparkline of the shard's heat history — plus the per-replica
    busy fractions and a SKEW banner when max/mean shard heat exceeds
    the default imbalance bound.  Corrupt ledger lines are counted, not
    fatal (the census read discipline)."""
    from .load import _iter_heat_records, read_heat
    from .slo import LOAD_TARGETS

    merged = read_heat(store_root)
    out = []
    out.append("== fleet load " + "=" * 50)
    out.append(f"  store {store_root}   ledger files {merged['files']}"
               + (f"   CORRUPT {merged['corrupt']}"
                  if merged["corrupt"] else "")
               + (f"   torn {merged['torn']}" if merged["torn"] else ""))
    shards = merged["shards"]
    if not shards:
        out.append("  (no heat records yet — is the fleet serving with "
                   "HYPEROPT_TPU_LOAD armed?)")
        return "\n".join(out) + "\n"
    # per-shard heat history for the sparklines: every record, oldest
    # first (the ledger is append-only per replica; cross-replica order
    # by ts is close enough for a trend line)
    series = {}
    for _fname, rec, _status in _iter_heat_records(store_root):
        if rec is None or rec.get("kind") != "heat":
            continue
        if rec.get("shard") is None:
            continue
        series.setdefault(str(int(rec["shard"])), []).append(
            (float(rec.get("ts") or 0.0), float(rec.get("heat_ms") or 0)))
    heats = {k: v["heat_ms"] for k, v in shards.items()}
    hot = max(heats.values()) or 1.0
    w = max(len(k) for k in shards) + 5
    out.append(f"  {'shard':<{w}} {'heat':>8}  {'share':<12}  "
               f"{'owner':<20}  trend")
    for k in sorted(shards, key=lambda s: -heats[s]):
        s = shards[k]
        hist = [h for _, h in sorted(series.get(k, []))]
        out.append(
            f"  shard{k:<{w - 5}} {heats[k] / 1e3:>7.1f}s  "
            f"[{_bar(heats[k] / hot, 10)}]  "
            f"{str(s.get('replica') or '?')[:20]:<20}  "
            f"{_spark(hist, width=width)}")
    skew = merged["heat_skew"]
    bound = LOAD_TARGETS["imbalance"]["skew_max"]
    line = f"  heat skew {skew:.2f}x (max/mean over {len(shards)} shards)"
    if skew > bound:
        line += f"  SKEW (over the {bound:.1f}x imbalance bound)"
    out.append(line)
    if merged["replicas"]:
        out.append("")
        out.append("  replica busy fractions (latest snapshot each):")
        for rid in sorted(merged["replicas"]):
            r = merged["replicas"][rid]
            busy = float(r.get("busy_frac") or 0.0)
            out.append(f"    {rid[:28]:<28} [{_bar(min(1.0, busy), 12)}] "
                       f"{busy:.0%}")
    return "\n".join(out) + "\n"


def render_tenants(source, width=24):
    """The per-tenant attribution view (ISSUE 20).  ``source`` is
    either a merged tenant STATUS dict (``GET /tenants`` /
    ``/snapshot``'s ``tenants`` section — full columns) or a store root
    (str — durable fleet-merged tenant heat from the heat ledgers,
    device-time only).  One row per tenant: a budget bar of its share
    of attributed device time, plus asks/tells/sheds and the ask-p99
    column when known; a NOISY-TENANT banner flags a tenant holding
    over half the fleet's attributed time while others wait."""
    out = ["== tenants " + "=" * 53]
    if isinstance(source, str):
        from .tenant import read_tenant_heat

        heat = read_tenant_heat(source)["tenants"]
        table = {t: {"device_ms": ms} for t, ms in heat.items()}
        out.append(f"  store {source}   (durable tenant heat; arm "
                   "HYPEROPT_TPU_TENANT + _LOAD for live columns)")
    else:
        status = source or {}
        table = dict(status.get("table") or {})
        out.append(f"  tracked {status.get('tenants', len(table))}"
                   f"   top-K {status.get('top_k', '?')}"
                   f"   evictions {status.get('evictions', 0)}"
                   f"   sheds {status.get('sheds', 0)}")
    if not table:
        out.append("  (no tenant attribution yet — is the service "
                   "serving with HYPEROPT_TPU_TENANT armed?)")
        return "\n".join(out) + "\n"
    total = sum(float(r.get("device_ms") or 0.0)
                for r in table.values()) or 1.0
    w = min(24, max(len(t) for t in table) + 2)
    out.append(f"  {'tenant':<{w}} {'device':>8}  {'share':<14}  "
               f"{'asks':>6} {'tells':>6} {'sheds':>6}  ask_p99")
    noisy = None
    for t in sorted(table,
                    key=lambda k: -float(table[k].get("device_ms") or 0)):
        r = table[t]
        ms = float(r.get("device_ms") or 0.0)
        share = ms / total
        if noisy is None and share > 0.5 and len(table) > 1:
            noisy = (t, share)
        p99 = r.get("ask_p99_ms")
        out.append(
            f"  {t[:w]:<{w}} {ms / 1e3:>7.1f}s  [{_bar(share, 10)}]  "
            f"{r.get('asks', '-'):>6} {r.get('tells', '-'):>6} "
            f"{r.get('sheds', '-'):>6}  "
            + (f"{p99:.0f}ms" if p99 is not None else "-"))
    if noisy is not None:
        out.append(f"  NOISY-TENANT {noisy[0]!r} holds {noisy[1]:.0%} of "
                   f"attributed device time (fair-share packing + "
                   f"HYPEROPT_TPU_TENANT_QUOTA bound it)")
    return "\n".join(out) + "\n"


def _profile_section(profile_recs, out):
    """On-demand / stall device captures recorded by obs/profiler.py: the
    pointers from this stream to its device-timeline artifacts."""
    if not profile_recs:
        return
    out.append("")
    out.append("== device captures " + "=" * 45)
    for r in profile_recs:
        if r.get("ok"):
            out.append(f"  {r.get('reason', '?'):<10} "
                       f"{_fmt_sec(r.get('wall_sec')):>8}  "
                       f"{r.get('trace_json') or r.get('dir', '?')}")
        else:
            out.append(f"  {r.get('reason', '?'):<10} FAILED  "
                       f"{r.get('error', '?')}")


# ---------------------------------------------------------------------------
# the shared headline serializer (``--format json`` == ``/snapshot``)
# ---------------------------------------------------------------------------


def headline_sections(phases, metrics, device_metrics, wall_sec=None):
    """The four headline report sections as pure data — report (phase
    breakdown), health, utilization, ask_pipeline.

    ONE serializer for both consumers: the live ``/snapshot`` endpoint
    (obs/serve.py) feeds it the tracer's phase totals + live registry
    snapshots, ``obs.report --format json`` feeds it the same shapes
    recovered from a recorded stream — so the two outputs can never drift
    (tests/test_serve.py golden-pins the structure).

    ``phases``: ``{name: {"sec", "count"}}``; ``metrics`` /
    ``device_metrics``: snapshotted metric dicts (the ``"metrics"`` value
    of ``MetricsRegistry.snapshot()``).
    """
    from .health import roofline_table, utilization_from_metrics

    total = sum(e.get("sec", 0.0) for e in phases.values()) or 1.0
    report = {
        name: {"sec": e.get("sec", 0.0), "count": e.get("count", 0),
               "frac": e.get("sec", 0.0) / total}
        for name, e in sorted(phases.items())
    }

    health = {"asks": metrics.get("health.asks", 0)}
    if health["asks"]:
        health.update(
            proposals=metrics.get("health.proposals", 0),
            prior_fallbacks=metrics.get("health.prior_fallbacks", 0),
            last_ei_p50=metrics.get("health.last_ei_p50"),
            last_dup_rate=metrics.get("health.last_dup_rate"),
            n_below=metrics.get("health.n_below"),
            n_above=metrics.get("health.n_above"),
            ei_p50=metrics.get("health.ei_p50"),
            dup_rate=metrics.get("health.dup_rate"),
        )

    blocked = metrics.get("ask.blocked_sec")
    ask_pipeline = {
        "calls": metrics.get("suggest.calls", 0),
        "speculative": metrics.get("suggest.speculative", 0),
        "inflight": metrics.get("suggest.inflight", 0),
        "queue_depth": metrics.get("queue_depth", 0),
        "blocked_sec": blocked if isinstance(blocked, dict) else None,
    }

    return {
        "report": report,
        "health": health,
        "utilization": utilization_from_metrics(device_metrics,
                                                wall_sec=wall_sec),
        # per-program roofline: static cost × measured execute spans, with
        # each program's share of the suggest phase wall clock — the
        # kernel-attribution view, live on /snapshot and offline here
        "roofline": roofline_table(device_metrics, phases=phases),
        "ask_pipeline": ask_pipeline,
    }


def _stream_sections(records):
    """Recover :func:`headline_sections` inputs from a recorded stream:
    phase totals re-aggregated from spans (same wall-clock-by-name sum the
    live ``PhaseTimings`` accumulates), metric dicts from the final
    embedded snapshot."""
    phases = {}
    for s in records:
        if s.get("kind") != "span" or s.get("aggregate") is False:
            # aggregate=False umbrella spans are excluded from the live
            # totals too — offline and live rebuild the SAME dict
            continue
        e = phases.setdefault(s["name"], {"sec": 0.0, "count": 0})
        e["sec"] += s.get("wall_sec", 0.0)
        e["count"] += 1
    metric_recs = [r for r in records if r.get("kind") == "metrics"]
    snap = metric_recs[-1].get("snapshot", {}) if metric_recs else {}
    metrics = snap.get("metrics", {})
    device = ((snap.get("shared") or {}).get("device") or {}).get(
        "metrics", {})
    run_ids = sorted({r["run_id"] for r in records if r.get("run_id")})
    return {"run_id": ",".join(run_ids) or None,
            "sections": headline_sections(phases, metrics, device)}


def json_report(streams, merge=False):
    """``--format json``: the machine-readable headline sections for one
    stream (or per controller with ``--merge``), via the SAME serializer
    the live ``/snapshot`` endpoint uses."""
    if not merge:
        return _stream_sections(streams[0][1])
    return {"merged": True,
            "controllers": {name: _stream_sections(recs)
                            for name, recs in streams}}


def render(records, top=5):
    """Build the report text from parsed JSONL records."""
    spans = [r for r in records if r.get("kind") == "span"]
    trial_events = [r for r in records if r.get("kind") == "trial_event"]
    metric_recs = [r for r in records if r.get("kind") == "metrics"]
    health_recs = [r for r in records if r.get("kind") == "health"]
    devmem_recs = [r for r in records if r.get("kind") == "devmem"]
    profile_recs = [r for r in records if r.get("kind") == "profile"]
    events = [r for r in records if r.get("kind") == "event"]

    out = []
    out.append("== phase-time breakdown " + "=" * 40)
    _phase_section(spans, out)
    _pipeline_section(spans, _last_snapshot_metrics(records), out)
    _resilience_section(_last_snapshot_metrics(records), out)
    _service_section(_last_snapshot_metrics(records), out)
    _quality_section(_last_snapshot_metrics(records), events, out)
    _storage_section(_last_snapshot_metrics(records), out)
    _probe_section(_last_snapshot_metrics(records), out)
    _megakernel_section(_last_snapshot_metrics(records), spans, out)
    _roofline_section(records, spans, out)
    _profile_section(profile_recs, out)
    out.append("")
    out.append("== search health " + "=" * 47)
    _health_section(health_recs, out)
    _devmem_section(devmem_recs, out)
    out.append("")
    out.append("== trial-state waterfall " + "=" * 39)
    _waterfall_section(trial_events, out)
    out.append("")
    out.append(f"== top-{top} slowest trials " + "=" * 38)
    _slowest_section(trial_events, out, top=top)
    out.append("")
    out.append("== metrics snapshot " + "=" * 44)
    _metrics_section(metric_recs, out)
    if events:
        out.append("")
        out.append("== events " + "=" * 54)
        for r in events:
            attrs = r.get("attrs", {})
            out.append(f"  {r['name']}  " + json.dumps(attrs, default=str))
    return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# cross-controller merge view (fmin_multihost per-process streams)
# ---------------------------------------------------------------------------

# the driver's allgather latency histograms, in schedule order — the merge
# view's skew table compares their per-controller means
_ALLGATHER_METRICS = (
    "allgather.resume_sec",
    "allgather.proposals_sec",
    "allgather.results_sec",
    "allgather.losses_sec",  # pre-payload streams (renamed to results)
    "allgather.checksum_sec",
)

_DIVERGENCE_EVENTS = ("controller_divergence", "resume_disagreement")


def _last_snapshot_metrics(records):
    metric_recs = [r for r in records if r.get("kind") == "metrics"]
    if not metric_recs:
        return {}
    return (metric_recs[-1].get("snapshot") or {}).get("metrics", {})


def _controller_summary(name, records):
    spans = [r for r in records if r.get("kind") == "span"]
    metrics = _last_snapshot_metrics(records)
    run_ids = sorted({r["run_id"] for r in records if r.get("run_id")})
    ts = [r["ts"] for r in records if "ts" in r]
    return {
        "name": name,
        "run_ids": run_ids,
        "spans": spans,
        "metrics": metrics,
        "generations": metrics.get("generations"),
        "t0": min(ts) if ts else None,
        "t1": max(ts) if ts else None,
        "events": [r for r in records if r.get("kind") == "event"
                   and r.get("name") in _DIVERGENCE_EVENTS],
    }


def render_merged(streams):
    """Cross-controller view over per-controller JSONL streams from one
    ``fmin_multihost`` run: summary + allgather skew + per-controller
    phase breakdown + correlated divergence context.  ``streams`` is a
    list of ``(name, records)``."""
    ctrls = [_controller_summary(name, recs) for name, recs in streams]
    out = []

    out.append("== controllers " + "=" * 49)
    w = max(len(c["name"]) for c in ctrls)
    for c in ctrls:
        gens = c["generations"]
        wall = (c["t1"] - c["t0"]) if c["t0"] is not None else None
        out.append(
            f"  {c['name']:<{w}}  run_id={','.join(c['run_ids']) or '?'}"
            f"  gens={gens if gens is not None else '?'}"
            f"  spans={len(c['spans'])}  wall={_fmt_sec(wall)}")

    out.append("")
    out.append("== allgather skew " + "=" * 46)
    any_row = False
    for metric in _ALLGATHER_METRICS:
        means = {}
        for c in ctrls:
            h = c["metrics"].get(metric)
            if isinstance(h, dict) and h.get("count"):
                means[c["name"]] = h["mean"]
        if not means:
            continue
        any_row = True
        vals = list(means.values())
        skew = max(vals) - min(vals)
        ratio = (max(vals) / min(vals)) if min(vals) > 0 else float("inf")
        per = "  ".join(f"{n} {_fmt_sec(m)}" for n, m in sorted(means.items()))
        out.append(f"  {metric:<26} {per}  skew {_fmt_sec(skew)}"
                   f" ({ratio:.1f}x)")
    if not any_row:
        out.append("  (no allgather metrics in the streams — single-process"
                   " run, or metrics snapshots missing)")

    # per-controller device memory: each controller samples its OWN devices
    # (obs/devmem.py), so the merged view is the cluster's HBM picture
    from .devmem import roll_up

    dm_rows = []
    for name, recs in streams:
        dms = [r for r in recs if r.get("kind") == "devmem"]
        if not dms:
            continue
        rolls = [roll_up(r.get("devices", [])) for r in dms]
        peaks = [r[1] for r in rolls if r[1] is not None]
        limits = [r[2] for r in rolls if r[2] is not None]
        hist = (dms[-1].get("census") or {}).get("history", {})
        dm_rows.append((name, len(dms),
                        max(peaks) if peaks else None,
                        max(limits) if limits else None,
                        hist.get("bytes")))
    if dm_rows:
        out.append("")
        out.append("== device memory per controller " + "=" * 32)
        w = max(len(n) for n, *_ in dm_rows)
        for name, n, peak, limit, hist_b in dm_rows:
            line = (f"  {name:<{w}}  samples {n}"
                    f"  peak {_fmt_bytes(peak):>10}")
            if limit:
                line += f"  limit {_fmt_bytes(limit):>10}"
                if peak is not None:
                    line += f"  peak watermark {peak / limit:.0%}"
            if hist_b is not None:
                line += f"  history {_fmt_bytes(hist_b)}"
            out.append(line)

    out.append("")
    out.append("== per-controller phase breakdown " + "=" * 30)
    for c in ctrls:
        out.append(f"  -- {c['name']}")
        _phase_section(c["spans"], out)

    out.append("")
    out.append("== divergence context " + "=" * 42)
    dumps = [(c["name"], e) for c in ctrls for e in c["events"]]
    if not dumps:
        out.append("  (no divergence events — every generation's fold"
                   " checksummed identically)")
    else:
        for name, e in sorted(dumps, key=lambda ne: ne[1].get("ts", 0)):
            attrs = e.get("attrs", {})
            out.append(f"  {name}: {e['name']}  "
                       + json.dumps(attrs, sort_keys=True, default=str))
        # correlate: which (gen, n_done) points diverged, seen by whom
        keyed = {}
        for name, e in dumps:
            a = e.get("attrs", {})
            keyed.setdefault((a.get("gen"), a.get("n_done")),
                             []).append(name)
        for (gen, n_done), names in sorted(keyed.items(),
                                           key=lambda kv: str(kv[0])):
            out.append(f"  gen={gen} n_done={n_done}: reported by "
                       + ", ".join(sorted(names)))
    return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# post-mortem view (flight-recorder dumps — obs/flight.py)
# ---------------------------------------------------------------------------


def _last_moments(records, death_ts, out, tail=12):
    """The ring's final records, as a T-minus timeline."""
    shown = [r for r in records
             if r.get("kind") in ("span", "event", "trial_event", "stall",
                                  "health", "devmem") and "ts" in r][-tail:]
    if not shown:
        out.append("  (empty ring)")
        return
    for r in shown:
        dt = death_ts - r["ts"]
        kind = r.get("kind")
        if kind == "span":
            # a span's ts is its START; the ring appended it at its END —
            # show when it finished so the timeline reads in ring order
            dt = death_ts - (r["ts"] + (r.get("wall_sec") or 0.0))
            what = (f"span {r.get('name', '?')} "
                    f"({_fmt_sec(r.get('wall_sec'))})")
            if r.get("error"):
                what += f"  error={r['error']}"
        elif kind == "trial_event":
            what = f"{r.get('event', '?')} tid={r.get('tid')}"
        elif kind == "stall":
            what = (f"STALL  quiet {_fmt_sec(r.get('quiet_for_sec'))}  "
                    f"(#{r.get('stall_count', '?')})")
        elif kind == "health":
            what = f"health ask ({r.get('algo', '?')})"
        elif kind == "devmem":
            census = r.get("census") or {}
            tot = census.get("total", {})
            devs = [d.get("bytes_in_use") for d in r.get("devices", [])
                    if d.get("bytes_in_use") is not None]
            what = (f"devmem  in-use {_fmt_bytes(max(devs) if devs else None)}"
                    f"  live {_fmt_bytes(tot.get('bytes'))}"
                    f" (x{tot.get('count', '?')})")
        else:
            what = f"event {r.get('name', '?')}"
        out.append(f"  T-{dt:8.2f}s  {what}")


def render_postmortem(records, name=None):
    """A flight dump (or any obs stream) as a last-moments narrative:
    death reason, open spans at death, last heartbeat per component,
    stall reports, in-flight trials, tail of the ring."""
    recs = list(records)
    dumps = [r for r in recs if r.get("kind") == "flight_dump"]
    open_spans = [r for r in recs if r.get("kind") == "open_span"]
    beat_recs = [r for r in recs if r.get("kind") == "last_heartbeats"]
    stalls = [r for r in recs if r.get("kind") == "stall"]
    trial_events = [r for r in recs if r.get("kind") == "trial_event"]
    ts_all = [r["ts"] for r in recs if "ts" in r]
    death_ts = dumps[-1]["ts"] if dumps else (max(ts_all) if ts_all else 0.0)

    out = []
    out.append("== flight dump " + "=" * 49)
    if dumps:
        d = dumps[-1]
        out.append(f"  reason={d.get('reason', '?')}  pid={d.get('pid', '?')}"
                   f"  records={d.get('n_records', '?')}"
                   + (f"  stream={name}" if name else ""))
    else:
        out.append("  (no flight_dump header — rendering a live stream as a "
                   "post-mortem)")

    out.append("")
    out.append("== open spans at death " + "=" * 41)
    if open_spans:
        w = max(len(r.get("name", "?")) for r in open_spans)
        for r in sorted(open_spans, key=lambda r: -r.get("age_sec", 0.0)):
            out.append(f"  {r.get('name', '?'):<{w}}  open for "
                       f"{_fmt_sec(r.get('age_sec')):>9}  "
                       f"thread {r.get('thread', '?')}")
    else:
        out.append("  (none — the process died between spans)")

    out.append("")
    out.append("== last heartbeats " + "=" * 45)
    beats = (beat_recs[-1].get("beats") or {}) if beat_recs else {}
    if beats:
        w = max(len(c) for c in beats)
        for comp, b in sorted(beats.items(),
                              key=lambda kv: kv[1].get("age_sec", 0.0)):
            line = (f"  {comp:<{w}}  {_fmt_sec(b.get('age_sec')):>9} before "
                    f"death")
            detail = b.get("detail")
            if detail:
                line += "  " + json.dumps(detail, sort_keys=True, default=str)
            out.append(line)
    else:
        out.append("  (no heartbeat record — watchdog disabled or never fed)")

    out.append("")
    out.append("== stalls " + "=" * 54)
    if stalls:
        s = stalls[-1]
        out.append(f"  {len(stalls)} stall record(s); last: quiet for "
                   f"{_fmt_sec(s.get('quiet_for_sec'))} "
                   f"(threshold {_fmt_sec(s.get('quiet_sec'))})")
        for tname, frames in sorted((s.get("stacks") or {}).items()):
            out.append(f"  thread {tname}:")
            for fr in frames[-4:]:
                out.append(f"    {fr}")
    else:
        out.append("  (no stall records — the run was heartbeating until "
                   "death)")

    out.append("")
    out.append("== in-flight trials " + "=" * 44)
    timelines = _trial_timelines(trial_events)
    inflight = []
    for tid, t in sorted(timelines.items()):
        if TRIAL_FINISHED in t or TRIAL_CANCELLED in t:
            continue
        start = t.get(TRIAL_CLAIMED, t.get(TRIAL_NEW))
        state = "claimed" if TRIAL_CLAIMED in t else "queued"
        age = (death_ts - start) if start is not None else None
        inflight.append(f"  tid {tid:>6}  {state} "
                        f"{_fmt_sec(age):>9} before death")
    out.extend(inflight if inflight
               else ["  (none — no trial was mid-evaluation)"])

    # device captures pinned in the flight ring (obs/profiler.py): the
    # stall escalation's bounded trace — a hang's postmortem points at
    # the device timeline artifact, not just host stacks
    _profile_section([r for r in recs if r.get("kind") == "profile"], out)

    # the memory narrative (devmem tail + at-death census attached by the
    # flight recorder when the sampler was armed — OOMs die explained)
    devmem_recs = [r for r in recs if r.get("kind") == "devmem"]
    census_recs = [r for r in recs if r.get("kind") == "devmem_census"]
    if devmem_recs or census_recs:
        _devmem_section(devmem_recs, out)
        if census_recs:
            census = census_recs[-1].get("census") or {}
            parts = [f"{o} {_fmt_bytes(b['bytes'])} (x{b['count']})"
                     for o, b in sorted(census.items()) if o != "total"]
            out.append("  at-death census: " + ("  ".join(parts) or "(empty)"))

    out.append("")
    out.append("== last records " + "=" * 48)
    _last_moments(recs, death_ts, out)
    return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# per-study audit timeline (ISSUE 11: obs.report --study <id>)
# ---------------------------------------------------------------------------

#: the WAL record kinds that belong to a study's durable timeline
_WAL_KINDS = ("admit", "snapshot", "ask", "tell", "close")


def study_timeline_events(study_id, streams):
    """Join one study's lifecycle out of mixed JSONL streams.

    ``streams`` is ``[(name, records)]`` — typically the service WAL
    (``service.wal.jsonl``) plus any obs/flight/access streams the
    caller has.  Returns ``(events, trace_hops)``:

    * ``events`` — the study's WAL records (admit/ask/tell/void/close/
      snapshot), each tagged with its source stream, sorted by ``ts``
      (records without one — pre-ISSUE-11 journals — keep file order at
      the front);
    * ``trace_hops`` — ``{trace_id: [span/access records]}`` for every
      trace id the study's records name, joined across ALL streams (the
      client→handler→wave→device correlation arc).
    """
    # materialize up front: the streams are walked TWICE (events, then
    # trace joins), and a caller handing iter_jsonl generators would
    # otherwise silently lose the whole correlation view on pass 2
    streams = [(name, list(records)) for name, records in streams]
    events = []
    traces = set()
    for name, records in streams:
        for r in records:
            if not isinstance(r, dict):
                continue
            kind = r.get("kind")
            if kind in _WAL_KINDS and r.get("sid") == study_id:
                events.append({**r, "_src": name})
                if r.get("trace"):
                    traces.add(r["trace"])
            elif kind == "access" and r.get("study_id") == study_id \
                    and r.get("trace"):
                traces.add(r["trace"])
    order = {id(e): i for i, e in enumerate(events)}
    events.sort(key=lambda e: (e.get("ts") is not None, e.get("ts") or 0.0,
                               order[id(e)]))
    trace_hops = {t: [] for t in traces}
    if traces:
        for name, records in streams:
            for r in records:
                if not isinstance(r, dict):
                    continue
                attrs = r.get("attrs") or {}
                hits = set()
                t = r.get("trace") or attrs.get("trace")
                if t in trace_hops:
                    hits.add(t)
                for t in attrs.get("links") or []:
                    if t in trace_hops:
                        hits.add(t)
                for t in hits:
                    trace_hops[t].append({**r, "_src": name})
        for hops in trace_hops.values():
            hops.sort(key=lambda r: r.get("ts") or 0.0)
    return events, trace_hops


def render_study_timeline(study_id, streams):
    """``--study``: one study's full lifecycle as a T+ timeline — every
    admit/ask/tell/void/evict/close/resume boundary from the WAL, each
    ask's wave/algo/degrade flags and trace id, plus the cross-stream
    correlation arc for every trace the study's records name."""
    events, trace_hops = study_timeline_events(study_id, streams)
    out = []
    out.append(f"== study timeline: {study_id} " + "=" * max(
        1, 46 - len(study_id)))
    if not events:
        out.append("  (no WAL records for this study in "
                   + ", ".join(n for n, _ in streams) + ")")
        return "\n".join(out) + "\n"
    t0 = next((e["ts"] for e in events if e.get("ts") is not None), 0.0)
    asks = tells = voids = degraded = 0
    for e in events:
        ts = e.get("ts")
        stamp = f"T+{ts - t0:9.3f}s" if ts is not None else "T+    ?    "
        kind = e["kind"]
        if kind == "admit":
            what = (f"admit     seed={e.get('seed')}"
                    + (f"  kwargs={e.get('kwargs')}" if e.get("kwargs")
                       else ""))
        elif kind == "snapshot":
            # a snapshot record is a compaction boundary: everything
            # before it was folded into this one registry entry —
            # after a crash-resume this is where replay picked up
            what = (f"snapshot  (compaction/resume boundary)  "
                    f"state={e.get('state')}  n_asked={e.get('n_asked')}"
                    f"  n_told={e.get('n_told')}")
        elif kind == "ask":
            algo = e.get("algo")
            if algo == "void":
                voids += 1
                what = f"void      tids={e.get('tids')}  (failed/shed ask)"
            else:
                asks += 1
                what = f"ask       tids={e.get('tids')}  algo={algo}"
                if algo == "rand":
                    degraded += 1
                    what += "  [startup or DEGRADED]"
        elif kind == "tell":
            tells += 1
            what = (f"tell      tid={e.get('tid')}  loss={e.get('loss')}"
                    + (f"  status={e['status']}" if e.get("status")
                       else ""))
        elif kind == "close":
            what = "close"
        else:  # pragma: no cover - _WAL_KINDS is closed
            what = kind
        if e.get("trace"):
            what += f"  trace={e['trace'][:16]}.."
        out.append(f"  {stamp}  {what}")
    out.append(f"  summary: {asks} asks ({degraded} rand-served, "
               f"{voids} void), {tells} tells")
    shown = {t: hops for t, hops in trace_hops.items() if hops}
    if shown:
        out.append("")
        out.append("== request correlation " + "=" * 41)
        for t in sorted(shown):
            hops = shown[t]
            arc = " -> ".join(
                f"{h.get('name') or h.get('kind')}"
                + (f"[{h['attrs']['wave']}]"
                   if (h.get("attrs") or {}).get("wave") is not None
                   else "")
                for h in hops[:8])
            out.append(f"  {t[:16]}..  {arc}"
                       + ("  (+%d more)" % (len(hops) - 8)
                          if len(hops) > 8 else ""))
    return "\n".join(out) + "\n"


def _study_streams(paths):
    """Resolve ``--study`` inputs: a directory means a store root (its
    ``service.wal.jsonl`` is the stream); files are read as JSONL."""
    from ..service.journal import wal_path_for

    streams = []
    for path in paths:
        p = wal_path_for(path) if os.path.isdir(path) else path
        if not os.path.exists(p):
            raise OSError(f"no such stream: {p}")
        streams.append((os.path.basename(p), read_jsonl(p)))
    return streams


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m hyperopt_tpu.obs.report",
        description="Render a hyperopt_tpu obs JSONL stream.")
    p.add_argument("jsonl", nargs="*",
                   help="telemetry stream(s) written by an armed run, or "
                        "flight dump(s) with --postmortem, or the "
                        "trajectory store with --trend (default: the "
                        "repo's .obs/trajectory.jsonl)")
    p.add_argument("--top", type=int, default=5,
                   help="how many slowest trials to list (single-stream "
                        "report only)")
    p.add_argument("--merge", action="store_true",
                   help="treat the inputs as per-controller streams from "
                        "one fmin_multihost run and render the "
                        "cross-controller view")
    p.add_argument("--postmortem", action="store_true",
                   help="render flight-recorder dump(s) as a last-moments "
                        "narrative")
    p.add_argument("--export-trace", metavar="OUT",
                   help="write Chrome/Perfetto trace-event JSON to OUT "
                        "instead of rendering (each input stream becomes "
                        "its own process track group)")
    p.add_argument("--format", choices=("text", "json"), default="text",
                   help="json: machine-readable headline sections "
                        "(report/health/utilization/ask-pipeline) via the "
                        "same serializer the live /snapshot endpoint uses")
    p.add_argument("--trend", action="store_true",
                   help="render the bench trajectory store "
                        "(.obs/trajectory.jsonl) as per-key sparkline "
                        "history instead of a run report")
    p.add_argument("--fleet", metavar="STORE_ROOT", default=None,
                   help="render the fleet-wide load view from the durable "
                        "heat ledgers under STORE_ROOT/fleet/heat/: merged "
                        "per-shard heat with sparklines, replica busy "
                        "fractions, and a SKEW banner on imbalance")
    p.add_argument("--tenants", metavar="SRC", default=None,
                   help="render the per-tenant attribution view: SRC is "
                        "a store root (durable fleet-merged tenant heat "
                        "from the heat ledgers) or a JSON file holding a "
                        "GET /tenants (or /snapshot) payload — budget "
                        "bars per tenant + a NOISY-TENANT banner")
    p.add_argument("--probes", metavar="PATH", default=None,
                   help="render the blackbox-probe verdict view from the "
                        "durable probe ledger(s): a <replica>.jsonl "
                        "ledger file, a fleet/probes dir, or the store "
                        "root — verdict census, golden provenance and "
                        "detection-latency stats per replica")
    p.add_argument("--study", metavar="ID", default=None,
                   help="render one study's audit timeline from the "
                        "service WAL (give the WAL file or the --store "
                        "root; extra obs/flight/access streams join the "
                        "request-correlation view)")
    args = p.parse_args(argv)
    if args.probes is not None:
        if (args.merge or args.postmortem or args.export_trace
                or args.trend or args.study or args.fleet):
            print("error: --probes is its own view; it does not combine "
                  "with --merge/--postmortem/--export-trace/--trend/"
                  "--study/--fleet", file=sys.stderr)
            return 2
        if args.format == "json":
            # erroring beats a scripted consumer silently getting text:
            # the ledgers are already machine-readable sealed JSONL and
            # the live view is served as JSON by GET /probes
            print("error: --probes renders text only; for machine-"
                  "readable verdicts GET /probes or read the ledgers "
                  "under fleet/probes/", file=sys.stderr)
            return 2
        if not os.path.exists(args.probes):
            print(f"error: no probe ledger or store at {args.probes}",
                  file=sys.stderr)
            return 2
        sys.stdout.write(render_probes(args.probes))
        return 0
    if args.tenants is not None:
        if (args.merge or args.postmortem or args.export_trace
                or args.trend or args.study or args.fleet):
            print("error: --tenants is its own view; it does not combine "
                  "with --merge/--postmortem/--export-trace/--trend/"
                  "--study/--fleet", file=sys.stderr)
            return 2
        if args.format == "json":
            # erroring beats a scripted consumer silently getting text:
            # the live view is already served as JSON by GET /tenants
            print("error: --tenants renders text only; for machine-"
                  "readable tables GET /tenants or read the heat "
                  "ledgers under fleet/heat/", file=sys.stderr)
            return 2
        if os.path.isdir(args.tenants):
            sys.stdout.write(render_tenants(args.tenants))
            return 0
        if not os.path.exists(args.tenants):
            print(f"error: no store root or payload file at "
                  f"{args.tenants}", file=sys.stderr)
            return 2
        try:
            with open(args.tenants, encoding="utf-8") as f:
                payload = json.load(f)
        except (OSError, ValueError) as e:
            print(f"error: cannot read {args.tenants}: {e}",
                  file=sys.stderr)
            return 2
        if isinstance(payload, dict) and "tenants" in payload \
                and isinstance(payload["tenants"], dict):
            # a /snapshot (or /fleet/load) payload: unwrap its section
            payload = payload["tenants"]
        sys.stdout.write(render_tenants(payload))
        return 0
    if args.fleet is not None:
        if (args.merge or args.postmortem or args.export_trace
                or args.trend or args.study):
            print("error: --fleet is its own view; it does not combine "
                  "with --merge/--postmortem/--export-trace/--trend/"
                  "--study", file=sys.stderr)
            return 2
        if args.format == "json":
            # erroring beats a scripted consumer silently getting text:
            # the merged view is already served as JSON by /fleet/load
            print("error: --fleet renders text only; for machine-"
                  "readable heat GET /fleet/load or read the ledgers "
                  "under fleet/heat/", file=sys.stderr)
            return 2
        if not os.path.isdir(args.fleet):
            print(f"error: no store root at {args.fleet}",
                  file=sys.stderr)
            return 2
        sys.stdout.write(render_fleet_load(args.fleet))
        return 0
    if args.study is not None:
        if args.merge or args.postmortem or args.export_trace or args.trend:
            print("error: --study is its own view; it does not combine "
                  "with --merge/--postmortem/--export-trace/--trend",
                  file=sys.stderr)
            return 2
        if args.format == "json":
            # erroring beats a scripted consumer silently getting text:
            # the WAL records behind the view are already JSONL
            print("error: --study renders text only; for machine-"
                  "readable records read the WAL (service.wal.jsonl) "
                  "or GET /study/<id>/timeline", file=sys.stderr)
            return 2
        if not args.jsonl:
            p.error("--study needs the service WAL (or store root), plus "
                    "any extra streams to correlate")
        try:
            streams = _study_streams(args.jsonl)
        except OSError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        sys.stdout.write(render_study_timeline(args.study, streams))
        return 0
    if args.format == "json" and args.postmortem:
        print("error: --format json applies to the report/merge views, "
              "not --postmortem", file=sys.stderr)
        return 2
    if args.trend:
        if args.merge or args.postmortem or args.export_trace:
            print("error: --trend is its own view; it does not combine "
                  "with --merge/--postmortem/--export-trace",
                  file=sys.stderr)
            return 2
        if args.format == "json":
            # erroring beats a scripted consumer silently getting text:
            # the store is already machine-readable JSONL
            print("error: --trend renders text only; for machine-readable "
                  "history use `python -m hyperopt_tpu.obs.trajectory "
                  "show`", file=sys.stderr)
            return 2
        if len(args.jsonl) > 1:
            print("error: --trend takes one trajectory store, got "
                  f"{len(args.jsonl)} paths", file=sys.stderr)
            return 2
        from .trajectory import load, trajectory_path

        path = args.jsonl[0] if args.jsonl else trajectory_path()
        if not os.path.exists(path):
            print(f"error: no trajectory store at {path} — run bench.py "
                  "or `python -m hyperopt_tpu.obs.trajectory backfill`",
                  file=sys.stderr)
            return 2
        sys.stdout.write(render_trend(load(path)))
        return 0
    if not args.jsonl:
        p.error("give telemetry stream(s), or --trend")
    for path in args.jsonl:
        if not os.path.exists(path):
            print(f"error: cannot read {path}: no such file",
                  file=sys.stderr)
            return 2
    if args.export_trace:
        from .export import write_trace

        # device captures referenced by kind="profile" records merge in
        # automatically, collected DURING the single conversion pass (a
        # vanished capture degrades to a skipped track group).  Safe
        # because export_trace consumes every host stream before it reads
        # device_traces, so the teed list is complete by then.
        device_traces = []

        def _tee_profiles(path):
            # capture paths were recorded relative to the RUN's cwd; when
            # the export runs elsewhere, retry them relative to the
            # stream file (run.jsonl and prof/ usually share a directory)
            base = os.path.dirname(os.path.abspath(path))
            for r in iter_jsonl(path):
                if (isinstance(r, dict) and r.get("kind") == "profile"
                        and r.get("ok") and r.get("trace_json")):
                    tj = r["trace_json"]
                    if not os.path.exists(tj):
                        alt = os.path.join(base, tj)
                        tj = alt if os.path.exists(alt) else None
                    if tj is None:
                        print(f"warning: skipping device capture "
                              f"{r.get('dir') or r['trace_json']}: artifact "
                              f"{r['trace_json']} not found (moved? or "
                              "export running from a different directory "
                              "than the run)", file=sys.stderr)
                    else:
                        device_traces.append((
                            os.path.basename(r.get("dir") or tj),
                            tj, r.get("t0")))
                yield r

        # iter_jsonl avoids holding the raw JSONL in memory; the converted
        # trace events themselves still accumulate for the final sort, so
        # peak memory is one event dict per record
        n = write_trace(args.export_trace,
                        [(os.path.basename(path), _tee_profiles(path))
                         for path in args.jsonl],
                        device_traces=device_traces)
        merged = (f" (+{len(device_traces)} device capture(s) merged)"
                  if device_traces else "")
        print(f"wrote {n} trace events to {args.export_trace}{merged} "
              "(load in https://ui.perfetto.dev)")
        return 0
    if len(args.jsonl) > 1 and not (args.merge or args.postmortem):
        print("error: multiple streams require --merge", file=sys.stderr)
        return 2
    streams = []
    for path in args.jsonl:
        try:
            records = read_jsonl(path)
        except OSError as e:
            print(f"error: cannot read {path}: {e}", file=sys.stderr)
            return 2
        streams.append((os.path.basename(path), records))
    if not any(recs for _, recs in streams):
        print("error: no telemetry records in "
              + ", ".join(args.jsonl), file=sys.stderr)
        return 1
    if args.format == "json":
        json.dump(json_report(streams, merge=args.merge), sys.stdout,
                  indent=2, sort_keys=True, default=str)
        sys.stdout.write("\n")
    elif args.postmortem:
        for name, recs in streams:
            sys.stdout.write(render_postmortem(recs, name=name))
    elif args.merge:
        sys.stdout.write(render_merged(streams))
    else:
        sys.stdout.write(render(streams[0][1], top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
