"""Tenant observatory: per-tenant attribution for the serving edge
(ISSUE 20).

ROADMAP item 5(b)'s fairness bug at high fan-out: the scheduler packs
waves first-come and the admission guard sheds globally, so one noisy
tenant starves everyone — and nothing in the stack could even *say
which tenant* was burning the fleet.  Every aggregate the repo keeps is
per-study or per-shard; this module adds the per-principal dimension:

**The tenant id** is opaque, bounded and sanitized
(:func:`sanitize_tenant`): a string of ≤ :data:`MAX_TENANT_LEN`
printable characters, default ``"anon"``.  Hostile values (control
bytes, non-strings, over-length) raise ``ValueError`` — the HTTP layer
maps that to 400, never 500.  The id is minted client-side
(``ServiceClient(tenant=...)`` stamps the ``x-tenant`` header on EVERY
request), accepted on ``POST /study``, carried on the study registry
and the WAL admit record (an OPTIONAL ``kwargs`` field, the canary
idiom — journals written before the field existed replay bitwise).

**The tenant ledger** (:class:`TenantLedger`, owned by the scheduler
exactly like the cost ledger) is fed at the same chokepoints: the wave
path's measured dispatch+readback share, ``_apply_tell``, and the
server's response observation (ask latency + sheds).  O(1) per-tenant
rows — ``{studies, asks, tells, sheds, device_ms, hbm_bytes,
ask-latency quantile sketch, activity EWMA}`` — under a HARD
cardinality bound: at most ``top_k`` named rows (K default 64) plus an
``other`` roll-up bucket.  A tenant-id bomb (10k distinct ids) evicts
the least-active row into ``other`` instead of growing memory; totals
are conserved across eviction.

**Actionability** rides the same measurement: the admission guard
takes per-tenant budgets (per-tenant 429 + ``Retry-After`` while other
tenants admit), and the scheduler's wave packer orders requests by
deficit-round-robin over tenants weighted by the inverse of each
tenant's EWMA'd device_ms share (:meth:`TenantLedger.drr_order`).
Packing ORDER only: per-id PRNG keys derive from the id value and the
study seed, never slot position or wave composition, so reordering is
proposal-invariant — armed == disarmed bit-identical, pinned directly
and over HTTP.  Disarmed (``HYPEROPT_TPU_TENANT=off``) means
``scheduler.tenants is None``: zero threads, zero allocations, one
``is None`` check on the wave path.

Fleet durability piggybacks the heat ledger (ISSUE 17): each cumulative
heat record optionally carries a ``tenants`` table; old readers ignore
the unknown field, :func:`read_tenant_heat` MAX-merges it per
(shard, tenant) and sums across shards — the ``GET /fleet/load`` and
``obs.report --tenants`` view.
"""

from __future__ import annotations

import logging
import threading
from collections import deque

__all__ = [
    "ANON",
    "OTHER",
    "MAX_TENANT_LEN",
    "DEFAULT_TOP_K",
    "sanitize_tenant",
    "TenantRow",
    "TenantLedger",
    "merge_status",
    "read_tenant_heat",
]

logger = logging.getLogger(__name__)

#: the default principal: requests and studies that never named one
ANON = "anon"

#: the roll-up bucket evicted/overflow tenants charge into — a RESERVED
#: id (sanitize_tenant refuses it from clients so the bucket can never
#: be impersonated)
OTHER = "other"

#: hard length bound on a tenant id (satellite 1)
MAX_TENANT_LEN = 128

#: default named-row bound (``HYPEROPT_TPU_TENANT_TOP_K``)
DEFAULT_TOP_K = 64

#: activity-EWMA weight — same memory as the cost ledger's busy EWMA
DEFAULT_ALPHA = 0.3

#: latency-sketch ring bound per tenant row (most-recent observations;
#: the same bounded-percentile discipline as obs.metrics.Histogram)
SKETCH_LEN = 256


def sanitize_tenant(value, default=ANON):
    """Validate one tenant id → its canonical string, or raise
    ``ValueError`` (the HTTP layer answers 400 — never 500 — on it).

    Rules (satellite 1, the hostile-id hardening):

    * ``None`` / empty string → ``default`` (``"anon"``);
    * must be ``str`` (bytes, ints, dicts and lists are client bugs,
      not principals);
    * length ≤ :data:`MAX_TENANT_LEN`;
    * no control bytes (ord < 32 or 127) — ids land in access logs,
      JSONL ledgers and HTTP headers, where a newline is an injection;
    * the reserved ``other`` bucket cannot be claimed by a client.
    """
    if value is None:
        return default
    if not isinstance(value, str):
        raise ValueError(
            f"tenant id must be a string, got {type(value).__name__}")
    if value == "":
        return default
    if len(value) > MAX_TENANT_LEN:
        raise ValueError(
            f"tenant id too long ({len(value)} > {MAX_TENANT_LEN})")
    for ch in value:
        o = ord(ch)
        if o < 32 or o == 127:
            raise ValueError(
                f"tenant id contains control byte 0x{o:02x}")
    if value == OTHER:
        raise ValueError(
            f"tenant id {OTHER!r} is reserved for the roll-up bucket")
    return value


def _metric_label(tenant):
    """Metric-name-safe tenant label (the gauges surface as
    ``hyperopt_tpu_service_tenant_*`` families and must lint)."""
    return "".join(c if c.isalnum() or c == "_" else "_"
                   for c in str(tenant))


class TenantRow:
    """One tenant's accumulated attribution.  All mutators are O(1),
    no I/O, no RNG — pure arithmetic on already-measured quantities."""

    __slots__ = ("tenant", "studies", "asks", "tells", "sheds",
                 "device_ms", "hbm_bytes", "ewma_ms", "deficit",
                 "_lat")

    def __init__(self, tenant):
        self.tenant = tenant
        self.studies = 0
        self.asks = 0
        self.tells = 0
        self.sheds = 0
        self.device_ms = 0.0
        self.hbm_bytes = 0.0
        self.ewma_ms = 0.0       # activity EWMA of attributed ms/tick
        self.deficit = 0.0       # deficit-round-robin credit (packer)
        self._lat = deque(maxlen=SKETCH_LEN)  # ask latency sketch (ms)

    def charge(self, share_ms, k, hbm_bytes, alpha):
        """Fold this tenant's K-row share of one cohort tick."""
        self.device_ms += share_ms
        self.asks += k
        self.hbm_bytes += hbm_bytes
        self.ewma_ms = alpha * share_ms + (1.0 - alpha) * self.ewma_ms

    def observe_latency(self, latency_ms):
        self._lat.append(float(latency_ms))

    def absorb(self, other):
        """Fold another row's totals into this one (eviction into the
        ``other`` bucket) — totals are conserved, the sketch is not
        (a percentile over mixed evicted tenants would mean nothing)."""
        self.studies += other.studies
        self.asks += other.asks
        self.tells += other.tells
        self.sheds += other.sheds
        self.device_ms += other.device_ms
        self.hbm_bytes += other.hbm_bytes
        self.ewma_ms = max(self.ewma_ms, other.ewma_ms)

    def _lat_pct(self, p):
        ring = sorted(self._lat)
        if not ring:
            return None
        return ring[min(len(ring) - 1, int(p * (len(ring) - 1) + 0.5))]

    def status_dict(self):
        out = {
            "studies": self.studies,
            "asks": self.asks,
            "tells": self.tells,
            "sheds": self.sheds,
            "device_ms": round(self.device_ms, 3),
            "hbm_bytes": round(self.hbm_bytes, 1),
            "ewma_ms": round(self.ewma_ms, 3),
        }
        p50, p99 = self._lat_pct(0.5), self._lat_pct(0.99)
        if p50 is not None:
            out["ask_p50_ms"] = round(p50, 3)
            out["ask_p99_ms"] = round(p99, 3)
        return out


class TenantLedger:
    """Per-scheduler tenant attribution (zero threads), the cost
    ledger's sibling: wave/tell mutations arrive under the scheduler's
    RLock so the hot path is lock-free; the ledger's own lock guards
    only row admission/eviction.  Scrape-side reads are deliberately
    unlocked (a scrape racing a wave sees the tick one charge early or
    late, both true).

    The HARD cardinality bound: at most ``top_k`` named rows plus the
    ``other`` bucket.  A charge for an unseen tenant past the bound
    evicts the least-active named row (minimum activity EWMA,
    tenant-name tie-break for determinism) into ``other`` — so a 10k-id
    bomb churns one row, never grows the table.  ``anon`` and ``other``
    are never evicted."""

    def __init__(self, metrics=None, top_k=None, alpha=DEFAULT_ALPHA):
        self.metrics = metrics
        self.top_k = DEFAULT_TOP_K if top_k is None else max(1, int(top_k))
        self.alpha = float(alpha)
        self._rows = {}
        self._lock = threading.Lock()
        self.evictions = 0
        # scheduler-level totals (attributed — they sum to the measured
        # tick times exactly, like the cost ledger's)
        self.device_ms = 0.0
        self.asks = 0
        self.tells = 0
        self.sheds = 0

    # -- row admission under the cardinality bound -------------------------

    def _row(self, tenant):
        row = self._rows.get(tenant)
        if row is not None:
            return row
        with self._lock:
            row = self._rows.get(tenant)
            if row is not None:
                return row
            named = [t for t in self._rows if t != OTHER]
            if len(named) >= self.top_k and tenant != OTHER:
                # evict the least-active named row into `other` —
                # `anon` is a principal like any other here, but a
                # fresh ledger always has room for it before the bound
                victim = min(
                    (t for t in named),
                    key=lambda t: (self._rows[t].ewma_ms, t))
                other = self._rows.get(OTHER)
                if other is None:
                    other = TenantRow(OTHER)
                    self._rows[OTHER] = other
                other.absorb(self._rows.pop(victim))
                self.evictions += 1
            row = TenantRow(tenant)
            self._rows[tenant] = row
            return row

    # -- the chokepoint hooks ----------------------------------------------

    def note_study(self, tenant):
        """One study admitted (create or WAL replay — the tenant table
        is REBUILT from admit records on resume, satellite 4)."""
        self._row(tenant).studies += 1

    def observe_tick(self, entries, device_sec, hbm_bytes=0.0):
        """Attribute one measured cohort tick.  ``entries`` is
        ``[(tenant, k_rows), ...]``; each tenant is charged
        ``k_i / sum(k)`` of the tick.  Called under the scheduler RLock;
        never touches proposals."""
        total_k = 0
        for _, k in entries:
            total_k += k
        if total_k <= 0:
            return
        ms = float(device_sec) * 1e3
        inv = 1.0 / total_k
        for tenant, k in entries:
            share = k * inv
            self._row(tenant).charge(ms * share, k, hbm_bytes * share,
                                     self.alpha)
        self.device_ms += ms
        self.asks += total_k

    def observe_tell(self, tenant):
        """One settled tell (canary excluded by the caller; replayed
        tells COUNT — they are the crash-resume rebuild)."""
        self.tells += 1
        self._row(tenant).tells += 1

    def observe_request(self, tenant, latency_sec=None, shed=False):
        """One finished HTTP ask, from the server's response path
        (probe traffic excluded by the caller, exactly as it is from
        the tenant SLOs)."""
        row = self._row(tenant)
        if shed:
            self.sheds += 1
            row.sheds += 1
        elif latency_sec is not None:
            row.observe_latency(float(latency_sec) * 1e3)

    def forget_study(self, tenant):
        """One study closed/forgotten — the studies gauge tracks LIVE
        studies; accumulated cost stays (history, not occupancy)."""
        row = self._rows.get(tenant)
        if row is not None and row.studies > 0:
            row.studies -= 1

    # -- the weighted-fair packer's inputs ----------------------------------

    def drr_order(self, tenants):
        """Deficit-round-robin serving order over ``tenants`` (any
        iterable, duplicates ignored): tenants earn credit inversely
        proportional to their EWMA'd device_ms share, so a light tenant
        outranks a noisy one until the noisy one's history decays.
        Returns the tenants sorted most-deserving first; mutates the
        rows' persistent deficit counters (bounded — deficits live on
        the bounded row table).  Pure arithmetic on already-measured
        charge history: never reads the RNG, never changes WHAT is
        proposed, only the packing order."""
        uniq = []
        seen = set()
        for t in tenants:
            if t not in seen:
                seen.add(t)
                uniq.append(t)
        if len(uniq) <= 1:
            return uniq
        rows = {t: self._row(t) for t in uniq}
        mean_ms = sum(r.ewma_ms for r in rows.values()) / len(rows)
        for t in uniq:
            # quantum: inverse activity share, normalized so an evenly
            # loaded set earns 1.0 each (plain round-robin)
            r = rows[t]
            r.deficit += (mean_ms + 1e-6) / (r.ewma_ms + 1e-6)
        order = sorted(uniq,
                       key=lambda t: (-rows[t].deficit, t))
        # the served (front) tenant spends one unit of credit; deficits
        # are clamped so an idle tenant cannot bank unbounded priority
        rows[order[0]].deficit -= 1.0
        for t in uniq:
            r = rows[t]
            if r.deficit > 64.0:
                r.deficit = 64.0
            elif r.deficit < -64.0:
                r.deficit = -64.0
        return order

    # -- pull-based publication --------------------------------------------

    def status(self):
        """The tenant roll-up (``GET /tenants`` + ``/snapshot``
        section): totals plus the bounded per-tenant table, most
        active first."""
        rows = list(self._rows.values())
        table = {}
        for row in sorted(rows, key=lambda r: (-r.device_ms, r.tenant)):
            table[row.tenant] = row.status_dict()
        return {
            "tenants": len(rows),
            "top_k": self.top_k,
            "evictions": self.evictions,
            "device_ms": round(self.device_ms, 3),
            "asks": self.asks,
            "tells": self.tells,
            "sheds": self.sheds,
            "table": table,
        }

    def publish(self):
        """Refresh the ``service.tenant.*`` gauges (scrape/snapshot
        time, the cost ledger's pull-based discipline) and return
        :meth:`status`."""
        st = self.status()
        if self.metrics is not None:
            g = self.metrics.gauge
            g("service.tenant.tracked").set(st["tenants"])
            g("service.tenant.evictions").set(st["evictions"])
            g("service.tenant.sheds").set(st["sheds"])
            for tenant, row in st["table"].items():
                base = f"service.tenant.{_metric_label(tenant)}"
                g(f"{base}.device_ms").set(row["device_ms"])
                g(f"{base}.asks").set(row["asks"])
                g(f"{base}.tells").set(row["tells"])
                g(f"{base}.sheds").set(row["sheds"])
                g(f"{base}.studies").set(row["studies"])
                if row.get("ask_p99_ms") is not None:
                    g(f"{base}.ask_p99_ms").set(row["ask_p99_ms"])
        return st

    def heat_table(self):
        """The per-tenant cumulative device_ms table one heat-ledger
        record carries (``tenants`` field, ISSUE-17 records; unknown to
        old readers, MAX-merged by :func:`read_tenant_heat`)."""
        return {row.tenant: round(row.device_ms, 3)
                for row in self._rows.values()}

    def study_status(self, tenant):
        row = self._rows.get(tenant)
        return None if row is None else row.status_dict()


def merge_status(statuses):
    """Merge per-scheduler :meth:`TenantLedger.status` dicts (a fleet
    replica runs one ledger per adopted shard) into the replica-level
    view: summed totals and the merged per-tenant table (still bounded:
    each input is)."""
    statuses = [s for s in statuses if s]
    if not statuses:
        return None
    out = {"tenants": 0, "evictions": 0, "device_ms": 0.0,
           "asks": 0, "tells": 0, "sheds": 0, "table": {}}
    top_k = 0
    for s in statuses:
        top_k = max(top_k, int(s.get("top_k") or 0))
        for k in ("evictions", "asks", "tells", "sheds"):
            out[k] += int(s.get(k) or 0)
        out["device_ms"] += float(s.get("device_ms") or 0.0)
        for tenant, row in (s.get("table") or {}).items():
            cur = out["table"].setdefault(tenant, {
                "studies": 0, "asks": 0, "tells": 0, "sheds": 0,
                "device_ms": 0.0, "hbm_bytes": 0.0, "ewma_ms": 0.0})
            for k in ("studies", "asks", "tells", "sheds"):
                cur[k] += int(row.get(k) or 0)
            for k in ("device_ms", "hbm_bytes"):
                cur[k] += float(row.get(k) or 0.0)
            cur["ewma_ms"] = max(cur["ewma_ms"],
                                 float(row.get("ewma_ms") or 0.0))
            # shards tick independently; report the WORST tail seen
            if row.get("ask_p99_ms") is not None:
                cur["ask_p99_ms"] = max(
                    float(cur.get("ask_p99_ms") or 0.0),
                    float(row["ask_p99_ms"]))
                cur.setdefault("ask_p50_ms", row.get("ask_p50_ms"))
    out["tenants"] = len(out["table"])
    out["top_k"] = top_k
    out["device_ms"] = round(out["device_ms"], 3)
    for cur in out["table"].values():
        cur["device_ms"] = round(cur["device_ms"], 3)
        cur["hbm_bytes"] = round(cur["hbm_bytes"], 1)
        cur["ewma_ms"] = round(cur["ewma_ms"], 3)
    return out


def read_tenant_heat(store_root):
    """The fleet-merged per-tenant heat view from the durable heat
    ledgers: heat records optionally carry a cumulative ``tenants``
    table per (shard, replica) snapshot — take the MAX per
    (shard, tenant) across records (cumulative snapshots, the shard
    heat discipline), then SUM across shards per tenant.  Tolerant of
    pre-ISSUE-20 records (no ``tenants`` field) and unreadable ledgers
    — the view must never fail a request."""
    from .load import _iter_heat_records

    per_shard = {}  # (shard, tenant) -> max cumulative device_ms
    try:
        for _fname, rec, _status in _iter_heat_records(store_root):
            if rec is None or rec.get("kind") != "heat":
                continue
            table = rec.get("tenants")
            if not isinstance(table, dict):
                continue
            shard = rec.get("shard")
            for tenant, ms in table.items():
                try:
                    ms = float(ms)
                except (TypeError, ValueError):
                    continue
                key = (shard, str(tenant))
                if ms > per_shard.get(key, 0.0):
                    per_shard[key] = ms
    except Exception:  # noqa: BLE001 - fail-open read
        logger.warning("tenant heat: ledger read failed (continuing "
                       "with what parsed)", exc_info=True)
    tenants = {}
    for (_shard, tenant), ms in per_shard.items():
        tenants[tenant] = round(tenants.get(tenant, 0.0) + ms, 3)
    return {"tenants": tenants}
