"""Always-on flight recorder: the last N seconds of a run's life, on disk
when the process dies.

The obs layer's spans/metrics/events only reach disk when a run is armed
(``HYPEROPT_TPU_OBS=<path>``), and a killed or wedged process never gets to
flush anything.  This module is the forensics pillar that survives both: a
process-global, lock-cheap in-memory ring of the most recent telemetry
records (spans — armed *or* disarmed — events, trial lifecycle, stall
reports) that is dumped to ``<run>.flight.jsonl`` when the process dies
abnormally:

* **unhandled exception** — a chained ``sys.excepthook``;
* **fatal signals** — SIGTERM / SIGINT / SIGABRT handlers that dump the
  ring, then hand control to whatever handler was installed before (or
  re-raise the default disposition so exit codes stay honest);
* **atexit** — a final dump for every explicitly-armed recorder, so even a
  clean exit leaves the forensics artifact the run asked for;
* **hard faults** — ``faulthandler`` is enabled at install time (SIGSEGV /
  SIGFPE / SIGBUS / SIGILL write C-level tracebacks to
  ``<dump>.faults``, or stderr when no dump path is configured).

Bounds: the ring holds at most ``max_records`` records *and* (by a cheap
shallow estimate, made exact at dump time) at most ``max_bytes`` of
payload, whichever trips first — a week-long run cannot grow it.
Recording must stay inside the repo's <2% disarmed-``fmin`` overhead bar
(``bench.py`` stage ``flight_overhead`` attaches the measured before/after
delta), so the hot path does **no serialization**: a size estimate and a
deque append under a short lock.  JSON encoding happens once, at dump
time, where the exact ``max_bytes`` budget is enforced newest-first.

The dump is ordinary obs JSONL — parse with
:func:`~hyperopt_tpu.obs.trace.read_jsonl`, render with
``python -m hyperopt_tpu.obs.report --postmortem run.flight.jsonl``.  A
dump carries, besides the ring itself:

* a ``kind="flight_dump"`` header (reason, pid, wall time);
* one ``kind="open_span"`` record per span still open at death — the
  phase the process died *inside*;
* a ``kind="last_heartbeats"`` record from the stall watchdog (per-
  component last-heartbeat ages — which collective a controller reached).

Arming the dump path: ``HYPEROPT_TPU_FLIGHT=<path>`` (``0``/``off``
disables recording entirely), or it derives from an armed obs stream
(``run.jsonl`` → ``run.flight.jsonl``).  With neither, the ring still
records and abnormal deaths dump to ``hyperopt_tpu.flight.jsonl`` in the
working directory; clean exits write nothing.
"""

from __future__ import annotations

import atexit
import faulthandler
import json
import logging
import os
import signal
import sys
import threading
import time
from collections import deque

__all__ = ["FlightRecorder", "get_flight", "flight_path_for"]

logger = logging.getLogger(__name__)

_DEFAULT_MAX_RECORDS = 4096
_DEFAULT_MAX_BYTES = 4 << 20  # 4 MiB of encoded JSONL

_FATAL_SIGNALS = tuple(
    s for s in (getattr(signal, n, None)
                for n in ("SIGTERM", "SIGINT", "SIGABRT"))
    if s is not None
)


def _json_default(o):
    # mirror trace._json_default: telemetry must never raise into the paths
    # it observes
    try:
        return float(o)
    except Exception:
        return str(o)


def flight_path_for(jsonl_path):
    """Dump path derived from an armed obs stream: ``run.jsonl`` →
    ``run.flight.jsonl`` (kept next to the stream it post-mortems)."""
    root, ext = os.path.splitext(str(jsonl_path))
    return f"{root}.flight{ext or '.jsonl'}"


def _estimate_bytes(rec):
    """Cheap shallow size estimate for the ring's byte bound — three dict
    lookups, no iteration, no serialization (the hot path pays this per
    record; the exact bound is enforced against real encoded bytes at dump
    time).  Stall records carry thread stacks, hence the flat surcharge."""
    n = 48 + 24 * len(rec)
    name = rec.get("name")
    if type(name) is str:
        n += len(name)
    attrs = rec.get("attrs")
    if type(attrs) is dict:
        n += 24 * len(attrs)
    if "stacks" in rec:
        n += 4096
    return n


class FlightRecorder:
    """Bounded in-memory ring of recent telemetry records + crash dumps.

    ``record`` is the hot call: encode once, append under a short lock,
    trim to the count/byte bounds.  Everything else (install, dump) runs
    at most a handful of times per process and never raises — a recorder
    failure must not take down the run it exists to post-mortem.
    """

    def __init__(self, max_records=_DEFAULT_MAX_RECORDS,
                 max_bytes=_DEFAULT_MAX_BYTES):
        self.enabled = True
        self.max_records = int(max_records)
        self.max_bytes = int(max_bytes)
        self.watchdog = None  # optional: last-heartbeat provider for dumps
        self.devmem = None  # optional: device-memory tail/census provider
        # optional live-record tap (the scrape server's SSE broadcast hub,
        # obs/serve.py).  One attribute load + None check per record when no
        # server is armed; the tap itself must never raise or block (the
        # broadcast hub appends to bounded per-client rings, dropping
        # oldest on overflow)
        self.tap = None
        # shutdown hooks (scrape-server close): run on fatal signals and at
        # atexit so an armed HTTP listener dies with the run, not after it
        self._shutdown_hooks = []
        self._ring = deque()  # (record dict, estimated bytes)
        self._bytes = 0
        # REENTRANT: the fatal-signal handler runs on the main thread
        # between bytecodes and calls record()/dump() — with a plain Lock a
        # signal landing while the main thread holds it would deadlock the
        # dying process instead of dumping
        self._lock = threading.RLock()
        # id(span) -> (name, start ts, thread name); plain dict ops are
        # GIL-atomic, dumps iterate a snapshot copy
        self._open_spans = {}
        self._targets = []
        self._installed = False
        self._prev_signal = {}
        self._prev_excepthook = None
        self._fault_file = None
        self._fh_stderr = False  # we enabled faulthandler, bound to stderr
        self.dump_count = 0
        self._seq = 0  # records ever appended (not bounded by the ring)
        self._abnormal_seq = None  # _seq at the last signal/exception dump

    # -- recording (the hot path) -----------------------------------------

    def record(self, rec: dict):
        """Append one record to the ring — no serialization on the hot
        path, just a shallow size estimate and a deque append."""
        if not self.enabled:
            return
        try:
            n = _estimate_bytes(rec)
        except Exception:
            return
        with self._lock:
            self._ring.append((rec, n))
            self._seq += 1
            self._bytes += n
            while self._ring and (len(self._ring) > self.max_records
                                  or self._bytes > self.max_bytes):
                self._bytes -= self._ring.popleft()[1]
        tap = self.tap
        if tap is not None:
            try:
                tap(rec)
            except Exception:  # a broken tap must not touch the hot path
                self.tap = None

    def note_open(self, key, name, ts):
        """Register a span as open; a dump reports every span still open at
        death (the phase the process died inside).  Stores the raw thread
        ident — name resolution happens at dump time, off the hot path."""
        if self.enabled:
            self._open_spans[key] = (name, ts, threading.get_ident())

    def note_close(self, key):
        self._open_spans.pop(key, None)

    def records(self):
        """Snapshot of the ring (oldest first)."""
        with self._lock:
            return [rec for rec, _ in self._ring]

    def clear(self):
        with self._lock:
            self._ring.clear()
            self._bytes = 0
        self._open_spans.clear()

    # -- arming ------------------------------------------------------------

    def add_target(self, path):
        path = str(path)
        with self._lock:
            if path not in self._targets:
                self._targets.append(path)

    def remove_target(self, path):
        path = str(path)
        with self._lock:
            if path in self._targets:
                self._targets.remove(path)

    def add_shutdown_hook(self, fn):
        """Register an idempotent, non-raising callable to run when the
        process dies (fatal signal or atexit) — how the scrape server's
        listener socket is closed on the flight recorder's signal path."""
        with self._lock:
            if fn not in self._shutdown_hooks:
                self._shutdown_hooks.append(fn)

    def remove_shutdown_hook(self, fn):
        with self._lock:
            if fn in self._shutdown_hooks:
                self._shutdown_hooks.remove(fn)

    def run_shutdown_hooks(self):
        """Run (and keep) the registered hooks; they are idempotent, so a
        signal dump followed by the atexit dump is safe."""
        with self._lock:
            hooks = list(self._shutdown_hooks)
        for fn in hooks:
            try:
                fn()
            except Exception:  # a dying process: best-effort only
                pass

    def install(self, path=None):
        """Arm the crash handlers (idempotent) and, when ``path`` is given,
        add it as a dump target.  Pre-existing signal handlers and the
        previous ``sys.excepthook`` are preserved and chained to."""
        if not self.enabled:
            return self
        if path:
            self.add_target(path)
        if not self._installed:
            self._installed = True
            atexit.register(self._atexit_dump)
            self._prev_excepthook = sys.excepthook
            sys.excepthook = self._excepthook
            for sig in _FATAL_SIGNALS:
                try:
                    self._prev_signal[sig] = signal.signal(
                        sig, self._signal_handler)
                except (ValueError, OSError):
                    # not the main thread / unsupported platform: the ring
                    # and the exception/atexit dumps still work
                    continue
        self._arm_faulthandler()
        return self

    def _arm_faulthandler(self):
        """Route hard faults (SIGSEGV class) to ``<first target>.faults``,
        or stderr while no target exists.  Runs on every install, not just
        the first: a process whose first run was disarmed upgrades the
        stderr binding to a file once an armed run names one.  A
        faulthandler someone else enabled is never stolen."""
        try:
            if self._fault_file is not None:
                return
            if self._targets and (self._fh_stderr
                                  or not faulthandler.is_enabled()):
                target = self._targets[0]
                d = os.path.dirname(target)
                if d:
                    os.makedirs(d, exist_ok=True)
                # the handle must stay open for faulthandler's lifetime;
                # an empty .faults file afterwards means "no hard faults"
                self._fault_file = open(target + ".faults", "w")
                faulthandler.enable(file=self._fault_file)
                self._fh_stderr = False
            elif not faulthandler.is_enabled():
                faulthandler.enable()
                self._fh_stderr = True
        except Exception:  # pragma: no cover - faulthandler is best-effort
            pass

    # -- dumping -----------------------------------------------------------

    def dump(self, reason, path=None):
        """Write header + ring + open spans + last heartbeats to ``path``
        (or every armed target, or the default cwd path).  Encoding happens
        here, once, and the exact ``max_bytes`` budget is enforced
        newest-first.  Never raises; returns the list of paths written."""
        with self._lock:
            recs = [rec for rec, _ in self._ring]
        lines, budget = [], self.max_bytes
        for rec in reversed(recs):  # newest-first under the exact budget
            try:
                line = json.dumps(rec, default=_json_default)
            except Exception:
                continue
            budget -= len(line) + 1
            if budget < 0:
                break
            lines.append(line)
        lines.reverse()  # back to chronological order
        targets = ([str(path)] if path
                   else list(self._targets) or ["hyperopt_tpu.flight.jsonl"])
        now = time.time()
        head = json.dumps({
            "kind": "flight_dump", "reason": str(reason), "ts": now,
            "pid": os.getpid(), "n_records": len(lines),
        })
        extra = []
        thread_names = {t.ident: t.name for t in threading.enumerate()}
        for name, ts, ident in list(self._open_spans.values()):
            extra.append(json.dumps({
                "kind": "open_span", "name": name, "ts": ts,
                "age_sec": now - ts,
                "thread": thread_names.get(ident, f"thread-{ident}"),
            }, default=_json_default))
        wd = self.watchdog
        if wd is not None:
            try:
                extra.append(json.dumps(
                    {"kind": "last_heartbeats", "ts": now,
                     "beats": wd.last_beats()}, default=_json_default))
            except Exception:
                pass
        dm = self.devmem
        if dm is not None:
            # the memory narrative: recent devmem samples + a live-array
            # census, so an OOM'd process dumps WHAT was holding HBM
            try:
                for rec in dm.tail():
                    extra.append(json.dumps(rec, default=_json_default))
                extra.append(json.dumps(dm.census_record(),
                                        default=_json_default))
            except Exception:
                pass
        written = []
        for target in targets:
            try:
                d = os.path.dirname(target)
                if d:
                    os.makedirs(d, exist_ok=True)
                # overwrite: a later dump (exception then atexit) supersedes
                # the earlier one — the ring only ever grows between them
                with open(target, "w") as f:
                    f.write(head + "\n")
                    for line in lines:
                        f.write(line + "\n")
                    for line in extra:
                        f.write(line + "\n")
                    f.flush()
                    os.fsync(f.fileno())
                written.append(target)
            except Exception:
                continue  # a dead target must not block the others
        self.dump_count += 1
        return written

    # -- death hooks -------------------------------------------------------

    def _signal_handler(self, signum, frame):
        try:
            name = signal.Signals(signum).name
        except ValueError:  # pragma: no cover
            name = str(signum)
        self.record({"kind": "event", "name": "fatal_signal",
                     "ts": time.time(), "attrs": {"signal": name}})
        self._abnormal_seq = self._seq
        self.dump(reason=f"signal:{name}")
        self.run_shutdown_hooks()
        prev = self._prev_signal.get(signum)
        if callable(prev):
            prev(signum, frame)
        elif prev == signal.SIG_DFL:
            # restore the default disposition and re-deliver, so the exit
            # status stays what a kill would have produced without us
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)
        # SIG_IGN / None: swallow, matching the pre-existing behavior

    def _excepthook(self, exc_type, exc, tb):
        try:
            self.record({"kind": "event", "name": "unhandled_exception",
                         "ts": time.time(),
                         "attrs": {"type": exc_type.__name__,
                                   "message": str(exc)[:500]}})
            if self.devmem is not None and "RESOURCE_EXHAUSTED" in str(exc):
                # device OOM: take one FRESH sample + census at the moment
                # of death (the tail alone shows the ramp, not the peak
                # that killed us) so the dump carries a memory narrative
                try:
                    self.devmem.sample(reason="oom")
                except Exception:
                    pass
            self._abnormal_seq = self._seq
            self.dump(reason=f"exception:{exc_type.__name__}")
        finally:
            (self._prev_excepthook or sys.__excepthook__)(exc_type, exc, tb)

    def _atexit_dump(self):
        # only explicitly-armed recorders leave an artifact on a CLEAN exit.
        # An abnormal death (signal/exception) already dumped above — do NOT
        # overwrite that dump with a misleading reason="atexit" header...
        # UNLESS the process demonstrably kept running afterwards (a caught
        # KeyboardInterrupt, say): new ring records since the abnormal dump
        # mean it describes a survived incident, not this death.
        if self._targets and (self._abnormal_seq is None
                              or self._seq > self._abnormal_seq):
            self.dump(reason="atexit")
        self.run_shutdown_hooks()


_global = None
_global_lock = threading.Lock()


def get_flight() -> FlightRecorder:
    """The process-global flight recorder (created on first use;
    ``HYPEROPT_TPU_FLIGHT=0``/``off`` disables recording entirely)."""
    global _global
    if _global is None:
        with _global_lock:
            if _global is None:
                fr = FlightRecorder()
                if os.environ.get("HYPEROPT_TPU_FLIGHT",
                                  "").strip().lower() in ("0", "off"):
                    fr.enabled = False
                _global = fr
    return _global
