"""SLO error-budget plane for the serving fleet (ISSUE 11).

The overload guard (ISSUE 10) answers "is this request servable *right
now*"; this module answers the operator's question — "is the service
meeting its objectives *over time*, and how fast is it spending the
error budget".  Three declarative objectives over the traffic the
server already observes:

* **availability** — fraction of requests that did not 5xx (target
  e.g. 99.9%: the error budget is the 0.1% that may);
* **ask_latency** — fraction of served asks faster than a threshold
  (a count-based latency SLO: "99% of asks under 500ms", not a single
  quantile estimate, so the budget math is exact);
* **shed_rate** — fraction of offered asks NOT shed (backpressure is
  correct behavior under overload, but a service shedding 20% of its
  asks for six hours is failing its users even though every 429 was
  individually right).

**Burn rates, not raw error rates.**  Following the multi-window
multi-burn-rate pattern (Google SRE workbook ch. 5): the *burn rate* of
a window is ``bad_fraction / (1 - target)`` — 1.0 means "spending the
budget exactly as fast as the SLO allows", N means the budget dies in
``period/N``.  Two window pairs:

* **fast** (5m AND 1h both over ``FAST_BURN`` = 14.4) — page-grade: at
  that rate a 30-day budget is gone in ~2 days, and the 5m window means
  it is happening *now* (the 1h guard keeps a single bad minute from
  paging);
* **slow** (30m AND 6h both over ``SLOW_BURN`` = 6) — ticket-grade
  sustained burn.

A pair may alert (and the budget may report exhausted) only once its
long window holds :data:`MIN_ALERT_EVENTS` events — at lower volume
both windows of a pair contain the same few events, the long window
stops guarding the short one, and a single slow request (the first
tick's XLA compile, every server start) would page.

Counting is time-bucketed (60s buckets, bounded ring per objective) and
the clock is injectable, so tier-1 tests drive rotation, exhaustion and
recovery on a fake clock without sleeping.  Evaluation is pull-based
(the scrape and snapshot paths call :meth:`SLOPlane.publish`; the
record path re-evaluates at most once per ``eval_interval``) — the
plane starts **zero threads**, armed or not.

**Escalation.**  When the fast pair trips, the plane fires its
escalation hook ONCE per episode (edge-triggered, with a cooldown) —
the server wires it to one bounded device-profiler capture
(``obs/profiler.py``), closing the loop from "SLO violated" to "here is
the device trace of the slow wave".

Gauges (``slo.<objective>.*`` on the service registry, exposed as
``hyperopt_tpu_slo_*`` on ``/metrics``): ``burn_fast`` / ``burn_slow``
(the worse window of each pair), ``budget_remaining_frac`` (over the
long 6h window), ``fast_alerting`` / ``slow_alerting`` / ``exhausted``
(0/1).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque

__all__ = ["SLOPlane", "Objective", "DEFAULT_TARGETS", "QUALITY_TARGETS",
           "LOAD_TARGETS", "PROBE_TARGETS", "TENANT_TARGETS", "WINDOWS",
           "FAST_BURN", "SLOW_BURN"]

logger = logging.getLogger(__name__)

#: (fast pair, slow pair) window lengths in seconds
WINDOWS = {"fast": (300.0, 3600.0), "slow": (1800.0, 21600.0)}

#: page-grade burn threshold: both fast windows at/above this alert
FAST_BURN = 14.4
#: ticket-grade sustained-burn threshold for the slow pair
SLOW_BURN = 6.0

#: one bucket per minute; the ring must cover the longest window
_BUCKET_SEC = 60.0
_MAX_BUCKETS = int(max(max(WINDOWS.values())) / _BUCKET_SEC) + 2

#: minimum events in a pair's LONG window before it may alert (or report
#: the budget exhausted): at low traffic both windows of a pair hold the
#: SAME handful of events, so the long window stops guarding the short
#: one and a single slow request (the first tick's XLA compile, every
#: server start) would page.  Below this volume the burn rates still
#: report — they just cannot alert or escalate.
MIN_ALERT_EVENTS = 10

#: default objective targets (overridable via the
#: ``HYPEROPT_TPU_SERVICE_SLO`` spec grammar — see ``_env.py``):
#: availability 99.9%, 99% of asks under 500ms, ≤5% of offered asks shed
DEFAULT_TARGETS = {
    "availability": {"target": 0.999},
    "ask_latency": {"target": 0.99, "threshold_ms": 500.0},
    "shed_rate": {"target": 0.95},
}

#: the search-quality objective (ISSUE 16, ``HYPEROPT_TPU_QUALITY_SLO``):
#: one event per LIVE tell, good = the told study is not stagnant after
#: folding the result.  Target 90% — a fleet where >10% of recent tells
#: land on plateaued studies is burning trial budget, not optimizing.
#: Kept out of DEFAULT_TARGETS: it only makes sense when the quality
#: plane is armed, so the server installs it separately.
QUALITY_TARGETS = {
    "stagnation": {"target": 0.90},
}

#: the fleet-imbalance objective (ISSUE 17): an observation is GOOD
#: when the heat-skew scalar (max/mean shard heat) sits at or under
#: ``skew_max``.  ``skew_max`` rides the spec dict — ``add_objective``
#: only reads target/threshold_ms, so the server keeps the bound and
#: feeds pre-judged booleans via ``record_load``.
LOAD_TARGETS = {
    "imbalance": {"target": 0.90, "skew_max": 3.0},
}

#: blackbox-prober objectives (ISSUE 18) — the CLIENT-view signals,
#: deliberately distinct from the server-side ``availability`` /
#: ``ask_latency`` pair: they are measured through the real HTTP path
#: (retries and redirect hops included), so a wedged listener — which
#: server-side objectives never see — burns budget here.
#: ``probe_golden_match`` is the correctness objective: the fraction of
#: probe cycles whose canary proposal-stream digest matched golden.
PROBE_TARGETS = {
    "probe_avail": {"target": 0.99},
    "probe_golden_match": {"target": 0.999},
    "probe_ask_p99_ms": {"target": 0.99, "threshold_ms": 2000.0},
}


#: per-tenant golden-signal objectives (ISSUE 20,
#: ``HYPEROPT_TPU_TENANT_SLO``) — installed per TOP-K tenant as
#: ``tenant:<id>:<name>`` via :meth:`SLOPlane.add_objective` at
#: gauge-refresh time (idempotent; the cardinality bound on the tenant
#: ledger bounds the objective count too), fed pre-judged booleans via
#: :meth:`SLOPlane.record_event` from the server's response path.
#: Probe-tagged canary traffic never reaches them.
TENANT_TARGETS = {
    "availability": {"target": 0.99},
    "ask_p99": {"target": 0.99, "threshold_ms": 2000.0},
    "shed_rate": {"target": 0.90},
}


class Objective:
    """One SLO: a name, a target fraction of GOOD events, and the
    bounded ring of per-minute (bucket_start, good, bad) counts it is
    evaluated over."""

    __slots__ = ("name", "target", "threshold_ms", "_buckets")

    def __init__(self, name, target, threshold_ms=None):
        self.name = str(name)
        self.target = float(target)
        if not 0.0 < self.target < 1.0:
            raise ValueError(
                f"SLO target must be in (0, 1), got {target!r} for {name}")
        self.threshold_ms = (None if threshold_ms is None
                             else float(threshold_ms))
        self._buckets = deque(maxlen=_MAX_BUCKETS)  # [bucket_ts, good, bad]

    @property
    def budget(self):
        """The error budget as a fraction of events (``1 - target``)."""
        return 1.0 - self.target

    def record(self, ok, now):
        """Count one event into the current minute bucket."""
        b = (now // _BUCKET_SEC) * _BUCKET_SEC
        if self._buckets and self._buckets[-1][0] == b:
            slot = self._buckets[-1]
        elif self._buckets and self._buckets[-1][0] > b:
            # a clock step backwards (or cross-thread skew): fold into
            # the newest bucket rather than corrupting ring order
            slot = self._buckets[-1]
        else:
            slot = [b, 0, 0]
            self._buckets.append(slot)
        slot[1 if ok else 2] += 1

    def window_counts(self, window_sec, now):
        """(good, bad) over the trailing ``window_sec``."""
        cutoff = now - float(window_sec)
        good = bad = 0
        for b, g, bd in reversed(self._buckets):
            if b + _BUCKET_SEC <= cutoff:
                break
            good += g
            bad += bd
        return good, bad

    def burn_rate(self, window_sec, now):
        """``bad_fraction / budget`` over the window; 0.0 with no
        traffic (an idle service is not burning budget)."""
        good, bad = self.window_counts(window_sec, now)
        total = good + bad
        if not total:
            return 0.0
        return (bad / total) / self.budget

    def status(self, now):
        fast = [self.burn_rate(w, now) for w in WINDOWS["fast"]]
        slow = [self.burn_rate(w, now) for w in WINDOWS["slow"]]
        fg, fb = self.window_counts(WINDOWS["fast"][1], now)
        good, bad = self.window_counts(WINDOWS["slow"][1], now)
        total = good + bad
        bad_frac = (bad / total) if total else 0.0
        remaining = 1.0 - bad_frac / self.budget
        # the volume guard (MIN_ALERT_EVENTS) applies to each pair's
        # LONG window: with fewer events the two windows are the same
        # sample and the pair's one-bad-minute veto is void
        return {
            "target": self.target,
            "threshold_ms": self.threshold_ms,
            "window_events": total,
            "burn_fast": min(fast),   # the PAIR alerts on its min: both
            "burn_slow": min(slow),   # windows must exceed the threshold
            "budget_remaining_frac": remaining,
            "fast_alerting": (min(fast) >= FAST_BURN
                              and fg + fb >= MIN_ALERT_EVENTS),
            "slow_alerting": (min(slow) >= SLOW_BURN
                              and total >= MIN_ALERT_EVENTS),
            "exhausted": remaining <= 0.0 and total >= MIN_ALERT_EVENTS,
        }


class SLOPlane:
    """The service's objectives + their gauges + the escalation hook.

    ``targets`` is a ``{name: {"target": .., ...}}`` dict (see
    :data:`DEFAULT_TARGETS`); unknown names are allowed (they count only
    what :meth:`record_request` routes to them — nothing, by default).
    ``metrics`` is the service :class:`~hyperopt_tpu.obs.metrics
    .MetricsRegistry` the ``slo.*`` gauges publish into.  ``clock`` is
    injectable wall time (fake-clock tests).  Thread-safe; no threads of
    its own."""

    def __init__(self, targets=None, metrics=None, clock=time.time,
                 escalation=None, eval_interval=1.0,
                 escalation_cooldown=600.0):
        targets = DEFAULT_TARGETS if targets is None else targets
        self.objectives = {}
        for name, spec in targets.items():
            self.objectives[name] = Objective(
                name, spec["target"],
                threshold_ms=spec.get("threshold_ms"))
        self.metrics = metrics
        self._clock = clock
        self.escalation = escalation
        self.eval_interval = float(eval_interval)
        self.escalation_cooldown = float(escalation_cooldown)
        self._lock = threading.Lock()
        self._last_eval = None
        self._fast_was_alerting = False
        self._last_escalation = None
        self.escalations = 0

    # -- recording ---------------------------------------------------------

    def record_request(self, endpoint, status, latency_sec=None,
                       shed=False, now=None):
        """Feed one finished request.  ``endpoint`` is the metric-label
        endpoint name (``ask``/``tell``/...); ``status`` the HTTP
        status; ``shed`` marks an overload shed (the 429s that came from
        the admission guard, not quota conflicts).  Routing:

        * availability counts EVERY request, bad = 5xx;
        * ask_latency counts served asks (2xx), bad = slower than its
          threshold;
        * shed_rate counts offered asks, bad = shed.
        """
        now = self._clock() if now is None else now
        with self._lock:
            avail = self.objectives.get("availability")
            if avail is not None:
                avail.record(status < 500, now)
            if endpoint == "ask":
                lat = self.objectives.get("ask_latency")
                if (lat is not None and 200 <= status < 300
                        and latency_sec is not None):
                    ok = (lat.threshold_ms is None
                          or latency_sec * 1e3 <= lat.threshold_ms)
                    lat.record(ok, now)
                sr = self.objectives.get("shed_rate")
                if sr is not None:
                    sr.record(not shed, now)
        self._maybe_evaluate(now)

    def add_objective(self, name, spec):
        """Install one more objective after construction (the server
        adds the quality plane's ``stagnation`` objective this way when
        both planes are armed).  Idempotent: an existing objective keeps
        its ring."""
        with self._lock:
            if name not in self.objectives:
                self.objectives[name] = Objective(
                    name, spec["target"],
                    threshold_ms=spec.get("threshold_ms"))

    def record_quality(self, stagnant, now=None):
        """Feed one live tell into the ``stagnation`` objective: good =
        the study is NOT stagnant after folding the result.  No-op when
        the objective was never installed (quality SLO disarmed)."""
        now = self._clock() if now is None else now
        with self._lock:
            obj = self.objectives.get("stagnation")
            if obj is None:
                return
            obj.record(not stagnant, now)
        self._maybe_evaluate(now)

    def record_load(self, balanced, now=None):
        """Feed one load observation into the ``imbalance`` objective:
        good = the fleet's heat skew sat within its bound when the load
        gauges refreshed.  No-op when the objective was never installed
        (load SLO disarmed)."""
        now = self._clock() if now is None else now
        with self._lock:
            obj = self.objectives.get("imbalance")
            if obj is None:
                return
            obj.record(bool(balanced), now)
        self._maybe_evaluate(now)

    def record_event(self, objective, ok, now=None):
        """Feed one pre-judged boolean into any installed objective by
        name (the per-tenant ``tenant:<id>:<name>`` objectives ride
        this — the server judges good/bad from the response it already
        has and this plane only does the burn math).  No-op when the
        objective was never installed."""
        now = self._clock() if now is None else now
        with self._lock:
            obj = self.objectives.get(str(objective))
            if obj is None:
                return
            obj.record(bool(ok), now)
        self._maybe_evaluate(now)

    def record_probe(self, objective, ok, now=None):
        """Feed one blackbox-probe observation into a ``probe_*``
        objective (the prober judges good/bad client-side — request
        succeeded, ask under threshold, cycle matched golden — and this
        plane only does the burn math).  No-op when the objective was
        never installed (probe SLO disarmed)."""
        now = self._clock() if now is None else now
        with self._lock:
            obj = self.objectives.get(str(objective))
            if obj is None:
                return
            obj.record(bool(ok), now)
        self._maybe_evaluate(now)

    # -- evaluation --------------------------------------------------------

    def status(self, now=None):
        """Per-objective status dict (the ``/snapshot`` + report
        section)."""
        now = self._clock() if now is None else now
        with self._lock:
            return {name: obj.status(now)
                    for name, obj in sorted(self.objectives.items())}

    def any_exhausted(self, now=None):
        return any(s["exhausted"] and s["window_events"]
                   for s in self.status(now).values())

    def publish(self, now=None):
        """Evaluate every objective and set the ``slo.*`` gauges;
        returns the status dict.  Called from the scrape/snapshot paths
        and (rate-limited) from :meth:`record_request`."""
        now = self._clock() if now is None else now
        st = self.status(now)
        if self.metrics is not None:
            for name, s in st.items():
                g = f"slo.{name}"
                self.metrics.gauge(f"{g}.burn_fast").set(s["burn_fast"])
                self.metrics.gauge(f"{g}.burn_slow").set(s["burn_slow"])
                self.metrics.gauge(f"{g}.budget_remaining_frac").set(
                    s["budget_remaining_frac"])
                self.metrics.gauge(f"{g}.fast_alerting").set(
                    1.0 if s["fast_alerting"] else 0.0)
                self.metrics.gauge(f"{g}.slow_alerting").set(
                    1.0 if s["slow_alerting"] else 0.0)
                self.metrics.gauge(f"{g}.exhausted").set(
                    1.0 if s["exhausted"] else 0.0)
        self._check_escalation(st, now)
        return st

    def _maybe_evaluate(self, now):
        """Rate-limited publish on the record path, so gauges and the
        escalation edge stay live even when nothing scrapes."""
        with self._lock:
            if (self._last_eval is not None
                    and now - self._last_eval < self.eval_interval):
                return
            self._last_eval = now
        self.publish(now)

    def _check_escalation(self, st, now):
        """Edge-triggered, cooled-down escalation: fire ONCE when the
        fast pair newly alerts on any objective with real traffic (the
        hook runs a bounded profiler capture — firing it per scrape
        would melt the thing it is trying to observe)."""
        alerting = any(s["fast_alerting"] and s["window_events"]
                       for s in st.values())
        fire = False
        with self._lock:
            if alerting and not self._fast_was_alerting:
                if (self._last_escalation is None
                        or now - self._last_escalation
                        >= self.escalation_cooldown):
                    self._last_escalation = now
                    self.escalations += 1
                    fire = True
            self._fast_was_alerting = alerting
        if fire:
            if self.metrics is not None:
                self.metrics.counter("slo.escalations").inc()
            hook = self.escalation
            if hook is not None:
                try:
                    hook()
                except Exception as e:  # noqa: BLE001 - never cascade
                    logger.warning("slo escalation hook failed: %s", e)
