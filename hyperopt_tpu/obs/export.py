"""Chrome / Perfetto trace-event export for obs JSONL streams.

The ASCII report (``obs.report``) answers "where did the time go" in
aggregate; a trace viewer answers it *visually*, span by span, across
threads and controllers.  This module converts any obs JSONL stream —
including the merged per-controller streams one ``fmin_multihost`` run
writes — into the Trace Event Format every Chrome-lineage viewer loads
(``chrome://tracing``, https://ui.perfetto.dev)::

    python -m hyperopt_tpu.obs.report --export-trace run.trace.json run.jsonl
    python -m hyperopt_tpu.obs.report --export-trace mh.trace.json \
        run.p0.jsonl run.p1.jsonl        # controllers as track groups

Mapping (one ``pid`` per input stream — Perfetto renders each as its own
process track group, named after the stream):

* ``kind="span"``   → complete ``X`` events (start ``ts``, ``dur``), one
  ``tid`` track per recording thread (span records carry ``thread``);
  depth/nesting is recovered by the viewer from containment.
* ``kind="event"``  → instant ``i`` events on the emitting track.
* ``kind="trial_event"`` → instant events on a dedicated ``trials`` track
  (the lifecycle waterfall as a timeline).
* ``kind="stall"`` / ``"flight_dump"`` / ``"open_span"`` → instant events
  on a ``forensics`` track, stacks and heartbeats in ``args``.
* ``kind="health"`` → ``C`` counter tracks (``ei_p50``, ``dup_rate``) so
  search health plots right under the span timeline.
* metric snapshots are skipped (they are end-of-run aggregates, not
  timeline points).

Events are emitted sorted by ``(pid, tid, ts)`` with metadata (``M``)
records first — the invariant ``scripts/validate_trace.py`` checks.

All ``ts``/``dur`` are microseconds (the trace-event unit); absolute epoch
timestamps are kept, which viewers handle fine and which lets merged
controller streams align on real time.
"""

from __future__ import annotations

import json

__all__ = ["to_trace_events", "export_trace", "write_trace"]

# reserved per-stream tids; real recording threads allocate upward from 10
_TID_MAIN = 0
_TID_TRIALS = 1
_TID_FORENSICS = 2
_TID_COUNTERS = 3

_COUNTER_STATS = ("ei_p50", "dup_rate")


def _us(ts):
    return float(ts) * 1e6


class _Tids:
    """Stable thread-name → tid allocation for one stream."""

    def __init__(self):
        self._by_name = {"MainThread": _TID_MAIN}
        self._next = 10

    def get(self, name):
        tid = self._by_name.get(name)
        if tid is None:
            tid = self._by_name[name] = self._next
            self._next += 1
        return tid

    def items(self):
        return sorted(self._by_name.items(), key=lambda kv: kv[1])


def to_trace_events(records, pid=0, name=None):
    """Convert one stream's records into trace events (unsorted; callers
    go through :func:`export_trace`, which sorts and adds nothing else)."""
    tids = _Tids()
    events = []
    used_tracks = set()

    def instant(tid, ev_name, ts, cat, args=None):
        e = {"name": ev_name, "ph": "i", "ts": _us(ts), "pid": pid,
             "tid": tid, "cat": cat, "s": "t"}
        if args:
            e["args"] = args
        used_tracks.add(tid)
        events.append(e)

    for r in records:
        kind = r.get("kind")
        ts = r.get("ts")
        if ts is None:
            continue
        if kind == "span":
            tid = tids.get(r.get("thread", "MainThread"))
            used_tracks.add(tid)
            args = dict(r.get("attrs") or {})
            for k in ("cpu_sec", "span_id", "parent_id", "run_id", "error"):
                if r.get(k) is not None:
                    args[k] = r[k]
            events.append({
                "name": r.get("name", "?"), "ph": "X", "ts": _us(ts),
                "dur": max(0.0, float(r.get("wall_sec", 0.0))) * 1e6,
                "pid": pid, "tid": tid, "cat": "span",
                "args": args,
            })
        elif kind == "event":
            instant(tids.get(r.get("thread", "MainThread")),
                    r.get("name", "event"), ts, "event",
                    r.get("attrs") or None)
        elif kind == "trial_event":
            instant(_TID_TRIALS,
                    f"{r.get('event', '?')} tid={r.get('tid')}", ts, "trial",
                    {k: v for k, v in r.items()
                     if k not in ("kind", "ts")} or None)
        elif kind == "stall":
            instant(_TID_FORENSICS, "stall", ts, "forensics",
                    {"quiet_for_sec": r.get("quiet_for_sec"),
                     "last_heartbeats": r.get("last_heartbeats"),
                     "stacks": r.get("stacks")})
        elif kind == "flight_dump":
            instant(_TID_FORENSICS, f"flight_dump:{r.get('reason', '?')}",
                    ts, "forensics", {"pid": r.get("pid"),
                                      "n_records": r.get("n_records")})
        elif kind == "open_span":
            instant(_TID_FORENSICS, f"open:{r.get('name', '?')}", ts,
                    "forensics", {"age_sec": r.get("age_sec"),
                                  "thread": r.get("thread")})
        elif kind == "health":
            for stat in _COUNTER_STATS:
                v = r.get(stat)
                if v is not None:
                    used_tracks.add(_TID_COUNTERS)
                    events.append({
                        "name": stat, "ph": "C", "ts": _us(ts), "pid": pid,
                        "tid": _TID_COUNTERS, "cat": "health",
                        "args": {stat: float(v)},
                    })

    meta = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": name or f"stream-{pid}"}}]
    reserved = {_TID_TRIALS: "trials", _TID_FORENSICS: "forensics",
                _TID_COUNTERS: "health"}
    for tname, tid in tids.items():
        if tid in used_tracks:
            meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                         "tid": tid, "args": {"name": tname}})
    for tid, tname in reserved.items():
        if tid in used_tracks:
            meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                         "tid": tid, "args": {"name": tname}})
    return meta + events


def export_trace(streams):
    """``[(name, records-iterable)]`` → a trace-event JSON object.  Each
    stream becomes its own ``pid`` track group (the multi-controller merge
    view); events are sorted ``(pid, tid, ts)``, metadata first — the
    layout ``scripts/validate_trace.py`` pins."""
    meta, events = [], []
    for pid, (name, records) in enumerate(streams):
        for e in to_trace_events(records, pid=pid, name=name):
            (meta if e["ph"] == "M" else events).append(e)
    events.sort(key=lambda e: (e["pid"], e["tid"], e["ts"]))
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def write_trace(path, streams):
    """Export ``streams`` and write the trace JSON to ``path``; returns the
    event count."""
    trace = export_trace(streams)
    with open(path, "w") as f:
        json.dump(trace, f)
    return len(trace["traceEvents"])
