"""Chrome / Perfetto trace-event export for obs JSONL streams.

The ASCII report (``obs.report``) answers "where did the time go" in
aggregate; a trace viewer answers it *visually*, span by span, across
threads and controllers.  This module converts any obs JSONL stream —
including the merged per-controller streams one ``fmin_multihost`` run
writes — into the Trace Event Format every Chrome-lineage viewer loads
(``chrome://tracing``, https://ui.perfetto.dev)::

    python -m hyperopt_tpu.obs.report --export-trace run.trace.json run.jsonl
    python -m hyperopt_tpu.obs.report --export-trace mh.trace.json \
        run.p0.jsonl run.p1.jsonl        # controllers as track groups

Mapping (one ``pid`` per input stream — Perfetto renders each as its own
process track group, named after the stream):

* ``kind="span"``   → complete ``X`` events (start ``ts``, ``dur``), one
  ``tid`` track per recording thread (span records carry ``thread``);
  depth/nesting is recovered by the viewer from containment.
* ``kind="event"``  → instant ``i`` events on the emitting track.
* ``kind="trial_event"`` → instant events on a dedicated ``trials`` track
  (the lifecycle waterfall as a timeline).
* ``kind="stall"`` / ``"flight_dump"`` / ``"open_span"`` → instant events
  on a ``forensics`` track, stacks and heartbeats in ``args``.
* ``kind="health"`` → ``C`` counter tracks (``ei_p50``, ``dup_rate``) so
  search health plots right under the span timeline.
* ``kind="profile"`` → instant events on the ``forensics`` track (the
  postmortem pointer to a device capture), and the capture's own
  ``*.trace.json.gz`` artifact merges in as additional *device* process
  track groups (see below).
* metric snapshots → ``C`` counter points for the per-program roofline
  (``roofline.<program>`` achieved GFLOP/s, joining the captured
  ``cost_analysis()`` gauges with the measured execute spans) at the
  snapshot's timestamp; everything else in a snapshot stays an
  end-of-run aggregate and is skipped.

**Device captures.**  A ``jax.profiler`` capture (obs/profiler.py) writes
its own trace-event JSON with profiler-relative microsecond timestamps
and arbitrary pids.  :func:`device_trace_events` folds one such artifact
into the merged export: pids are remapped into a reserved range (1000+)
so they can never collide with the host streams, process names get a
``device:`` prefix, and every timestamp is shifted by the capture's
recorded wall-clock epoch so host spans and device kernels align on one
timeline.  ``obs.report --export-trace`` does this automatically for
every ``kind="profile"`` record whose artifact still exists.

Events are emitted sorted by ``(pid, tid, ts)`` with metadata (``M``)
records first — the invariant ``scripts/validate_trace.py`` checks.

All ``ts``/``dur`` are microseconds (the trace-event unit); absolute epoch
timestamps are kept, which viewers handle fine and which lets merged
controller streams align on real time.
"""

from __future__ import annotations

import gzip
import json

__all__ = ["to_trace_events", "export_trace", "write_trace",
           "device_trace_events", "flow_events"]

#: device-capture track groups are remapped to pids >= this, far above any
#: realistic host-stream count, so the two namespaces can never collide
DEVICE_PID_BASE = 1000

# reserved per-stream tids; real recording threads allocate upward from 10
_TID_MAIN = 0
_TID_TRIALS = 1
_TID_FORENSICS = 2
_TID_COUNTERS = 3

_COUNTER_STATS = ("ei_p50", "dup_rate")


def _us(ts):
    return float(ts) * 1e6


class _Tids:
    """Stable thread-name → tid allocation for one stream."""

    def __init__(self):
        self._by_name = {"MainThread": _TID_MAIN}
        self._next = 10

    def get(self, name):
        tid = self._by_name.get(name)
        if tid is None:
            tid = self._by_name[name] = self._next
            self._next += 1
        return tid

    def items(self):
        return sorted(self._by_name.items(), key=lambda kv: kv[1])


def to_trace_events(records, pid=0, name=None):
    """Convert one stream's records into trace events (unsorted; callers
    go through :func:`export_trace`, which sorts and adds nothing else)."""
    tids = _Tids()
    events = []
    used_tracks = set()

    def instant(tid, ev_name, ts, cat, args=None):
        e = {"name": ev_name, "ph": "i", "ts": _us(ts), "pid": pid,
             "tid": tid, "cat": cat, "s": "t"}
        if args:
            e["args"] = args
        used_tracks.add(tid)
        events.append(e)

    for r in records:
        kind = r.get("kind")
        ts = r.get("ts")
        if ts is None:
            continue
        if kind == "span":
            tid = tids.get(r.get("thread", "MainThread"))
            used_tracks.add(tid)
            args = dict(r.get("attrs") or {})
            for k in ("cpu_sec", "span_id", "parent_id", "run_id", "error"):
                if r.get(k) is not None:
                    args[k] = r[k]
            events.append({
                "name": r.get("name", "?"), "ph": "X", "ts": _us(ts),
                "dur": max(0.0, float(r.get("wall_sec", 0.0))) * 1e6,
                "pid": pid, "tid": tid, "cat": "span",
                "args": args,
            })
        elif kind == "event":
            instant(tids.get(r.get("thread", "MainThread")),
                    r.get("name", "event"), ts, "event",
                    r.get("attrs") or None)
            # quality improvements (ISSUE 16) also plot as a best-loss
            # counter track per study: the convergence curve rendered
            # right under the serving spans that produced it
            if r.get("name") == "quality.improvement":
                attrs = r.get("attrs") or {}
                best = attrs.get("best")
                sid = attrs.get("study")
                if best is not None and sid is not None:
                    used_tracks.add(_TID_COUNTERS)
                    events.append({
                        "name": f"best_loss.{sid}", "ph": "C",
                        "ts": _us(ts), "pid": pid,
                        "tid": _TID_COUNTERS, "cat": "quality",
                        "args": {"best_loss": float(best)},
                    })
        elif kind == "trial_event":
            instant(_TID_TRIALS,
                    f"{r.get('event', '?')} tid={r.get('tid')}", ts, "trial",
                    {k: v for k, v in r.items()
                     if k not in ("kind", "ts")} or None)
        elif kind == "stall":
            instant(_TID_FORENSICS, "stall", ts, "forensics",
                    {"quiet_for_sec": r.get("quiet_for_sec"),
                     "last_heartbeats": r.get("last_heartbeats"),
                     "stacks": r.get("stacks")})
        elif kind == "flight_dump":
            instant(_TID_FORENSICS, f"flight_dump:{r.get('reason', '?')}",
                    ts, "forensics", {"pid": r.get("pid"),
                                      "n_records": r.get("n_records")})
        elif kind == "open_span":
            instant(_TID_FORENSICS, f"open:{r.get('name', '?')}", ts,
                    "forensics", {"age_sec": r.get("age_sec"),
                                  "thread": r.get("thread")})
        elif kind == "health":
            for stat in _COUNTER_STATS:
                v = r.get(stat)
                if v is not None:
                    used_tracks.add(_TID_COUNTERS)
                    events.append({
                        "name": stat, "ph": "C", "ts": _us(ts), "pid": pid,
                        "tid": _TID_COUNTERS, "cat": "health",
                        "args": {stat: float(v)},
                    })
        elif kind == "profile":
            instant(_TID_FORENSICS, f"profile:{r.get('reason', '?')}", ts,
                    "forensics", {"ok": r.get("ok"), "dir": r.get("dir"),
                                  "trace_json": r.get("trace_json"),
                                  "sec": r.get("sec")})
        elif kind == "metrics":
            # per-program roofline counters: one point per embedded
            # snapshot (a multi-run() stream plots a real series).  The
            # join itself lives in health.roofline_table — the single
            # formula behind /snapshot, obs.report and these counters.
            from .health import roofline_table

            dev = (((r.get("snapshot") or {}).get("shared") or {})
                   .get("device") or {}).get("metrics", {})
            for st, row in roofline_table(dev).items():
                flops_per_sec = row.get("achieved_flops_per_sec")
                if flops_per_sec is None:
                    continue  # cost captured but no execute spans yet
                used_tracks.add(_TID_COUNTERS)
                events.append({
                    "name": f"roofline.{st}", "ph": "C", "ts": _us(ts),
                    "pid": pid, "tid": _TID_COUNTERS, "cat": "roofline",
                    "args": {"gflops": flops_per_sec / 1e9},
                })
            # per-shard heat counter tracks (ISSUE 17): cumulative
            # shard heat plotted under the serving spans — from the
            # service.load.shard.* gauges (flat metric snapshots and
            # /snapshot-shaped `sections.service` embeds) or the
            # snapshot's own `load.shards` table
            snap = r.get("snapshot") or {}
            heats = {}
            for src in (snap.get("metrics") or {},
                        (snap.get("sections") or {}).get("service") or {}):
                for mname, v in src.items():
                    if (mname.startswith("service.load.shard.")
                            and mname.endswith(".heat_ms")
                            and isinstance(v, (int, float))):
                        shard = mname[len("service.load.shard."):
                                      -len(".heat_ms")]
                        heats[shard] = float(v)
            shards_tbl = (snap.get("load") or {}).get("shards") or {}
            for shard, row in shards_tbl.items():
                if isinstance(row, dict) and row.get("heat_ms") is not None:
                    heats.setdefault(str(shard), float(row["heat_ms"]))
            for shard, v in sorted(heats.items()):
                used_tracks.add(_TID_COUNTERS)
                events.append({
                    "name": f"heat.shard{shard}", "ph": "C",
                    "ts": _us(ts), "pid": pid, "tid": _TID_COUNTERS,
                    "cat": "load", "args": {"heat_ms": v},
                })
            # per-tenant counter tracks (ISSUE 20): each tenant's
            # cumulative device time plotted alongside the shard heat —
            # from the service.tenant.<t>.device_ms gauges or the
            # snapshot's own `tenants.table`
            ten_ms = {}
            for src in (snap.get("metrics") or {},
                        (snap.get("sections") or {}).get("service") or {}):
                for mname, v in src.items():
                    if (mname.startswith("service.tenant.")
                            and mname.endswith(".device_ms")
                            and isinstance(v, (int, float))):
                        t = mname[len("service.tenant."):
                                  -len(".device_ms")]
                        ten_ms[t] = float(v)
            ten_tbl = (snap.get("tenants") or {}).get("table") or {}
            for t, row in ten_tbl.items():
                if isinstance(row, dict) and row.get("device_ms") is not None:
                    ten_ms.setdefault(str(t), float(row["device_ms"]))
            for t, v in sorted(ten_ms.items()):
                used_tracks.add(_TID_COUNTERS)
                events.append({
                    "name": f"tenant.{t}", "ph": "C",
                    "ts": _us(ts), "pid": pid, "tid": _TID_COUNTERS,
                    "cat": "tenant", "args": {"device_ms": v},
                })

    meta = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": name or f"stream-{pid}"}}]
    reserved = {_TID_TRIALS: "trials", _TID_FORENSICS: "forensics",
                _TID_COUNTERS: "health"}
    for tname, tid in tids.items():
        if tid in used_tracks:
            meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                         "tid": tid, "args": {"name": tname}})
    for tid, tname in reserved.items():
        if tid in used_tracks:
            meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                         "tid": tid, "args": {"name": tname}})
    return meta + events


_DEVICE_PH_KEEP = {"X", "i", "I", "C", "M"}


def device_trace_events(path, pid_base, name=None, epoch_offset_sec=None):
    """One ``jax.profiler`` capture artifact (``*.trace.json.gz`` or plain
    ``.json``) → ``(events, n_pids)`` ready to merge into the host export.

    * original pids remap densely onto ``pid_base + i`` (the reserved
      device range — host streams can never collide);
    * ``process_name`` metadata gets a ``device:<capture name>:`` prefix,
      and any pid the capture left unnamed gets one synthesized (the
      merged-artifact lint requires every track group named);
    * non-metadata timestamps shift by ``epoch_offset_sec`` (the capture's
      recorded wall-clock start) so device kernels line up with the
      host spans' absolute-epoch microseconds; negative timestamps clamp
      to the capture start;
    * only viewer-meaningful phases survive (``X i I C M``); ``X`` events
      missing a duration get ``dur=0``.
    """
    opener = gzip.open if str(path).endswith(".gz") else open
    with opener(path, "rt") as f:
        data = json.load(f)
    raw = data.get("traceEvents") if isinstance(data, dict) else data
    if not isinstance(raw, list):
        return [], 0
    off_us = float(epoch_offset_sec) * 1e6 if epoch_offset_sec else 0.0
    pid_map = {}
    named = set()
    meta, events = [], []
    for e in raw:
        if not isinstance(e, dict) or e.get("ph") not in _DEVICE_PH_KEEP:
            continue
        orig_pid = e.get("pid")
        if not isinstance(orig_pid, int):
            continue
        pid = pid_map.setdefault(orig_pid, pid_base + len(pid_map))
        if e["ph"] == "M":
            m = dict(e)
            m["pid"] = pid
            m.setdefault("tid", 0)
            if m.get("name") == "process_name":
                orig = (m.get("args") or {}).get("name", orig_pid)
                m["args"] = {"name": f"device:{name or 'capture'}:{orig}"}
                named.add(pid)
            meta.append(m)
            continue
        ts = e.get("ts")
        tid = e.get("tid")
        if not isinstance(ts, (int, float)) or not isinstance(tid, int):
            continue
        out = dict(e)
        out["pid"] = pid
        out["ts"] = max(0.0, float(ts)) + off_us
        if e["ph"] == "X" and not isinstance(e.get("dur"), (int, float)):
            out["dur"] = 0.0
        events.append(out)
    for orig_pid, pid in pid_map.items():
        if pid not in named:
            meta.append({"name": "process_name", "ph": "M", "pid": pid,
                         "tid": 0,
                         "args": {"name": f"device:{name or 'capture'}:"
                                          f"{orig_pid}"}})
    return meta + events, len(pid_map)


def _flow_id(trace_id):
    """Stable integer flow id from a trace id's leading hex (60 bits —
    comfortably inside the signed-64 range viewers assume)."""
    return int(str(trace_id)[:15] or "0", 16)


def flow_events(events):
    """Request-trace flow events (ISSUE 11): every ``X`` span stamped
    with a ``trace`` attr (or ``links`` list — the wave span's fan-in)
    joins that trace's flow.  One flow per trace id, rendered by
    Perfetto as a connected arc across pid track groups: client attempt
    → server handler → wave → cohort tick.

    Chrome flow-event grammar: ``s`` (start) on the first slice, ``t``
    (step) on each middle one, ``f`` (finish, ``bp: "e"``) on the last —
    each bound to its slice by (pid, tid) and a ``ts`` inside the
    slice.  Flows with fewer than two slices are dropped (nothing to
    connect).  ``scripts/validate_trace.py`` lints exactly these
    invariants (no dangling ids, binding slices exist)."""
    by_trace = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        args = e.get("args") or {}
        hits = set()
        t = args.get("trace")
        if isinstance(t, str) and t:
            hits.add(t)
        links = args.get("links")
        if isinstance(links, list):
            hits.update(x for x in links if isinstance(x, str) and x)
        for t in hits:
            by_trace.setdefault(t, []).append(e)
    flows = []
    for t, slices in sorted(by_trace.items()):
        if len(slices) < 2:
            continue  # a single-hop flow draws no arc
        slices.sort(key=lambda e: e["ts"])
        try:
            fid = _flow_id(t)
        except ValueError:
            # a foreign producer's non-hex trace attr must not kill the
            # whole export (the torn-line/fail-open posture); its spans
            # still render, only the connecting arc is skipped
            continue
        for i, e in enumerate(slices):
            ph = "s" if i == 0 else ("f" if i == len(slices) - 1 else "t")
            f = {"name": "reqtrace", "cat": "reqtrace", "ph": ph,
                 "id": fid, "ts": e["ts"], "pid": e["pid"],
                 "tid": e["tid"], "args": {"trace": t}}
            if ph == "f":
                f["bp"] = "e"  # bind to the ENCLOSING slice, not the next
            flows.append(f)
    return flows


def export_trace(streams, device_traces=()):
    """``[(name, records-iterable)]`` → a trace-event JSON object.  Each
    stream becomes its own ``pid`` track group (the multi-controller merge
    view); ``device_traces`` — ``[(name, artifact path, epoch t0), ...]``
    from ``kind="profile"`` records — merge in as device track groups in
    the reserved pid range.  Spans carrying request-trace ids
    additionally emit flow events (:func:`flow_events`) so one trace
    renders as a connected client→handler→wave→device arc.  Events are
    sorted ``(pid, tid, ts)``, metadata first — the layout
    ``scripts/validate_trace.py`` pins."""
    meta, events = [], []
    for pid, (name, records) in enumerate(streams):
        for e in to_trace_events(records, pid=pid, name=name):
            (meta if e["ph"] == "M" else events).append(e)
    events.extend(flow_events(events))
    pid_base = DEVICE_PID_BASE
    for name, path, t0 in device_traces:
        try:
            merged, n_pids = device_trace_events(
                path, pid_base, name=name, epoch_offset_sec=t0)
        except (OSError, ValueError) as e:
            # a vanished/corrupt capture artifact degrades to a skipped
            # track group, never a failed export of the host spans
            import logging

            logging.getLogger(__name__).warning(
                "skipping device capture %s: %s", path, e)
            continue
        pid_base += n_pids
        for e in merged:
            (meta if e["ph"] == "M" else events).append(e)
    events.sort(key=lambda e: (e["pid"], e["tid"], e["ts"]))
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def write_trace(path, streams, device_traces=()):
    """Export ``streams`` (+ any device captures) and write the trace JSON
    to ``path``; returns the event count."""
    trace = export_trace(streams, device_traces=device_traces)
    with open(path, "w") as f:
        json.dump(trace, f)
    return len(trace["traceEvents"])
