"""hyperopt_tpu.obs — unified run telemetry: spans, metrics, trial events.

The paper's pitch is "as fast as the hardware allows"; this package is how
a run *proves* where its time goes.  Three pillars, one config:

* :mod:`~hyperopt_tpu.obs.trace` — nested spans (wall + CPU time,
  structured attrs) streamed as JSONL; absorbs the old ``PhaseTimings``.
* :mod:`~hyperopt_tpu.obs.metrics` — process-global, per-namespace
  counters / gauges / bounded histograms with deterministic snapshots.
* :mod:`~hyperopt_tpu.obs.events` — durable trial-lifecycle event log
  (``FileStore`` persists it as an attachment for post-mortems).

Plus the crash/stall forensics layer that works even when nothing above is
armed:

* :mod:`~hyperopt_tpu.obs.flight` — always-on bounded ring of recent
  records, dumped to ``<run>.flight.jsonl`` on fatal signals, unhandled
  exceptions and atexit (render with ``obs.report --postmortem``).
* :mod:`~hyperopt_tpu.obs.watchdog` — stall detector over heartbeats from
  all four execution paths; emits ``kind="stall"`` records with thread
  stacks (``HYPEROPT_TPU_WATCHDOG=<quiet seconds>``).
* :mod:`~hyperopt_tpu.obs.export` — Chrome/Perfetto trace-event export
  (``obs.report --export-trace out.json run.jsonl``).

And the request-scoped plane for the serving fleet (ISSUE 11):

* :mod:`~hyperopt_tpu.obs.reqtrace` — W3C-traceparent-style trace
  context (one trace id per logical client request, contextvar-carried)
  threaded client → handler → wave → tick → WAL.
* :mod:`~hyperopt_tpu.obs.slo` — declarative SLO objectives evaluated
  as multi-window burn rates (``slo_*`` gauges on ``/metrics``, an
  escalation hook into the device profiler).

One flag arms everything: ``HYPEROPT_TPU_OBS=<run.jsonl>`` (or the ``obs=``
kwarg on ``fmin``/``fmin_multihost``) turns on the JSONL stream, and the
pre-existing ``HYPEROPT_TPU_PROFILE=<dir>`` ``jax.profiler`` hook now rides
the same :class:`ObsConfig`.  Render a captured run with::

    python -m hyperopt_tpu.obs.report run.jsonl
"""

from __future__ import annotations

import contextlib
import dataclasses
import itertools
import logging
import os
import time

from . import events as events_mod
from . import flight as flight_mod
from . import watchdog as watchdog_mod
from .events import EventLog
from .flight import FlightRecorder, flight_path_for, get_flight
from .metrics import MetricsRegistry, adopt_metrics, get_metrics, reset_metrics
from .profiler import annotation_ctx
from .trace import JsonlSink, PhaseTimings, Tracer, iter_jsonl, read_jsonl
from .watchdog import Watchdog, get_watchdog

__all__ = [
    "ObsConfig",
    "RunObs",
    "Tracer",
    "JsonlSink",
    "PhaseTimings",
    "EventLog",
    "MetricsRegistry",
    "FlightRecorder",
    "Watchdog",
    "get_flight",
    "get_watchdog",
    "flight_path_for",
    "get_metrics",
    "reset_metrics",
    "adopt_metrics",
    "iter_jsonl",
    "read_jsonl",
]

logger = logging.getLogger(__name__)

_run_counter = itertools.count(1)


@dataclasses.dataclass
class ObsConfig:
    """Everything that arms a run's telemetry, in one object.

    ``level``:

    * ``"off"``   — no aggregation at all (phase timings still accumulate:
      they are load-bearing API, not telemetry).
    * ``"basic"`` — the default: in-memory metrics + phase totals, no I/O.
    * ``"trace"`` — additionally stream every span/event/metric snapshot to
      ``jsonl_path``.

    ``profile_dir`` arms the bounded device-capture plane
    (:mod:`~hyperopt_tpu.obs.profiler`): programmatic / ``/profile?sec=N``
    / stall-escalation ``jax.profiler`` captures land under this
    directory, and the fmin tick, device chunk and driver generation get
    ``TraceAnnotation`` ids on the device timeline.  ``profile_full``
    keeps the legacy whole-run ``jax.profiler.trace`` wrapper instead
    (``HYPEROPT_TPU_PROFILE=full:<dir>``) — the two are exclusive per run
    because jax allows one trace session per process, and a whole-run
    session would starve every bounded capture.

    ``flight_path`` pins the flight-recorder crash-dump path explicitly
    (``HYPEROPT_TPU_FLIGHT=<path>``); left None it derives from
    ``jsonl_path`` (``run.jsonl`` → ``run.flight.jsonl``) or, for fully
    disarmed runs, falls back to the recorder's cwd default on abnormal
    death only.  The ring itself is always on regardless of ``level``
    (disable the whole recorder with ``HYPEROPT_TPU_FLIGHT=0``).

    ``http_port`` arms the live scrape server (``obs/serve.py``:
    ``/metrics`` + ``/snapshot`` + ``/events``) — ``HYPEROPT_TPU_OBS_HTTP``
    or ``fmin(obs_http=<port>)``; 0 binds an ephemeral port, and a
    ``"host:port"`` string binds beyond the loopback default (remote
    Prometheus / cross-host ``obs.top``).
    ``devmem_period`` arms device-memory telemetry (``obs/devmem.py``)
    at that sample period in seconds — ``HYPEROPT_TPU_DEVMEM``.  Both are
    independent of ``level`` (registry scraping needs no JSONL stream) and
    both fail open: a bad env value, an occupied port, or a backend
    without ``memory_stats`` warn once and disable.
    """

    level: str = "basic"
    jsonl_path: str | None = None
    profile_dir: str | None = None  # bounded-capture plane (obs/profiler.py)
    profile_full: str | None = None  # legacy whole-run jax.profiler.trace
    run_id: str | None = None
    flight_path: str | None = None
    http_port: int | str | None = None  # port, or "host:port"
    devmem_period: float | None = None

    @classmethod
    def from_env(cls, env=None):
        from .._env import parse_devmem_period, parse_obs_http
        from .profiler import split_profile_mode

        env = os.environ if env is None else env
        raw = env.get("HYPEROPT_TPU_OBS", "").strip()
        profile_dir, profile_full = split_profile_mode(
            env.get("HYPEROPT_TPU_PROFILE", ""))
        raw_flight = env.get("HYPEROPT_TPU_FLIGHT", "").strip()
        # "0"/"off" (handled by flight.get_flight) and bare "1" are not
        # paths; anything else names the dump file
        flight_path = (raw_flight
                       if raw_flight not in ("", "0", "1", "off") else None)
        if raw in ("", "1", "basic"):
            level, jsonl_path = "basic", None
        elif raw in ("0", "off"):
            level, jsonl_path = "off", None
        else:  # a path arms the full trace stream
            level, jsonl_path = "trace", raw
        return cls(level=level, jsonl_path=jsonl_path,
                   profile_dir=profile_dir, profile_full=profile_full,
                   flight_path=flight_path,
                   http_port=parse_obs_http(env),
                   devmem_period=parse_devmem_period(env))

    @classmethod
    def resolve(cls, obs):
        """Normalize the ``obs=`` kwarg every entry point accepts: None →
        environment; a string → JSONL path at level "trace"; an ObsConfig →
        itself."""
        if obs is None:
            return cls.from_env()
        if isinstance(obs, cls):
            return obs
        if isinstance(obs, (str, os.PathLike)):
            env_cfg = cls.from_env()
            return cls(level="trace", jsonl_path=str(obs),
                       profile_dir=env_cfg.profile_dir,
                       profile_full=env_cfg.profile_full,
                       flight_path=env_cfg.flight_path,
                       http_port=env_cfg.http_port,
                       devmem_period=env_cfg.devmem_period)
        raise TypeError(f"obs must be None, a path, or ObsConfig; got {obs!r}")


class RunObs:
    """Per-run telemetry bundle: one tracer + one metrics namespace + one
    event log, all honoring one :class:`ObsConfig`.

    The registry namespace is ``run_id`` (process-global registry, per-run
    namespace), so concurrent runs in one process never mix counters while
    anything holding the run id can read the numbers back.
    """

    def __init__(self, config=None, totals=None, run_id=None):
        self.config = config if config is not None else ObsConfig.from_env()
        self.run_id = (run_id or self.config.run_id
                       or f"run-{next(_run_counter)}")
        armed = self.config.level == "trace" and self.config.jsonl_path
        self.sink = JsonlSink(self.config.jsonl_path) if armed else None
        self.tracer = Tracer(sink=self.sink, totals=totals,
                             run_id=self.run_id)
        self.metrics = get_metrics(self.run_id)
        self.events = EventLog(sink=self.sink)
        self._finished = False
        # forensics: always-on flight ring + crash handlers (installed once
        # per process, at the first run).  The dump path is explicit
        # (HYPEROPT_TPU_FLIGHT=<path>), derived from the armed stream, or —
        # for fully disarmed runs — the recorder's abnormal-death default.
        fpath = self.config.flight_path
        if fpath is None and self.config.jsonl_path:
            fpath = flight_path_for(self.config.jsonl_path)
        # a derived target is per-run: finish() removes it so clean exits
        # don't litter; an explicit HYPEROPT_TPU_FLIGHT path is persistent
        self._flight_target = (fpath if self.config.flight_path is None
                               else None)
        self.flight = get_flight().install(fpath)
        self.watchdog = get_watchdog()
        if self.watchdog is not None:
            # stall detection is scoped to live runs: retained here,
            # released by finish() — a process that outlives its runs must
            # not report its own idleness as a stall forever
            self.watchdog.retain()
            if self.sink is not None:
                # armed runs stream stall records next to their spans
                self.watchdog.attach_sink(self.sink)
        # device-profiling plane (obs/profiler.py): arm-optional and
        # thread-free — the DeviceProfiler is a directory + a lock, and a
        # capture runs on whichever thread asked for it (HTTP handler /
        # watchdog).  Armed runs register the once-per-run stall
        # escalation so a hang dies with a device trace next to the
        # flight dump; disarmed runs construct nothing here (profiler.py
        # itself must stay stdlib-only at import time — jax imports live
        # inside capture/annotation calls).
        self.profiler = None
        if self.config.profile_dir:
            from .profiler import DeviceProfiler

            self.profiler = DeviceProfiler(self.config.profile_dir, obs=self)
            if self.watchdog is not None:
                self.watchdog.add_escalation(self.profiler.capture_on_stall)
        # live observability plane (obs/serve.py, obs/devmem.py): both are
        # arm-optional — a disarmed run imports neither module, starts no
        # thread, and its hot path stays exactly the pre-serve code
        self.http = None
        self.devmem = None
        if self.config.devmem_period is not None:
            from .devmem import DevMemSampler

            self.devmem = DevMemSampler(self, period=self.config.devmem_period)
            self.devmem.start()
        if self.config.http_port is not None:
            from .serve import ObsHTTPServer

            http = ObsHTTPServer(self.config.http_port, obs=self)
            # fail-open: an occupied port warned once inside start()
            self.http = http if http.start() else None

    @classmethod
    def resolve(cls, obs, totals=None, run_id=None):
        """``obs=`` kwarg → RunObs: passes an existing RunObs through (so
        ``fmin`` can hand its bundle to the device runner), builds one from
        a config/path/None otherwise."""
        if isinstance(obs, cls):
            return obs
        return cls(ObsConfig.resolve(obs), totals=totals, run_id=run_id)

    # -- sugar used by the instrumented call sites ------------------------

    def span(self, name, **attrs):
        return self.tracer.span(name, **attrs)

    def event(self, name, **attrs):
        self.tracer.event(name, **attrs)

    def heartbeat(self, component, **detail):
        """Feed the stall watchdog (no-op when it is disabled): the four
        execution paths call this at every liveness-proving boundary so a
        quiet period means a real hang, not a slow phase."""
        if self.watchdog is not None:
            self.watchdog.beat(component, **detail)

    def devmem_sample(self):
        """Span-boundary device-memory sample (rate-limited to the
        configured period; obs/devmem.py).  A disarmed run pays one
        attribute check."""
        if self.devmem is not None:
            self.devmem.maybe_sample()

    def trial_event(self, event, tid, **attrs):
        self.events.emit(event, tid, **attrs)

    def counter(self, name):
        return self.metrics.counter(name)

    def gauge(self, name):
        return self.metrics.gauge(name)

    def histogram(self, name):
        return self.metrics.histogram(name)

    def annotate(self, name, **ids):
        """A device-timeline ``TraceAnnotation`` for one loop boundary
        (fmin tick / device chunk / driver generation) when the capture
        plane is armed; a shared null context otherwise — the disarmed
        call sites pay one attribute check, nothing else.  An integer
        ``step=`` id maps to ``StepTraceAnnotation`` (TensorBoard's
        step-time view); every other id becomes a timeline arg, which is
        how captured kernels are attributed to trial/generation ids."""
        return annotation_ctx(self.profiler, name, **ids)

    def profiler_ctx(self):
        """``jax.profiler.trace`` over the whole loop when the LEGACY
        full-trace mode is armed (``HYPEROPT_TPU_PROFILE=full:<dir>``).
        The bare ``<dir>`` form arms the bounded-capture plane instead
        (``self.profiler``; obs/profiler.py) and leaves the loop
        unwrapped, so on-demand ``/profile`` and stall captures can open
        their own — exclusive — trace sessions."""
        pdir = self.config.profile_full
        if not pdir:
            return contextlib.nullcontext()
        import jax

        logger.info("profiling to %s (jax.profiler.trace)", pdir)
        return jax.profiler.trace(pdir)

    def snapshot(self, extra_namespaces=("device",)):
        """This run's metrics snapshot plus the shared device namespace
        (compile/execute split and run-cache hit rates live there because
        the compiled-run cache itself is process-global)."""
        snap = self.metrics.snapshot()
        for ns in extra_namespaces:
            if ns != self.run_id:
                snap.setdefault("shared", {})[ns] = get_metrics(ns).snapshot()
        if self.tracer.totals:
            snap["phase_timings"] = self.tracer.totals.summary()
        return snap

    def finish(self):
        """Flush the run: write the final metrics snapshot to the JSONL
        stream, close the sink's handle (it reopens in append mode if the
        run is re-entered — iterator-protocol fmin), and release this run's
        namespace from the global registry table so a long-lived sweep
        process doesn't grow it without bound.  ``self.metrics`` stays
        alive for anyone holding the bundle; idempotent.  A run re-entered
        after a finish (``for trials in FMinIter(...)``) must :meth:`rearm`
        first, or anything resolving the namespace by run id would get a
        fresh empty registry while the bundle keeps counting into this
        one."""
        if self.devmem is not None and not self._finished:
            # one final sample (the run's last watermark lands in the
            # stream/snapshot), then stop the sampler thread
            self.devmem.sample(reason="finish")
            self.devmem.stop()
        if self.http is not None:
            self.http.stop()
        if self.sink is not None:
            # ts is load-bearing: the Perfetto export drops ts-less
            # records, and this snapshot is what feeds the roofline
            # counter tracks (obs/export.py)
            self.sink.write({"kind": "metrics", "run_id": self.run_id,
                             "ts": time.time(),
                             "snapshot": self.snapshot()})
            if self.watchdog is not None:
                self.watchdog.detach_sink(self.sink)
            self.sink.close()
        if self.watchdog is not None and not self._finished:
            if self.profiler is not None:
                self.watchdog.remove_escalation(self.profiler.capture_on_stall)
            self.watchdog.release()
        if self._flight_target is not None:
            # the run survived: drop its derived dump target so a clean
            # process exit doesn't litter; the ring keeps recording
            self.flight.remove_target(self._flight_target)
        reset_metrics(self.run_id)
        self._finished = True

    def rearm(self):
        """Re-enter a finished run: re-register this bundle's OWN metrics
        registry — accumulated counters and all — under the run id
        (``finish()`` released the namespace; without the explicit re-adopt
        a resumed iterator-protocol ``FMinIter`` would silently split its
        counters between this object and a fresh registry created by the
        next ``get_metrics(run_id)`` caller).  The JSONL sink needs no
        re-arm: it reopens in append mode on the next write.  No-op while
        the run is live; ``FMinIter.run()`` calls this at every entry."""
        if self._finished:
            adopt_metrics(self.run_id, self.metrics)
            if self._flight_target is not None:
                self.flight.add_target(self._flight_target)
            if self.watchdog is not None:
                self.watchdog.retain()
                if self.sink is not None:
                    self.watchdog.attach_sink(self.sink)
                if self.profiler is not None:
                    # a hang in this new leg must still get its (one)
                    # device trace — the budget is per leg, not per object
                    self.profiler.reset_stall_budget()
                    self.watchdog.add_escalation(
                        self.profiler.capture_on_stall)
            if self.devmem is not None:
                self.devmem.start()  # restart the sampler thread
            if self.config.http_port is not None:
                # a shut-down http.server cannot restart: rebuild.  A
                # pinned port that the finished server just released binds
                # again; an ephemeral port may move (url is re-read)
                from .serve import ObsHTTPServer

                http = ObsHTTPServer(self.config.http_port, obs=self)
                self.http = http if http.start() else None
            self._finished = False
