"""Load & cost attribution observatory (ISSUE 17).

ROADMAP item 5's load-aware serving arc needs a measurement the repo
never had: the fleet steward balances shards by *count*
(``ceil(M / live)``), and nothing attributed wave/device time to the
studies, cohorts or shards that consumed it — "who is spending the
fused suggest tick" was unanswerable.  Two halves:

**The cost ledger** (:class:`CostLedger`, owned by the
:class:`~hyperopt_tpu.service.scheduler.StudyScheduler`): fed at the
wave chokepoint with each cohort tick's MEASURED dispatch+readback
seconds, candidate count and history bytes, attributed across the
tick's studies by their K-row share (a study asking 3 of the tick's 4
rows is charged 3/4 of the tick).  Accumulation is O(1) per study —
``{device_ms, asks, tells, waves, cand, hbm_bytes}`` rows plus an
activity EWMA — with a per-scheduler roll-up (shard heat, busy-fraction
duty EWMA) the fleet surfaces read.  The standing obs invariant holds:
armed attribution NEVER feeds the RNG or perturbs proposals (armed ==
disarmed bit-identical, pinned directly and over HTTP by
``tests/test_load.py``), and disarmed (``HYPEROPT_TPU_LOAD=off``) means
``scheduler.load is None`` — zero threads, zero allocations, one
``is None`` check on the wave path.

**The durable heat ledger**: fleet replicas roll their per-shard heat
up to ``fleet/heat/<replica>.jsonl`` under the shared store root —
O_APPEND single-line records sealed with the ISSUE-15 CRC32C idiom
(:func:`~hyperopt_tpu.service.integrity.seal`), torn-line tolerant on
read, warn-once on ENOSPC like the signature census.  Records are
cumulative snapshots (each includes inherited baseline heat), so the
merged per-shard heat is the MAX across all replicas' records: heat
survives restarts, and migration adoption inherits the shard's
accumulated heat via :func:`inherited_heat` — a shard doesn't cool off
by moving.  ``GET /fleet/load``, the ``service.load.*`` gauge family
(per-shard heat, per-replica busy fraction, the fleet **heat-skew**
gauge = max/mean shard heat), ``obs.report --fleet`` and ``obs/top.py``
all read these two surfaces; the steward's heat-aware handoff orders
its volunteer release by them.  This is the measured-load signal
ROADMAP items 5(b) tenant fairness and 5(c) load-aware rebalancing
will consume.
"""

from __future__ import annotations

import logging
import os
import threading
import time

__all__ = [
    "DEFAULT_BUSY_ALPHA",
    "StudyCost",
    "CostLedger",
    "HeatLedger",
    "merge_status",
    "heat_skew",
    "heat_dir_for",
    "heat_path_for",
    "read_heat",
    "inherited_heat",
]

logger = logging.getLogger(__name__)

#: activity-EWMA weight (per-study attributed ms per tick, and the
#: scheduler-level busy-fraction duty cycle): ~the last dozen ticks
#: dominate, matching the quality plane's improvement EWMA
DEFAULT_BUSY_ALPHA = 0.3

#: heat-ledger directory under a store root (next to fleet/wal etc.)
HEAT_DIR = os.path.join("fleet", "heat")


def _sanitize(label):
    """Metric-name-safe label (the gauges surface as
    ``hyperopt_tpu_service_load_*`` families and must lint)."""
    return "".join(c if c.isalnum() or c == "_" else "_" for c in str(label))


class StudyCost:
    """One study's accumulated attributed cost.  ``charge`` is the only
    wave-side mutator: O(1), no I/O, no RNG — pure arithmetic on the
    measured tick."""

    __slots__ = ("study_id", "cohort", "device_ms", "asks", "tells",
                 "waves", "cand", "hbm_bytes", "ewma_ms")

    def __init__(self, study_id, cohort=None):
        self.study_id = study_id
        self.cohort = cohort
        self.device_ms = 0.0
        self.asks = 0
        self.tells = 0
        self.waves = 0
        self.cand = 0.0
        self.hbm_bytes = 0.0
        self.ewma_ms = 0.0

    def charge(self, share_ms, k, cand, hbm_bytes, alpha):
        """Fold this study's K-row share of one cohort tick."""
        self.device_ms += share_ms
        self.asks += k
        self.waves += 1
        self.cand += cand
        self.hbm_bytes += hbm_bytes
        self.ewma_ms = alpha * share_ms + (1.0 - alpha) * self.ewma_ms

    def status_dict(self):
        """The per-study cost section (``GET /studies``)."""
        return {
            "cohort": self.cohort,
            "device_ms": round(self.device_ms, 3),
            "asks": self.asks,
            "tells": self.tells,
            "waves": self.waves,
            "cand": round(self.cand, 1),
            "hbm_bytes": round(self.hbm_bytes, 1),
            "ewma_ms": round(self.ewma_ms, 3),
        }


class CostLedger:
    """Per-scheduler device-time attribution (zero threads).

    ``metrics`` is the service registry the ``service.load.*`` gauges
    publish into (pull-based: :meth:`publish` refreshes at
    scrape/snapshot time).  Lock discipline mirrors the quality plane:
    every wave/tell mutation arrives under the scheduler's RLock, so
    the hot path is lock-free — the ledger's own lock guards only row
    admission.  Scrape-side reads are deliberately unlocked (a scrape
    racing a wave sees the tick one charge early or late, both true).

    Fleet identity (:meth:`bind`) and inherited baseline heat
    (:meth:`inherit`) are set by :class:`~hyperopt_tpu.service.fleet
    .FleetReplica` at adoption — ``heat_ms`` then reports the shard's
    CUMULATIVE lifetime heat, not just this owner's share."""

    def __init__(self, metrics=None, alpha=DEFAULT_BUSY_ALPHA):
        self.metrics = metrics
        self.alpha = float(alpha)
        self.shard = None
        self.replica = None
        self._studies = {}
        self._lock = threading.Lock()
        # scheduler-level totals (attributed, so they sum to the
        # measured tick times exactly)
        self.device_ms = 0.0
        self.inherited_ms = 0.0  # baseline adopted from the heat ledger
        self.asks = 0
        self.tells = 0
        self.waves = 0
        self.cand = 0.0
        self.hbm_bytes = 0.0
        self.busy = 0.0          # duty-cycle EWMA (device sec / wall sec)
        self._last_tick = None   # monotonic ts of the previous tick

    # -- fleet identity ----------------------------------------------------

    def bind(self, shard=None, replica=None):
        """Attach the (shard, replica) identity the fleet rows carry."""
        self.shard = None if shard is None else int(shard)
        self.replica = None if replica is None else str(replica)

    def inherit(self, heat_ms):
        """Adopt a baseline heat (the shard's accumulated heat under
        previous owners, read from the ledger).  Idempotent via max —
        re-adoption never doubles heat."""
        self.inherited_ms = max(self.inherited_ms, float(heat_ms or 0.0))

    @property
    def heat_ms(self):
        """The shard's cumulative heat: inherited baseline + everything
        this scheduler attributed itself."""
        return self.inherited_ms + self.device_ms

    # -- the wave-chokepoint hook ------------------------------------------

    def observe_tick(self, entries, device_sec, cand=0.0, hbm_bytes=0.0,
                     cohort=None):
        """Attribute one measured cohort tick.  ``entries`` is
        ``[(study_id, k_rows), ...]`` — the tick's asks and their K-row
        counts; each study is charged ``k_i / sum(k)`` of the tick's
        ``device_sec``, ``cand`` and ``hbm_bytes``.  Called under the
        scheduler RLock (see class docstring); never touches proposals."""
        total_k = 0
        for _, k in entries:
            total_k += k
        if total_k <= 0:
            return
        ms = float(device_sec) * 1e3
        inv = 1.0 / total_k
        for study_id, k in entries:
            row = self._studies.get(study_id)
            if row is None:
                with self._lock:
                    row = self._studies.get(study_id)
                    if row is None:
                        row = StudyCost(study_id, cohort=cohort)
                        self._studies[study_id] = row
            if row.cohort is None and cohort is not None:
                row.cohort = cohort  # first device tick names the cohort
            share = k * inv
            row.charge(ms * share, k, cand * share, hbm_bytes * share,
                       self.alpha)
        self.device_ms += ms
        self.asks += total_k
        self.waves += 1
        self.cand += float(cand)
        self.hbm_bytes += float(hbm_bytes)
        # busy-fraction duty EWMA: device seconds over the wall seconds
        # since the previous tick (clamped — a tick can't be busier
        # than 100% of its own interval)
        now = time.monotonic()
        if self._last_tick is not None:
            wall = now - self._last_tick
            duty = float(device_sec) / max(wall, float(device_sec), 1e-9)
            self.busy = self.alpha * duty + (1.0 - self.alpha) * self.busy
        self._last_tick = now

    def observe_tell(self, study_id):
        """Count one LIVE settled tell (replay excluded by the caller —
        adopted heat arrives through :meth:`inherit`, never recounted)."""
        self.tells += 1
        row = self._studies.get(study_id)
        if row is None:
            with self._lock:
                row = self._studies.get(study_id)
                if row is None:
                    row = StudyCost(study_id)
                    self._studies[study_id] = row
        row.tells += 1

    def forget(self, study_id):
        with self._lock:
            self._studies.pop(study_id, None)

    def study_status(self, study_id):
        """Cost section for one study, or None if never charged.
        Lock-free read (see class docstring)."""
        row = self._studies.get(study_id)
        return None if row is None else row.status_dict()

    # -- pull-based publication --------------------------------------------

    def status(self):
        """The load roll-up (``/snapshot`` + ``/fleet/load`` section):
        scheduler totals plus the per-cohort table."""
        rows = list(self._studies.values())
        cohorts = {}
        for row in rows:
            key = row.cohort or "unticked"
            c = cohorts.setdefault(key, {
                "studies": 0, "device_ms": 0.0, "asks": 0, "tells": 0,
                "waves": 0})
            c["studies"] += 1
            c["device_ms"] += row.device_ms
            c["asks"] += row.asks
            c["tells"] += row.tells
            c["waves"] += row.waves
        for c in cohorts.values():
            c["device_ms"] = round(c["device_ms"], 3)
        return {
            "shard": self.shard,
            "replica": self.replica,
            "studies": len(rows),
            "device_ms": round(self.device_ms, 3),
            "inherited_ms": round(self.inherited_ms, 3),
            "heat_ms": round(self.heat_ms, 3),
            "busy_frac": round(self.busy, 4),
            "asks": self.asks,
            "tells": self.tells,
            "waves": self.waves,
            "cand": round(self.cand, 1),
            "hbm_bytes": round(self.hbm_bytes, 1),
            "cohorts": cohorts,
        }

    def publish(self):
        """Refresh the per-shard ``service.load.shard.*`` gauges (bound
        fleet schedulers only) and return :meth:`status` — the
        scrape/snapshot hook.  Fleet-level merged gauges (heat skew,
        totals) are set by the server from :func:`merge_status`."""
        st = self.status()
        if self.metrics is not None and self.shard is not None:
            base = f"service.load.shard.{self.shard}"
            g = self.metrics.gauge
            g(f"{base}.heat_ms").set(st["heat_ms"])
            g(f"{base}.busy_frac").set(st["busy_frac"])
            g(f"{base}.device_ms").set(st["device_ms"])
            g(f"{base}.waves").set(st["waves"])
        return st

    def heat_record(self):
        """One cumulative heat-ledger snapshot for this scheduler (the
        roll-up the replica appends).  Monotone per owner: every record
        includes the inherited baseline, so the merged MAX across all
        replicas' records is the shard's lifetime heat."""
        return {
            "kind": "heat",
            "replica": self.replica,
            "shard": self.shard,
            "heat_ms": round(self.heat_ms, 3),
            "device_ms": round(self.device_ms, 3),
            "busy_frac": round(self.busy, 4),
            "studies": len(self._studies),
            "asks": self.asks,
            "tells": self.tells,
            "waves": self.waves,
            "cand": round(self.cand, 1),
            "hbm_bytes": round(self.hbm_bytes, 1),
            "ts": time.time(),
        }


def heat_skew(values):
    """The fleet imbalance scalar: max/mean over per-shard heats — 1.0
    is perfectly balanced, N means the hottest shard carries N× the
    average.  1.0 when there is nothing to compare (≤1 shard, or no
    heat anywhere: an idle fleet is not imbalanced)."""
    vals = [float(v) for v in values if v is not None]
    if len(vals) < 2:
        return 1.0
    mean = sum(vals) / len(vals)
    if mean <= 0.0:
        return 1.0
    return max(vals) / mean


def merge_status(statuses):
    """Merge per-scheduler :meth:`CostLedger.status` dicts (the fleet
    server runs one ledger per adopted shard) into the replica-level
    view: summed totals, the per-shard table, and the heat-skew scalar
    over the shards this replica can see."""
    statuses = [s for s in statuses if s]
    if not statuses:
        return None
    out = {"studies": 0, "device_ms": 0.0, "heat_ms": 0.0,
           "asks": 0, "tells": 0, "waves": 0, "cand": 0.0,
           "hbm_bytes": 0.0, "busy_frac": 0.0, "shards": {}}
    for s in statuses:
        for k in ("studies", "asks", "tells", "waves"):
            out[k] += int(s.get(k) or 0)
        for k in ("device_ms", "heat_ms", "cand", "hbm_bytes"):
            out[k] += float(s.get(k) or 0.0)
        # shards tick sequentially within one process wave loop, so the
        # replica's duty cycle is the sum of its schedulers' duties
        out["busy_frac"] += float(s.get("busy_frac") or 0.0)
        if s.get("shard") is not None:
            out["shards"][str(s["shard"])] = {
                "heat_ms": s.get("heat_ms"),
                "busy_frac": s.get("busy_frac"),
                "device_ms": s.get("device_ms"),
                "studies": s.get("studies"),
                "asks": s.get("asks"),
                "tells": s.get("tells"),
                "waves": s.get("waves"),
            }
    for k in ("device_ms", "heat_ms", "cand", "hbm_bytes"):
        out[k] = round(out[k], 3)
    out["busy_frac"] = round(out["busy_frac"], 4)
    out["heat_skew"] = round(heat_skew(
        [v["heat_ms"] for v in out["shards"].values()]), 4)
    return out


# ---------------------------------------------------------------------------
# the durable heat ledger: fleet/heat/<replica>.jsonl under the store root
# ---------------------------------------------------------------------------


def heat_dir_for(store_root):
    return os.path.join(str(store_root), HEAT_DIR)


def heat_path_for(store_root, replica_id):
    """One append-only ledger file per replica — replicas never share a
    file, so no write interleaving; readers merge the directory."""
    return os.path.join(heat_dir_for(store_root), f"{replica_id}.jsonl")


class HeatLedger:
    """Append-only durable heat records for one replica (the signature
    census's O_APPEND idiom): every line sealed with the ISSUE-15
    CRC32C field, best-effort on ANY OSError — a full disk must cost
    heat durability, never a request — with a warn-once latch."""

    def __init__(self, path):
        self.path = str(path)
        self._warned = False

    def append(self, rec):
        from ..service import integrity

        line = (integrity.seal(rec) + "\n").encode()
        try:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            fd = os.open(self.path,
                         os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
            try:
                os.write(fd, line)
            finally:
                os.close(fd)
        except OSError as e:
            if not self._warned:
                self._warned = True
                logger.warning("heat ledger: cannot append to %s (%s); "
                               "shard heat will not survive a restart",
                               self.path, e)


def _iter_heat_records(store_root):
    """Every readable heat record under the store root, with corruption
    counted instead of raised: CORRUPT lines are skipped loudly (a
    bit-flip costs one snapshot, never the view), TORN final lines
    silently (the normal crash artifact)."""
    from ..service import integrity

    d = heat_dir_for(store_root)
    try:
        names = sorted(os.listdir(d))
    except (FileNotFoundError, NotADirectoryError):
        return
    for fname in names:
        if not fname.endswith(".jsonl"):
            continue
        path = os.path.join(d, fname)
        for chk in integrity.iter_checked_jsonl(path):
            if chk.status == integrity.CORRUPT:
                logger.warning("heat ledger: %s:%d corrupt record "
                               "skipped", path, chk.lineno)
                yield fname, None, chk.status
                continue
            if chk.rec is None:  # torn tail
                yield fname, None, chk.status
                continue
            yield fname, chk.rec, chk.status


def read_heat(store_root):
    """The merged fleet-wide heat view from every replica's ledger
    file: per-shard cumulative heat (MAX across records — each record
    is a cumulative snapshot including inherited baseline, so the max
    survives any ownership chain), per-replica latest snapshot (busy
    fraction, held totals), and the fleet heat-skew scalar."""
    shards = {}
    replicas = {}
    files = set()
    corrupt = torn = 0
    for fname, rec, status in _iter_heat_records(store_root):
        files.add(fname)
        if rec is None:
            from ..service import integrity

            if status == integrity.CORRUPT:
                corrupt += 1
            else:
                torn += 1
            continue
        if rec.get("kind") != "heat":
            continue
        shard = rec.get("shard")
        if shard is not None:
            k = str(int(shard))
            cur = shards.get(k)
            if cur is None or float(rec.get("heat_ms") or 0.0) \
                    > cur["heat_ms"]:
                shards[k] = {
                    "heat_ms": float(rec.get("heat_ms") or 0.0),
                    "replica": rec.get("replica"),
                    "waves": rec.get("waves"),
                    "asks": rec.get("asks"),
                    "tells": rec.get("tells"),
                    "ts": rec.get("ts"),
                }
        rid = rec.get("replica")
        if rid is not None:
            cur = replicas.get(rid)
            if cur is None or float(rec.get("ts") or 0.0) \
                    >= float(cur.get("ts") or 0.0):
                replicas[rid] = {
                    "busy_frac": rec.get("busy_frac"),
                    "shard": rec.get("shard"),
                    "ts": rec.get("ts"),
                }
    return {
        "shards": shards,
        "replicas": replicas,
        "heat_skew": round(heat_skew(
            [v["heat_ms"] for v in shards.values()]), 4),
        "files": len(files),
        "corrupt": corrupt,
        "torn": torn,
    }


def inherited_heat(store_root, shard):
    """The cumulative heat an adopter of ``shard`` inherits: the MAX
    ``heat_ms`` any replica ever recorded for it (records are
    cumulative snapshots, so the max IS the lifetime total).  0.0 for
    a never-heated shard or an unreadable ledger — adoption must never
    fail on observability."""
    best = 0.0
    try:
        k = int(shard)
        for _, rec, _status in _iter_heat_records(store_root):
            if rec is None or rec.get("kind") != "heat":
                continue
            if rec.get("shard") is not None and int(rec["shard"]) == k:
                best = max(best, float(rec.get("heat_ms") or 0.0))
    except Exception:  # noqa: BLE001 - fail-open read
        logger.warning("heat ledger: inherited-heat read failed for "
                       "shard %s (continuing cold)", shard, exc_info=True)
    return best
