"""Search-quality observability plane (ISSUE 16).

Every other gated surface in the repo measures *throughput* — asks/sec,
p99s, HBM bytes.  This module measures whether the optimizer is actually
*optimizing*, which is the gate the ROADMAP's megakernel arc needs:
int8/fp8 history and a fused Pallas scoring loop cannot be bit-exact-
pinned against the f32 reference, so they must instead clear directional
search-quality bars.  Two halves:

**Online convergence telemetry** (:class:`QualityPlane`, owned by the
:class:`~hyperopt_tpu.service.scheduler.StudyScheduler`): per-study
incremental tracking at tell time — zero device work, O(1) per tell —
of the best-so-far curve, simple regret against the zoo entry's known
``optimum``/``loss_target`` (resolved from the study's ``{"zoo": name}``
space spec), an improvement-rate EWMA, trials-since-improvement, and a
streaming plateau detector generalizing
:func:`hyperopt_tpu.early_stop.no_progress_loss` to the serving side:
the same ``new_loss < best - |best| * pct/100`` improvement test, but
edge-triggered per episode instead of stopping the loop.  Emissions:

* ``improvement`` / ``stagnation`` events on the study's audit timeline
  and (via the scheduler's sink-less tracer) the flight ring;
* ``quality.*`` gauges per ``(algo, space-signature)`` cohort key,
  refreshed pull-style at scrape/snapshot time (zero threads);
* a stagnant-fraction objective riding the :mod:`~hyperopt_tpu.obs.slo`
  burn-rate plane (``slo.stagnation.*`` gauges) when the server has one.

Armed telemetry NEVER changes proposals: observation reads the settled
loss and the study's bookkeeping only — never the RNG stream, never the
trial docs (the PR 2/11 pattern, pinned bit-identical by
``tests/test_quality.py`` including over HTTP).  Disarmed
(``HYPEROPT_TPU_QUALITY=off``) means ``scheduler.quality is None``: no
tracker objects, zero threads, zero per-tell allocations beyond one
``is None`` check (the bench ``quality_overhead`` stage gates the armed
delta at ≤5% absolute).

**The standing per-algo quality table**: :func:`summarize_run` and
:func:`quality_record` define the ``kind="quality"`` JSONL record shape
shared by ``bench.py``'s ``search_quality`` stage (tpe / rand / anneal /
mix / atpe over ``zoo.make_study_mix``) and
``scripts/compare_atpe.py``.  The bench stage's per-algo scalars
(``trials_to_target_<algo>``, ``final_regret_<algo>``,
``solved_frac_<algo>``) land in ``.obs/trajectory.jsonl`` with
directions registered in :data:`~hyperopt_tpu.obs.trajectory
.KEY_DIRECTIONS` — the quality bars ``scripts/bench_gate.py`` holds the
megakernel PRs to.  ``trajectory.load`` filters ``kind == "bench"``, so
``kind="quality"`` rows share the store without perturbing the gate's
windowed medians.
"""

from __future__ import annotations

import hashlib
import threading
import time

__all__ = [
    "DEFAULT_PLATEAU_WINDOW",
    "DEFAULT_PLATEAU_PCT",
    "DEFAULT_EWMA_ALPHA",
    "QUALITY_ALGOS",
    "StudyQuality",
    "QualityPlane",
    "merge_status",
    "summarize_run",
    "quality_record",
]

#: tells without an improvement before the plateau detector fires —
#: mirrors ``early_stop.no_progress_loss``'s ``iteration_stop_count``
DEFAULT_PLATEAU_WINDOW = 20

#: required relative improvement in percent (``no_progress_loss``'s
#: ``percent_increase``): 0.0 = any strictly-better loss resets the clock
DEFAULT_PLATEAU_PCT = 0.0

#: improvement-rate EWMA weight: ~the last dozen tells dominate
DEFAULT_EWMA_ALPHA = 0.3

#: cap on the stored best-so-far change-point curve per study (the curve
#: only grows on improvements, so this bounds pathological streams only)
_CURVE_CAP = 128

#: the algorithms the standing quality table covers (bench.py
#: ``search_quality`` stage; one ``trials_to_target_<algo>`` /
#: ``final_regret_<algo>`` / ``solved_frac_<algo>`` triple each)
QUALITY_ALGOS = ("tpe", "rand", "anneal", "mix", "atpe")


def _sanitize(label):
    """Metric-name-safe cohort label (the gauges surface as
    ``hyperopt_tpu_quality_*`` families and must pass exposition lint)."""
    return "".join(c if c.isalnum() or c == "_" else "_" for c in str(label))


class StudyQuality:
    """One study's incremental convergence state, folded at tell time.

    ``observe`` is the only mutator: O(1), no I/O, no RNG.  The
    improvement test is exactly ``no_progress_loss``'s —
    ``loss < best - |best| * (pct / 100)`` — and the stagnation flag is
    its streaming, edge-triggered form: it fires ONCE when
    ``trials_since_improvement`` crosses ``window`` and clears on the
    next improvement, so a long plateau is one timeline event, not one
    per tell."""

    __slots__ = ("study_id", "cohort", "optimum", "loss_target", "window",
                 "pct", "alpha", "best", "n_told", "since_improvement",
                 "stagnant", "improvements", "stagnations", "ewma",
                 "trials_to_target", "solved", "curve")

    def __init__(self, study_id, cohort, optimum=None, loss_target=None,
                 window=DEFAULT_PLATEAU_WINDOW, pct=DEFAULT_PLATEAU_PCT,
                 alpha=DEFAULT_EWMA_ALPHA):
        self.study_id = study_id
        self.cohort = cohort
        self.optimum = None if optimum is None else float(optimum)
        self.loss_target = (None if loss_target is None
                            else float(loss_target))
        self.window = int(window)
        self.pct = float(pct)
        self.alpha = float(alpha)
        self.best = None
        self.n_told = 0
        self.since_improvement = 0
        self.stagnant = False
        self.improvements = 0
        self.stagnations = 0
        self.ewma = None  # improvement-rate EWMA (loss units per tell)
        self.trials_to_target = None
        self.solved = False
        self.curve = []  # best-so-far change points: (n_told, best)

    def observe(self, loss):
        """Fold one told result (``loss`` is the ok loss, None for a
        failed trial).  Returns ``"improvement"``, ``"stagnation"`` or
        None — the edge events worth a timeline entry."""
        self.n_told += 1
        prev = self.best
        if loss is not None:
            loss = float(loss)
            if prev is None or loss < prev:
                self.best = loss
        improved = loss is not None and (
            prev is None or loss < prev - abs(prev) * (self.pct / 100.0))
        if improved:
            delta = 0.0 if prev is None else max(prev - loss, 0.0)
            self.ewma = (delta if self.ewma is None
                         else self.alpha * delta
                         + (1.0 - self.alpha) * self.ewma)
            self.since_improvement = 0
            self.stagnant = False
            self.improvements += 1
            if len(self.curve) < _CURVE_CAP:
                self.curve.append((self.n_told, self.best))
            if (not self.solved and self.loss_target is not None
                    and self.best <= self.loss_target):
                self.solved = True
                self.trials_to_target = self.n_told
            return "improvement"
        if self.ewma is not None:
            # a non-improving tell decays the rate toward zero — the
            # EWMA answers "is this study still moving", not "how big
            # was the last win"
            self.ewma *= (1.0 - self.alpha)
        self.since_improvement += 1
        if not self.stagnant and self.since_improvement >= self.window:
            self.stagnant = True
            self.stagnations += 1
            return "stagnation"
        return None

    @property
    def regret(self):
        """Simple regret vs the known optimum, or None when either side
        is unknown.  Clamped at 0 — a surrogate domain whose sampled
        best beats the recorded optimum is a zoo calibration artifact,
        not negative regret."""
        if self.best is None or self.optimum is None:
            return None
        return max(self.best - self.optimum, 0.0)

    def status_dict(self):
        """The per-study quality section (``GET /studies``)."""
        out = {
            "cohort": self.cohort,
            "n_told": self.n_told,
            "best_loss": self.best,
            "stagnant": self.stagnant,
            "trials_since_improvement": self.since_improvement,
            "improvement_ewma": self.ewma,
        }
        if self.optimum is not None:
            out["regret"] = self.regret
        if self.loss_target is not None:
            out["solved"] = self.solved
            out["trials_to_target"] = self.trials_to_target
        return out


class QualityPlane:
    """Per-study convergence telemetry for a scheduler (zero threads).

    ``metrics`` is the service registry the ``quality.*`` gauges publish
    into (pull-based: :meth:`publish` refreshes at scrape/snapshot
    time); ``tracer`` feeds improvement/stagnation events to the flight
    ring (and any armed sink); ``slo`` is an
    :class:`~hyperopt_tpu.obs.slo.SLOPlane` carrying a ``stagnation``
    objective (installed by the server via
    ``parse_quality_slo``), fed one good/bad observation per live tell.
    Lock discipline: every mutation arrives under the scheduler's
    RLock (live tell and replay both), so the per-tell path is
    lock-free; the plane's own lock guards only tracker admission.
    Scrape-side reads are deliberately unlocked — a scrape racing a
    tell sees the study one tell early or late, both true snapshots."""

    def __init__(self, metrics=None, tracer=None, slo=None,
                 window=DEFAULT_PLATEAU_WINDOW, pct=DEFAULT_PLATEAU_PCT,
                 alpha=DEFAULT_EWMA_ALPHA):
        self.metrics = metrics
        self.tracer = tracer
        self.slo = slo
        self.window = int(window)
        self.pct = float(pct)
        self.alpha = float(alpha)
        self._studies = {}
        self._lock = threading.Lock()

    # -- study registry ----------------------------------------------------

    def _admit(self, st):
        """Build the tracker for one study: the cohort key is
        ``(serving algo, space signature)`` — the zoo name when the
        study came over the wire with a ``{"zoo": ...}`` spec (which
        also supplies the optimum/target for regret), a short signature
        hash otherwise."""
        optimum = target = None
        label = None
        spec = getattr(st, "space_spec", None)
        if isinstance(spec, dict) and "zoo" in spec:
            from ..zoo import ZOO

            zrec = ZOO.get(str(spec["zoo"]))
            if zrec is not None:
                label = zrec.name
                optimum = zrec.optimum
                target = zrec.loss_target
        if label is None:
            try:
                sig = repr(st.domain.cs.signature())
            except Exception:  # noqa: BLE001 - cohort label is best-effort
                sig = repr(getattr(st, "study_id", "?"))
            label = "sig_" + hashlib.sha1(
                sig.encode()).hexdigest()[:10]
        # service-side studies are TPE-served (rand only at the startup/
        # degrade/warming floors) — the cohort's algo axis is "tpe"
        cohort = _sanitize(f"tpe.{label}")
        q = StudyQuality(st.study_id, cohort, optimum=optimum,
                         loss_target=target, window=self.window,
                         pct=self.pct, alpha=self.alpha)
        self._studies[st.study_id] = q
        return q

    def forget(self, study_id):
        with self._lock:
            self._studies.pop(study_id, None)

    def study_status(self, study_id):
        """Quality section for one study, or None if never told.
        Lock-free read: a scrape racing a tell sees the study one tell
        early or late — both are true snapshots."""
        q = self._studies.get(study_id)
        return None if q is None else q.status_dict()

    # -- the per-tell hook -------------------------------------------------

    def observe_tell(self, st, loss, replay=False):
        """Fold one settled tell (``loss`` = the ok loss, None for a
        failed trial).  Called by the scheduler's ``_apply_tell`` (live
        AND replay) and the store-ahead replay branch — observation
        happens exactly once per told trial either way.  Emits the edge
        events; never touches proposals.

        Lock-free on the hot path: callers already hold the scheduler
        RLock (live tell and replay both), so per-study mutation is
        serialized upstream — only tracker admission (the registry
        insert) takes the plane lock, and that happens once per study."""
        q = self._studies.get(st.study_id)
        if q is None:
            with self._lock:
                q = self._studies.get(st.study_id)
                if q is None:
                    q = self._admit(st)
        event = q.observe(loss)
        if event is not None:
            st.note(event, best=q.best, regret=q.regret,
                    n_told=q.n_told,
                    since=(q.since_improvement
                           if event == "stagnation" else None),
                    replay=True if replay else None)
            if self.metrics is not None:
                self.metrics.counter(f"quality.{event}s").inc()
            if self.tracer is not None:
                self.tracer.event(
                    f"quality.{event}", study=st.study_id,
                    cohort=q.cohort, best=q.best, regret=q.regret,
                    n_told=q.n_told)
        if self.slo is not None and not replay:
            # replayed history must not re-burn the live error budget
            try:
                self.slo.record_quality(q.stagnant)
            except Exception:  # noqa: BLE001 - observability never fails a tell
                pass
        return event

    # -- pull-based publication --------------------------------------------

    def status(self):
        """The quality roll-up (``/snapshot`` section): global counts
        plus the per-cohort table.  Lock-free snapshot of the registry
        (see :meth:`study_status`)."""
        qs = list(self._studies.values())
        cohorts = {}
        for q in qs:
            c = cohorts.setdefault(q.cohort, {
                "studies": 0, "stagnant": 0, "solved": 0,
                "best_loss": None, "best_regret": None})
            c["studies"] += 1
            c["stagnant"] += 1 if q.stagnant else 0
            c["solved"] += 1 if q.solved else 0
            if q.best is not None and (c["best_loss"] is None
                                       or q.best < c["best_loss"]):
                c["best_loss"] = q.best
            r = q.regret
            if r is not None and (c["best_regret"] is None
                                  or r < c["best_regret"]):
                c["best_regret"] = r
        n = len(qs)
        stagnant = sum(1 for q in qs if q.stagnant)
        return {
            "studies": n,
            "stagnant": stagnant,
            "stagnant_frac": (stagnant / n) if n else 0.0,
            "solved": sum(1 for q in qs if q.solved),
            "improvements": sum(q.improvements for q in qs),
            "stagnations": sum(q.stagnations for q in qs),
            "cohorts": cohorts,
        }

    def publish(self):
        """Refresh the ``quality.*`` gauges and return :meth:`status`
        (the scrape/snapshot hook — the compile/store gauge pattern)."""
        st = self.status()
        if self.metrics is not None:
            g = self.metrics.gauge
            g("quality.studies").set(st["studies"])
            g("quality.stagnant").set(st["stagnant"])
            g("quality.stagnant_frac").set(st["stagnant_frac"])
            g("quality.solved").set(st["solved"])
            for key, c in st["cohorts"].items():
                base = f"quality.cohort.{key}"
                g(f"{base}.studies").set(c["studies"])
                g(f"{base}.stagnant").set(c["stagnant"])
                g(f"{base}.solved").set(c["solved"])
                if c["best_regret"] is not None:
                    g(f"{base}.best_regret").set(c["best_regret"])
        return st


def merge_status(statuses):
    """Merge per-scheduler :meth:`QualityPlane.status` dicts (the fleet
    server's ``/snapshot`` runs one plane per adopted shard)."""
    statuses = [s for s in statuses if s]
    if not statuses:
        return None
    if len(statuses) == 1:
        return statuses[0]
    out = {"studies": 0, "stagnant": 0, "solved": 0,
           "improvements": 0, "stagnations": 0, "cohorts": {}}
    for s in statuses:
        for k in ("studies", "stagnant", "solved", "improvements",
                  "stagnations"):
            out[k] += int(s.get(k) or 0)
        for key, c in (s.get("cohorts") or {}).items():
            m = out["cohorts"].setdefault(key, {
                "studies": 0, "stagnant": 0, "solved": 0,
                "best_loss": None, "best_regret": None})
            m["studies"] += c.get("studies", 0)
            m["stagnant"] += c.get("stagnant", 0)
            m["solved"] += c.get("solved", 0)
            for fld in ("best_loss", "best_regret"):
                v = c.get(fld)
                if v is not None and (m[fld] is None or v < m[fld]):
                    m[fld] = v
    out["stagnant_frac"] = (out["stagnant"] / out["studies"]
                            if out["studies"] else 0.0)
    return out


# ---------------------------------------------------------------------------
# the standing quality table: offline run summaries + the JSONL record
# ---------------------------------------------------------------------------


def summarize_run(losses, budget, loss_target=None, optimum=None):
    """Summarize one finished optimization run for the quality table.

    ``losses`` is the per-trial loss sequence in tell order (None for
    failed trials).  Returns ``best``, ``solved`` (best ≤ target),
    ``trials_to_target`` (1-based trial index of the first
    target-clearing loss; ``budget`` when unsolved, so aggregation
    penalizes failure instead of dropping it) and ``final_regret``
    (vs the known optimum; None when the optimum is unknown)."""
    best = None
    t2t = None
    for i, loss in enumerate(losses):
        if loss is None:
            continue
        loss = float(loss)
        if best is None or loss < best:
            best = loss
            if (t2t is None and loss_target is not None
                    and best <= float(loss_target)):
                t2t = i + 1
    solved = t2t is not None
    return {
        "best": best,
        "solved": solved,
        "trials_to_target": t2t if solved else int(budget),
        "final_regret": (max(best - float(optimum), 0.0)
                         if best is not None and optimum is not None
                         else None),
        "budget": int(budget),
    }


def quality_record(source, algos, config=None, root=None):
    """One ``kind="quality"`` trajectory-store record: the search-quality
    sibling of the ``kind="bench"`` rows (``trajectory.load`` filters by
    kind, so both share ``.obs/trajectory.jsonl`` without perturbing the
    perf gate).  ``algos`` maps algo name → summary dict — at minimum
    the three table scalars (``trials_to_target``, ``final_regret``,
    ``solved_frac``), plus whatever per-domain detail the producer has
    (``scripts/compare_atpe.py`` stores its full row table)."""
    from . import trajectory

    return {
        "kind": "quality",
        "ts": time.time(),
        "source": str(source),
        "git_rev": trajectory.git_rev(root),
        "config": dict(config or {}),
        "algos": {str(k): dict(v) for k, v in (algos or {}).items()},
    }
