"""Programmatic, bounded ``jax.profiler`` device captures.

The span/metric pillars see *host* time; ``cost_analysis()`` sees *static*
FLOP estimates.  What neither sees is the actual device timeline — which
XLA program ran when, for how long, overlapped with what.  This module is
the device-time half of the observability plane: bounded, on-demand
profiler captures that can be triggered three ways, all riding one armed
config (``HYPEROPT_TPU_PROFILE=<dir>`` / ``fmin(profile=<dir>)``):

* **programmatically** — ``RunObs.profiler.capture(sec)`` from any thread;
* **on demand over HTTP** — ``GET /profile?sec=N`` on the live scrape
  server (``obs/serve.py``) starts a capture, blocks for its bounded
  duration, and answers with the artifact paths as JSON;
* **automatically on a stall** — the watchdog's escalation hook takes ONE
  bounded capture per run when the process goes quiet, so a hang dies
  with a device trace next to the flight dump instead of only host
  stacks.

Every capture is **bounded** (``sec`` clamps to ``max_capture_sec``) and
**exclusive** (``jax.profiler`` supports one trace session per process; a
concurrent request fails open with a busy error instead of raising into
the run).  Captures run on the *caller's* thread — the HTTP handler or
watchdog thread that asked — so a disarmed run starts zero new threads
and an armed-but-idle one starts none either.

Each capture lands in its own ``capture-<n>-<reason>`` directory under
the armed profile dir and is recorded as a ``kind="profile"`` JSONL
record (+ flight-ring event) carrying the located ``*.trace.json.gz``
trace-event artifact, the capture's wall-clock epoch, and the trigger
reason.  ``obs.report --export-trace`` folds referenced captures into the
merged Perfetto export next to the host spans (``obs/export.py``), with
the capture's epoch aligning the two timelines.

**Timeline annotations.**  :func:`annotation_ctx` wraps
``jax.profiler.TraceAnnotation`` / ``StepTraceAnnotation`` so the fmin
tick, the device-loop chunk, and the driver generation show up *named*
(with trial/generation/study ids) inside any capture that overlaps them.
Disarmed runs get a shared null context — one attribute check, no jax
import, proposals bit-identical (pinned by tests/test_profiler.py).

Legacy whole-run traces: ``HYPEROPT_TPU_PROFILE=full:<dir>`` keeps the
old trace-the-entire-loop behavior (``RunObs.profiler_ctx``); the bare
``<dir>`` form now arms the bounded capture plane, because a whole-run
trace session would block every on-demand and stall capture for the
run's entire lifetime (one session per process).  docs/MIGRATION.md
documents the switch.
"""

from __future__ import annotations

import contextlib
import glob
import logging
import os
import threading
import time

__all__ = ["DeviceProfiler", "find_capture_artifact", "annotation_ctx",
           "split_profile_mode"]

logger = logging.getLogger(__name__)

#: hard ceiling on one capture's duration — a typo'd ``/profile?sec=3600``
#: must not profile (and slow) an hour of the run it observes
DEFAULT_MAX_CAPTURE_SEC = 30.0

#: bounded duration of the automatic stall-escalation capture
DEFAULT_STALL_CAPTURE_SEC = 5.0

#: retained completed-capture records (a /profile poller against a
#: multi-day run must not grow the process)
CAPTURES_KEEP = 256

#: failed captures streamed to the sink/flight ring before going quiet —
#: a postmortem needs the first failures, not a poller's millionth retry
FAILURE_STREAM_MAX = 20

_NULL_CTX = contextlib.nullcontext()


def split_profile_mode(raw):
    """``HYPEROPT_TPU_PROFILE`` value → ``(capture_dir, full_trace_dir)``.

    ``<dir>`` arms the bounded capture plane; ``full:<dir>`` keeps the
    legacy whole-run ``jax.profiler.trace`` wrapper instead (the two are
    mutually exclusive per run: a whole-run session would starve every
    bounded capture).  Empty/unset → ``(None, None)``.
    """
    raw = (raw or "").strip()
    if not raw:
        return None, None
    if raw.startswith("full:"):
        full = raw[len("full:"):].strip()
        return None, (full or None)
    return raw, None


def find_capture_artifact(capture_dir):
    """Newest ``*.trace.json.gz`` under one capture's directory tree, or
    None.  ``jax.profiler`` writes
    ``<dir>/plugins/profile/<stamp>/<host>.trace.json.gz`` — the
    trace-event JSON every Chrome-lineage viewer (and our Perfetto merge)
    loads — next to the ``.xplane.pb`` TensorBoard artifact."""
    hits = glob.glob(os.path.join(
        str(capture_dir), "**", "*.trace.json.gz"), recursive=True)
    return max(hits, key=os.path.getmtime) if hits else None


def annotation_ctx(profiler, name, **ids):
    """A ``jax.profiler.TraceAnnotation`` for the named loop boundary when
    the capture plane is armed, a shared null context otherwise.

    The call sites (fmin tick, device chunk, driver generation) run this
    every iteration, so the disarmed cost must be one ``is None`` check —
    no jax import, no object construction.  Annotation args become the
    ``args`` of the device timeline's X event, which is how a capture's
    kernels are attributed back to trial/generation/study ids
    (``scripts/validate_trace.py`` lints their presence in merged
    artifacts)."""
    if profiler is None:
        return _NULL_CTX
    return profiler.annotation(name, **ids)


class DeviceProfiler:
    """Bounded, exclusive, fail-open ``jax.profiler`` capture manager.

    Construction is cheap and thread-free: the profiler holds a directory,
    a lock and counters.  A capture runs synchronously on the calling
    thread (HTTP handler / watchdog / test) — ``start_trace``, a bounded
    sleep, ``stop_trace`` — then locates the trace-event artifact and
    records a ``kind="profile"`` record to the run's sink and the flight
    ring.  Any backend error (no profiler support, a session already
    active, an unwritable dir) degrades to a once-logged warning and an
    ``{"ok": False}`` result: profiling must never take down the run it
    observes.
    """

    def __init__(self, out_dir, obs=None,
                 max_capture_sec=DEFAULT_MAX_CAPTURE_SEC,
                 stall_capture_sec=DEFAULT_STALL_CAPTURE_SEC,
                 clock=time.sleep):
        self.out_dir = str(out_dir)
        self.obs = obs  # RunObs (or anything with .sink/.run_id), optional
        self.max_capture_sec = float(max_capture_sec)
        self.stall_capture_sec = float(stall_capture_sec)
        self._sleep = clock  # injectable for tests (no real waiting)
        self._lock = threading.Lock()  # one trace session per process
        self._count = 0
        self._stall_captured = False  # once-per-run bound
        self._warned_unsupported = False
        self._failures_streamed = 0
        self.captures = []  # capture records, oldest first, bounded

    # -- annotations -------------------------------------------------------

    def annotation(self, name, **ids):
        """``TraceAnnotation`` carrying ``ids`` as timeline args; the
        ``step`` id (fmin tick / driver generation number) additionally
        makes TensorBoard's step-time view work via
        ``StepTraceAnnotation``.  Fail-open: a backend without profiler
        support degrades to the null context."""
        try:
            import jax.profiler as jp

            if "step" in ids:
                step = ids.pop("step")
                return jp.StepTraceAnnotation(name, step_num=int(step),
                                              **_str_args(ids))
            return jp.TraceAnnotation(name, **_str_args(ids))
        except Exception:
            return _NULL_CTX

    # -- captures ----------------------------------------------------------

    def capture(self, sec, reason="ondemand"):
        """One bounded capture: returns the ``kind="profile"`` record
        (``ok=True`` with artifact paths) or an ``ok=False`` record naming
        why (busy / unsupported / bad duration).  Never raises.  Failure
        records stream to the sink/flight ring too — a postmortem must
        show that a stall capture was ATTEMPTED and why it failed, not
        just silently lack one."""
        try:
            sec = float(sec)
        except (TypeError, ValueError):
            return self._record({
                "kind": "profile", "ok": False, "ts": time.time(),
                "reason": str(reason),
                "error": f"bad capture duration {sec!r}"})
        if not sec > 0:
            return self._record({
                "kind": "profile", "ok": False, "ts": time.time(),
                "reason": str(reason),
                "error": f"capture duration must be > 0, got {sec}"})
        sec = min(sec, self.max_capture_sec)
        if not self._lock.acquire(blocking=False):
            # jax supports one profiler session per process: a concurrent
            # request reports busy instead of raising into the run
            return self._record({
                "kind": "profile", "ok": False, "ts": time.time(),
                "reason": str(reason), "busy": True,
                "error": "capture already in progress"})
        try:
            return self._capture_locked(sec, reason)
        finally:
            self._lock.release()

    def _capture_locked(self, sec, reason):
        self._count += 1
        cap_dir = os.path.join(self.out_dir,
                               f"capture-{self._count}-{reason}")
        t0 = time.time()
        rec = {"kind": "profile", "reason": str(reason), "ts": t0,
               "sec": sec, "dir": cap_dir}
        try:
            import jax.profiler as jp

            os.makedirs(cap_dir, exist_ok=True)
            jp.start_trace(cap_dir)
        except Exception as e:
            if "already" in str(e).lower():
                # a FOREIGN in-process session (another run's profiler, a
                # user's own jax.profiler.trace) — our lock only covers
                # this instance, jax's limit is process-wide.  Transient,
                # so report busy (retryable: a stall escalation keeps its
                # once-per-run budget), not unsupported (which latches).
                rec.update(ok=False, busy=True,
                           error=f"{type(e).__name__}: {e}")
                return self._record(rec)
            # fail-open: CPU backends support this, but a backend/build
            # without profiler hooks must degrade to a warning, not an
            # exception into the run
            if not self._warned_unsupported:
                self._warned_unsupported = True
                logger.warning(
                    "device profiler capture unavailable (%s: %s); "
                    "/profile and stall captures degrade to errors for "
                    "this run — spans, metrics and the flight ring are "
                    "unaffected", type(e).__name__, e)
            rec.update(ok=False, error=f"{type(e).__name__}: {e}")
            return self._record(rec)
        try:
            self._sleep(sec)
        finally:
            t1 = time.time()
            try:
                jp.stop_trace()
            except Exception as e:
                rec.update(ok=False, error=f"{type(e).__name__}: {e}")
                return self._record(rec)
        rec.update(ok=True, t0=t0, t1=t1, wall_sec=t1 - t0,
                   trace_json=find_capture_artifact(cap_dir))
        return self._record(rec)

    def capture_on_stall(self, stall_rec=None):
        """The watchdog escalation hook: ONE bounded capture per run, so a
        6-hour hang produces one device trace, not 72.  The capture runs on
        the watchdog's own thread — the stalled main thread may be wedged
        inside the very device call the trace is meant to show.  A BUSY
        miss (an in-flight /profile holds the session) does not consume
        the once-per-run budget — the next stall period retries, so the
        hang still dies with a trace; any other failure (unsupported
        backend, unwritable dir) latches, because it would fail the same
        way every period."""
        if self._stall_captured:
            return None
        rec = self.capture(self.stall_capture_sec, reason="stall")
        if not rec.get("busy"):
            self._stall_captured = True
        if rec.get("ok"):
            logger.warning(
                "stall escalation: captured %.1fs device trace to %s "
                "(referenced from the flight dump)",
                rec["wall_sec"], rec["dir"])
        return rec

    def reset_stall_budget(self):
        """Re-open the once-per-run stall-capture budget.  Called by
        ``RunObs.rearm()`` when the iterator protocol re-enters a finished
        run — a hang in the second leg must still die with a device trace,
        bounded at one capture per leg."""
        self._stall_captured = False

    @property
    def capture_count(self):
        return self._count

    # -- plumbing ----------------------------------------------------------

    def _record(self, rec):
        """Stream the capture record (success OR failure) next to the
        run's spans and pin it in the flight ring — the postmortem's
        pointer to the device trace, or to why there isn't one.  Returns
        ``rec`` so every ``capture()`` exit path is one expression.

        Bounded against pollers: ``captures`` keeps the newest
        ``CAPTURES_KEEP`` records, and after ``FAILURE_STREAM_MAX``
        streamed failures further ones only go back to the caller (an
        unsupported backend fails the same way on every ``/profile``
        retry — the sink needs the first screamful, not a multi-day
        poller's worth)."""
        self.captures.append(rec)
        if len(self.captures) > CAPTURES_KEEP:
            del self.captures[: len(self.captures) - CAPTURES_KEEP]
        if not rec.get("ok"):
            self._failures_streamed += 1
            if self._failures_streamed > FAILURE_STREAM_MAX:
                return rec
        obs = self.obs
        sink = getattr(obs, "sink", None)
        if getattr(obs, "run_id", None) is not None:
            rec.setdefault("run_id", obs.run_id)
        from .flight import get_flight

        get_flight().record(rec)
        if sink is not None:
            sink.write(rec)
        return rec


def _str_args(ids):
    """TraceAnnotation metadata values must be TraceMe-encodable; str() is
    the lowest common denominator and what the timeline shows anyway."""
    return {k: str(v) for k, v in ids.items()}
