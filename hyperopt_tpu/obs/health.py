"""Search-health diagnostics and device-utilization accounting.

Fourth pillar of the run-telemetry layer, answering the two questions the
span/metric/event pillars cannot: *is the optimizer actually searching
well*, and *how hard is the hardware actually working*.

**Search health.**  A TPE run that degenerates into prior sampling or
duplicate candidates looks identical to a healthy one until the final
loss.  When a run is armed (``fmin(..., obs="run.jsonl")``), the TPE
suggest kernel returns a small auxiliary diagnostics buffer per ask —
EI-score quantiles, the selected candidate's EI rank, duplicate-candidate
rate, below/above split sizes, per-param posterior shape (effective
mixture-component count, prior-mass fraction) and the ε-prior fallback
flag (``tpe._mix_prior``) — which :func:`record_tpe_health` folds into the
run's metrics namespace and JSONL stream.  ``rand``/``anneal`` proposals
get the cheap subset (duplicate rate + proposal spread across the batch)
via :func:`record_proposal_health`, computed host-side from values already
fetched — zero extra device work.  Disarmed runs pay exactly one
``getattr`` per suggest call: the diagnostics variant of the kernel is a
*separate* jit cache entry, so the hot path neither recompiles nor fetches
an extra buffer (tests/test_health.py pins this).

What "healthy" looks like (docs/DESIGN.md §9 for the full reading guide):

* ``ei_p50`` drifting *upward* over asks — the below-model keeps finding
  regions the above-model considers unlikely.  A flat ~0 trend means the
  two models agree everywhere: the posterior has collapsed to the prior.
* ``dup_rate`` near 0 — candidates are distinct.  A rising dup rate means
  the below-model has concentrated into near-point masses (or a quantized
  param has saturated its grid) and extra candidates buy nothing.
* ``sel_rank`` 0 under argmax selection; small-but-nonzero under softmax
  (that is the batch-diversity mechanism working, not a bug).
* ``prior_mass_frac`` decaying toward ``1/(n_below+1)`` as evidence
  accumulates; pinned near 1 means the split has too few points to matter.
* ``prior_takes`` tracking ``prior_eps`` × proposals — much higher means
  EI is being out-competed by its own exploration floor.

**Device utilization.**  :func:`record_program_cost` captures a compiled
program's static FLOP/byte cost (``Compiled.cost_analysis()``) into the
process-global ``"device"`` metrics namespace at AOT-compile time
(``device_fmin._aot_compile``; :func:`capture_jit_cost` does the same for
jit-only call sites, armed runs only — it pays one extra lowering).
:func:`utilization_snapshot` joins those costs with the measured execute
spans into achieved FLOP/s, arithmetic intensity and device-busy fraction;
``bench.py`` attaches the result to stage results and the headline JSON
line.

**Multi-controller merge.**  :func:`controller_stream_path` names the
per-controller JSONL streams ``fmin_multihost`` writes (one per process,
run_id tagged ``-p<index>``); ``python -m hyperopt_tpu.obs.report --merge
a.jsonl b.jsonl`` renders them as one cross-controller view (allgather
skew, per-controller phase breakdown, divergence-context correlation).
"""

from __future__ import annotations

import os
import time

import numpy as np

from .metrics import get_metrics

__all__ = [
    "HEALTH_STATS",
    "record_tpe_health",
    "record_proposal_health",
    "live_health_postfix",
    "cost_analysis_summary",
    "record_program_cost",
    "capture_jit_cost",
    "utilization_snapshot",
    "utilization_from_metrics",
    "roofline_table",
    "controller_stream_path",
]

#: order of the per-label stat vector the TPE diagnostics kernel packs
#: (algos/tpe.py sym: _diag_stats) — the contract between device and host.
HEALTH_STATS = (
    "ei_p10",
    "ei_p50",
    "ei_p90",
    "ei_max",
    "sel_rank",
    "dup_rate",
    "eff_components",
    "prior_mass_frac",
    "prior_take",
)

_IDX = {name: i for i, name in enumerate(HEALTH_STATS)}

# summary stats carried per-label in the JSONL health record (the full
# 9-vector per label per ask would bloat the stream for wide spaces)
_LABEL_STATS = ("ei_p50", "dup_rate", "eff_components", "prior_mass_frac")


def _finite_mean(a, axis=None):
    """Mean over finite entries (EI quantiles can be -inf when every
    candidate fell outside one model's support); 0.0 when none are."""
    a = np.asarray(a, np.float64)
    mask = np.isfinite(a)
    n = mask.sum(axis=axis)
    s = np.where(mask, a, 0.0).sum(axis=axis)
    return np.where(n > 0, s / np.maximum(n, 1), 0.0)


def record_tpe_health(obs, labels, stats, splits, algo="tpe"):
    """Fold one armed TPE ask's diagnostics into metrics + JSONL.

    ``stats``: ``[B, L, len(HEALTH_STATS)]`` host array (B proposals in the
    ask, L labels); ``splits``: ``[B, 2]`` (n_below, n_above — identical
    across the batch, every proposal saw the same history).
    """
    stats = np.asarray(stats, np.float64)
    if stats.ndim != 3 or not stats.size:
        return
    B, L = stats.shape[0], stats.shape[1]
    splits = np.asarray(splits).reshape(B, 2)
    n_below, n_above = int(splits[0, 0]), int(splits[0, 1])

    agg = _finite_mean(stats.reshape(-1, stats.shape[-1]), axis=0)  # [S]
    lab = _finite_mean(stats, axis=0)                               # [L, S]
    takes = int(np.nansum(stats[:, :, _IDX["prior_take"]]))

    m = obs.metrics
    m.counter("health.asks").inc()
    m.counter("health.proposals").inc(B)
    m.counter("health.prior_fallbacks").inc(takes)
    for name in ("ei_p50", "sel_rank", "dup_rate", "eff_components",
                 "prior_mass_frac"):
        m.histogram(f"health.{name}").observe(float(agg[_IDX[name]]))
    m.gauge("health.last_ei_p50").set(float(agg[_IDX["ei_p50"]]))
    m.gauge("health.last_dup_rate").set(float(agg[_IDX["dup_rate"]]))
    m.gauge("health.n_below").set(n_below)
    m.gauge("health.n_above").set(n_above)

    if obs.sink is None:
        return
    rec = {"kind": "health", "algo": algo, "ts": time.time(),
           "run_id": obs.run_id, "n": B, "n_label_proposals": B * L,
           "n_below": n_below, "n_above": n_above,
           "prior_takes": takes}
    for name in HEALTH_STATS:
        if name != "prior_take":
            rec[name] = float(agg[_IDX[name]])
    rec["labels"] = {
        l: {name: float(lab[j, _IDX[name]]) for name in _LABEL_STATS}
        for j, l in enumerate(labels)
    }
    obs.sink.write(rec)


def record_proposal_health(obs, algo, labels, flats):
    """The cheap health subset for non-TPE suggesters (``rand``,
    ``anneal``, any :class:`~hyperopt_tpu.algos.algobase.SuggestAlgo`):
    per-label duplicate rate and proposal spread across one ask's batch.
    Computed from the host-side flat samples the suggester already fetched
    — no extra device work.  Callers skip batches of < 2 (both stats are
    degenerate at width 1)."""
    B = len(flats)
    if B < 2:
        return
    per = {}
    dups, spreads = [], []
    for l in labels:
        v = np.sort(np.asarray([f[l] for f in flats], np.float64))
        scale = max(float(v[-1] - v[0]), 1e-12)
        dup = float(np.mean(np.diff(v) <= 1e-6 * scale))
        spread = float(np.std(v))
        per[l] = {"dup_rate": dup, "spread": spread}
        dups.append(dup)
        spreads.append(spread)
    dup_mean = float(np.mean(dups))
    spread_mean = float(np.mean(spreads))

    m = obs.metrics
    m.counter("health.asks").inc()
    m.counter("health.proposals").inc(B)
    m.histogram("health.dup_rate").observe(dup_mean)
    m.gauge("health.last_dup_rate").set(dup_mean)
    if obs.sink is not None:
        obs.sink.write({"kind": "health", "algo": algo, "ts": time.time(),
                        "run_id": obs.run_id, "n": B,
                        "dup_rate": dup_mean, "spread": spread_mean,
                        "labels": per})


def live_health_postfix(obs):
    """Compact live-progress string ("EI p50 0.42  dup 3%") from the run's
    latest health gauges, or None before the first armed ask."""
    if obs is None:
        return None
    metrics = getattr(obs, "metrics", None)
    if metrics is None:
        return None
    reg = metrics._metrics
    asks = reg.get("health.asks")
    if asks is None or not asks.value:
        return None
    parts = []
    g = reg.get("health.last_ei_p50")
    if g is not None:
        parts.append(f"EI p50 {g.value:.3g}")
    d = reg.get("health.last_dup_rate")
    if d is not None:
        parts.append(f"dup {d.value * 100:.0f}%")
    return "  ".join(parts) or None


# ---------------------------------------------------------------------------
# device-utilization accounting (cost_analysis × execute spans)
# ---------------------------------------------------------------------------


def cost_analysis_summary(compiled):
    """``{"flops", "bytes"}`` per dispatch from a compiled program's
    ``cost_analysis()``, or None when the backend doesn't report one.
    Static XLA metadata — reading it never syncs the device."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    flops = float(ca.get("flops", 0.0) or 0.0)
    nbytes = float(ca.get("bytes accessed", 0.0) or 0.0)
    if flops <= 0.0 and nbytes <= 0.0:
        return None
    return {"flops": flops, "bytes": nbytes}


def record_program_cost(name, compiled, metrics=None):
    """Record a compiled program's per-dispatch FLOPs/bytes as
    ``<name>.flops`` / ``<name>.bytes`` gauges (default: the process-global
    ``"device"`` namespace, next to the ``<name>.execute_sec`` histograms
    they join against)."""
    cost = cost_analysis_summary(compiled)
    if cost is None:
        return None
    reg = metrics if metrics is not None else get_metrics("device")
    reg.gauge(f"{name}.flops").set(cost["flops"])
    reg.gauge(f"{name}.bytes").set(cost["bytes"])
    return cost


# (id(jitted fn), name) pairs already captured: capture pays one extra
# lowering+compile, so it must run once per program per process
_cost_captured = set()


def capture_jit_cost(fn, args, name, metrics=None):
    """``record_program_cost`` for a plain ``jax.jit`` call site: lower +
    AOT-compile once to read the cost table.  Armed runs only (the extra
    compile is the cost of the measurement); no-op on repeat calls and on
    backends without AOT support."""
    key = (id(fn), name)
    if key in _cost_captured:
        return None
    _cost_captured.add(key)
    try:
        compiled = fn.lower(*args).compile()
    except Exception:
        return None
    return record_program_cost(name, compiled, metrics)


def utilization_snapshot(wall_sec=None, stages=("chunk", "whole_run"),
                         metrics=None):
    """Join captured program costs with measured execute spans into
    achieved FLOP/s, arithmetic intensity and (given the enclosing wall
    clock) device-busy fraction.

    ``execute_sec`` spans are wall clock around dispatch→readback, so
    "busy fraction" is an *upper bound proxy*: the share of ``wall_sec``
    spent inside device-program round trips (host dispatch overhead
    included).  Honest enough to answer "was the run device-bound or
    host-bound" from the artifacts alone.  Caveat: the ``"device"``
    namespace is process-cumulative — in a process running several stages,
    the execute totals cover every stage so far, and the clip keeps the
    fraction sane rather than exact."""
    reg = metrics if metrics is not None else get_metrics("device")
    return utilization_from_metrics(reg.snapshot()["metrics"],
                                    wall_sec=wall_sec, stages=stages)


def utilization_from_metrics(dev, wall_sec=None,
                             stages=("chunk", "whole_run")):
    """:func:`utilization_snapshot` over an already-snapshotted metrics
    dict — the form a RECORDED stream's final snapshot arrives in, so the
    live ``/snapshot`` endpoint and ``obs.report --format json`` share one
    join (obs/serve.py, report.headline_sections)."""
    out = {}
    busy_total = 0.0
    for st in stages:
        fl = dev.get(f"{st}.flops")
        ex = dev.get(f"{st}.execute_sec")
        if fl is None or not isinstance(ex, dict) or not ex.get("count"):
            continue
        by = dev.get(f"{st}.bytes") or 0.0
        sec, n = float(ex["sum"]), int(ex["count"])
        busy_total += sec
        entry = {
            "flops_per_dispatch": fl,
            "bytes_per_dispatch": by,
            "dispatches": n,
            "execute_sec_total": sec,
            "achieved_flops_per_sec": (fl * n / sec) if sec > 0 else 0.0,
            "arithmetic_intensity": (fl / by) if by else None,
        }
        if wall_sec:
            entry["busy_fraction"] = min(1.0, sec / wall_sec)
        out[st] = entry
    if out and wall_sec:
        out["device_busy_fraction"] = min(1.0, busy_total / wall_sec)
    # programs with a captured cost but no execute-span pair (the armed
    # suggest kernels — their execute time lives in phase_timings, not the
    # device namespace): report the static costs so every captured gauge
    # has a reader
    costs = {}
    for name, v in dev.items():
        if name.endswith(".flops"):
            st = name[: -len(".flops")]
            if st not in out:
                costs[st] = {"flops_per_dispatch": v,
                             "bytes_per_dispatch": dev.get(f"{st}.bytes", 0.0)}
    if costs:
        out["program_costs"] = costs
    return out


def roofline_table(device_metrics, phases=None, ask_sec=None):
    """Per-program roofline rows: every captured ``cost_analysis()`` cost
    joined with its measured execute spans.

    ``{program: {flops_per_dispatch, bytes_per_dispatch, dispatches,
    execute_sec_total, achieved_flops_per_sec, arithmetic_intensity,
    pct_of_ask}}`` — ``pct_of_ask`` is the program's execute total as a
    fraction of the run's ``suggest`` phase wall clock (``ask_sec``
    overrides; ``phases`` is the ``{name: {"sec", "count"}}`` dict the
    tracer/report already carry), answering "which program actually owns
    the ask latency" from the artifacts alone.  Programs with a captured
    cost but no execute spans yet report the static half only — every
    gauge keeps a reader.  Arithmetic intensity is FLOPs per byte
    accessed: with the measured FLOP/s this is everything a roofline plot
    needs."""
    if ask_sec is None and phases:
        ask_sec = (phases.get("suggest") or {}).get("sec")
    rows = {}
    for key, fl in device_metrics.items():
        if not (isinstance(key, str) and key.endswith(".flops")):
            continue
        st = key[: -len(".flops")]
        by = float(device_metrics.get(f"{st}.bytes") or 0.0)
        row = {
            "flops_per_dispatch": float(fl),
            "bytes_per_dispatch": by,
            "arithmetic_intensity": (float(fl) / by) if by else None,
        }
        ex = device_metrics.get(f"{st}.execute_sec")
        if isinstance(ex, dict) and ex.get("count"):
            sec, n = float(ex["sum"]), int(ex["count"])
            row.update(
                dispatches=n,
                execute_sec_total=sec,
                achieved_flops_per_sec=(float(fl) * n / sec) if sec > 0
                else 0.0,
            )
            if ask_sec:
                row["pct_of_ask"] = min(1.0, sec / float(ask_sec))
        rows[st] = row
    return rows


# ---------------------------------------------------------------------------
# multi-controller streams
# ---------------------------------------------------------------------------


def controller_stream_path(path, process_index):
    """Per-controller JSONL path for a multi-process run: ``run.jsonl`` →
    ``run.p<i>.jsonl`` (every controller writes its own stream; merge them
    with ``python -m hyperopt_tpu.obs.report --merge run.p0.jsonl ...``)."""
    root, ext = os.path.splitext(str(path))
    return f"{root}.p{int(process_index)}{ext or '.jsonl'}"
