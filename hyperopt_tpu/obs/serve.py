"""In-process observability scrape server: ``/metrics`` + ``/snapshot`` +
``/events``.

PRs 1–4 made a run *recordable*; this module makes it *watchable*.  A
stdlib-only ``http.server`` daemon thread rides the run and serves three
endpoints straight from the existing registries — never by replaying
JSONL:

* ``GET /metrics`` — Prometheus text exposition of every
  ``MetricsRegistry`` namespace (counters as ``_total``, histograms as
  summaries with quantile labels, the namespace as a sanitized label), so
  an unattended sweep plugs into a normal Prometheus/Grafana stack.
* ``GET /snapshot`` — JSON: the live analog of the ``obs.report`` headline
  sections (phase breakdown, search health, device utilization,
  ask-pipeline state — built by the SAME serializer ``obs.report --format
  json`` uses, :func:`~hyperopt_tpu.obs.report.headline_sections`, so the
  two can never drift) plus live-only extras: in-flight trials, last
  heartbeats, the latest device-memory sample.
* ``GET /events`` — Server-Sent-Events tail of the span/event stream via
  the flight recorder's record tap.  Each client gets a BOUNDED ring
  (drop-oldest on overflow, reported as a ``dropped`` field on the next
  event) so a slow or stalled scraper can never backpressure a span.
* ``GET /profile?sec=N`` — one bounded on-demand ``jax.profiler`` device
  capture (``obs/profiler.py``; requires the capture plane armed via
  ``HYPEROPT_TPU_PROFILE=<dir>`` / ``fmin(profile=<dir>)``); blocks for
  the bounded duration and answers the capture record — artifact paths
  included — as JSON.  ``curl $url/profile?sec=1`` then load the
  ``trace.json.gz`` (or the merged ``obs.report --export-trace``
  artifact) in https://ui.perfetto.dev.

Arming: ``HYPEROPT_TPU_OBS_HTTP=<port>`` or ``fmin(obs_http=<port>)``
(``obs_http=0`` binds an ephemeral port — read it back from
``trials.obs_http_url``).  The server is fail-open everywhere: an occupied
port, a serialization error, or a mid-run disarm degrade to a once-logged
warning, never an exception into the loop.  Shutdown is wired three ways:
``RunObs.finish()`` (run exit), the flight recorder's fatal-signal path,
and atexit.
"""

from __future__ import annotations

import json
import logging
import re
import threading
import time
from collections import deque

from .metrics import Counter, Gauge, Histogram, all_namespaces, get_metrics

__all__ = ["ObsHTTPServer", "prometheus_text", "Broadcast"]

logger = logging.getLogger(__name__)

_NAME_PREFIX = "hyperopt_tpu_"
_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_NAME_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")


def _metric_name(name):
    """Registry metric name → valid Prometheus metric name (dots and any
    other illegal characters collapse to underscores)."""
    out = _NAME_PREFIX + _NAME_SANITIZE.sub("_", str(name))
    if not _NAME_OK.match(out):  # e.g. a leading digit after the prefix
        out = _NAME_PREFIX + "_" + _NAME_SANITIZE.sub("_", str(name))
    return out


def _label_value(v):
    """Escape a label VALUE per the exposition format (backslash, quote,
    newline)."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt(v):
    if v is None:
        return "NaN"
    f = float(v)
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    return repr(f)


def prometheus_text(namespaces=None):
    """The whole process's metrics as Prometheus text exposition format.

    One metric family per (sanitized) registry metric name; the registry
    namespace rides as a ``namespace`` label so concurrent runs stay
    distinguishable.  Counters expose ``_total``, histograms become
    summaries (``quantile`` series + ``_sum``/``_count``), gauges map
    directly.  Built from live registry objects — a scrape never touches
    JSONL or the hot path.
    """
    if namespaces is None:
        namespaces = all_namespaces()
    families = {}  # prom name -> {"type": ..., "samples": [line, ...]}
    for ns in namespaces:
        label = f'namespace="{_label_value(ns)}"'
        for name, m in get_metrics(ns).iter_metrics():
            pname = _metric_name(name)
            if isinstance(m, Counter):
                fam = families.setdefault(pname + "_total",
                                          {"type": "counter", "samples": []})
                fam["samples"].append(
                    f"{pname}_total{{{label}}} {_fmt(m.value)}")
            elif isinstance(m, Histogram):
                fam = families.setdefault(pname,
                                          {"type": "summary", "samples": []})
                snap = m.snapshot()
                for q, key in (("0.5", "p50"), ("0.9", "p90"),
                               ("0.99", "p99")):
                    if key in snap:
                        fam["samples"].append(
                            f'{pname}{{{label},quantile="{q}"}} '
                            f"{_fmt(snap[key])}")
                fam["samples"].append(
                    f"{pname}_sum{{{label}}} {_fmt(snap.get('sum', 0.0))}")
                fam["samples"].append(
                    f"{pname}_count{{{label}}} {_fmt(snap.get('count', 0))}")
            elif isinstance(m, Gauge):
                fam = families.setdefault(pname,
                                          {"type": "gauge", "samples": []})
                fam["samples"].append(f"{pname}{{{label}}} {_fmt(m.value)}")
    lines = []
    for pname in sorted(families):
        fam = families[pname]
        # the classic text/plain; version=0.0.4 format keys metadata by
        # the literal sample name, so a counter's TYPE line must name the
        # `_total` family itself (the base-name split is OpenMetrics-only)
        lines.append(f"# TYPE {pname} {fam['type']}")
        lines.extend(fam["samples"])
    return "\n".join(lines) + ("\n" if lines else "")


# ---------------------------------------------------------------------------
# SSE broadcast hub (the /events tail)
# ---------------------------------------------------------------------------


class _Subscriber:
    __slots__ = ("ring", "event", "dropped")

    def __init__(self, maxlen):
        self.ring = deque(maxlen=maxlen)
        self.event = threading.Event()
        self.dropped = 0


class Broadcast:
    """Fan one record stream out to N bounded subscriber rings.

    ``publish`` is called from the flight recorder's tap — i.e. from
    inside instrumented code — so it must be cheap and can never block: a
    full ring drops its OLDEST record (the subscriber learns via a
    ``dropped`` counter on the next event it reads) instead of slowing the
    writer.
    """

    def __init__(self):
        self._subs = []
        self._lock = threading.Lock()

    def publish(self, rec):
        for sub in list(self._subs):
            if len(sub.ring) == sub.ring.maxlen:
                sub.dropped += 1  # deque drops the oldest on append
            sub.ring.append(rec)
            sub.event.set()

    def subscribe(self, maxlen=256):
        sub = _Subscriber(int(maxlen))
        with self._lock:
            self._subs.append(sub)
        return sub

    def unsubscribe(self, sub):
        with self._lock:
            if sub in self._subs:
                self._subs.remove(sub)

    def drain(self, sub, timeout=1.0):
        """Wait up to ``timeout`` for records; returns (records, dropped
        since last drain)."""
        sub.event.wait(timeout)
        out = []
        while sub.ring:
            try:
                out.append(sub.ring.popleft())
            except IndexError:  # raced the publisher's trim
                break
        sub.event.clear()
        dropped, sub.dropped = sub.dropped, 0
        return out, dropped

    @property
    def n_subscribers(self):
        return len(self._subs)


_BROADCAST = Broadcast()
_tap_servers = 0  # live servers; the flight tap installs while > 0
_tap_lock = threading.Lock()


def _retain_tap():
    from .flight import get_flight

    global _tap_servers
    with _tap_lock:
        _tap_servers += 1
        get_flight().tap = _BROADCAST.publish


def _release_tap():
    from .flight import get_flight

    global _tap_servers
    with _tap_lock:
        _tap_servers = max(0, _tap_servers - 1)
        if _tap_servers == 0 and get_flight().tap is _BROADCAST.publish:
            # restore the disarmed hot path to a bare None check
            get_flight().tap = None


# ---------------------------------------------------------------------------
# the server
# ---------------------------------------------------------------------------


def split_hostport(value, default_host="127.0.0.1"):
    """``9109`` / ``"9109"`` / ``"0.0.0.0:9109"`` → ``(host, port)``.  The
    default binds loopback (scraping a sweep must be opt-in exposure);
    ``host:port`` opens it to a remote Prometheus / ``obs.top``."""
    if isinstance(value, str) and ":" in value:
        host, port = value.rsplit(":", 1)
        return host or default_host, int(port)
    return default_host, int(value)


class ObsHTTPServer:
    """Daemon-thread HTTP server over one run's registries (see module
    docstring).  ``start()`` returns False — after one warning — instead of
    raising when the port is taken (or out of range); every handler catches
    its own serialization errors the same way."""

    def __init__(self, port, obs=None, host=None):
        try:
            if host is None:
                host, port = split_hostport(port)
            self.port = int(port)
        except (TypeError, ValueError):
            self.port = None  # start() warns and fails open
        self.host = host or "127.0.0.1"
        self.obs = obs  # RunObs (or any object with metrics/tracer/events)
        self._httpd = None
        self._thread = None
        self._stopped = False

    # -- payload builders (all registry snapshots, never JSONL replay) ----

    def snapshot_dict(self):
        """The ``/snapshot`` payload: shared headline sections + live-only
        extras."""
        from .report import headline_sections

        obs = self.obs
        out = {"ts": time.time(), "endpoint": "snapshot"}
        if obs is None:
            return out
        out["run_id"] = obs.run_id
        # dict() snapshots are C-level copies: the run thread keeps
        # adding phases/metrics while the HTTP thread serializes
        phases = {k: {"sec": v["sec"], "count": v["count"]}
                  for k, v in dict(obs.tracer.totals or {}).items()}
        metrics = obs.metrics.snapshot()["metrics"]
        device = get_metrics("device").snapshot()["metrics"]
        out["sections"] = headline_sections(phases, metrics, device)
        # headline scalars obs.top reads without digging into sections
        if "best_loss" in metrics:
            out["best_loss"] = metrics["best_loss"]
        out["trials_completed"] = metrics.get("trials.completed", 0)
        # live-only extras: what a report over a dead stream cannot know
        trial_events = obs.events.records()
        out["inflight_trials"] = _inflight(trial_events)
        wd = getattr(obs, "watchdog", None)
        if wd is not None:
            out["last_heartbeats"] = wd.last_beats()
        dm = getattr(obs, "devmem", None)
        if dm is not None:
            tail = dm.tail()
            if tail:
                out["devmem"] = tail[-1]
        return out

    # -- lifecycle ---------------------------------------------------------

    @property
    def url(self):
        if self._httpd is None:
            return None
        return f"http://{self.host}:{self._httpd.server_address[1]}"

    def start(self):
        """Bind + serve on a daemon thread.  False (after one warning) on
        any bind failure — an occupied port must never kill the run."""
        import http.server

        if self.port is None:
            logger.warning("obs scrape server: unparseable port/host value; "
                           "live observability disabled for this run")
            return False
        handler = _make_handler(self)
        try:
            self._httpd = http.server.ThreadingHTTPServer(
                (self.host, self.port), handler)
        # OverflowError: port out of [0, 65535] (e.g. a multihost
        # per-controller offset past the top) — fail open like a
        # collision, per the never-kill-the-run contract
        except (OSError, OverflowError, ValueError) as e:
            logger.warning(
                "obs scrape server: cannot bind %s:%d (%s); live "
                "observability disabled for this run — the JSONL stream "
                "and flight recorder are unaffected", self.host, self.port,
                e)
            self._httpd = None
            return False
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.25},
            name="hyperopt-obs-http", daemon=True)
        self._thread.start()
        _retain_tap()
        from .flight import get_flight

        get_flight().add_shutdown_hook(self.stop)
        logger.info("obs scrape server listening on %s "
                    "(/metrics /snapshot /events)", self.url)
        return True

    def stop(self):
        """Idempotent shutdown: close the listener, stop the serve loop,
        release the flight tap.  Runs on RunObs.finish(), fatal signals
        (flight shutdown hooks) and atexit."""
        if self._stopped:
            return
        self._stopped = True
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            try:
                httpd.shutdown()
                httpd.server_close()
            except Exception:
                pass
            _release_tap()
        from .flight import get_flight

        get_flight().remove_shutdown_hook(self.stop)


def _inflight(trial_events):
    """Claimed-or-queued-but-unfinished trials from the lifecycle ring."""
    from .events import (TRIAL_CANCELLED, TRIAL_CLAIMED, TRIAL_FINISHED,
                         TRIAL_NEW)

    timelines = {}
    for r in trial_events:
        t = timelines.setdefault(r["tid"], {})
        t.setdefault(r["event"], r["ts"])
    now = time.time()
    out = []
    for tid, t in sorted(timelines.items()):
        if TRIAL_FINISHED in t or TRIAL_CANCELLED in t:
            continue
        start = t.get(TRIAL_CLAIMED, t.get(TRIAL_NEW))
        out.append({"tid": tid,
                    "state": ("claimed" if TRIAL_CLAIMED in t else "queued"),
                    "age_sec": (now - start) if start is not None else None})
    return out


def _make_handler(server):
    import http.server

    class Handler(http.server.BaseHTTPRequestHandler):
        # the run's logger, not stderr-per-request
        def log_message(self, fmt, *args):
            logger.debug("obs http: " + fmt, *args)

        def _send(self, body, content_type):
            data = body.encode() if isinstance(body, str) else body
            self.send_response(200)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):  # noqa: N802 (stdlib handler contract)
            path, _, query = self.path.partition("?")
            try:
                if path == "/metrics":
                    self._send(prometheus_text(),
                               "text/plain; version=0.0.4; charset=utf-8")
                elif path == "/snapshot":
                    self._send(json.dumps(server.snapshot_dict(),
                                          default=str, sort_keys=True),
                               "application/json")
                elif path == "/events":
                    self._sse()
                elif path == "/profile":
                    self._profile(query)
                elif path == "/":
                    self._send(
                        "hyperopt_tpu obs: /metrics /snapshot /events "
                        "/profile?sec=N\n",
                        "text/plain")
                else:
                    self.send_error(404)
            except (BrokenPipeError, ConnectionResetError):
                pass  # client went away mid-write: normal for scrapers
            except Exception as e:
                # fail-open: a serialization bug answers 500 once per
                # request and never propagates into the run
                logger.warning("obs http: %s failed: %s", path, e)
                try:
                    self.send_error(500)
                except Exception:
                    pass

        def _profile(self, query):
            """``GET /profile?sec=N``: one bounded on-demand device capture
            (obs/profiler.py), run synchronously on THIS handler thread —
            the run keeps ticking while the profiler session records it.
            Fail-open contract: a disarmed profiler plane, a busy session,
            or a backend without profiler support all answer structured
            JSON with ``ok: false`` (HTTP 200 — the failure is in-band so
            ``curl | jq`` scripting stays one code path), never a raised
            exception into the run."""
            from urllib.parse import parse_qs

            params = parse_qs(query or "")
            sec = (params.get("sec") or ["3"])[0]
            prof = getattr(server.obs, "profiler", None)
            if prof is None:
                body = {"ok": False,
                        "error": "profiler plane not armed — set "
                                 "HYPEROPT_TPU_PROFILE=<dir> or "
                                 "fmin(profile=<dir>)"}
            else:
                body = prof.capture(sec, reason="http")
            self._send(json.dumps(body, default=str, sort_keys=True),
                       "application/json")

        def _sse(self):
            sub = _BROADCAST.subscribe()
            try:
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.end_headers()
                while not server._stopped:
                    recs, dropped = _BROADCAST.drain(sub, timeout=1.0)
                    if dropped:
                        recs = ([{"kind": "sse_overflow",
                                  "dropped": dropped}] + recs)
                    if not recs:
                        self.wfile.write(b": keepalive\n\n")
                        self.wfile.flush()
                        continue
                    for rec in recs:
                        data = json.dumps(rec, default=str)
                        self.wfile.write(f"data: {data}\n\n".encode())
                    self.wfile.flush()
            finally:
                _BROADCAST.unsubscribe(sub)

    return Handler
