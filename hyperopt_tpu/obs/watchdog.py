"""Stall watchdog: turn "the job hung at hour 6" into a named blocked call.

The execution paths this repo runs — ``FMinIter`` ask→tell ticks, the
chunked device loop, executor worker threads, the multi-controller driver's
collectives — all share one failure mode no exception ever reports: a hung
objective, a dead NFS mount, or a peer controller that never reaches its
allgather leaves the process alive but silent.  The watchdog is a daemon
thread fed by cheap heartbeats from all of those paths; once *no* component
has beaten for a configurable quiet period it emits a ``kind="stall"``
record carrying

* the last heartbeat per component (age + structured detail — for the
  driver that detail is the last collective reached and whether the
  process was *entering* or *leaving* it), and
* every thread's current stack (``sys._current_frames()``), so the blocked
  frame is named, not guessed.

Stall records go to the flight-recorder ring (always), any armed JSONL
sinks (``Watchdog.attach_sink``) and the log — and they fire **once per
quiet period**, not once per tick: a 6-hour hang under a 5-minute quiet
period produces ~72 stall records, not tens of thousands.  A fresh
heartbeat re-arms the detector.

Heartbeats are dictionary stores under the GIL — no lock on the beat path —
so instrumented hot loops pay ~a dict assignment per tick.

**What this detects — and what it doesn't.**  Quiet is *global*: a stall
fires when the whole process stops proving liveness — a blocked
collective, a wedged device readback, a serial objective that never
returns, a worker stuck on dead NFS.  Two boundaries follow.  (1) A
serial trial merely *slower* than the quiet period is indistinguishable
from a hung one; the stall record is still truthful (the stacks show the
run is inside the user objective, and the log says so) — size
``HYPEROPT_TPU_WATCHDOG`` above your slowest legitimate trial to keep
those reports meaningful.  (2) In asynchronous mode the *driver* keeps
beating while it polls, so one deadlocked worker among many does not
register as a process-wide stall — per-trial budgets
(``ExecutorTrials(timeout=...)``, ``FileStore.reclaim_stale``) are the
designed detector for individual hung trials there; the watchdog's job
is the whole process going dark.

Configuration: ``HYPEROPT_TPU_WATCHDOG=<seconds>`` sets the quiet period
(default 300); ``0``/``off`` disables the global watchdog.  The ``clock``
parameter exists for deterministic tests (fake clocks drive
:meth:`Watchdog.check` directly).
"""

from __future__ import annotations

import logging
import os
import sys
import threading
import time
import traceback

from .flight import get_flight

__all__ = ["Watchdog", "get_watchdog", "beat"]

logger = logging.getLogger(__name__)

_DEFAULT_QUIET_SEC = 300.0


class Watchdog:
    """Quiet-period stall detector over named component heartbeats."""

    def __init__(self, quiet_sec=_DEFAULT_QUIET_SEC, interval=None,
                 clock=time.monotonic, flight=None, max_stack_frames=12):
        self.quiet_sec = float(quiet_sec)
        # tick a few times per quiet period, but never busier than 2 Hz and
        # never lazier than 30 s — a stall is reported within ~1.25x quiet
        self.interval = (float(interval) if interval is not None
                         else min(max(self.quiet_sec / 4.0, 0.5), 30.0))
        self._clock = clock
        self._flight = flight
        self.max_stack_frames = int(max_stack_frames)
        self._beats = {}  # component -> (mono ts, wall ts, detail dict|None)
        self._sinks = []
        # stall-escalation hooks (obs/profiler.py registers a bounded
        # once-per-run device capture): run AFTER the stall record is
        # emitted, on the watchdog thread, each wrapped so an escalation
        # failure can never take the detector down with it
        self._escalations = []
        self._lock = threading.Lock()
        self._thread = None
        self._stop = threading.Event()
        self._last_stall_mono = None
        self.stall_count = 0
        # live-run refcount: stall detection only runs while at least one
        # run is active (RunObs retains/releases) — otherwise a notebook or
        # server that ran one fmin would emit bogus stall reports every
        # quiet period for the rest of the process lifetime
        self._active = 0

    # -- feeding -----------------------------------------------------------

    def beat(self, component, **detail):
        """Record liveness for ``component`` (a dict store — safe and cheap
        from any thread).  ``detail`` is kept verbatim for the stall report
        and the flight dump's ``last_heartbeats`` record."""
        self._beats[component] = (self._clock(), time.time(), detail or None)

    def last_beats(self):
        """Per-component last heartbeat: age (seconds), wall ts, detail."""
        now = self._clock()
        out = {}
        # dict() is a single C-level copy (atomic under the GIL); iterating
        # self._beats directly could raise mid-insert from a worker thread
        for comp, (mono, wall, detail) in sorted(dict(self._beats).items()):
            entry = {"age_sec": now - mono, "ts": wall}
            if detail:
                entry["detail"] = detail
            out[comp] = entry
        return out

    # -- run lifecycle (RunObs retain/release) -----------------------------

    def retain(self):
        """A run went live: stall detection is meaningful again."""
        with self._lock:
            self._active += 1

    def release(self):
        """A run finished.  At zero live runs detection quiesces (the
        beats table is kept — a crash dump's last-heartbeat record should
        still say what the process did last)."""
        with self._lock:
            self._active = max(0, self._active - 1)
            if self._active == 0:
                self._last_stall_mono = None

    # -- sinks -------------------------------------------------------------

    def attach_sink(self, sink):
        """Also stream stall records to ``sink`` (an armed run's
        ``JsonlSink``); detach on run finish."""
        if sink is None:
            return
        with self._lock:
            if sink not in self._sinks:
                self._sinks.append(sink)

    def detach_sink(self, sink):
        with self._lock:
            if sink in self._sinks:
                self._sinks.remove(sink)

    # -- escalations -------------------------------------------------------

    def add_escalation(self, fn):
        """Register a callable run with the stall record after each stall
        report — the profiler plane's hook for "hangs die with a device
        trace".  Escalations run on the watchdog thread (the stalled main
        thread may be wedged inside the very call being diagnosed) and are
        individually exception-guarded."""
        with self._lock:
            if fn not in self._escalations:
                self._escalations.append(fn)

    def remove_escalation(self, fn):
        with self._lock:
            if fn in self._escalations:
                self._escalations.remove(fn)

    # -- detection ---------------------------------------------------------

    def check(self, now=None):
        """Emit and return a stall record when every component has been
        quiet for ``quiet_sec``; None otherwise.  Fires once per quiet
        period: after a stall report, the next fires only after another
        full quiet period of silence.  A fresh heartbeat re-arms."""
        now = self._clock() if now is None else now
        beats = dict(self._beats)  # atomic snapshot vs concurrent beat()
        with self._lock:
            if not beats or self._active <= 0:
                return None
            last = max(mono for mono, _, _ in beats.values())
            if now - last < self.quiet_sec:
                self._last_stall_mono = None  # alive again: re-arm
                return None
            if (self._last_stall_mono is not None
                    and now - self._last_stall_mono < self.quiet_sec):
                return None  # already reported this quiet period
            self._last_stall_mono = now
            self.stall_count += 1
            count = self.stall_count
            quiet_for = now - last
        rec = {
            "kind": "stall",
            "ts": time.time(),
            "quiet_sec": self.quiet_sec,
            "quiet_for_sec": quiet_for,
            "stall_count": count,
            "last_heartbeats": self.last_beats(),
            "stacks": self._thread_stacks(),
        }
        self._emit(rec)
        return rec

    def _thread_stacks(self):
        """``{thread name: [file:line func, ...]}`` for every live thread
        except the watchdog's own (its stack is always this function)."""
        names = {t.ident: t.name for t in threading.enumerate()}
        own = self._thread.ident if self._thread is not None else None
        stacks = {}
        for ident, frame in sys._current_frames().items():
            if ident == own:
                continue
            frames = traceback.extract_stack(frame)[-self.max_stack_frames:]
            stacks[names.get(ident, f"thread-{ident}")] = [
                f"{f.filename}:{f.lineno} {f.name}" for f in frames
            ]
        return stacks

    def _emit(self, rec):
        fl = self._flight if self._flight is not None else get_flight()
        fl.record(rec)
        with self._lock:
            sinks = list(self._sinks)
        for sink in sinks:
            try:
                sink.write(rec)
            except Exception:  # a dead sink must not kill the watchdog
                pass
        beats = rec["last_heartbeats"]
        newest_comp, newest = None, None
        for comp, b in beats.items():
            if newest is None or b["age_sec"] < newest:
                newest_comp, newest = comp, b["age_sec"]
        # self-explaining false-positive hint: if the last sign of life was
        # entering an evaluation, a slow-but-healthy trial looks exactly
        # like this — tell the reader which knob separates the two
        hint = ""
        if newest_comp in ("fmin.evaluate", "executor.trial",
                           "worker.trial"):
            hint = (" (last beat entered a trial evaluation: a hung "
                    "objective, or one slower than the quiet period — "
                    "raise HYPEROPT_TPU_WATCHDOG if trials legitimately "
                    "take this long)")
        logger.warning(
            "stall: no heartbeat from any component for %.0fs "
            "(newest %s ago from %s; components: %s) — thread stacks "
            "recorded%s",
            rec["quiet_for_sec"],
            f"{newest:.0f}s" if newest is not None else "?",
            newest_comp or "?",
            ", ".join(sorted(beats)) or "none", hint)
        with self._lock:
            escalations = list(self._escalations)
        for fn in escalations:
            try:
                fn(rec)
            except Exception:  # an escalation must never kill the detector
                logger.exception("stall escalation %r failed", fn)

    # -- thread lifecycle --------------------------------------------------

    def start(self):
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="hyperopt-obs-watchdog", daemon=True)
            self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval):
            try:
                self.check()
            except Exception:  # pragma: no cover - must never die silently
                logger.exception("watchdog check failed")

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


_global = None
_global_lock = threading.Lock()
_DISABLED = object()


def get_watchdog():
    """The process-global watchdog (started lazily on first use), or None
    when ``HYPEROPT_TPU_WATCHDOG`` is ``0``/``off``."""
    global _global
    if _global is _DISABLED:
        return None
    if _global is None:
        with _global_lock:
            if _global is None:
                raw = os.environ.get("HYPEROPT_TPU_WATCHDOG", "").strip()
                if raw.lower() in ("0", "off", "false"):
                    _global = _DISABLED
                    return None
                try:
                    quiet = float(raw) if raw else _DEFAULT_QUIET_SEC
                except ValueError:
                    quiet = _DEFAULT_QUIET_SEC
                wd = Watchdog(quiet_sec=quiet)
                wd.start()
                fl = get_flight()
                if fl.watchdog is None:
                    fl.watchdog = wd  # dumps report last heartbeats
                _global = wd
    return _global if _global is not _DISABLED else None


def beat(component, **detail):
    """Module-level heartbeat: feed the global watchdog from call sites that
    hold no obs handle (executor worker threads, the standalone worker, the
    device runner's module paths).  A disabled watchdog makes this a cheap
    no-op."""
    wd = get_watchdog()
    if wd is not None:
        wd.beat(component, **detail)
