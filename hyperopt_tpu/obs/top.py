"""``obs.top`` — a live, curses-free terminal dashboard over a running
sweep.

Usage::

    python -m hyperopt_tpu.obs.top http://127.0.0.1:9109        # scrape
    python -m hyperopt_tpu.obs.top http://h0:9109 http://h1:9110  # multihost
    python -m hyperopt_tpu.obs.top run.jsonl                    # tail files
    python -m hyperopt_tpu.obs.top rundir/                      # tail a dir

URL mode polls each server's ``/snapshot`` endpoint (the scrape server
``fmin(obs_http=...)`` / ``HYPEROPT_TPU_OBS_HTTP`` arms — obs/serve.py);
give one URL per controller for the multihost per-controller view (the
driver offsets ``run.p<i>`` ports by process index).  File mode re-reads
JSONL streams and rebuilds the same sections via the shared serializer —
useful when the run armed a stream but no server.

The screen redraws with plain ANSI (clear + home) every ``--interval``
seconds: best loss + throughput, ask-pipeline inflight/blocked, EI/dup
sparklines (trend accumulated across refreshes), HBM watermark, and a
per-controller liveness table (last-heartbeat ages).  ``--once`` renders a
single frame without clearing — scripts and tests use that.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

from .report import _bar, _fmt_bytes, _fmt_sec, _spark

__all__ = ["main", "render_frame", "fetch_snapshot", "snapshot_from_stream",
           "snapshot_from_records"]

_CLEAR = "\x1b[2J\x1b[H"


def fetch_snapshot(url, timeout=3.0):
    """GET ``<url>/snapshot`` → dict, or ``{"error": ...}`` (a dead
    controller renders as a dead row, never a dead dashboard)."""
    import urllib.request

    if not url.rstrip("/").endswith("/snapshot"):
        url = url.rstrip("/") + "/snapshot"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return json.loads(r.read().decode())
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


class _StreamTail:
    """Incrementally-tailed JSONL source: each refresh parses only the
    bytes appended since the last one (a refresh loop over a multi-hour
    stream must not re-parse hundreds of MB per frame).  A torn final
    line (the run mid-write) is left for the next frame."""

    def __init__(self, path):
        self.path = path
        self.offset = 0
        self.records = []

    def read_new(self):
        # binary mode: the resume offset is a byte count, and text-mode
        # seek to arbitrary integers is undefined (and drifts on
        # non-UTF-8 locales)
        with open(self.path, "rb") as f:
            f.seek(self.offset)
            while True:
                line = f.readline()
                if not line or not line.endswith(b"\n"):
                    break  # EOF or torn tail: retry from offset next frame
                self.offset += len(line)
                line = line.strip()
                if not line:
                    continue
                try:
                    self.records.append(json.loads(line.decode("utf-8")))
                except (ValueError, UnicodeDecodeError):
                    pass  # torn-then-flushed garbage: skip like iter_jsonl

    def snapshot(self):
        try:
            self.read_new()
        except OSError as e:
            return {"error": f"{type(e).__name__}: {e}"}
        return snapshot_from_records(self.records)


def snapshot_from_records(records):
    """Rebuild the snapshot shape from parsed JSONL records via the SAME
    serializer the live endpoint uses — then overlay what a MID-RUN
    stream can tell us that the sections cannot: the metrics snapshot the
    sections are built from is only written at ``RunObs.finish()``, so
    until the run exits the trial count comes from lifecycle events and
    the health gauges from the live ``kind="health"`` records."""
    from .events import TRIAL_FINISHED
    from .report import _stream_sections

    out = _stream_sections(records)
    out["ts"] = max((r["ts"] for r in records if "ts" in r), default=None)
    dms = [r for r in records if r.get("kind") == "devmem"]
    if dms:
        out["devmem"] = dms[-1]
    # best loss from the stream's final metrics snapshot gauge
    metric_recs = [r for r in records if r.get("kind") == "metrics"]
    if metric_recs:
        m = (metric_recs[-1].get("snapshot") or {}).get("metrics", {})
        if "best_loss" in m:
            out["best_loss"] = m["best_loss"]
        out["trials_completed"] = m.get("trials.completed", 0)
    else:
        out["trials_completed"] = sum(
            1 for r in records if r.get("kind") == "trial_event"
            and r.get("event") == TRIAL_FINISHED)
    health = out["sections"]["health"]
    if not health.get("asks"):
        hrecs = [r for r in records if r.get("kind") == "health"]
        if hrecs:
            health["asks"] = len(hrecs)
            last = hrecs[-1]
            if "ei_p50" in last:
                health["last_ei_p50"] = last["ei_p50"]
            if "dup_rate" in last:
                health["last_dup_rate"] = last["dup_rate"]
    return out


def snapshot_from_stream(path):
    """One-shot file-mode source (``--once`` / tests): full read."""
    return _StreamTail(path).snapshot()


def discover_fleet(seed_url, timeout=5.0):
    """Fleet discovery (ISSUE 17): one replica's ``/healthz`` advertises
    every replica's address (``replica_addrs``, built from the published
    ownership table), so the whole fleet dashboards from a single seed
    URL instead of requiring every URL by hand.  Returns the replica
    base URLs, seed first; a failed discovery degrades to just the
    seed (a dead seed renders as one dead row, never a dead
    dashboard)."""
    import urllib.request

    url = seed_url.rstrip("/")
    out = [url]
    try:
        with urllib.request.urlopen(f"{url}/healthz",
                                    timeout=timeout) as r:
            h = json.loads(r.read().decode())
    except Exception as e:  # noqa: BLE001 - degrade to the seed alone
        print(f"fleet discovery failed on {url}/healthz: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return out
    live = set(h.get("replicas") or [])
    addrs = h.get("replica_addrs") or {}
    for rid in sorted(addrs):
        if live and rid not in live:
            continue  # departed replica still in the ownership table
        a = str(addrs[rid]).rstrip("/")
        if a and a not in out:
            out.append(a)
    return out


def _expand_sources(args_sources):
    """URLs pass through; a directory expands to its ``*.jsonl`` streams
    (flight dumps excluded)."""
    out = []
    for src in args_sources:
        if src.startswith(("http://", "https://")):
            out.append(("url", src))
        elif os.path.isdir(src):
            for p in sorted(glob.glob(os.path.join(src, "*.jsonl"))):
                if ".flight." not in os.path.basename(p):
                    out.append(("file", p))
        else:
            out.append(("file", src))
    return out


class History:
    """Per-source trend memory across refreshes: EI p50, dup rate, HBM
    watermark, completed-trial counts (for throughput)."""

    def __init__(self, width=120):
        self.width = width
        self.series = {}
        self._counts = []  # (mono ts, trials completed)

    def push(self, key, value):
        if value is None:
            return
        s = self.series.setdefault(key, [])
        s.append(float(value))
        del s[:-self.width]

    def trend(self, key):
        return self.series.get(key, [])

    def push_count(self, n_completed, now=None):
        if n_completed is None:
            return
        self._counts.append((time.monotonic() if now is None else now,
                             float(n_completed)))
        del self._counts[:-self.width]

    def throughput(self):
        """trials/sec over the sampled window (None before 2 samples)."""
        if len(self._counts) < 2:
            return None
        (t0, n0), (t1, n1) = self._counts[0], self._counts[-1]
        if t1 <= t0:
            return None
        return max(0.0, (n1 - n0) / (t1 - t0))


def _metric_scalar(m, default=0):
    """A service-registry metric snapshot value as a scalar (histograms
    snapshot as dicts — take the count)."""
    if isinstance(m, dict):
        return m.get("count", default)
    return m if isinstance(m, (int, float)) else default


def _render_service_source(name, snap, out, w):
    """The serving-process view (ISSUE 11): a ``service.server``
    ``/snapshot`` has no fmin sections — render the study table, traffic
    + shed rate, degrade-ladder state and the SLO budget bars instead
    (pre-PR the dashboard showed nothing for a serving process)."""
    svc = (snap.get("sections") or {}).get("service") or {}
    asks = int(_metric_scalar(svc.get("service.asks")))
    tells = int(_metric_scalar(svc.get("service.tells")))
    shed = int(_metric_scalar(svc.get("service.shed.ask")))
    studies = snap.get("studies") or []
    live = sum(1 for s in studies if s.get("state") == "active")
    line = (f"  {name:<{w}}  SERVICE  studies {live}/{len(studies)}"
            f"  asks {asks}  tells {tells}")
    if shed or asks:
        line += f"  shed {shed / max(1, shed + asks):.1%}"
    wave = svc.get("service.wave_sec") or {}
    if isinstance(wave, dict) and wave.get("count"):
        line += (f"  wave p50 {_fmt_sec(wave.get('p50'))}"
                 f" p99 {_fmt_sec(wave.get('p99'))}")
    util = snap.get("slot_utilization")
    if isinstance(util, (int, float)):
        line += f"  slots {util:.0%}"
    if snap.get("draining"):
        line += "  DRAINING"
    out.append(line)
    # the COMPILE row (ISSUE 14): warming-state admission + the
    # background compile queue + kernel-bank reuse, from /snapshot's
    # compile section — cold-start behavior at a glance
    comp = snap.get("compile")
    if comp:
        cline = (f"  {'':<{w}}  COMPILE  warming "
                 f"{comp.get('warming_studies', 0)}"
                 f"  queue {comp.get('queue_depth', 0)}"
                 f"  compiled {comp.get('compiled', 0)}"
                 f"  bank {comp.get('bank_hits', 0)}/"
                 f"{comp.get('bank_keys', 0)}")
        if comp.get("widen"):
            cline += "  WIDEN"
        if comp.get("errors"):
            cline += f"  ERRORS {comp['errors']}"
        out.append(cline)
    # the FLEET row (ISSUE 12): which replica this is, the shard leases
    # (+ epochs) it holds out of the fleet's keyspace, live peer count,
    # adoption/handoff traffic and WAL sync health — the /healthz body
    # rendered one line per replica
    fleet = snap.get("fleet")
    if fleet:
        held = fleet.get("shards_held") or []
        shards = fleet.get("shards") or {}
        epochs = sorted({int(s.get("epoch") or 0)
                         for s in shards.values()})
        fline = (f"  {'':<{w}}  FLEET  {fleet.get('replica', '?')}"
                 f"  shards {len(held)}/{fleet.get('n_shards', '?')}"
                 f" {held}")
        if epochs:
            fline += f"  epochs {epochs[0]}" + (
                f"-{epochs[-1]}" if len(epochs) > 1 else "")
        fline += f"  replicas {len(fleet.get('replicas') or [])}"
        # held-shard heat summary (ISSUE 17): cumulative device heat
        # across held shards + the replica's busy duty cycle, with the
        # hottest held shard called out
        fl_load = fleet.get("load") or {}
        if fl_load.get("heat_ms") is not None:
            fline += (f"  heat {float(fl_load['heat_ms']) / 1e3:.1f}s"
                      f"  busy {float(fl_load.get('busy_frac') or 0):.0%}")
            hot = max(((k, s) for k, s in shards.items()
                       if s.get("heat_ms") is not None),
                      key=lambda kv: kv[1]["heat_ms"], default=None)
            if hot is not None:
                fline += (f"  hot shard{hot[0]} "
                          f"{float(hot[1]['heat_ms']) / 1e3:.1f}s")
        if fleet.get("adoptions") or fleet.get("handoffs"):
            fline += (f"  adopt {fleet.get('adoptions', 0)}"
                      f"  handoff {fleet.get('handoffs', 0)}")
        if fleet.get("leases_lost"):
            fline += f"  LOST {fleet['leases_lost']}"
        if fleet.get("wal_sync_errors"):
            fline += f"  WAL-SYNC-ERRORS {fleet['wal_sync_errors']}"
        if fleet.get("draining"):
            fline += "  DRAINING"
        out.append(fline)
    # the STORE row (ISSUE 15): disk watermark, store-full shed state,
    # quarantined studies and GC reclaim — the storage-integrity plane
    # at a glance, from /snapshot's store section
    store = snap.get("store")
    if store and (store.get("free_bytes") is not None
                  or store.get("store_full")
                  or store.get("quarantined")):
        sline = f"  {'':<{w}}  STORE "
        free = store.get("free_bytes")
        if free is not None:
            gb = float(free) / 1e9
            sline += (f" free {gb:.1f}G"
                      f"  used {float(store.get('used_frac', 0)):.0%}")
        if store.get("store_full"):
            sline += "  FULL (507 shed)"
        elif store.get("low"):
            sline += "  LOW"
        q = int(store.get("quarantined") or 0)
        if q:
            sline += f"  QUARANTINED {q}"
        gc = store.get("gc") or {}
        if gc.get("reclaimed_bytes"):
            sline += f"  gc {gc['reclaimed_bytes'] / 1e6:.1f}M"
        out.append(sline)
    # the QUALITY row (ISSUE 16): is the fleet actually optimizing —
    # stagnant/solved study counts and the worst-off cohort, from
    # /snapshot's quality section
    qual = snap.get("quality")
    if qual and qual.get("studies"):
        qline = (f"  {'':<{w}}  QUALITY  studies {qual.get('studies', 0)}"
                 f"  stagnant {qual.get('stagnant', 0)}"
                 f" ({float(qual.get('stagnant_frac', 0.0)):.0%})"
                 f"  solved {qual.get('solved', 0)}")
        cohorts = qual.get("cohorts") or {}
        worst = max(
            ((c, v) for c, v in cohorts.items()
             if v.get("best_regret") is not None),
            key=lambda kv: kv[1]["best_regret"], default=None)
        if worst is not None:
            qline += (f"  worst {worst[0][:24]}"
                      f" regret {float(worst[1]['best_regret']):.4g}")
        if (float(qual.get("stagnant_frac", 0.0)) >= 0.5
                and qual.get("studies", 0) > 1):
            qline += "  STAGNANT"
        out.append(qline)
    # the PROBE row (ISSUE 18): the blackbox canary's verdict — is the
    # server provably serving the RIGHT proposals as a client sees it —
    # from /snapshot's probes section (prober-armed servers only)
    probes = snap.get("probes")
    if probes and probes.get("armed"):
        last = probes.get("last") or {}
        pline = (f"  {'':<{w}}  PROBE  "
                 f"{'green' if probes.get('green') else 'RED'}"
                 f"  cycles {probes.get('cycles', 0)}"
                 f"  verdict {last.get('verdict', '?')}"
                 f"  streak {probes.get('golden_match_streak', 0)}")
        det = probes.get("detection")
        if det:
            pline += f"  detect {float(det['mean_sec']):.1f}s"
        if probes.get("escalations"):
            pline += (f"  MISMATCH x{probes['escalations']} "
                      "(golden-stream divergence)")
        out.append(pline)
    # the TENANT row (ISSUE 20): who is consuming this server — tracked
    # tenant count, the dominant tenant's device-time share, and shed
    # pressure, from /snapshot's tenants section (tenant-armed servers)
    ten = snap.get("tenants")
    if ten and ten.get("tenants"):
        tline = (f"  {'':<{w}}  TENANT  tracked {ten.get('tenants', 0)}"
                 f"  asks {ten.get('asks', 0)}"
                 f"  dev {float(ten.get('device_ms', 0.0)):.0f}ms")
        table = ten.get("table") or {}
        total_ms = sum(float(r.get("device_ms") or 0.0)
                       for r in table.values())
        top_t = max(table.items(),
                    key=lambda kv: float(kv[1].get("device_ms") or 0.0),
                    default=None)
        if top_t is not None and total_ms > 0:
            share = float(top_t[1].get("device_ms") or 0.0) / total_ms
            tline += f"  top {top_t[0][:24]} ({share:.0%})"
            if share > 0.5 and len(table) > 1:
                tline += "  NOISY"
        if ten.get("sheds"):
            tline += f"  sheds {ten['sheds']}"
        if ten.get("evictions"):
            tline += f"  evicted {ten['evictions']}"
        out.append(tline)
    degrade = snap.get("degrade")
    if degrade and (degrade.get("level") or degrade.get("faults")):
        out.append(f"  {'':<{w}}  ladder {degrade.get('name', '?')}"
                   f"  faults {degrade.get('faults', 0)}"
                   f"  clean {degrade.get('clean_waves', 0)}/"
                   f"{degrade.get('recover_after', '?')}")
    slo = snap.get("slo") or {}
    for obj in sorted(slo):
        s = slo[obj]
        rem = s.get("budget_remaining_frac")
        if rem is None:
            continue
        frac = max(0.0, min(1.0, float(rem)))
        line = (f"  {'':<{w}}  slo {obj:<14} [{_bar(frac, 12)}] "
                f"{float(rem) * 100:6.1f}%  burn "
                f"{float(s.get('burn_fast', 0)):4.1f}x/"
                f"{float(s.get('burn_slow', 0)):4.1f}x")
        if s.get("exhausted") and s.get("window_events"):
            line += "  EXHAUSTED"
        elif s.get("fast_alerting") and s.get("window_events"):
            line += "  FAST-BURN"
        out.append(line)
    # the hottest studies (most recently active first)
    top = sorted(studies, key=lambda s: -(s.get("last_active") or 0))[:6]
    for s in top:
        best = s.get("best_loss")
        line = (
            f"  {'':<{w}}    {str(s.get('study_id', '?'))[:24]:<24}"
            f"  {s.get('state', '?'):<7}"
            f"  trials {s.get('n_trials', 0):>4}"
            f"  pending {s.get('n_pending', 0):>3}"
            + (f"  best {best:.6g}" if isinstance(best, (int, float))
               else "  best -"))
        sq = s.get("quality") or {}
        if sq.get("regret") is not None:
            line += f"  regret {float(sq['regret']):.4g}"
        if sq.get("stagnant"):
            line += "  STAGNANT"
        out.append(line)


def render_frame(sources, histories, now=None):
    """One dashboard frame (pure text) from ``[(name, snapshot), ...]`` —
    the testable core of the refresh loop."""
    now = time.time() if now is None else now
    out = []
    out.append("hyperopt-tpu obs.top — "
               + time.strftime("%H:%M:%S", time.localtime(now))
               + f"  ({len(sources)} source{'s' if len(sources) != 1 else ''})")
    out.append("")

    # -- per-controller liveness table ------------------------------------
    w = max(len(name) for name, _ in sources)
    for name, snap in sources:
        hist = histories.setdefault(name, History())
        if "error" in snap:
            out.append(f"  {name:<{w}}  DEAD  {snap['error']}")
            continue
        if snap.get("service") or "studies" in snap:
            _render_service_source(name, snap, out, w)
            continue
        sections = snap.get("sections") or {}
        health = sections.get("health") or {}
        ask = sections.get("ask_pipeline") or {}
        best = snap.get("best_loss")
        n_done = snap.get("trials_completed")
        hist.push("ei_p50", health.get("last_ei_p50"))
        hist.push("dup", health.get("last_dup_rate"))
        hist.push_count(n_done)
        tp = hist.throughput()
        line = f"  {name:<{w}}"
        line += (f"  best {best:.6g}" if isinstance(best, (int, float))
                 else "  best -")
        if n_done is not None:
            line += f"  done {n_done:.0f}"
        line += (f"  {tp:.2f} trials/s" if tp is not None else "")
        line += (f"  asks {ask.get('calls', 0)}"
                 f"  inflight {ask.get('inflight', 0):.0f}")
        blocked = ask.get("blocked_sec") or {}
        if blocked.get("count"):
            line += f"  blocked p50 {_fmt_sec(blocked.get('p50'))}"
        dm = snap.get("devmem")
        if dm:
            from .devmem import roll_up

            in_use, _, _, frac = roll_up(dm.get("devices", []))
            if frac is not None:
                line += f"  hbm {frac * 100:.0f}%"
            elif in_use is not None:
                line += f"  hbm {_fmt_bytes(in_use)}"
        out.append(line)
        # the kernel-attribution headline: which program owns the ask —
        # the hottest roofline row (by measured execute time) with its
        # achieved FLOP/s and share of the suggest phase
        roof = sections.get("roofline") or {}
        hot = max((r for r in roof.items() if r[1].get("dispatches")),
                  key=lambda r: r[1].get("execute_sec_total", 0.0),
                  default=None)
        if hot is not None:
            st, r = hot
            rline = (f"  {'':<{w}}  hot kernel {st} x{r['dispatches']}"
                     f"  {_fmt_sec(r.get('execute_sec_total'))}")
            gf = r.get("achieved_flops_per_sec")
            if gf:
                rline += f"  {gf / 1e9:.2f} GF/s"
            if r.get("pct_of_ask") is not None:
                rline += f"  {r['pct_of_ask'] * 100:.0f}% of ask"
            out.append(rline)
        beats = snap.get("last_heartbeats") or {}
        if beats:
            newest = min(beats.values(),
                         key=lambda b: b.get("age_sec", float("inf")))
            comp = min(beats, key=lambda c: beats[c].get("age_sec",
                                                         float("inf")))
            out.append(f"  {'':<{w}}  last beat {comp} "
                       f"{_fmt_sec(newest.get('age_sec'))} ago"
                       + (f"  inflight trials "
                          f"{len(snap.get('inflight_trials') or [])}"
                          if snap.get("inflight_trials") is not None
                          else ""))

    # -- trends (first live source) ---------------------------------------
    for name, snap in sources:
        if "error" in snap:
            continue
        hist = histories[name]
        shown = False
        for key, label in (("ei_p50", "EI p50 "), ("dup", "dup    ")):
            t = hist.trend(key)
            if len(t) >= 2:
                if not shown:
                    out.append("")
                    out.append(f"  trends ({name}):")
                    shown = True
                out.append(f"    {label} {t[-1]:+.3g}  {_spark(t)}")
        break
    return "\n".join(out) + "\n"


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m hyperopt_tpu.obs.top",
        description="Live terminal dashboard over scrape server URLs or "
                    "recorded JSONL streams.")
    p.add_argument("sources", nargs="*",
                   help="scrape server URL(s) (http://host:port), JSONL "
                        "stream(s), or a run directory")
    p.add_argument("--fleet", metavar="SEED_URL", default=None,
                   help="discover every fleet replica's URL from this "
                        "seed replica's /healthz (replica_addrs) and "
                        "dashboard them all")
    p.add_argument("--interval", type=float, default=2.0,
                   help="refresh period in seconds (default 2)")
    p.add_argument("--once", action="store_true",
                   help="render one frame and exit (no screen clearing)")
    p.add_argument("--frames", type=int, default=None,
                   help="exit after N frames (default: until Ctrl-C)")
    args = p.parse_args(argv)

    srcs = list(args.sources)
    if args.fleet:
        srcs.extend(u for u in discover_fleet(args.fleet)
                    if u not in srcs)
    sources = _expand_sources(srcs)
    if not sources:
        print("error: no sources (empty directory, or no --fleet seed?)",
              file=sys.stderr)
        return 2
    histories = {}
    tails = {src: _StreamTail(src) for kind, src in sources
             if kind == "file"}
    n = 0
    try:
        while True:
            snaps = []
            for kind, src in sources:
                name = (src if kind == "url" else os.path.basename(src))
                snap = (fetch_snapshot(src) if kind == "url"
                        else tails[src].snapshot())
                snaps.append((name, snap))
            frame = render_frame(snaps, histories)
            if args.once:
                sys.stdout.write(frame)
                return 0
            sys.stdout.write(_CLEAR + frame)
            sys.stdout.flush()
            n += 1
            if args.frames is not None and n >= args.frames:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
