"""Span/event tracer: nested context-manager spans with wall + CPU time,
structured attributes, and an optional JSONL sink.

This is the first pillar of the run-telemetry layer (SURVEY.md §5 tracing
row).  It absorbs and supersedes the ad-hoc ``PhaseTimings`` dict that used
to live in ``fmin.py``: the tracer aggregates every span's wall clock into a
:class:`PhaseTimings` (``totals``), so ``trials.phase_timings`` keeps its
exact historical shape (plain picklable dict of ``{"sec", "count"}``) while
armed runs additionally stream one JSON line per span.

Design constraints:

* **Dependency-free and cheap when disarmed** — with no sink, a span costs
  two clock reads, a dict update and one bounded flight-ring append; the
  default ``fmin`` path must not regress (<2% on the bench headline is the
  acceptance bar, measured by the ``flight_overhead`` bench stage).
* **Thread-correct nesting** — the open-span stack is thread-local, so
  executor worker threads and the driver thread each get their own parent
  chain while sharing one sink/aggregate.
* **Post-mortem friendly** — records carry absolute timestamps (``ts``)
  next to monotonic durations, so interleaved multi-source JSONL files sort
  into one timeline; every finished span also lands in the process-global
  flight-recorder ring (``obs/flight.py``) so a killed process still dumps
  its recent history, and open spans are registered with the ring so the
  dump names the phase the process died inside.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time

from .flight import get_flight

__all__ = ["PhaseTimings", "Tracer", "JsonlSink", "iter_jsonl", "read_jsonl"]

logger = logging.getLogger(__name__)


class PhaseTimings(dict):
    """Per-phase wall-clock accounting for the ask→tell loop (SURVEY.md §5
    tracing row).  Maps phase name → ``{"sec": total, "count": calls}``;
    lives on the trials object (``trials.phase_timings``) so it survives
    pickling/resume and is inspectable after ``fmin`` returns.

    Since the obs layer landed this is the *aggregate view* the
    :class:`Tracer` maintains — the tracer owns the measurement, this dict
    owns the accumulated totals (and stays a plain dict so checkpoints
    written before the tracer existed still load).
    """

    def add(self, phase, dt):
        e = self.setdefault(phase, {"sec": 0.0, "count": 0})
        e["sec"] += dt
        e["count"] += 1

    def summary(self):
        total = sum(e["sec"] for e in self.values()) or 1.0
        return {
            k: {**e, "frac": e["sec"] / total}
            for k, e in sorted(self.items(), key=lambda kv: -kv[1]["sec"])
        }


class JsonlSink:
    """Append-only JSONL writer shared by tracer, metrics and event log.

    Writes are serialized under a lock and flushed per record (a crashed
    run's partial stream is still a valid prefix).  The file handle opens
    lazily so constructing a sink for a run that never emits costs nothing.

    A dead filesystem (revoked mount, full disk) must not raise into the
    instrumented ask→tell hot path: the first ``OSError`` on open/write/
    flush logs once, closes the handle and permanently disables the sink —
    telemetry degrades to the in-memory flight ring, the run keeps going.
    """

    def __init__(self, path):
        self.path = str(path)
        self._f = None
        self._lock = threading.Lock()
        self._dead = False

    def write(self, record: dict):
        if self._dead:
            return
        line = json.dumps(record, default=_json_default)
        with self._lock:
            if self._dead:
                return
            try:
                if self._f is None:
                    d = os.path.dirname(self.path)
                    if d:
                        os.makedirs(d, exist_ok=True)
                    self._f = open(self.path, "a")
                self._f.write(line + "\n")
                self._f.flush()
            except (OSError, ValueError) as e:
                self._dead = True
                if self._f is not None:
                    try:
                        self._f.close()
                    except OSError:
                        pass
                    self._f = None
                logger.error(
                    "obs sink %s failed (%s); disabling the JSONL stream — "
                    "telemetry degrades to the in-memory flight ring",
                    self.path, e)

    def close(self):
        with self._lock:
            if self._f is not None:
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None

    # sinks ride on objects that cross pickle boundaries (Trials backends);
    # only the path is identity — the handle reopens on next write, and a
    # resumed process gets a fresh try at a sink its parent declared dead
    def __getstate__(self):
        return {"path": self.path}

    def __setstate__(self, state):
        self.path = state["path"]
        self._f = None
        self._lock = threading.Lock()
        self._dead = False


def _json_default(o):
    # numpy scalars and anything else non-JSON: degrade to float/str, never
    # let a telemetry write raise into the instrumented hot path
    try:
        return float(o)
    except Exception:
        return str(o)


def iter_jsonl(path):
    """Stream a JSONL file one record at a time, skipping unparseable
    lines with a warning instead of raising: a process killed mid-write
    leaves a torn final line, and one partial record must never make the
    whole post-mortem unreadable.  ``obs.report`` and the trace exporter
    read through here so a multi-hour multi-controller stream is never
    materialized wholesale in memory."""
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                yield json.loads(line)
            except ValueError:
                logger.warning(
                    "%s:%d: skipping unparseable JSONL record "
                    "(torn write from a killed process?)", path, lineno)


def read_jsonl(path):
    """List-returning wrapper over :func:`iter_jsonl` for callers that want
    the whole (small) stream at once — the historical interface."""
    return list(iter_jsonl(path))


class _Span:
    __slots__ = ("tracer", "name", "attrs", "aggregate", "span_id",
                 "parent_id", "depth", "ts", "_t0", "_c0", "_pushed")

    def __init__(self, tracer, name, attrs, aggregate=True):
        self.tracer = tracer
        self.name = name
        self.attrs = attrs
        self.aggregate = aggregate

    def __enter__(self):
        tr = self.tracer
        fl = tr.flight
        if tr.sink is None:
            # disarmed fast path: two clock reads + the flight ring's
            # open-span note — this is what the default fmin loop pays
            self._pushed = False
            self.ts = time.time()
            self._t0 = time.perf_counter()
            if fl is not None:
                fl.note_open(id(self), self.name, self.ts)
            return self
        stack = tr._stack()
        self.span_id = tr._next_id()
        self.parent_id = stack[-1].span_id if stack else None
        self.depth = len(stack)
        stack.append(self)
        # the stack push is recorded on the span itself: if the tracer is
        # disarmed mid-span, __exit__ must still pop THIS frame or every
        # later span on the thread inherits a phantom parent/depth
        self._pushed = True
        self.ts = time.time()
        if fl is not None:
            fl.note_open(id(self), self.name, self.ts)
        self._c0 = time.process_time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        wall = time.perf_counter() - self._t0
        tr = self.tracer
        if self._pushed:
            stack = tr._stack()
            if stack and stack[-1] is self:
                stack.pop()
        if self.aggregate and tr.totals is not None:
            tr.totals.add(self.name, wall)
        fl = tr.flight
        feed = fl is not None and fl.enabled
        if fl is not None:
            # unconditional: a recorder disabled mid-span must still clear
            # the open-span entry its __enter__ registered, or every later
            # dump reports a phantom open-at-death span
            fl.note_close(id(self))
        # spans entered armed keep streaming even if the tracer was
        # disarmed meanwhile (the push is what grants stream identity);
        # with neither a ring nor a stream consuming, build nothing
        stream = tr.sink is not None and self._pushed
        if not (feed or stream):
            return False
        rec = {
            "kind": "span",
            "name": self.name,
            "ts": self.ts,
            "wall_sec": wall,
        }
        if not self.aggregate:
            # umbrella spans (fmin's "run", device.compile) are excluded
            # from the live phase totals; mark them so offline consumers
            # (report --format json) can rebuild the SAME totals
            rec["aggregate"] = False
        if self._pushed:
            rec["cpu_sec"] = time.process_time() - self._c0
            rec["span_id"] = self.span_id
            rec["parent_id"] = self.parent_id
            rec["depth"] = self.depth
        # thread identity on EVERY recorded span (not just armed ones): the
        # trace exporter assigns tracks by it, and post-mortem dumps of
        # disarmed multi-threaded runs are exactly where it matters
        rec["thread"] = threading.current_thread().name
        if tr.run_id is not None:
            rec["run_id"] = tr.run_id
        if self.attrs:
            rec["attrs"] = self.attrs
        if exc_type is not None:
            rec["error"] = exc_type.__name__
        if feed:
            fl.record(rec)
        if stream:
            tr.sink.write(rec)
        return False


class Tracer:
    """Produces nested spans; aggregates per-name wall clock into
    ``totals`` and (when armed) streams one record per span to ``sink``."""

    def __init__(self, sink=None, totals=None, run_id=None, flight=None):
        self.sink = sink
        self.totals = totals if totals is not None else PhaseTimings()
        self.run_id = run_id
        # every span/event also feeds the process-global flight ring (the
        # post-mortem path that works even when no sink is armed)
        self.flight = flight if flight is not None else get_flight()
        self._local = threading.local()
        self._id_lock = threading.Lock()
        self._id = 0

    def _stack(self):
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _next_id(self):
        with self._id_lock:
            self._id += 1
            return self._id

    def span(self, name, aggregate=True, **attrs):
        """Context manager timing one phase; nests under any open span on
        this thread.  ``aggregate=False`` keeps an umbrella span (e.g. the
        whole ``run``) out of the per-phase totals, which would otherwise
        double-count its children."""
        return _Span(self, name, attrs, aggregate=aggregate)

    def event(self, name, **attrs):
        """Instantaneous structured record (divergence dumps, stop reasons);
        always lands in the flight ring, streamed when a sink is armed."""
        rec = {"kind": "event", "name": name, "ts": time.time()}
        if self.run_id is not None:
            rec["run_id"] = self.run_id
        if attrs:
            rec["attrs"] = attrs
        if self.flight is not None:
            self.flight.record(rec)
        if self.sink is not None:
            self.sink.write(rec)
