"""Append-only perf-trajectory store: the repo's bench history as data.

``BENCH_r01..r05.json`` record what each PR's bench run printed, but as
opaque blobs: the *trajectory* — did ``ask_p50_ms`` creep up over six
PRs, did ``sharded_cand_per_sec`` keep scaling — was unanswerable without
re-reading five JSON tails by hand.  This module gives every bench run a
durable, machine-readable record:

* ``.obs/trajectory.jsonl`` — one JSONL record per bench run (schema
  below), append-only, committed to the repo so the history travels with
  the code.  Torn final lines (a killed bench) are tolerated by every
  reader via :func:`~hyperopt_tpu.obs.trace.iter_jsonl`.
* :func:`record_from_bench_json` backfills the checked-in ``BENCH_r*``
  artifacts; ``python -m hyperopt_tpu.obs.trajectory backfill`` seeds the
  store from day one.
* ``bench.py`` calls :func:`append` after every run, stamping the current
  git revision and mesh/dtype config next to the headline keys.
* ``python -m hyperopt_tpu.obs.report --trend`` renders the per-key
  sparkline history; ``scripts/bench_gate.py`` gates new runs against the
  windowed median of the stored history (direction-aware — see
  :data:`KEY_DIRECTIONS`) instead of a single baseline file.

Record schema (one line of ``.obs/trajectory.jsonl``)::

    {"kind": "bench", "ts": <epoch>, "round": <int|None>,
     "source": "BENCH_r04.json" | "bench.py",
     "git_rev": "<short sha>|None", "backend": "tpu|cpu|...",
     "config": {"n_devices": ..., "hist_dtype": ..., "shard": ...},
     "keys": {<scalar metric>: <float>, ...},
     "series": {<tail metric>: [<float>, ...], ...}}

``keys`` holds one representative value per metric, and only TRUSTED
ones: live bench runs name theirs exactly
(``record_from_headline(keys_override=...)`` — bench.py knows which
stage is the TPE loop); backfilled rounds keep tail metrics in
``series`` only (a recorded tail's first occurrence can name a
different stage, so promoting it to the shared key would poison the
windowed median).  ``series`` keeps every occurrence for metrics that
legitimately repeat (``sharded_cand_per_sec`` per shard count,
``ask_p50_ms`` for tpe then rand), compared positionally by the gate.
"""

from __future__ import annotations

import glob
import json
import logging
import os
import re
import subprocess
import sys
import time

from .trace import iter_jsonl

__all__ = [
    "KEY_DIRECTIONS",
    "TRAJECTORY_PATH",
    "append",
    "load",
    "git_rev",
    "record_from_bench_json",
    "record_from_headline",
    "backfill",
    "trajectory_path",
]

logger = logging.getLogger(__name__)

#: repo-relative location of the store (one dir for every obs artifact the
#: repo commits, so ``.obs/`` can grow siblings later)
TRAJECTORY_PATH = os.path.join(".obs", "trajectory.jsonl")

#: Direction metadata for every gated trajectory key: which way is a
#: REGRESSION, and the default allowed relative change vs the windowed
#: median (shared-hardware noise makes tails loose — see bench_gate.py).
#: This table is the single source for ``scripts/bench_gate.py`` and the
#: ``--trend`` renderer (an unknown key renders but never gates).
KEY_DIRECTIONS = {
    "value": {"direction": "higher", "threshold": 0.20},
    "vs_baseline": {"direction": "higher", "threshold": 0.35},
    "trials_per_sec": {"direction": "higher", "threshold": 0.20},
    "candidates_per_sec": {"direction": "higher", "threshold": 0.20},
    "cv_fits_per_sec": {"direction": "higher", "threshold": 0.20},
    "sharded_cand_per_sec": {"direction": "higher", "threshold": 0.20},
    "ask_p50_ms": {"direction": "lower", "threshold": 0.35},
    "ask_p95_ms": {"direction": "lower", "threshold": 0.50},
    "ask_p99_ms": {"direction": "lower", "threshold": 1.00},
    "peak_hbm_bytes": {"direction": "lower", "threshold": 0.30},
    "history_bytes": {"direction": "lower", "threshold": 0.10},
    # armed-but-idle profiler plane vs off (bench.py profiler_overhead
    # stage).  The bar catches a plane that stopped being idle (an
    # accidental always-on session or capture thread costs tens of
    # percent), not single-digit drift: the stage's min-of-3 wall clock
    # swings ±15-20% run-to-run on shared/single-core hardware (the
    # committed round measured -0.167), so anything tighter gates noise.
    "profiler_overhead_frac": {"direction": "lower", "threshold": 0.35,
                               "absolute": True},
    # request-trace + SLO plane armed vs disarmed per-ask delta through
    # the real handler path (bench.py trace_overhead stage, ISSUE 11).
    # Absolute, like profiler_overhead_frac: the bar catches the plane
    # growing a per-ask serialization/I/O cost (tens of percent), not
    # the scheduler-noise swings of a sub-ms handler loop.
    "trace_overhead_frac": {"direction": "lower", "threshold": 0.35,
                            "absolute": True},
    # fleet shard-reclaim latency (bench.py fleet_recovery stage): wall
    # seconds from a controller dying mid-shard to a survivor holding the
    # reclaimed lease.  Dominated by the stage's lease_ttl constant plus
    # poll jitter; the loose bar catches a broken reclaim path (latency
    # jumping to the barrier timeout), not scheduler noise.
    "recovery_latency_sec": {"direction": "lower", "threshold": 1.00},
    # multi-study serving throughput (bench.py multi_study stage): asks
    # served per wall second at 1k concurrent studies over batched cohort
    # ticks.  The loose-ish bar absorbs shared-hardware noise; a real
    # regression here means the study axis stopped batching.
    "studies_per_sec": {"direction": "higher", "threshold": 0.25},
    # per-ask completion latency of a 1k-study wave (every ask completes
    # with its wave) — deliberately NOT named ask_p99_ms: that key is the
    # single-study interactive loop's, ~1000x smaller, and sharing the
    # name would corrupt the tail-mined series
    "study_ask_p99_ms": {"direction": "lower", "threshold": 1.00},
    # occupied / total cohort slots after the measured waves: near-
    # deterministic for a fixed mix (pow2 slot padding is the only
    # slack), so a drop means the packer started stranding slots
    "slot_utilization_frac": {"direction": "higher", "threshold": 0.15},
    # crash-restart availability gap (bench.py service_resume stage):
    # fresh-scheduler construction on a crashed store root — WAL replay
    # + store rescan + regenerating one in-flight ask per study.
    # Dominated by per-cohort XLA compiles on the regeneration waves;
    # the loose bar catches replay going accidentally quadratic, not
    # compile-time noise
    "resume_latency_sec": {"direction": "lower", "threshold": 1.00},
    # shed fraction of offered asks at 2x sustained capacity through
    # the real handler path: healthy backpressure sits near the excess
    # fraction (~0.5); a collapse toward zero means the bounded
    # admission queue stopped bounding (the overload pin's regression
    # mode — latency explodes instead of clients being told to back
    # off).  Direction "higher" so the gate fires on that collapse.
    "shed_rate_frac": {"direction": "higher", "threshold": 0.60},
    # replicated-fleet serving throughput (bench.py fleet_scale stage,
    # ISSUE 12): ask+tell rounds/sec through in-process fleet replicas
    # at the largest measured replica count.  Loose-ish bar — the stage
    # runs real per-shard schedulers and WAL fsyncs on shared hardware;
    # a real regression means shard routing or the per-shard WAL grew a
    # per-request cost.
    "fleet_studies_per_sec": {"direction": "higher", "threshold": 0.35},
    # shard-failover latency (same stage): wall seconds from a replica
    # abandoning its shards (SIGKILL analog: leases simply stop
    # heartbeating) to a survivor holding + serving the reclaimed
    # shard.  Dominated by the stage's lease TTL constant + steward
    # poll; the loose bar catches a broken reclaim/adopt path (latency
    # jumping toward the client retry ceiling), not scheduler noise.
    "reclaim_latency_sec": {"direction": "lower", "threshold": 1.00},
    # brand-new-space first-ask tail (bench.py coldstart stage, ISSUE
    # 14): p99 of the FIRST TPE-eligible ask of never-seen spaces with
    # the compile plane armed — served at the warming rand floor while
    # the cohort program compiles off-thread.  Its regression mode is an
    # ask BLOCKING on a compile (ms → seconds), which the loose relative
    # bar catches comfortably while absorbing rand-floor noise.
    "cold_study_ask_p99_ms": {"direction": "lower", "threshold": 1.00},
    # background compile queue high-water mark during the cold phase:
    # bounded by the distinct-cohort count; a jump means dedupe or the
    # worker broke and the queue grew past the workload's key count.
    "compile_queue_depth_max": {"direction": "lower", "threshold": 2.00},
    # census kernel-bank reuse across the stage's simulated restart:
    # warmed keys that actually served live traffic / keys warmed.
    # Near 1.0 when the census round-trips; a collapse toward 0 means
    # the bank stopped matching live cohort keys.
    "bank_hit_frac": {"direction": "higher", "threshold": 0.40},
    # WAL checksum overhead on the serving path (bench.py
    # store_integrity stage, ISSUE 15): relative min-of-reps
    # wall-clock delta of real ask+tell round loops through handle()
    # with sealed records vs the checksum-disabled baseline.  The seal
    # is a constant per-record cost (never tail-concentrated), so this
    # mean-side bound bounds its study_ask_p99_ms contribution too.
    # Absolute fixed bar at the acceptance criterion: within 5% or the
    # CRC is too hot for the hot path.
    "checksum_overhead_frac": {"direction": "lower", "threshold": 0.05,
                               "absolute": True},
    # bytes the bounded store GC reclaimed from the stage's seeded
    # garbage (superseded copies, stale tmps, expired dumps).  The
    # stage plants a known-size garbage set, so a collapse means the
    # GC stopped finding it, not that the workload shrank.
    "gc_reclaimed_bytes": {"direction": "higher", "threshold": 0.50},
    # offline scrub throughput over the stage's WAL (records/sec).
    # Loose bar: the scan is pure-Python CRC; a collapse means the
    # verifier went accidentally quadratic.
    "scrub_records_per_sec": {"direction": "higher", "threshold": 0.50},
    # -- the standing per-algo search-QUALITY table (bench.py
    # search_quality stage, ISSUE 16): the zoo mix run to budget under
    # each algorithm.  These are the megakernel's quality bars — the
    # non-bit-exact scoring-loop rewrites (int8/fp8 history, fused
    # Pallas EI) land against THESE instead of impossible bitwise pins.
    # trials_to_target_*: mean 1-based trial index of the first
    # target-clearing loss (budget when unsolved — failure is penalized,
    # not dropped).  Stochastic across the fixed seed set, so the bars
    # are loose; a real regression (a broken posterior, a mis-weighted
    # EI) moves tpe toward rand's level, far past them.
    "trials_to_target_tpe": {"direction": "lower", "threshold": 0.30},
    "trials_to_target_rand": {"direction": "lower", "threshold": 0.30},
    "trials_to_target_anneal": {"direction": "lower", "threshold": 0.30},
    "trials_to_target_mix": {"direction": "lower", "threshold": 0.30},
    "trials_to_target_atpe": {"direction": "lower", "threshold": 0.30},
    # final_regret_*: mean simple regret vs the zoo optimum at budget
    # exhaustion (optimum-known domains only).  Heavier-tailed than
    # trials-to-target — one unlucky hartmann6 run dominates the mean —
    # hence the looser bar.
    "final_regret_tpe": {"direction": "lower", "threshold": 0.75},
    "final_regret_rand": {"direction": "lower", "threshold": 0.75},
    "final_regret_anneal": {"direction": "lower", "threshold": 0.75},
    "final_regret_mix": {"direction": "lower", "threshold": 0.75},
    "final_regret_atpe": {"direction": "lower", "threshold": 0.75},
    # solved_frac_*: fraction of mix studies whose best cleared the zoo
    # loss_target within budget.  Small denominator (the mix size), so
    # one flipped study moves it by 1/n — the bar allows that, a
    # posterior-breaking change zeroes it.
    "solved_frac_tpe": {"direction": "higher", "threshold": 0.30},
    "solved_frac_rand": {"direction": "higher", "threshold": 0.30},
    "solved_frac_anneal": {"direction": "higher", "threshold": 0.30},
    "solved_frac_mix": {"direction": "higher", "threshold": 0.30},
    "solved_frac_atpe": {"direction": "higher", "threshold": 0.30},
    # armed-vs-disarmed quality-plane per-tell delta through the real
    # handle() path (bench.py quality_overhead stage).  Absolute fixed
    # bar at the acceptance criterion, the checksum_overhead_frac
    # pattern: within 5% or the tracker is too hot for the tell path.
    "quality_overhead_frac": {"direction": "lower", "threshold": 0.05,
                              "absolute": True},
    # armed-vs-disarmed cost-attribution per-wave delta through the
    # real handle() path (bench.py load_attribution stage) — the same
    # 5% absolute acceptance bar: attribution must be noise on the
    # wave, not a tax
    "attribution_overhead_frac": {"direction": "lower", "threshold": 0.05,
                                  "absolute": True},
    # heat skew (max/mean shard heat) of the bench stage's deliberately
    # skewed placement — lower is better (1.0 = balanced); a regression
    # means attribution stopped seeing the imbalance it exists to see
    "shard_heat_skew": {"direction": "lower", "threshold": 0.30},
    # blackbox time-to-detect (bench.py blackbox_probe stage, ISSUE 18):
    # wall seconds from corruption injected into the serving path to the
    # prober's first non-green verdict, driven with a tight probe period
    # so the measurement is the detection pipeline, not the period.  The
    # loose bar catches detection taking extra cycles (a broken digest
    # or lint path), not shared-hardware cycle-time noise.
    "probe_detection_latency_sec": {"direction": "lower",
                                    "threshold": 1.00},
    # armed-vs-disarmed prober tax on TENANT traffic through the real
    # handle() path while canary cycles run concurrently — the same 5%
    # absolute acceptance bar as the other planes: blackbox auditing
    # must be noise on the tenants it audits, not a tax.
    "probe_overhead_frac": {"direction": "lower", "threshold": 0.05,
                            "absolute": True},
    # fused-suggest megakernel throughput (bench.py megakernel stage,
    # ISSUE 19): candidates/sec through the armed (interpret-on-CPU /
    # Pallas-on-TPU) cohort at the stage's largest (components,
    # candidates, hist_cap) point.  Loose bar — the interpret path is an
    # XLA emulation whose constant factors swing with scheduler noise; a
    # real regression means the fused tick grew a per-candidate cost.
    "megakernel_cand_per_sec": {"direction": "higher", "threshold": 0.35},
    # quantized-history HBM footprint: int8 resident history bytes /
    # f32 resident history bytes at EQUAL hist_cap.  Near-deterministic
    # (pure dtype arithmetic plus the unquantized losses/flags rows), so
    # the absolute fixed bar sits at the acceptance criterion: int8 must
    # stay <= 0.3x f32 or quantization stopped paying for its cap.
    "megakernel_int8_bytes_frac": {"direction": "lower", "threshold": 0.30,
                                   "absolute": True},
    # tenant-fairness skew (bench.py tenant_fairness stage, ISSUE 20):
    # light-tenant ask p99 under a 10:1 noisy neighbour, as a multiple
    # of the light tenant's solo p99, with the DRR packer armed.  The
    # acceptance bar is 3x; the loose trajectory bar catches the packer
    # silently degenerating to first-come order, not shared-hardware
    # tail noise.
    "tenant_p99_skew": {"direction": "lower", "threshold": 0.50},
    # armed-vs-disarmed tenant-plane per-ask delta through the real
    # handle() path — the same 5% absolute acceptance bar as the other
    # planes: attribution + DRR must be noise on the ask, not a tax.
    "tenant_overhead_frac": {"direction": "lower", "threshold": 0.05,
                             "absolute": True},
}

#: metrics mined from a bench round's recorded output tail (the same
#: regex bench_gate has always used — the JSON detail block is printed to
#: stderr and only its tail survives in BENCH_r*.json)
TAIL_METRICS = ("trials_per_sec", "candidates_per_sec", "cv_fits_per_sec",
                "sharded_cand_per_sec",
                "ask_p50_ms", "ask_p95_ms", "ask_p99_ms",
                "peak_hbm_bytes", "history_bytes",
                "profiler_overhead_frac", "trace_overhead_frac",
                "recovery_latency_sec",
                "studies_per_sec", "study_ask_p99_ms",
                "slot_utilization_frac",
                "resume_latency_sec", "shed_rate_frac",
                "fleet_studies_per_sec", "reclaim_latency_sec",
                "cold_study_ask_p99_ms", "compile_queue_depth_max",
                "bank_hit_frac",
                "checksum_overhead_frac", "gc_reclaimed_bytes",
                "scrub_records_per_sec",
                "trials_to_target_tpe", "trials_to_target_rand",
                "trials_to_target_anneal", "trials_to_target_mix",
                "trials_to_target_atpe",
                "final_regret_tpe", "final_regret_rand",
                "final_regret_anneal", "final_regret_mix",
                "final_regret_atpe",
                "solved_frac_tpe", "solved_frac_rand",
                "solved_frac_anneal", "solved_frac_mix",
                "solved_frac_atpe",
                "quality_overhead_frac",
                "attribution_overhead_frac", "shard_heat_skew",
                "probe_detection_latency_sec", "probe_overhead_frac",
                "megakernel_cand_per_sec", "megakernel_int8_bytes_frac",
                "tenant_p99_skew", "tenant_overhead_frac")


def trajectory_path(root=None):
    """Absolute store path under ``root`` (default: the repo root, two
    levels above this file)."""
    if root is None:
        root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(root, TRAJECTORY_PATH)


def git_rev(root=None):
    """Short git revision of ``root``, or None (a store consumer must
    never require git to be present)."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=root or os.getcwd(), capture_output=True, text=True,
            timeout=10)
    except Exception:
        return None
    rev = (out.stdout or "").strip()
    return rev if out.returncode == 0 and rev else None


def load(path=None):
    """Every parseable ``kind="bench"`` record in the store, oldest
    first.  Torn lines (a bench killed mid-append) warn and skip via
    ``iter_jsonl`` — one partial record must never blind the gate to the
    rest of the history.  Filtering by kind here keeps every consumer
    (the gate, ``--trend``) sane when pointed at the wrong JSONL — a
    telemetry stream renders as an empty store, not thousands of header
    rows."""
    path = path or trajectory_path()
    if not os.path.exists(path):
        return []
    return [r for r in iter_jsonl(path)
            if isinstance(r, dict) and r.get("kind") == "bench"]


def append(record, path=None):
    """Append one record (a single JSON line + flush) and return the path.
    Append-only by design: the store is a history, and rewriting history
    is exactly the failure mode a regression gate exists to prevent."""
    path = path or trajectory_path()
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "a") as f:
        f.write(json.dumps(record, default=float, sort_keys=True) + "\n")
        f.flush()
    return path


def _mine_tail(tail):
    """``{metric: [occurrences]}`` from a recorded output tail."""
    series = {}
    for name in TAIL_METRICS:
        vals = re.findall(rf'"{name}":\s*(-?[0-9][0-9.eE+-]*)', tail or "")
        if vals:
            series[name] = [float(v) for v in vals]
    return series


def _split_keys(parsed, series, tail_fallback=True):
    """Scalar key dict for a record: the parsed headline values, plus —
    when ``tail_fallback`` — the first occurrence of each tail metric as
    a provisional scalar view.  The fallback is ONLY safe for live
    bench.py records, where ``keys_override`` replaces it with exactly
    named figures before the record is stored; backfilled rounds must
    NOT use it, because a recorded tail's first occurrence can name a
    different stage than the live runs' representative (r02's first
    ``candidates_per_sec`` is the numpy baseline) — storing it under the
    same key would let a real TPE-loop regression hide behind a
    baseline-level median."""
    keys = {}
    for k in ("value", "vs_baseline"):
        v = (parsed or {}).get(k)
        if isinstance(v, (int, float)):
            keys[k] = float(v)
    if tail_fallback:
        for name, vals in series.items():
            keys.setdefault(name, vals[0])
    return keys


def record_from_bench_json(path):
    """A trajectory record backfilled from one checked-in ``BENCH_r*.json``
    artifact (the driver's ``{n, cmd, rc, tail, parsed}`` shape).  Rounds
    that crashed (``rc != 0``, ``parsed: null``) still record — an empty
    round is part of the trajectory, and the gate skips keys it lacks."""
    with open(path) as f:
        rec = json.load(f)
    m = re.search(r"BENCH_r(\d+)\.json$", os.path.basename(path))
    parsed = rec.get("parsed") or {}
    series = _mine_tail(rec.get("tail"))
    return {
        "kind": "bench",
        "ts": os.path.getmtime(path),
        "round": int(m.group(1)) if m else None,
        "source": os.path.basename(path),
        "git_rev": None,  # the artifact predates the store; unknowable
        "rc": rec.get("rc"),
        "backend": parsed.get("backend"),
        "config": {},
        "keys": _split_keys(parsed, series, tail_fallback=False),
        "series": series,
    }


def record_from_headline(headline, detail_tail=None, config=None, root=None,
                         keys_override=None):
    """The record ``bench.py`` appends after printing its headline line:
    the parsed headline dict + metrics mined from the detail JSON it just
    wrote to stderr, stamped with the live git revision and mesh/dtype
    config.

    ``keys_override`` replaces the first-tail-occurrence scalar view for
    metrics the producer can name exactly — bench.py knows which stage is
    the TPE loop, the regex miner only knows text order (its first
    ``candidates_per_sec`` hit is the numpy baseline stage, not the
    headline kernel).  The full ``series`` keeps every occurrence either
    way."""
    series = _mine_tail(detail_tail)
    keys = _split_keys(headline, series)
    for k, v in (keys_override or {}).items():
        if isinstance(v, (int, float)):
            keys[k] = float(v)
    return {
        "kind": "bench",
        "ts": time.time(),
        "round": None,
        "source": "bench.py",
        "git_rev": git_rev(root),
        "rc": 0,
        "backend": headline.get("backend"),
        "config": dict(config or {}),
        "keys": keys,
        "series": series,
    }


def backfill(root=None, path=None, force=False):
    """Seed the store from every ``BENCH_r*.json`` under ``root`` (round
    order), skipping rounds already present unless ``force``.  Returns the
    list of rounds appended."""
    if root is None:
        root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
    path = path or trajectory_path(root)
    have = {r.get("round") for r in load(path)
            if r.get("round") is not None} if not force else set()

    def round_no(p):
        m = re.search(r"BENCH_r(\d+)\.json$", p)
        return int(m.group(1)) if m else -1

    appended = []
    for bench_path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json")),
                             key=round_no):
        rec = record_from_bench_json(bench_path)
        if rec["round"] in have:
            continue
        append(rec, path)
        appended.append(rec["round"])
    return appended


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m hyperopt_tpu.obs.trajectory",
        description="Manage the append-only bench trajectory store "
                    "(.obs/trajectory.jsonl).")
    p.add_argument("cmd", choices=("backfill", "show"),
                   help="backfill: seed from BENCH_r*.json; show: dump "
                        "the stored records")
    p.add_argument("--root", default=None,
                   help="repo root (default: auto-detected)")
    p.add_argument("--path", default=None, help="store path override")
    p.add_argument("--force", action="store_true",
                   help="backfill rounds even if already present")
    args = p.parse_args(argv)
    if args.cmd == "backfill":
        rounds = backfill(root=args.root, path=args.path, force=args.force)
        print(f"backfilled rounds: {rounds or 'none (all present)'}")
        return 0
    for rec in load(args.path or trajectory_path(args.root)):
        print(json.dumps(rec, sort_keys=True, default=float))
    return 0


if __name__ == "__main__":
    sys.exit(main())
