"""Blackbox prober & continuous correctness audit (ISSUE 18).

Every observability layer so far is *whitebox* — the server reporting
on itself.  Nothing continuously verifies, from the **client's** side
of the socket, that the fleet is actually serving *correct* proposals.
The determinism contract (same seed ⇒ same proposal stream) makes that
check cheap and airtight: a pinned-seed canary study's proposal stream
has exactly one right answer, so one low-rate synthetic study per
probe cycle detects silent wrong-answers — stale widened programs,
mislabeled degrade/warming floors, replica divergence, corruption that
slipped past the checksums — within a bounded number of cycles.

One :class:`Prober` is one rate-limited, deadline-bounded, fail-open
daemon thread.  Each cycle drives the canary (``zoo["quadratic1"]``,
pinned seed, rand startup then TPE asks) through the **real**
``ServiceClient``/HTTP path — admit → ask → tell → close — and renders
one sealed verdict on three axes:

* **golden-stream correctness** — the canary's proposal-stream digest
  (sha256 over the canonical JSON of ``[{tid, params}, ...]``) must
  match the committed golden fixture (``probe_golden.json``, keyed by
  JAX backend) bitwise.  An un-flagged stream that differs is a
  ``mismatch`` — silent corruption, a degraded floor mislabeled
  ``algo:"tpe"``, seed skew.  In fleet mode the same canary replays
  against every target replica via direct addressing and the digests
  cross-check (replica divergence no per-study WAL can see).
* **client-view golden signals** — per-request availability and ask
  latency as the user experiences them (retries and redirect hops
  included), feeding the blackbox SLO objectives (``probe_avail``,
  ``probe_golden_match``, ``probe_ask_p99_ms``) on the existing
  burn-rate plane — distinct from the server-side objectives, so a
  wedged listener finally burns budget.
* **response-contract lint** — schema fields, trace echo, and
  warming/degraded flags consistent with the timeline/WAL record the
  probe's trace id lands in (an honest flag demotes the verdict to
  ``degraded``, never ``mismatch`` — forced degrades are detected
  loudly but not confused with corruption).

Verdicts append to a CRC32C-sealed, torn-line-tolerant
``fleet/probes/<replica>.jsonl`` ledger (the heat-ledger idiom).  A
golden mismatch emits a flight-ring record, an evidence bundle
(responses + canary timeline/WAL segment + trace ids) and ONE
edge-triggered bounded profiler capture per episode (cooldown, like
the SLO plane's escalation).

Canary traffic is free by construction: canary studies carry
``canary=True`` through admission (excluded from quality/load/SLO
tenant metrics and the census bank — ``service/scheduler.py``), use a
non-default ``n_EI_candidates`` so they never share a cohort slot with
tenant studies, and the disarmed prober is literally absent — zero
threads, zero allocations (the server holds ``prober = None``).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time
from collections import deque

from ..service import integrity
from .trace import Tracer

__all__ = ["Prober", "ProbeLedger", "CANARY", "DEFAULT_PROBE_PERIOD_SEC",
           "canary_key", "stream_digest", "load_golden", "local_digest",
           "regen_golden", "probes_path_for", "read_probes",
           "detection_stats", "main"]

logger = logging.getLogger(__name__)

#: probe cycle cadence (overridable: HYPEROPT_TPU_PROBE_PERIOD / --probe)
DEFAULT_PROBE_PERIOD_SEC = 30.0

#: the pinned canary study.  ``n_ei`` is deliberately NON-default so the
#: canary compiles its own cohort program and never shares a cohort slot
#: (or a census row) with tenant studies of the same space.  Changing
#: ANY field invalidates the committed golden fixture — regen it
#: (``python -m hyperopt_tpu.obs.prober --regen-golden``).
CANARY = {
    "zoo": "quadratic1",
    "seed": 20180621,
    "n_startup": 3,
    "asks": 6,
    "n_ei": 31,
}

#: verdict severity order (worst wins when axes disagree)
_VERDICTS = ("ok", "degraded", "contract", "mismatch", "error")

#: probe spans feed the process flight ring (sink-less tracer), so they
#: ride into postmortem dumps and the Perfetto export next to the waves
#: they probed
_tracer = Tracer()

#: subdirectory of a store root holding the per-replica probe ledgers
PROBES_DIR = os.path.join("fleet", "probes")


def probes_path_for(store_root, replica_id):
    """One append-only verdict ledger per replica (the heat-ledger
    layout): replicas never share a file, readers merge the dir."""
    return os.path.join(str(store_root), PROBES_DIR,
                        f"{replica_id}.jsonl")


def canary_key(canary=None):
    """The fixture key for a canary config — any drift in the pinned
    study invalidates the committed digest."""
    c = dict(CANARY, **(canary or {}))
    return (f"{c['zoo']}:s{c['seed']}:n{c['n_startup']}"
            f":a{c['asks']}:e{c['n_ei']}:v1")


def stream_digest(stream):
    """Bitwise digest of one canary proposal stream: sha256 (16 hex) of
    the canonical JSON of ``[{"tid": .., "params": {..}}, ...]``.
    Floats survive the HTTP JSON round trip exactly (shortest-repr), so
    the digest a blackbox probe computes equals the digest the same
    stream yields in-process."""
    body = json.dumps(
        [{"tid": int(e["tid"]), "params": e["params"]} for e in stream],
        sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(body.encode("utf-8")).hexdigest()[:16]


def _golden_path():
    return os.path.join(os.path.dirname(__file__), "probe_golden.json")


def _backend_key():
    """The golden fixture is keyed by JAX backend: the determinism
    contract pins streams per backend, not across backends (CPU vs TPU
    float paths differ bitwise)."""
    try:
        import jax

        return str(jax.default_backend())
    except Exception:  # noqa: BLE001 - fixture lookup must never raise
        return "cpu"


def load_golden(canary=None, backend=None, path=None):
    """The committed golden digest for this canary + backend, or None
    (unknown backend / missing fixture → the prober self-pins on first
    trust: TOFU, flagged ``golden_source: "tofu"`` in every verdict)."""
    path = path or _golden_path()
    try:
        with open(path, encoding="utf-8") as f:
            fx = json.load(f)
        return fx["digests"][canary_key(canary)][backend or _backend_key()]
    except (OSError, ValueError, KeyError, TypeError):
        return None


# ---------------------------------------------------------------------------
# transports: how a probe cycle talks to a server
# ---------------------------------------------------------------------------


class _HTTPTransport:
    """The production transport: one :class:`ServiceClient` pinned to a
    SINGLE replica URL (fleet divergence checks need direct addressing,
    not seed failover), ``x-probe: 1`` on every request so the server
    keeps canary traffic out of the tenant SLO objectives."""

    def __init__(self, url, timeout=10.0):
        from ..retry import RetryPolicy
        from ..service.client import ServiceClient

        self.client = ServiceClient(
            url, timeout=timeout,
            retry=RetryPolicy(max_retries=2, base_delay=0.05,
                              max_delay=0.5),
            headers={"x-probe": "1"})

    def request(self, method, path, body=None):
        return self.client.request(method, path, body,
                                   retryable=(429, 503, 507))


class _LocalTransport:
    """In-process transport over ``ServiceHTTPServer.handle`` — the
    golden-fixture regen path and the tier-1 tests (no sockets).  The
    digest is transport-invariant: params round-trip through JSON here
    too, exactly like the wire."""

    def __init__(self, server):
        self.server = server

    def request(self, method, path, body=None):
        status, payload = self.server.handle(
            method, path, body or {}, headers={"x-probe": "1"})
        # the wire round trip: floats in params become JSON text and
        # back, so local and HTTP digests agree byte-for-byte
        return status, json.loads(json.dumps(payload, default=str))


# ---------------------------------------------------------------------------
# the sealed verdict ledger
# ---------------------------------------------------------------------------


class ProbeLedger:
    """Append-only sealed verdict lines for one replica (the
    ``HeatLedger`` idiom): O_APPEND single-line writes, CRC32C sealed,
    best-effort on ANY OSError with a warn-once latch — a full disk
    must cost verdict durability, never a probe cycle."""

    def __init__(self, path):
        self.path = str(path)
        self._warned = False

    def append(self, rec):
        line = (integrity.seal(rec) + "\n").encode()
        try:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            fd = os.open(self.path,
                         os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
            try:
                os.write(fd, line)
            finally:
                os.close(fd)
        except OSError as e:
            if not self._warned:
                self._warned = True
                logger.warning("probe ledger: cannot append to %s (%s); "
                               "verdicts will not survive a restart",
                               self.path, e)


def read_probes(path):
    """Classified read of one verdict ledger: returns ``(records,
    n_corrupt, n_torn)`` — CORRUPT lines are counted and skipped (a
    bit-flip costs one verdict, never the view), the TORN final line
    silently (the normal crash artifact)."""
    recs, corrupt, torn = [], 0, 0
    try:
        for c in integrity.iter_checked_jsonl(path):
            if c.rec is None:
                if c.status == integrity.CORRUPT:
                    corrupt += 1
                else:
                    torn += 1
                continue
            if c.status == integrity.CORRUPT:
                corrupt += 1
                continue
            if c.rec.get("kind") == "probe":
                recs.append(c.rec)
    except OSError:
        pass
    return recs, corrupt, torn


def detection_stats(recs):
    """Detection-latency statistics over a verdict sequence: for every
    green→red edge, the gap between the last green verdict and the
    first non-green one — the blackbox time-to-detect the obs.report
    section and the bench stage publish."""
    lats = []
    last_ok_ts = None
    was_ok = None
    for r in sorted(recs, key=lambda r: r.get("ts") or 0.0):
        ok = r.get("verdict") == "ok"
        ts = r.get("ts")
        if ts is None:
            continue
        if not ok and was_ok and last_ok_ts is not None:
            lats.append(ts - last_ok_ts)
        if ok:
            last_ok_ts = ts
        was_ok = ok
    if not lats:
        return {"episodes": 0}
    lats.sort()
    return {"episodes": len(lats),
            "min_sec": lats[0], "max_sec": lats[-1],
            "mean_sec": sum(lats) / len(lats)}


# ---------------------------------------------------------------------------
# the prober
# ---------------------------------------------------------------------------


class Prober:
    """One blackbox prober: N target replicas, one canary per target
    per cycle, one sealed verdict per target.  ``start()`` runs the
    daemon thread; tests call :meth:`run_cycle` directly (clock
    injectable, no sleeping).  Fail-open everywhere: a probe cycle can
    render an ``error`` verdict but never raise out of the thread."""

    def __init__(self, targets, period=None, slo=None, metrics=None,
                 ledger_path=None, replica="single", wal_path=None,
                 canary=None, golden=None, clock=time.time,
                 transport_factory=None, request_timeout=None,
                 escalation_cooldown=600.0, evidence_dir=None,
                 profile_capture=True, keep=64):
        self.targets = [str(t).rstrip("/") for t in
                        ([targets] if isinstance(targets, str)
                         else list(targets))]
        if not self.targets:
            raise ValueError("prober needs at least one target")
        self.period = float(period if period is not None
                            else DEFAULT_PROBE_PERIOD_SEC)
        self.slo = slo
        self.metrics = metrics
        self.replica = str(replica)
        self.wal_path = wal_path
        self.canary = dict(CANARY, **(canary or {}))
        self.backend = _backend_key()
        if golden is not None:
            self.golden, self.golden_source = str(golden), "pinned"
        else:
            g = load_golden(self.canary, backend=self.backend)
            # TOFU fallback for backends without a committed fixture:
            # the first clean un-flagged stream self-pins, later cycles
            # (and every cross-replica check) still compare bitwise
            self.golden = g
            self.golden_source = "fixture" if g is not None else "tofu"
        self._clock = clock
        self.ledger = (ProbeLedger(ledger_path) if ledger_path else None)
        self.evidence_dir = evidence_dir or (
            os.path.join(os.path.dirname(str(ledger_path)), "evidence")
            if ledger_path else None)
        # each cycle must finish well inside its period (rate-limited
        # AND deadline-bounded); per-request budget derives from it
        self.cycle_deadline = max(1.0, 0.8 * self.period)
        self._timeout = (request_timeout if request_timeout is not None
                         else max(0.5, self.cycle_deadline
                                  / max(1, self.canary["asks"] + 3)))
        self._transport_factory = (transport_factory
                                   or (lambda url: _HTTPTransport(
                                       url, timeout=self._timeout)))
        self.escalation_cooldown = float(escalation_cooldown)
        self.profile_capture = bool(profile_capture)
        self._lock = threading.Lock()
        self._thread = None
        self._stop = threading.Event()
        self.cycles = 0
        self.verdicts = {v: 0 for v in _VERDICTS}
        self.recent = deque(maxlen=int(keep))
        self.streak = 0          # consecutive golden-matching cycles
        self.last = None         # newest per-cycle summary record
        self._last_ok_ts = None
        self._was_ok = None
        self.detection_latencies = deque(maxlen=int(keep))
        self._in_episode = False  # edge trigger for escalation
        self._last_escalation = None
        self.escalations = 0
        self.evidence_bundles = deque(maxlen=8)  # paths, for /probes

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        """Spawn the probe loop (daemon, one thread).  Idempotent."""
        with self._lock:
            if self._thread is not None:
                return self._thread
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="hyperopt-prober", daemon=True)
            self._thread.start()
            return self._thread

    def stop(self, timeout=5.0):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
        self._thread = None

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.run_cycle()
            except Exception:  # noqa: BLE001 - the fail-open contract
                logger.warning("probe cycle failed (continuing)",
                               exc_info=True)
            self._stop.wait(self.period)

    # -- one probe cycle ---------------------------------------------------

    def run_cycle(self, now=None):
        """Drive the canary against every target, cross-check digests,
        render + seal one verdict per target and roll the summary.
        Returns the cycle record (the last entry of ``recent``)."""
        now = self._clock() if now is None else now
        self.cycles += 1
        cycle = self.cycles
        deadline = time.monotonic() + self.cycle_deadline
        results = []
        with _tracer.span("probe.cycle", cycle=cycle,
                          targets=len(self.targets)):
            for url in self.targets:
                results.append(self._probe_target(url, cycle, deadline))
        # fleet divergence: every clean un-flagged stream must agree
        # bitwise across replicas — a diverging replica is corrupt even
        # when no golden fixture exists for this backend (TOFU mode)
        digests = {r["target"]: r.get("digest") for r in results
                   if r.get("digest") and not r.get("flagged")}
        diverged = len(set(digests.values())) > 1
        if self.golden is None and self.golden_source == "tofu":
            clean = [r for r in results
                     if r["verdict"] == "ok" and r.get("digest")]
            if clean and not diverged:
                self.golden = clean[0]["digest"]
                logger.warning(
                    "prober: no committed golden for backend %r — "
                    "self-pinned digest %s (TOFU); commit it via "
                    "--regen-golden to detect cross-restart drift",
                    self.backend, self.golden)
        worst = "ok"
        for r in results:
            if diverged and r.get("digest") and not r.get("flagged"):
                r["diverged"] = True
                if _VERDICTS.index(r["verdict"]) \
                        < _VERDICTS.index("mismatch"):
                    r["verdict"] = "mismatch"
                    r["why"] = "replica stream divergence"
            if _VERDICTS.index(r["verdict"]) > _VERDICTS.index(worst):
                worst = r["verdict"]
        for r in results:
            r["ts"] = now
            r["verdict_cycle"] = worst
            self._seal_and_count(r)
        summary = {"cycle": cycle, "ts": now, "verdict": worst,
                   "diverged": diverged,
                   "targets": {r["target"]: r["verdict"]
                               for r in results}}
        self._roll(summary, results, now)
        return summary

    def _probe_target(self, url, cycle, deadline):
        """One canary study against one replica → one verdict record."""
        c = self.canary
        rec = {"kind": "probe", "cycle": cycle, "replica": self.replica,
               "target": url, "backend": self.backend,
               "golden": self.golden, "golden_source": self.golden_source,
               "canary": canary_key(c)}
        stream, flags, lat_ms, traces, err = [], [], [], [], None
        responses = []
        timeline = None
        sid = None
        t = self._transport_factory(url)
        try:
            status, payload = self._probe_req(
                t, "POST", "/study",
                {"zoo": c["zoo"], "seed": c["seed"],
                 "n_startup_jobs": c["n_startup"],
                 "n_EI_candidates": c["n_ei"], "canary": True},
                responses, lat_ms, deadline)
            if status != 200:
                raise RuntimeError(f"canary admit failed: HTTP {status} "
                                   f"{payload.get('error')}")
            sid = payload["study_id"]
            from ..zoo import ZOO

            objective = ZOO[c["zoo"]].objective
            for i in range(c["asks"]):
                status, payload = self._probe_req(
                    t, "POST", "/ask",
                    {"study_id": sid, "n": 1,
                     "req": f"probe-{self.replica}-{cycle}-{i}"},
                    responses, lat_ms, deadline, is_ask=True)
                if status != 200:
                    raise RuntimeError(
                        f"canary ask failed: HTTP {status} "
                        f"{payload.get('error')}")
                for tr in payload["trials"]:
                    stream.append({"tid": tr["tid"],
                                   "params": tr["params"]})
                    flags.append({
                        "algo": tr.get("algo"),
                        "degraded": bool(tr.get("degraded")
                                         or payload.get("degraded")),
                        "warming": bool(tr.get("warming")
                                        or payload.get("warming"))})
                if payload.get("trace"):
                    traces.append(payload["trace"])
                loss = float(objective(dict(
                    payload["trials"][0]["params"])))
                status, _ = self._probe_req(
                    t, "POST", "/tell",
                    {"study_id": sid,
                     "tid": payload["trials"][0]["tid"], "loss": loss},
                    responses, lat_ms, deadline)
                if status not in (200, 409):
                    raise RuntimeError(f"canary tell failed: "
                                       f"HTTP {status}")
            status, timeline = self._probe_req(
                t, "GET", f"/study/{sid}/timeline", None,
                responses, lat_ms, deadline)
            if status != 200:
                timeline = None
        except Exception as e:  # noqa: BLE001 - becomes the verdict
            err = f"{type(e).__name__}: {e}"
        finally:
            if sid is not None:
                try:
                    self._probe_req(t, "POST", "/close",
                                    {"study_id": sid},
                                    responses, lat_ms, deadline)
                except Exception:  # noqa: BLE001 - best-effort close
                    pass
        rec["study_id"] = sid
        rec["trace_ids"] = traces
        rec["asks"] = len(stream)
        if lat_ms:
            s = sorted(lat_ms)
            rec["latency_ms"] = {
                "p50": s[len(s) // 2], "max": s[-1],
                "mean": sum(s) / len(s)}
        flagged = any(f["degraded"] or f["warming"] for f in flags)
        rec["flagged"] = flagged
        violations = self._lint_contract(responses, flags, timeline,
                                         traces)
        if err is not None:
            rec["verdict"], rec["why"] = "error", err
        else:
            rec["digest"] = stream_digest(stream)
            if flagged:
                # honest degrade/warming: detected and reported, but a
                # flagged floor is NOT silent corruption — the stream
                # legitimately differs from golden
                rec["verdict"] = "degraded"
                rec["why"] = "degraded/warming-flagged proposals"
            elif self.golden is not None \
                    and rec["digest"] != self.golden:
                rec["verdict"] = "mismatch"
                rec["why"] = (f"stream digest {rec['digest']} != "
                              f"golden {self.golden}")
            elif violations:
                rec["verdict"] = "contract"
                rec["why"] = "; ".join(violations[:3])
            else:
                rec["verdict"] = "ok"
        if violations:
            rec["violations"] = violations
        if rec["verdict"] == "mismatch":
            rec["evidence"] = self._evidence_bundle(
                rec, responses, timeline) or None
        # SLO feed: golden_match burns on mismatch only (an honest
        # degrade is the ladder doing its job; availability burned
        # already if requests failed)
        if self.slo is not None:
            try:
                self.slo.record_probe("probe_golden_match",
                                      rec["verdict"] != "mismatch",
                                      now=self._clock())
            except Exception:  # noqa: BLE001
                pass
        return rec

    def _probe_req(self, transport, method, path, body, responses,
                   lat_ms, deadline, is_ask=False):
        """One client-view exchange: measured wall latency (retries and
        hops included), availability + ask-latency SLO feed, bounded by
        the cycle deadline."""
        if time.monotonic() > deadline:
            raise TimeoutError("probe cycle deadline exceeded")
        t0 = time.perf_counter()
        ok = False
        try:
            status, payload = transport.request(method, path, body)
            ok = status < 500
            return status, payload
        finally:
            dt_ms = (time.perf_counter() - t0) * 1e3
            lat_ms.append(dt_ms)
            if len(responses) < 64:
                responses.append({"method": method, "path": path,
                                  "latency_ms": round(dt_ms, 3),
                                  "ok": ok})
            if self.slo is not None:
                try:
                    now = self._clock()
                    self.slo.record_probe("probe_avail", ok, now=now)
                    if is_ask and ok:
                        obj = self.slo.objectives.get("probe_ask_p99_ms")
                        thr = (obj.threshold_ms if obj is not None
                               else None)
                        self.slo.record_probe(
                            "probe_ask_p99_ms",
                            thr is None or dt_ms <= thr, now=now)
                except Exception:  # noqa: BLE001
                    pass

    @staticmethod
    def _lint_contract(responses, flags, timeline, traces):
        """Response-contract lint: schema fields already enforced by
        the drive (KeyError → error verdict); here the cross-checks —
        trace echo, and flags consistent with the timeline record each
        probe trace id landed in."""
        violations = []
        if timeline is None or not isinstance(timeline, dict):
            return violations  # timeline fetch failed: availability's job
        events = timeline.get("events")
        if not isinstance(events, list):
            violations.append("timeline carries no events list")
            return violations
        asks = {e.get("trace"): e for e in events
                if e.get("event") == "ask" and e.get("trace")}
        for i, (trace, f) in enumerate(zip(traces, flags)):
            ev = asks.get(trace)
            if ev is None:
                violations.append(
                    f"ask #{i}: trace {trace} not on the study timeline")
                continue
            resp_floor = (f["degraded"] or f["warming"]
                          or f["algo"] == "rand")
            wal_floor = (ev.get("algo") == "rand"
                         and i >= 0)  # startup asks are rand too
            if ev.get("algo") == "rand" and f["algo"] == "tpe":
                violations.append(
                    f"ask #{i}: response says tpe, WAL says rand "
                    "(mislabeled floor)")
            if bool(ev.get("degraded")) != bool(f["degraded"]):
                violations.append(
                    f"ask #{i}: degraded flag disagrees with the "
                    f"timeline record (resp={f['degraded']})")
            del resp_floor, wal_floor
        return violations

    # -- verdict plumbing --------------------------------------------------

    def _seal_and_count(self, rec):
        self.verdicts[rec["verdict"]] = (
            self.verdicts.get(rec["verdict"], 0) + 1)
        if self.ledger is not None:
            self.ledger.append(dict(rec))
        if self.metrics is not None:
            try:
                self.metrics.counter(
                    f"probe.verdict.{rec['verdict']}").inc()
            except Exception:  # noqa: BLE001
                pass

    def _roll(self, summary, results, now):
        """Fold one cycle into the rolling state: streak, detection
        latency, gauges, escalation edge."""
        ok = summary["verdict"] == "ok"
        with self._lock:
            self.streak = self.streak + 1 if ok else 0
            if not ok and self._was_ok and self._last_ok_ts is not None:
                lat = now - self._last_ok_ts
                summary["detection_latency_sec"] = lat
                self.detection_latencies.append(lat)
            if ok:
                self._last_ok_ts = now
                self._in_episode = False
            self._was_ok = ok
            self.last = summary
            self.recent.append(summary)
        if self.metrics is not None:
            try:
                g = self.metrics.gauge
                g("probe.cycles").set(float(self.cycles))
                g("probe.last_verdict_code").set(
                    float(_VERDICTS.index(summary["verdict"])))
                g("probe.golden_match_streak").set(float(self.streak))
                g("probe.last_cycle_ts").set(float(now))
                g("probe.targets").set(float(len(self.targets)))
                if summary.get("detection_latency_sec") is not None:
                    g("probe.detection_latency_sec").set(
                        summary["detection_latency_sec"])
            except Exception:  # noqa: BLE001
                pass
        if summary["verdict"] == "mismatch":
            self._escalate(summary, now)

    def _escalate(self, summary, now):
        """Once-per-episode escalation on a golden mismatch: a flight
        ring record always; one bounded profiler capture when the
        capture plane is armed — edge-triggered with a cooldown, so a
        red streak produces ONE capture, not one per cycle."""
        try:
            from .flight import get_flight

            get_flight().record({"kind": "probe_mismatch",
                                 "ts": now, "cycle": summary["cycle"],
                                 "targets": summary["targets"]})
        except Exception:  # noqa: BLE001
            pass
        fire = False
        with self._lock:
            if not self._in_episode:
                self._in_episode = True
                if (self._last_escalation is None
                        or now - self._last_escalation
                        >= self.escalation_cooldown):
                    self._last_escalation = now
                    self.escalations += 1
                    fire = True
        if not fire:
            return
        if self.metrics is not None:
            try:
                self.metrics.counter("probe.escalations").inc()
            except Exception:  # noqa: BLE001
                pass
        logger.warning("prober: GOLDEN MISMATCH on cycle %d (%s) — "
                       "the fleet is serving wrong proposals",
                       summary["cycle"], summary["targets"])
        if not self.profile_capture:
            return
        from .profiler import DeviceProfiler, split_profile_mode

        cap_dir, _full = split_profile_mode(
            os.environ.get("HYPEROPT_TPU_PROFILE"))
        if cap_dir is None:
            return

        def _capture():
            prof = DeviceProfiler(cap_dir)
            rec = prof.capture(2.0, reason="probe_mismatch")
            logger.warning("prober: captured device trace on mismatch "
                           "(ok=%s dir=%s)", rec.get("ok"),
                           rec.get("dir"))

        threading.Thread(target=_capture, name="hyperopt-probe-capture",
                         daemon=True).start()

    def _evidence_bundle(self, rec, responses, timeline):
        """Write the mismatch evidence bundle: the raw responses, the
        canary's timeline, the trace ids, and the WAL segment the
        canary landed in (when a WAL path is known).  Best-effort —
        evidence must never fail the verdict."""
        if self.evidence_dir is None:
            return None
        try:
            d = os.path.join(
                self.evidence_dir,
                f"c{rec['cycle']}-{rec['replica']}-"
                f"{int(self._clock())}")
            os.makedirs(d, exist_ok=True)
            with open(os.path.join(d, "bundle.json"), "w",
                      encoding="utf-8") as f:
                json.dump({"verdict": rec.get("verdict"),
                           "why": rec.get("why"),
                           "digest": rec.get("digest"),
                           "golden": rec.get("golden"),
                           "golden_source": rec.get("golden_source"),
                           "target": rec.get("target"),
                           "study_id": rec.get("study_id"),
                           "trace_ids": rec.get("trace_ids"),
                           "responses": responses,
                           "timeline": timeline}, f, indent=1,
                          default=str)
            sid = rec.get("study_id")
            if self.wal_path and sid:
                try:
                    with open(self.wal_path, encoding="utf-8",
                              errors="replace") as src, \
                            open(os.path.join(d, "wal_segment.jsonl"),
                                 "w", encoding="utf-8") as dst:
                        for line in src:
                            if sid in line:
                                dst.write(line)
                except OSError:
                    pass
            self.evidence_bundles.append(d)
            return d
        except Exception:  # noqa: BLE001
            return None

    # -- surfaces ----------------------------------------------------------

    def green(self, now=None, max_age=None):
        """Blackbox-green: the newest cycle verdict is ``ok`` AND fresh
        (within ``max_age``, default 3 periods).  The rolling-restart
        gate and /healthz consume this."""
        now = self._clock() if now is None else now
        max_age = (3.0 * self.period) if max_age is None else max_age
        last = self.last
        return (last is not None and last["verdict"] == "ok"
                and now - last["ts"] <= max_age)

    def healthz_fields(self, now=None):
        now = self._clock() if now is None else now
        last = self.last
        return {
            "last_verdict": last["verdict"] if last else None,
            "age_sec": (now - last["ts"]) if last else None,
            "golden_match_streak": self.streak,
            "cycles": self.cycles,
            "green": self.green(now=now),
        }

    def status_dict(self, now=None):
        """The ``GET /probes`` payload (also the /snapshot section)."""
        now = self._clock() if now is None else now
        with self._lock:
            recent = list(self.recent)
            det = list(self.detection_latencies)
        out = {"armed": True, "replica": self.replica,
               "targets": list(self.targets), "period_sec": self.period,
               "canary": canary_key(self.canary),
               "backend": self.backend, "golden": self.golden,
               "golden_source": self.golden_source,
               "cycles": self.cycles, "verdicts": dict(self.verdicts),
               "golden_match_streak": self.streak,
               "green": self.green(now=now),
               "escalations": self.escalations,
               "evidence_bundles": list(self.evidence_bundles),
               "last": self.last, "recent": recent[-20:]}
        if det:
            s = sorted(det)
            out["detection"] = {"episodes": len(s), "min_sec": s[0],
                                "max_sec": s[-1],
                                "mean_sec": sum(s) / len(s)}
        return out


# ---------------------------------------------------------------------------
# golden fixture: local drive + regen
# ---------------------------------------------------------------------------


def local_digest(canary=None, compile_plane=False):
    """Drive the canary against a fresh in-process server (the REAL
    handler path, JSON round-tripped like the wire) and return
    ``(digest, flagged)``.  The golden regression test and the regen
    CLI share this exact drive."""
    from ..service.scheduler import StudyScheduler
    from ..service.server import ServiceHTTPServer

    c = dict(CANARY, **(canary or {}))
    sched = StudyScheduler(wal=False, quality=False, load=False,
                           compile_plane=False if not compile_plane
                           else None)
    srv = ServiceHTTPServer(0, scheduler=sched, trace=False, slo=False)
    p = Prober(["local"], period=3600.0, canary=c, golden="_",
               transport_factory=lambda url: _LocalTransport(srv))
    rec = p._probe_target("local", 1, time.monotonic() + 600.0)
    if rec["verdict"] == "error":
        raise RuntimeError(f"canary drive failed: {rec.get('why')}")
    return rec["digest"], rec["flagged"]


def regen_golden(path=None, canary=None):
    """Recompute the canary digest on THIS backend and rewrite the
    fixture entry (``--regen-golden``).  Refuses a flagged stream —
    a golden must only ever pin a clean full-quality stream."""
    path = path or _golden_path()
    digest, flagged = local_digest(canary)
    if flagged:
        raise RuntimeError(
            "canary stream was degraded/warming-flagged; a golden "
            "fixture must pin a clean full-quality stream (disarm the "
            "degrade ladder / compile plane and retry)")
    try:
        with open(path, encoding="utf-8") as f:
            fx = json.load(f)
    except (OSError, ValueError):
        fx = {}
    fx.setdefault("version", 1)
    fx.setdefault("canary", dict(CANARY, **(canary or {})))
    fx.setdefault("digests", {}).setdefault(
        canary_key(canary), {})[_backend_key()] = digest
    with open(path, "w", encoding="utf-8") as f:
        json.dump(fx, f, indent=1, sort_keys=True)
        f.write("\n")
    return digest


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m hyperopt_tpu.obs.prober",
        description="Blackbox prober: synthetic canary studies against "
                    "live ask/tell replicas, golden-stream divergence "
                    "detection, sealed verdict ledger.")
    p.add_argument("--targets", default=None,
                   help="comma-separated replica base URLs (>=2 arms "
                        "the cross-replica divergence check)")
    p.add_argument("--period", type=float, default=None,
                   help="probe cycle period in seconds (default: "
                        "$HYPEROPT_TPU_PROBE_PERIOD or 30)")
    p.add_argument("--cycles", type=int, default=0,
                   help="run N cycles then exit non-zero unless all "
                        "green (0 = run forever)")
    p.add_argument("--ledger", default=None,
                   help="verdict ledger path (sealed JSONL)")
    p.add_argument("--replica", default="standalone",
                   help="identity stamped on verdicts/ledger")
    p.add_argument("--regen-golden", action="store_true",
                   help="recompute the canary digest on this backend "
                        "and rewrite hyperopt_tpu/obs/probe_golden.json")
    args = p.parse_args(argv)

    logging.basicConfig(level=logging.INFO, format="%(message)s")
    if args.regen_golden:
        digest = regen_golden()
        print(f"probe_golden.json: pinned {canary_key()} "
              f"[{_backend_key()}] = {digest}")
        return 0
    if not args.targets:
        p.error("--targets is required (or use --regen-golden)")
    from .._env import parse_probe_period

    prober = Prober(
        [u for u in args.targets.split(",") if u.strip()],
        period=(args.period if args.period is not None
                else parse_probe_period()),
        ledger_path=args.ledger, replica=args.replica)
    if args.cycles > 0:
        bad = 0
        for _ in range(args.cycles):
            rec = prober.run_cycle()
            print(json.dumps(rec, default=str))
            if rec["verdict"] != "ok":
                bad += 1
            time.sleep(min(prober.period, 1.0))
        return 1 if bad else 0
    prober.start()
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        prober.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
