"""Trial-lifecycle event log.

Third pillar of the run-telemetry layer: an append-only record of every
state transition a trial goes through — ``trial_new`` / ``trial_claimed`` /
``trial_heartbeat`` / ``trial_finished`` / ``trial_cancelled`` /
``trial_reclaimed`` — so a post-mortem can reconstruct *why* a run behaved
the way it did (which worker claimed what, where time was lost between
queue and claim, which trials were reclaimed from dead workers) without the
process that produced it.

Two persistence modes:

* in-memory bounded ring (``EventLog()``) — the in-process backends
  (``ExecutorTrials``, the host loop);
* durable append file (``EventLog(sink=FileEventSink(path))``) — the
  ``FileStore`` wires this to ``attachments/obs_events.jsonl`` inside the
  store directory, so the log survives driver AND worker death and is
  shared by every process on the store (O_APPEND line writes are atomic
  for line-sized records on POSIX).
"""

from __future__ import annotations

import json
import os
import time
from collections import deque

from .flight import get_flight

__all__ = [
    "TRIAL_NEW",
    "TRIAL_CLAIMED",
    "TRIAL_HEARTBEAT",
    "TRIAL_FINISHED",
    "TRIAL_CANCELLED",
    "TRIAL_RECLAIMED",
    "EventLog",
    "FileEventSink",
    "load_events",
]

TRIAL_NEW = "trial_new"
TRIAL_CLAIMED = "trial_claimed"
TRIAL_HEARTBEAT = "trial_heartbeat"
TRIAL_FINISHED = "trial_finished"
TRIAL_CANCELLED = "trial_cancelled"
TRIAL_RECLAIMED = "trial_reclaimed"


class FileEventSink:
    """Durable append-only event sink.

    Deliberately holds NO file handle: each record is one ``O_APPEND``
    write of one line, so concurrent writers (driver + N worker processes)
    interleave whole lines, and the sink pickles freely inside a Trials
    backend checkpoint.
    """

    def __init__(self, path):
        self.path = str(path)

    def write(self, record: dict):
        line = (json.dumps(record, default=str) + "\n").encode()
        fd = os.open(self.path, os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644)
        try:
            os.write(fd, line)
        finally:
            os.close(fd)


class EventLog:
    """Emit + remember trial lifecycle events.

    ``emit`` must never raise into the store/driver hot path — a telemetry
    failure (full disk, revoked mount) degrades to the in-memory ring.
    """

    def __init__(self, sink=None, keep=4096):
        self.sink = sink
        self._ring = deque(maxlen=keep)

    def emit(self, event, tid, **attrs):
        rec = {"kind": "trial_event", "event": event, "tid": tid,
               "ts": time.time()}
        if attrs:
            rec.update(attrs)
        self._ring.append(rec)
        # the flight ring too: a crash dump reconstructs in-flight trials
        # (claimed-but-never-finished) from exactly these records
        get_flight().record(rec)
        if self.sink is not None:
            try:
                self.sink.write(rec)
            except OSError:
                pass
        return rec

    def records(self):
        """The in-memory ring (most recent ``keep`` events)."""
        return list(self._ring)

    def by_event(self, event):
        return [r for r in self._ring if r["event"] == event]


def load_events(path):
    """Read a durable event file back (tolerates a torn final line from a
    killed writer)."""
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and rec.get("kind") == "trial_event":
                out.append(rec)
    return out
