"""Back-compat alias: this module was named ``graphviz_mod`` before it was
established that a package SUBMODULE cannot shadow the top-level PyPI
``graphviz`` package under Python 3 absolute imports — so the real module
is now ``hyperopt_tpu.graphviz`` (full reference parity:
``hyperopt/graphviz.py``)."""

from .graphviz import *  # noqa: F401,F403
from .graphviz import dot_hyperparameters  # noqa: F401
