"""Core runtime: trial documents, trial stores, Domain, Ctrl.

Parity target: ``hyperopt/base.py`` (sym: JOB_STATE_*, STATUS_*, Trials,
Domain, Ctrl, trials_from_docs, miscs_to_idxs_vals, miscs_update_idxs_vals,
spec_from_misc, SONify).

TPU-first additions:

* ``Trials`` keeps the reference's list-of-SON-documents API (pickle-compatible
  shape), but also maintains an incremental **padded structure-of-arrays
  history** per hyperparameter label — ``vals[f32, cap]``, ``active[bool, cap]``,
  ``losses[f32, cap]`` — the dense device-side analog of the sparse
  ``(idxs, vals)`` form produced by ``hyperopt/vectorize.py``.  Suggesters
  consume this directly; capacities grow by power-of-two buckets so the jitted
  TPE kernel recompiles only O(log n) times as history grows.
* ``Domain`` compiles the search space once (``spaces.compile_space``) instead
  of building a pyll ``VectorizeHelper`` program; evaluation assembles the
  structured config on host and calls the objective, or — when the objective
  is JAX-traceable — evaluates a whole batch of configs under ``vmap``.
"""

from __future__ import annotations

import datetime
import math
import numbers

import numpy as np

import jax
import jax.numpy as jnp

from .exceptions import (
    AllTrialsFailed,
    InvalidLoss,
    InvalidResultStatus,
    InvalidTrial,
    StaleHistoryError,
)
from .spaces import CompiledSpace, as_expr, compile_space

__all__ = [
    "JOB_STATE_NEW",
    "JOB_STATE_RUNNING",
    "JOB_STATE_DONE",
    "JOB_STATE_ERROR",
    "JOB_STATE_CANCEL",
    "JOB_STATES",
    "STATUS_NEW",
    "STATUS_RUNNING",
    "STATUS_SUSPENDED",
    "STATUS_OK",
    "STATUS_FAIL",
    "STATUS_STRINGS",
    "SONify",
    "miscs_to_idxs_vals",
    "miscs_update_idxs_vals",
    "spec_from_misc",
    "Trials",
    "trials_from_docs",
    "Ctrl",
    "Domain",
    "PaddedHistory",
]

# -- job states (hyperopt/base.py sym: JOB_STATE_*) -------------------------
JOB_STATE_NEW = 0
JOB_STATE_RUNNING = 1
JOB_STATE_DONE = 2
JOB_STATE_ERROR = 3
JOB_STATE_CANCEL = 4
JOB_STATES = [JOB_STATE_NEW, JOB_STATE_RUNNING, JOB_STATE_DONE, JOB_STATE_ERROR, JOB_STATE_CANCEL]

# -- result statuses (hyperopt/base.py sym: STATUS_*) -----------------------
STATUS_NEW = "new"
STATUS_RUNNING = "running"
STATUS_SUSPENDED = "suspended"
STATUS_OK = "ok"
STATUS_FAIL = "fail"
STATUS_STRINGS = (STATUS_NEW, STATUS_RUNNING, STATUS_SUSPENDED, STATUS_OK, STATUS_FAIL)

# Smallest padded-history capacity bucket.  128 keeps a standard ~100-eval
# run inside ONE bucket — a growth recompile of the TPE kernel costs seconds
# on a remote-compiled TPU, far more than the few KB of extra padding.
_MIN_CAP = 128


def coarse_utcnow():
    """Timestamp truncated to ms (hyperopt/utils.py sym: coarse_utcnow)."""
    now = datetime.datetime.now(datetime.timezone.utc).replace(tzinfo=None)
    return now.replace(microsecond=(now.microsecond // 1000) * 1000)


def SONify(arg):
    """Coerce to JSON/BSON-safe python types (hyperopt/base.py sym: SONify)."""
    if isinstance(arg, dict):
        return {SONify(k): SONify(v) for k, v in arg.items()}
    if isinstance(arg, (list, tuple)):
        return [SONify(a) for a in arg]
    if isinstance(arg, (np.ndarray, jax.Array)):
        return SONify(np.asarray(arg).tolist())
    if isinstance(arg, (np.bool_, bool)):
        return bool(arg)
    if isinstance(arg, numbers.Integral):
        return int(arg)
    if isinstance(arg, numbers.Real):
        return float(arg)
    if isinstance(arg, (str, bytes, type(None), datetime.datetime)):
        return arg
    raise TypeError(f"cannot SONify {type(arg)}: {arg!r}")


# -- misc helpers (hyperopt/base.py sym: miscs_to_idxs_vals etc.) -----------


def miscs_update_idxs_vals(miscs, idxs, vals, assert_all_vals_used=True, idxs_map=None):
    """Write per-label sparse (idxs, vals) into trial misc documents."""
    if idxs_map is None:
        idxs_map = {}
    misc_by_id = {m["tid"]: m for m in miscs}
    for m in miscs:
        m.setdefault("idxs", {})
        m.setdefault("vals", {})
        for label in idxs:
            m["idxs"].setdefault(label, [])
            m["vals"].setdefault(label, [])
    for label in idxs:
        for tid, val in zip(idxs[label], vals[label]):
            tid = idxs_map.get(tid, tid)
            if tid in misc_by_id:
                misc_by_id[tid]["idxs"][label] = [tid]
                misc_by_id[tid]["vals"][label] = [val]
            elif assert_all_vals_used:
                raise InvalidTrial(f"no misc with tid {tid}")
    return miscs


def miscs_to_idxs_vals(miscs, keys=None):
    """Gather per-label sparse (idxs, vals) from trial misc documents."""
    if keys is None:
        if len(miscs) == 0:
            raise ValueError("cannot infer keys from empty miscs")
        keys = list(miscs[0]["idxs"].keys())
    idxs = {k: [] for k in keys}
    vals = {k: [] for k in keys}
    for m in miscs:
        for k in keys:
            t = m["idxs"].get(k, [])
            v = m["vals"].get(k, [])
            assert len(t) == len(v)
            idxs[k].extend(t)
            vals[k].extend(v)
    return idxs, vals


def spec_from_misc(misc):
    """Flat ``{label: value}`` config from one misc (hyperopt/base.py sym:
    spec_from_misc) — inactive conditional params are absent."""
    spec = {}
    for k, v in misc["vals"].items():
        if len(v) == 0:
            continue
        if len(v) == 1:
            spec[k] = v[0]
        else:
            raise InvalidTrial(f"multiple values for {k} in one trial")
    return spec


def _validate_trial_doc(doc):
    required = ("tid", "spec", "result", "misc", "state", "exp_key", "owner", "version")
    for k in required:
        if k not in doc:
            raise InvalidTrial(f"trial document missing key {k!r}: {sorted(doc)}")
    if doc["state"] not in JOB_STATES:
        raise InvalidTrial(f"invalid state {doc['state']!r}")
    misc = doc["misc"]
    for k in ("tid", "cmd", "idxs", "vals"):
        if k not in misc:
            raise InvalidTrial(f"trial misc missing key {k!r}")
    if misc["tid"] != doc["tid"]:
        raise InvalidTrial(f"tid mismatch: {misc['tid']} != {doc['tid']}")
    return doc


def _bucket_cap(n: int) -> int:
    """Smallest power-of-two bucket ≥ n (min _MIN_CAP) — bounds recompiles."""
    cap = _MIN_CAP
    while cap < n:
        cap *= 2
    return cap


_updater_cache = {}  # (labels, cap, dtype, qkey) -> jitted row update


def _get_history_updater(labels, cap, dtype="float32", qparams=None):
    """One jitted program that folds a packed trial row into every device
    array of the history — ONE dispatch per completed trial instead of
    2·L+2 separate ``.at[]`` updates (which each cost a host↔device round
    trip over a tunneled accelerator).  ``dtype`` is the mirror's STORAGE
    dtype (``HYPEROPT_TPU_HIST_DTYPE``); rows arrive f32 and cast on the
    scatter — or, under an armed int8/fp8 plan (``qparams`` per-label
    scale/zero, baked as trace constants), affine-encode on the scatter;
    losses then stay bf16 (``quant.losses_dtype``)."""
    from . import quant

    key = (labels, cap, str(dtype), quant.qkey(qparams, labels))
    fn = _updater_cache.get(key)
    if fn is None:
        L = len(labels)
        quantized = qparams is not None and quant.is_quant_name(dtype)
        ldt = quant.losses_dtype(dtype)
        dt = None if quantized else jnp.dtype(dtype)

        def update(dev, row):
            # row layout: [vals(L), active(L), loss, has_loss, index]
            i = row[2 * L + 2].astype(jnp.int32)
            if quantized:
                vals = {
                    l: dev["vals"][l].at[i].set(
                        quant.quantize(row[j], qparams[l], dtype))
                    for j, l in enumerate(labels)
                }
            else:
                vals = {
                    l: dev["vals"][l].at[i].set(row[j].astype(dt))
                    for j, l in enumerate(labels)
                }
            return {
                "vals": vals,
                "active": {
                    l: dev["active"][l].at[i].set(row[L + j] > 0.5)
                    for j, l in enumerate(labels)
                },
                "losses": dev["losses"].at[i].set(row[2 * L].astype(ldt)),
                "has_loss": dev["has_loss"].at[i].set(row[2 * L + 1] > 0.5),
            }

        fn = _updater_cache[key] = jax.jit(update)
    return fn


class PaddedHistory:
    """Dense, padded structure-of-arrays view of trial history.

    This is what the jitted suggesters consume: for each label a
    ``(vals[cap], active[cap])`` pair plus ``losses[cap]`` and the live count
    ``n``.  Padding slots have ``active=False`` and ``loss=+inf``; capacities
    are power-of-two buckets so kernel shapes are stable.  The dense analog of
    the reference's sparse per-label ``(idxs, vals)`` (SURVEY.md §7.1).

    The numpy arrays are the source of truth (cheap host appends, pickling);
    ``device_view`` maintains a **device-resident mirror** so the per-suggest
    cost is one incremental update dispatch, not a re-upload of every array
    (the round-2 host-loop bottleneck: ~2·L+2 transfers per proposal over
    the TPU tunnel).

    ``HYPEROPT_TPU_HIST_DTYPE=bf16`` compresses the DEVICE mirror's float
    arrays (``vals``, ``losses``) to bfloat16 — half the resident HBM at
    unchanged ``cap``; kernels upcast to f32 on read (docs/DESIGN.md §13).
    The host numpy arrays stay float32 and authoritative, so
    pickle/checkpoint/resume never see the compressed form; the dtype is
    captured at construction and travels through pickle, so a resumed run
    keeps proposing bit-identically to the uninterrupted one.

    ``int8``/``fp8`` (ISSUE 19) go further: once :meth:`ensure_qparams`
    arms the space-derived affine code (``quant.py``), the mirror's vals
    arrays hold 1-byte codes (losses bf16) and every host value is
    SNAPPED to the dequantized grid at append time — the invariant that
    keeps crash-resume bitwise (quant.py rule 2).  Until armed (paths
    that never see the space, e.g. pure-random suggest), a quant
    hist_dtype stores bf16 — compression without the truncation hazard
    of a raw int8 astype.  ``qparams`` (or its absence) travels through
    pickle alongside ``hist_dtype``.
    """

    def __init__(self, labels, hist_dtype=None):
        from ._env import parse_hist_dtype

        self.labels = tuple(labels)
        self.hist_dtype = str(hist_dtype) if hist_dtype else parse_hist_dtype()
        self.qparams = None  # {label: (scale, zero, islog)} once armed
        self.n = 0
        self.cap = _MIN_CAP
        self._vals = {l: np.zeros(self.cap, np.float32) for l in self.labels}
        self._active = {l: np.zeros(self.cap, bool) for l in self.labels}
        self._losses = np.full(self.cap, np.inf, np.float32)
        self._has_loss = np.zeros(self.cap, bool)
        self._dev = None  # device mirror of the arrays above
        self._dev_synced = 0  # rows folded into the mirror
        self._pending_commit_n = 0
        # True while the mirror's buffers are donated to an in-flight fused
        # program and the returned handle has not been committed back
        self._donated = False

    def _grow(self, need):
        new_cap = _bucket_cap(need)
        if new_cap <= self.cap:
            return
        pad = new_cap - self.cap
        for l in self.labels:
            self._vals[l] = np.concatenate([self._vals[l], np.zeros(pad, np.float32)])
            self._active[l] = np.concatenate([self._active[l], np.zeros(pad, bool)])
        self._losses = np.concatenate([self._losses, np.full(pad, np.inf, np.float32)])
        self._has_loss = np.concatenate([self._has_loss, np.zeros(pad, bool)])
        self.cap = new_cap
        self._dev = None  # shapes changed: full re-upload at next view

    def append(self, flat_vals: dict, loss):
        """Record one finished trial (flat {label: value}; absent =
        inactive).  Under an armed quant plan the stored value is the
        SNAPPED grid point — what the device mirror will decode — so host
        and device agree bitwise across pickle/WAL resume."""
        if self.qparams is not None:
            from . import quant
        self._grow(self.n + 1)
        i = self.n
        for l in self.labels:
            if l in flat_vals and flat_vals[l] is not None:
                v = float(flat_vals[l])
                if self.qparams is not None:
                    v = float(quant.snap_np(v, self.qparams[l],
                                            self.hist_dtype))
                self._vals[l][i] = v
                self._active[l][i] = True
        if loss is not None and math.isfinite(float(loss)):
            self._losses[i] = float(loss)
            self._has_loss[i] = True
        self.n += 1

    def _pack_row(self, i):
        L = len(self.labels)
        row = np.empty(2 * L + 3, np.float32)
        for j, l in enumerate(self.labels):
            row[j] = self._vals[l][i]
            row[L + j] = 1.0 if self._active[l][i] else 0.0
        row[2 * L] = self._losses[i]
        row[2 * L + 1] = 1.0 if self._has_loss[i] else 0.0
        row[2 * L + 2] = float(i)  # cap ≤ 2^24: exact in f32
        return row

    # ONE fixed row bucket: the fused tell+ask kernel folds rows with a
    # single vectorized scatter per array (tpe._apply_rows), so a larger
    # bucket costs nothing at trace or run time — and a FIXED bucket means
    # the fused program compiles exactly once per space instead of once per
    # completed-row count (round-5 compile-time item: the (rows=1, ids=4)
    # first-call shape forced a second full XLA compile).
    _ROW_BUCKETS = (16,)

    def pack_rows(self, start, K, noop_index=None):
        """``[K, 2L+3]`` float32 tell-row matrix for trials ``start..n``
        in the ``_pack_row`` layout, padded to ``K`` rows with out-of-
        bounds no-op indices (``mode='drop'`` discards them in-trace).
        The row form every fused tell+ask program folds — both the
        single-study one (:meth:`device_state`) and the multi-study
        cohort stack (``service/scheduler.py``).

        ``noop_index`` is the drop index for padding rows — ``cap`` by
        default; a cohort whose slot capacity differs from this
        history's bucket passes its OWN capacity (an index that is
        in-bounds for the consuming kernel would scatter a garbage row).
        """
        L = len(self.labels)
        rows = np.zeros((K, 2 * L + 3), np.float32)
        rows[:, 2 * L + 2] = float(self.cap if noop_index is None
                                   else noop_index)
        for j, i in enumerate(range(start, self.n)):
            rows[j] = self._pack_row(i)
        return rows

    def host_padded(self):
        """Full-capacity VIEWS of the host arrays (``vals``/``active``/
        ``losses``/``has_loss``), padding included — what the multi-study
        cohort stacks into its ``[S, cap]`` device mirror.  Read-only by
        contract: the arrays are the authoritative host state."""
        return {
            "vals": self._vals,
            "active": self._active,
            "losses": self._losses,
            "has_loss": self._has_loss,
        }

    def _mirror_plan(self):
        """Effective ``(storage name, qparams)`` for the device mirror: a
        quant ``hist_dtype`` is honored only once :meth:`ensure_qparams`
        armed the code — before that (paths that never see the space) the
        mirror stores bf16, which compresses without the silent-truncation
        hazard of a raw astype to int8."""
        from . import quant

        if quant.is_quant_name(self.hist_dtype):
            if self.qparams is not None:
                return self.hist_dtype, self.qparams
            return "bfloat16", None
        return self.hist_dtype, None

    def ensure_qparams(self, cs):
        """Arm (once) the space-derived int8/fp8 code for this history.

        No-op unless ``hist_dtype`` is a quant name and the code is not
        yet armed.  A space/backend the code cannot represent degrades
        this history to bf16 permanently (``quant.resolve`` warns once
        and bumps the ``suggest.quant.fallback`` counter — an ask never
        fails).  On success, already-recorded rows are retro-snapped to
        the dequantized grid (quant.py rule 2: every later quantization
        must round an exact grid point) and the mirror is invalidated so
        the next view uploads codes."""
        from . import quant

        if self.qparams is not None or not quant.is_quant_name(self.hist_dtype):
            return
        name, qp = quant.resolve(cs, self.hist_dtype, context="history")
        if qp is None or any(l not in qp for l in self.labels):
            self.hist_dtype = "bfloat16"
            return
        self._check_not_donated("ensure_qparams")
        self.qparams = {l: qp[l] for l in self.labels}
        for l in self.labels:
            m = self._active[l][: self.n]
            if m.any():
                v = self._vals[l][: self.n]
                v[m] = quant.snap_np(v[m], self.qparams[l], self.hist_dtype)
        self._dev = None

    def _full_upload(self):
        # tag the cap-sized mirror buffers for the devmem live-array census
        # (obs/devmem.py) — uploads are rare (first view / growth), so the
        # set-add is off the per-suggest path
        from . import quant
        from .obs.devmem import register_owner

        register_owner("history", (self.cap,))
        name, qp = self._mirror_plan()
        # jnp.array (copy=True), NOT asarray: the mirror is DONATED into
        # the fused tell+ask program, and on the CPU backend asarray can
        # zero-copy a (page-aligned, e.g. large-cap) numpy buffer —
        # donating an aliased buffer lets XLA free memory the
        # authoritative host arrays still own (heap corruption; the
        # cohort stack reproduced it, see service/scheduler.py)
        if qp is not None:
            vals = {l: jnp.array(quant.quantize_np(self._vals[l], qp[l],
                                                   name))
                    for l in self.labels}
            losses = jnp.array(self._losses, dtype=quant.losses_dtype(name))
        else:
            dt = jnp.dtype(name)
            vals = {l: jnp.array(self._vals[l], dtype=dt)
                    for l in self.labels}
            losses = jnp.array(self._losses, dtype=dt)
        self._dev = {
            "vals": vals,
            "active": {l: jnp.array(self._active[l]) for l in self.labels},
            "losses": losses,
            "has_loss": jnp.array(self._has_loss),
        }
        self._dev_synced = self.n

    def _check_not_donated(self, what):
        if self._donated:
            raise StaleHistoryError(
                f"PaddedHistory.{what}: the device mirror was DONATED to a "
                "fused tell+ask dispatch and the program's returned history "
                "has not been committed back.  Call commit_device(new_dev) "
                "with the kernel's returned handle (or abandon_device() to "
                "drop the mirror) before touching device state again — "
                "reusing a donated buffer crashes XLA with an opaque "
                "invalid-buffer error.")

    def device_state(self, donate=False):
        """``(dev, rows)`` for FUSED update+propose kernels.

        ``dev`` is the device mirror as of the last commit; ``rows`` is a
        ``[K, 2L+3]`` float32 matrix of trials not yet folded into it,
        K padded to a small bucket so kernels retrace O(1) times.  Padding
        rows carry ``index = cap`` so ``.at[i].set(..., mode='drop')``
        ignores them in-trace.  The caller applies ``rows`` inside its own
        program (saving one device program per ask→tell iteration — on a
        tunneled TPU each program costs tens of ms of completion latency)
        and hands the updated mirror back via :meth:`commit_device`.

        ``donate=True`` declares that the caller's program is jitted with
        ``donate_argnums`` on the history: XLA aliases the update in place
        (zero-copy scatter instead of a cap-sized copy per tick) and the
        returned buffers become INVALID the moment the program dispatches.
        Until :meth:`commit_device` hands the program's returned history
        back, every further device access raises
        :class:`~hyperopt_tpu.exceptions.StaleHistoryError` — the guard
        that turns the classic donated-buffer-reuse crash into a clear
        error.  The numpy arrays stay the host source of truth throughout
        (appends, pickling and rebuilds never depend on the mirror).
        """
        self._check_not_donated("device_state")
        delta = self.n - self._dev_synced
        if self._dev is None or delta > self._ROW_BUCKETS[-1]:
            self._full_upload()
            delta = 0
        K = next(b for b in self._ROW_BUCKETS if b >= max(delta, 1))
        rows = self.pack_rows(self._dev_synced, K)
        self._pending_commit_n = self.n
        self._pending_commit_cap = self.cap
        self._donated = bool(donate)
        return self._dev, rows

    def commit_device(self, dev):
        """Adopt a kernel-updated mirror (see :meth:`device_state`)."""
        if getattr(self, "_pending_commit_cap", self.cap) != self.cap:
            # capacity grew between dispatch and commit: the returned
            # handle has the OLD shapes — drop it, rebuild at next use
            self._dev = None
        else:
            self._dev = dev
            self._dev_synced = self._pending_commit_n
        self._donated = False

    def abandon_device(self):
        """Drop the device mirror after a FAILED donated dispatch: the
        donated buffers are gone and no updated handle exists, so the next
        device access rebuilds the mirror from the host arrays."""
        self._dev = None
        self._donated = False

    def host_materialize(self):
        """Host-side snapshot of the folded history (checkpoint/pickle
        boundary).  The numpy arrays are authoritative by construction —
        device kernels only ever *read* history the host already folded —
        so this never blocks on (possibly donated) device buffers."""
        return {
            "vals": {l: self._vals[l][: self.n].copy() for l in self.labels},
            "active": {l: self._active[l][: self.n].copy()
                       for l in self.labels},
            "losses": self._losses[: self.n].copy(),
            "has_loss": self._has_loss[: self.n].copy(),
        }

    # pickle boundary: jax buffers (possibly donated/invalid) never travel;
    # the mirror rebuilds lazily from the authoritative numpy arrays
    def __getstate__(self):
        state = self.__dict__.copy()
        state["_dev"] = None
        state["_dev_synced"] = 0
        state["_donated"] = False
        return state

    def __setstate__(self, state):
        # pickles from before the storage-dtype round carry no hist_dtype;
        # they were f32 by construction (and pre-quant ones no qparams)
        state.setdefault("hist_dtype", "float32")
        state.setdefault("qparams", None)
        self.__dict__.update(state)

    def device_view(self):
        """Device-resident arrays for the jitted kernels, synced incrementally
        (one fused update dispatch per new row; full upload only on capacity
        growth or first use)."""
        self._check_not_donated("device_view")
        if self._dev is None:
            self._full_upload()
        elif self._dev_synced < self.n:
            delta = self.n - self._dev_synced
            if delta > 16:
                # many rows landed at once (batch eval): re-upload wholesale
                self._dev = None
                return self.device_view()
            name, qp = self._mirror_plan()
            update = _get_history_updater(self.labels, self.cap, name, qp)
            for i in range(self._dev_synced, self.n):
                self._dev = update(self._dev, self._pack_row(i))
            self._dev_synced = self.n
        return {**self._dev, "n": self.n, "cap": self.cap}


class Ctrl:
    """Control object handed to low-level objectives
    (hyperopt/base.py sym: Ctrl: checkpoint, inject_results, current_trial)."""

    def __init__(self, trials, current_trial=None):
        self.trials = trials
        self.current_trial = current_trial

    @property
    def attachments(self):
        return self.trials.attachments

    def checkpoint(self, result=None):
        """Record a partial result for the in-flight trial and persist it
        through the backend, so a crashed worker's progress survives
        (hyperopt/base.py sym: Ctrl.checkpoint; the reference's MongoCtrl
        writes partials to mongod — SURVEY.md §5 checkpoint row)."""
        if self.current_trial is None:
            return
        if result is not None:
            self.current_trial["result"] = result
        self.trials.checkpoint_trial(self.current_trial)

    def inject_results(self, specs, results, miscs, new_tids=None):
        if new_tids is None:
            new_tids = self.trials.new_trial_ids(len(specs))
        docs = self.trials.new_trial_docs(new_tids, specs, results, miscs)
        for doc in docs:
            doc["state"] = JOB_STATE_DONE
        return self.trials.insert_trial_docs(docs)


class Trials:
    """In-memory trial store, document-compatible with the reference
    (hyperopt/base.py sym: Trials), plus an incremental padded SoA history.

    ``asynchronous=False``: ``fmin`` evaluates trials serially in-process.
    Subclasses with ``asynchronous=True`` (see ``parallel/executor.py``)
    dispatch evaluation elsewhere, the analog of MongoTrials/SparkTrials.
    """

    asynchronous = False

    def __init__(self, exp_key=None, refresh=True):
        self._ids = set()
        self._dynamic_trials = []
        self._exp_key = exp_key
        self.attachments = {}
        self._history = None  # PaddedHistory, built lazily once labels known
        self._history_synced = 0  # scan position over _dynamic_trials
        self._history_pending = []  # seen-but-unsettled docs, revisited
        if refresh:
            self.refresh()

    # -- basic container protocol ----------------------------------------

    def __len__(self):
        return len(self._trials)

    def __iter__(self):
        return iter(self._trials)

    def __getitem__(self, item):
        return self._trials[item]

    # -- refresh / insert --------------------------------------------------

    def refresh(self):
        if self._exp_key is None:
            self._trials = [d for d in self._dynamic_trials if d["state"] != JOB_STATE_ERROR]
        else:
            self._trials = [
                d
                for d in self._dynamic_trials
                if d["state"] != JOB_STATE_ERROR and d["exp_key"] == self._exp_key
            ]
        self._ids.update(d["tid"] for d in self._dynamic_trials)

    def insert_trial_doc(self, doc):
        doc = _validate_trial_doc(doc)
        self._dynamic_trials.append(doc)
        return doc["tid"]

    def insert_trial_docs(self, docs):
        return [self.insert_trial_doc(d) for d in docs]

    def delete_all(self):
        self._dynamic_trials = []
        self._ids = set()
        self.attachments = {}
        self._history = None
        self._history_synced = 0
        self._history_pending = []
        self.refresh()

    # -- id/doc generation -------------------------------------------------

    def checkpoint_trial(self, doc):
        """Persist a mid-trial partial result (Ctrl.checkpoint backend hook).
        In-memory trials share doc objects with the evaluator, so the
        mutation is already visible; durable backends override this to write
        the doc through (FileTrials → store, ExecutorTrials → lock+stamp)."""

    def new_trial_ids(self, n):
        aa = len(self._ids)
        rval = list(range(aa, aa + n))
        self._ids.update(rval)
        return rval

    def new_trial_docs(self, tids, specs, results, miscs):
        rval = []
        for tid, spec, result, misc in zip(tids, specs, results, miscs):
            doc = {
                "state": JOB_STATE_NEW,
                "tid": tid,
                "spec": spec,
                "result": result,
                "misc": misc,
                "exp_key": self._exp_key,
                "owner": None,
                "version": 0,
                "book_time": None,
                "refresh_time": None,
            }
            rval.append(doc)
        return rval

    def source_trial_docs(self, tids, specs, results, miscs, sources):
        rval = self.new_trial_docs(tids, specs, results, miscs)
        for doc in rval:
            doc["from_tid"] = sources[0]["tid"] if sources else None
        return rval

    # -- properties (hyperopt/base.py sym: Trials.{trials,tids,...}) -------

    @property
    def trials(self):
        return self._trials

    @property
    def tids(self):
        return [d["tid"] for d in self._trials]

    @property
    def specs(self):
        return [d["spec"] for d in self._trials]

    @property
    def results(self):
        return [d["result"] for d in self._trials]

    @property
    def miscs(self):
        return [d["misc"] for d in self._trials]

    @property
    def idxs_vals(self):
        return miscs_to_idxs_vals(self.miscs)

    @property
    def idxs(self):
        return self.idxs_vals[0]

    @property
    def vals(self):
        return self.idxs_vals[1]

    def losses(self, bandit=None):
        return [r.get("loss") for r in self.results]

    def statuses(self, bandit=None):
        return [r.get("status") for r in self.results]

    def count_by_state_synced(self, arg, trials=None):
        if trials is None:
            trials = self._trials
        if isinstance(arg, int):
            queue = [d for d in trials if d["state"] == arg]
        else:
            queue = [d for d in trials if d["state"] in arg]
        return len(queue)

    def count_by_state_unsynced(self, arg):
        if self._exp_key is not None:
            exp_trials = [d for d in self._dynamic_trials if d["exp_key"] == self._exp_key]
        else:
            exp_trials = self._dynamic_trials
        return self.count_by_state_synced(arg, trials=exp_trials)

    def average_best_error(self, domain=None):
        """Mean true-loss of the best-scoring ok trials
        (hyperopt/base.py sym: Trials.average_best_error)."""
        if domain is None:
            results = [r for r in self.results if r.get("status") == STATUS_OK]
            losses = np.array([r["loss"] for r in results if r.get("loss") is not None])
            if len(losses) == 0:
                raise AllTrialsFailed()
            return float(losses.min())
        results = [r for r in self.results if domain.status(r) == STATUS_OK]
        losses = np.array([domain.loss(r) for r in results], dtype=float)
        if len(losses) == 0:
            raise AllTrialsFailed()
        vars_ = np.array([domain.loss_variance(r) or 0.0 for r in results], dtype=float)
        true = np.array(
            [
                domain.true_loss(r) if domain.true_loss(r) is not None else l
                for r, l in zip(results, losses)
            ],
            dtype=float,
        )
        thresh = losses.min() + 3 * np.sqrt(vars_[np.argmin(losses)] if len(vars_) else 0.0)
        best = true[losses <= thresh]
        return float(best.mean())

    @property
    def best_trial(self):
        candidates = [
            d
            for d in self._trials
            if d["result"].get("status") == STATUS_OK and d["result"].get("loss") is not None
        ]
        if not candidates:
            raise AllTrialsFailed()
        return min(candidates, key=lambda d: d["result"]["loss"])

    @property
    def argmin(self):
        return spec_from_misc(self.best_trial["misc"])

    def trial_attachments(self, trial):
        """Per-trial attachment dict view keyed under ATTACH::<tid>::."""
        tid = trial["tid"]
        store = self.attachments
        prefix = f"ATTACH::{tid}::"

        class _View:
            def __setitem__(_, k, v):
                store[prefix + k] = v

            def __getitem__(_, k):
                return store[prefix + k]

            def __contains__(_, k):
                return (prefix + k) in store

            def __delitem__(_, k):
                del store[prefix + k]

            def keys(_):
                return [k[len(prefix):] for k in store if k.startswith(prefix)]

        return _View()

    # -- padded SoA history (TPU-native addition) --------------------------

    def padded_history(self, labels):
        """Device view of the folded history (see :meth:`history_object`)."""
        return self.history_object(labels).device_view()

    def history_object(self, labels):
        """Incrementally fold DONE trials into the dense padded history and
        return the :class:`PaddedHistory`.  O(new + in-flight trials) per call.

        With an asynchronous backend completions arrive out of order, so a
        single watermark would let one slow in-flight trial hide every later
        DONE trial from the posterior (head-of-line blocking).  Instead:
        settled docs fold as soon as they are seen; unsettled ones go into a
        pending set revisited on every call.  Fold order is completion order,
        which is what the linear-forgetting weights should see anyway.
        """
        if self._history is None or self._history.labels != tuple(labels):
            self._history = PaddedHistory(labels)
            self._history_synced = 0
            self._history_pending = []
        docs = self._dynamic_trials

        def fold(doc):
            if doc["state"] != JOB_STATE_DONE:
                return  # ERROR/CANCEL: settled but contributes nothing
            result = doc["result"]
            loss = result.get("loss") if result.get("status") == STATUS_OK else None
            self._history.append(spec_from_misc(doc["misc"]), loss)

        still_pending = []
        for doc in self._history_pending:
            if doc["state"] in (JOB_STATE_NEW, JOB_STATE_RUNNING):
                still_pending.append(doc)
            else:
                fold(doc)
        self._history_pending = still_pending
        while self._history_synced < len(docs):
            doc = docs[self._history_synced]
            self._history_synced += 1
            if doc["state"] in (JOB_STATE_NEW, JOB_STATE_RUNNING):
                self._history_pending.append(doc)
            else:
                fold(doc)
        return self._history

    def fmin(
        self,
        fn,
        space,
        algo=None,
        max_evals=None,
        timeout=None,
        loss_threshold=None,
        max_queue_len=None,
        rstate=None,
        verbose=False,
        pass_expr_memo_ctrl=None,
        catch_eval_exceptions=False,
        return_argmin=True,
        show_progressbar=True,
        early_stop_fn=None,
        trials_save_file="",
        device_loop=False,
        obs=None,
        obs_http=None,
        profile=None,
        lookahead=0,
        compile_cache=None,
    ):
        from .fmin import fmin as _fmin

        return _fmin(
            fn,
            space,
            algo=algo,
            max_evals=max_evals,
            timeout=timeout,
            loss_threshold=loss_threshold,
            trials=self,
            rstate=rstate,
            verbose=verbose,
            allow_trials_fmin=False,
            pass_expr_memo_ctrl=pass_expr_memo_ctrl,
            catch_eval_exceptions=catch_eval_exceptions,
            return_argmin=return_argmin,
            max_queue_len=max_queue_len,
            show_progressbar=show_progressbar,
            early_stop_fn=early_stop_fn,
            trials_save_file=trials_save_file,
            device_loop=device_loop,
            obs=obs,
            obs_http=obs_http,
            profile=profile,
            lookahead=lookahead,
            compile_cache=compile_cache,
        )

    # pickle: drop the numpy history (rebuilt lazily) for a compact file, and
    # drop the live Domain attachment FMinIter installs — it closes over the
    # user objective (often a lambda) and jitted handles; fmin re-installs it
    # on resume.  Cloudpickled byte blobs (the async form) are kept.
    def __getstate__(self):
        state = self.__dict__.copy()
        state["_history"] = None
        state["_history_synced"] = 0
        state["_history_pending"] = []
        # the live obs bundle FMinIter hands the suggesters (tracer locks,
        # open sink) is a per-run handle, not run state: drop it from
        # checkpoints; fmin re-installs one on resume
        state.pop("obs_health", None)
        state.pop("obs_profiler", None)  # holds the capture lock
        attachments = dict(state.get("attachments", {}))
        dom = attachments.get("FMinIter_Domain")
        if dom is not None and not isinstance(dom, (bytes, bytearray)):
            del attachments["FMinIter_Domain"]
        state["attachments"] = attachments
        return state


def trials_from_docs(docs, validate=True, **kwargs):
    """Build Trials from documents (hyperopt/base.py sym: trials_from_docs)."""
    rval = Trials(**kwargs)
    if validate:
        for doc in docs:
            _validate_trial_doc(doc)
    rval._dynamic_trials = list(docs)
    rval.refresh()
    return rval


def trials_from_flat_history(cs, vals, active, losses, cmd):
    """Materialize a dense flat history as a reference-shaped :class:`Trials`
    — one DONE document per trial, sparse idxs/vals built from the active
    masks (inactive conditional params get empty lists, the
    hyperopt/vectorize.py doc form), finite loss → STATUS_OK else
    STATUS_FAIL.  The one doc builder behind every device-resident bridge
    (``device_fmin.fmin_device(return_trials=True)``,
    ``parallel.MultihostResult.to_trials``).

    ``vals``/``active``: ``{label: array[n]}``; ``losses``: ``array[n]``
    (non-finite = failed trial); ``cmd``: the ``misc["cmd"]`` tag naming the
    producing driver.
    """
    n = len(losses)
    docs = []
    for i in range(n):
        idxs, vs = {}, {}
        for l in cs.labels:
            if active[l][i]:
                v = vals[l][i]
                v = int(round(float(v))) if cs.params[l].is_int else float(v)
                idxs[l], vs[l] = [i], [v]
            else:
                idxs[l], vs[l] = [], []
        loss = float(losses[i])
        result = ({"loss": loss, "status": STATUS_OK}
                  if np.isfinite(loss) else {"status": STATUS_FAIL})
        docs.append({
            "state": JOB_STATE_DONE, "tid": i, "spec": None,
            "result": result,
            "misc": {"tid": i, "cmd": (cmd, None), "idxs": idxs, "vals": vs},
            "exp_key": None, "owner": None, "version": 0,
            "book_time": None, "refresh_time": None,
        })
    trials = Trials()
    trials.insert_trial_docs(docs)
    trials.refresh()
    return trials


class Domain:
    """Binds objective + compiled search space
    (hyperopt/base.py sym: Domain.__init__, Domain.evaluate).

    The pyll machinery (``self.expr``, ``VectorizeHelper``, ``s_idxs_vals``,
    ``memo_from_config``) is replaced by a ``CompiledSpace``: a static param
    table plus jitted samplers.  ``evaluate`` assembles the structured config
    on host; ``evaluate_batch_traced`` vmaps objective evaluation on device
    for JAX-traceable objectives (the reference has no analog — SURVEY.md
    §2.2 row "Data parallel").
    """

    rec_eval_print_node_on_error = False

    def __init__(
        self,
        fn,
        expr,
        workdir=None,
        pass_expr_memo_ctrl=None,
        name=None,
        loss_target=None,
    ):
        self.fn = fn
        self.space = expr
        self.expr = as_expr(expr)
        self.cs: CompiledSpace = compile_space(expr)
        self.params = self.cs.params
        self.workdir = workdir
        self.name = name
        self.loss_target = loss_target
        self.pass_expr_memo_ctrl = bool(
            pass_expr_memo_ctrl
            if pass_expr_memo_ctrl is not None
            else getattr(fn, "fmin_pass_expr_memo_ctrl", False)
        )

    @property
    def labels(self):
        return self.cs.labels

    def evaluate(self, config, ctrl, attach_attachments=True):
        """Run the objective on one flat config (hyperopt/base.py sym:
        Domain.evaluate)."""
        if self.pass_expr_memo_ctrl:
            rval = self.fn(expr=self.expr, memo=dict(config), ctrl=ctrl)
        else:
            pyll_rval = self.cs.assemble(config)
            rval = self.fn(pyll_rval)

        if isinstance(rval, (float, int, np.floating, np.integer)) or (
            isinstance(rval, (np.ndarray, jax.Array)) and np.ndim(rval) == 0
        ):
            loss = float(rval)
            if math.isnan(loss):
                raise InvalidLoss(f"objective returned NaN for config {config}")
            dict_rval = {"loss": loss, "status": STATUS_OK}
        else:
            dict_rval = dict(rval)
            status = dict_rval.get("status")
            if status not in STATUS_STRINGS:
                raise InvalidResultStatus(f"invalid status {status!r}")
            if status == STATUS_OK:
                if "loss" not in dict_rval:
                    raise InvalidLoss("ok result without loss")
                loss = float(dict_rval["loss"])
                if math.isnan(loss):
                    raise InvalidLoss(f"objective returned NaN for config {config}")
                dict_rval["loss"] = loss

        if attach_attachments and ctrl is not None:
            attachments = dict_rval.pop("attachments", {})
            if ctrl.current_trial is not None:
                view = ctrl.trials.trial_attachments(ctrl.current_trial)
                for k, v in attachments.items():
                    view[k] = v
        return dict_rval

    def evaluate_async(self, config, ctrl, attach_attachments=True):
        return self.evaluate(config, ctrl, attach_attachments)

    def make_batch_eval(self):
        """Return a jitted ``(flat_batch) -> losses`` for traceable objectives:
        assembles under trace (lax.switch for choices) and vmaps the user fn."""

        def one(flat):
            structured = self.cs.assemble(flat, traced=True)
            return self.fn(structured)

        return jax.jit(jax.vmap(one))

    def short_str(self):
        return f"Domain{{{getattr(self.fn, '__name__', 'fn')}}}"

    # -- result field accessors (hyperopt/base.py sym: Domain.loss etc.) ---

    def loss(self, result, config=None):
        return result.get("loss")

    def loss_variance(self, result, config=None):
        return result.get("loss_variance", 0.0)

    def true_loss(self, result, config=None):
        return result.get("true_loss", result.get("loss"))

    def true_loss_variance(self, result, config=None):
        return result.get("true_loss_variance", 0.0)

    def status(self, result, config=None):
        return result.get("status")

    def new_result(self):
        return {"status": STATUS_NEW}
