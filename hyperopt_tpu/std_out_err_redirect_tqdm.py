"""Redirect stdout/stderr through ``tqdm.write`` while a progress bar is
live, so objective-function prints don't shred the bar.

Parity target: ``hyperopt/std_out_err_redirect_tqdm.py`` (sym:
DummyTqdmFile, std_out_err_redirect_tqdm) — same module name so reference
imports keep working.
"""

from __future__ import annotations

import contextlib
import sys

__all__ = ["DummyTqdmFile", "std_out_err_redirect_tqdm"]


class DummyTqdmFile:
    """File-like that routes writes through ``tqdm.write`` (which repaints
    the bar below the printed text)."""

    def __init__(self, file):
        self.file = file

    def write(self, x):
        if len(x.rstrip()) > 0:  # skip the bare newlines print() emits
            from tqdm import tqdm

            # tqdm.write's default end="\n" supplies the line break the
            # skipped bare-"\n" write would have; with end="" consecutive
            # prints would concatenate onto one line
            tqdm.write(x.rstrip("\n"), file=self.file)

    def flush(self):
        getattr(self.file, "flush", lambda: None)()

    def isatty(self):
        return getattr(self.file, "isatty", lambda: False)()


@contextlib.contextmanager
def std_out_err_redirect_tqdm():
    """Within the block, stdout/stderr prints go through ``tqdm.write``;
    yields the original stdout (hand it to ``tqdm(file=...)``)."""
    orig_out_err = sys.stdout, sys.stderr
    try:
        sys.stdout, sys.stderr = map(DummyTqdmFile, orig_out_err)
        yield orig_out_err[0]
    finally:
        sys.stdout, sys.stderr = orig_out_err
