"""End-to-end multi-controller fmin: the whole ask→tell loop under
``jax.distributed``.

Parity target: the reference's distributed story is a complete driver —
``hyperopt/mongoexp.py`` (sym: MongoTrials + MongoWorker, SURVEY.md §3.4):
one mongod holds the trial state, N worker hosts race to claim and evaluate
trials, the driver folds results as they land.  The TPU-native equivalent is
**SPMD**: every controller process runs this SAME driver; there is no
coordinator beyond ``jax.distributed``'s runtime.  Per generation:

1. **Propose globally** — one batch of ``B`` proposals from the shared TPE
   posterior via ``sharding.suggest_batch_sharded`` over the GLOBAL mesh
   (per-trial keys sharded across every process's devices; history
   replicated).  Proposals are deterministic in ``(seed, global trial id,
   history)``, so every controller sees the same global batch.
2. **Shard evaluation** — controller ``p`` evaluates trials ``j`` with
   ``j % P == p`` (round-robin keeps the load balanced when objective cost
   varies with position in the batch).  This is the MongoWorker analog: the
   expensive objective work is what distributes.
3. **Fold deterministically** — per-controller losses are exchanged with
   ``multihost_utils.process_allgather`` and folded into the padded history
   in GLOBAL trial-id order, so every controller assembles a bitwise
   identical history whatever the completion interleaving (the async
   out-of-order hazard of the Mongo design cannot occur by construction).
4. **Divergence checksum** — a digest of the folded rows is allgathered and
   compared; any mismatch (nondeterministic objective, history corruption,
   compiler divergence across hosts) raises ``ControllerDivergence``
   immediately on every controller instead of silently optimizing different
   posteriors.  (``multihost.replicate_global`` trusts cross-process value
   equality; this is the guard that makes the trust checkable.)

The loop is deterministic in ``(seed, batch, max_evals)`` and INDEPENDENT of
the process count: ``fmin_multihost(..., _force_single=True)`` runs the
identical algorithm on one process, and the 2-process test asserts the
results match bitwise (tests/_multihost_child.py).
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
import time

import numpy as np

import jax
import jax.numpy as jnp

from .. import chaos
from ..exceptions import AllTrialsFailed, FleetDegraded
from ..obs import ObsConfig, RunObs
from ..obs.health import controller_stream_path
from ..spaces import compile_space
from ..algos import tpe
from . import payload as payload_mod

__all__ = ["fmin_multihost", "MultihostResult", "ControllerDivergence",
           "FleetDegraded"]


class ControllerDivergence(RuntimeError):
    """Controllers assembled different global histories (nondeterministic
    objective or corrupt replication) — optimization state is no longer
    consistent across processes."""


@dataclasses.dataclass
class MultihostResult:
    """What every controller returns (identical on all of them)."""

    best: dict            # structured best sample (space_eval form)
    best_loss: float
    n_evals: int
    losses: np.ndarray    # [n_evals] in global trial-id order
    vals: dict            # {label: np.ndarray[n_evals]} flat history
    checksum: str         # digest of the folded history (divergence guard)
    active: dict = dataclasses.field(repr=False)  # {label: bool[n_evals]}
    _cs: object = dataclasses.field(repr=False)   # CompiledSpace of the run

    def to_trials(self):
        """Materialize the run as a reference-shaped :class:`Trials` (every
        trial a document with sparse idxs/vals, inactive conditional params
        empty) so downstream tooling — ``plotting.*``, ``argmin``,
        ``best_trial``, checkpoint pickling — works unchanged, the same
        bridge ``device_fmin.fmin_device(return_trials=True)`` provides."""
        from ..base import trials_from_flat_history

        return trials_from_flat_history(
            self._cs, self.vals, self.active, self.losses, "fmin_multihost")


def _default_cfg(batch):
    cfg = {"prior_weight": 1.0, "n_EI_candidates": 64, "gamma": 1.0,
           "LF": 100}
    if batch > 1:
        # wide shared-posterior batches need diversity-preserving selection
        # (see tpe._select_candidate)
        cfg.update(ei_select="softmax", ei_tau=0.5, prior_eps=0.1)
    return cfg


def _gen_seed(seed, gen):
    """Per-generation base seed, deterministic in (seed, gen)."""
    return (int(seed) + 0x9E3779B1 * (gen + 1)) & 0xFFFFFFFF


def _digest_generation(digest, labels, flats, losses, B):
    """Advance the divergence digest by one generation's rows — per trial,
    the f32 raw loss then each label's f32 value, in global trial-id
    order.  THE shared byte order: the collective fold, the fleet fold and
    the checkpoint replay all call this (or write the row-major
    ``[n, 1+L]`` f32 equivalent), which is what makes their checksums
    comparable bitwise."""
    for j in range(B):
        digest.update(np.float32(losses[j]).tobytes())
        digest.update(
            b"".join(np.float32(flats[l][j]).tobytes() for l in labels))


def _timed_gather(fn, timeout, what, obs, on_timeout):
    """Run one collective with a MONOTONIC deadline.  ``timeout=None`` is
    the direct call — zero threads, zero behavior change (the default).

    With a timeout, the collective runs on a daemon thread; if it misses
    the deadline the peer is presumed dead/partitioned (the hang this
    exists to break), ``on_timeout()`` checkpoints the last verified
    generation, and :class:`FleetDegraded` tells the operator to restart
    the surviving fleet — which resumes bitwise at any size.  The blocked
    thread is deliberately abandoned: the collective can only be freed by
    the peer that will never arrive, and the process is about to exit on
    the raise anyway."""
    if timeout is None:
        return fn()
    box = {}
    done = threading.Event()

    def run():
        try:
            box["out"] = fn()
        except BaseException as e:  # surfaced on the caller thread
            box["err"] = e
        finally:
            done.set()

    th = threading.Thread(target=run, name="hyperopt-allgather", daemon=True)
    th.start()
    if not done.wait(timeout):  # Event.wait is monotonic under the hood
        obs.event("allgather_timeout", point=what, timeout_sec=timeout)
        obs.counter("allgather.timeouts").inc()
        ckpt = False
        try:
            # on_timeout returns True when it actually wrote a checkpoint
            # (no checkpoint_file configured / resume-path timeout → False)
            ckpt = bool(on_timeout())
        except Exception:
            pass  # best-effort checkpoint: the raise below must win
        raise FleetDegraded(
            f"collective '{what}' did not complete within {timeout:.0f}s — "
            "a controller is dead or partitioned; "
            + ("the last verified generation is checkpointed: restart the "
               "surviving fleet (any size) with the same checkpoint_file "
               "to resume bitwise" if ckpt else
               "NO checkpoint was written (no checkpoint_file configured, "
               "or the run already resumed from the one on disk) — restart "
               "the fleet from its last durable state"))
    if "err" in box:
        raise box["err"]
    return box["out"]


def _controller_port(port, pid):
    """Per-controller scrape port: explicit base port + process index
    (``obs.top`` scrapes each controller's ``run.p<i>`` server); 0 stays 0
    (every controller gets its own ephemeral port anyway).  ``host:port``
    strings keep the host and offset the port; an offset past 65535 fails
    open at bind time (serve.py)."""
    if not port:
        return port
    try:
        if isinstance(port, str) and ":" in port:
            host, _, base = port.rpartition(":")
            if int(base) == 0:  # host-form ephemeral: each controller's own
                return port
            return f"{host}:{int(base) + int(pid)}"
        return int(port) + int(pid)
    except (TypeError, ValueError):
        # malformed value: pass through untouched — the server's own parse
        # guard fails open with a warning (never kill a multihost sweep
        # over a scrape-port typo)
        return port


def fmin_multihost(fn, space, max_evals, batch=None, seed=0, cfg=None,
                   n_startup=None, checkpoint_file=None, obs=None,
                   _force_single=False, fleet_dir=None, n_shards=None,
                   lease_ttl=15.0, allgather_timeout=None):
    """Minimize ``fn`` over ``space`` across every process of a
    ``jax.distributed`` runtime.  Call from ALL processes with identical
    arguments (SPMD); returns the same :class:`MultihostResult` everywhere.

    ``fn`` is a host callable on the structured sample (the reference's
    objective contract).  ``batch`` proposals are issued per generation
    (default: one per global device).  ``_force_single`` runs the identical
    algorithm on this process alone — the determinism reference the
    multi-process result must match bitwise.

    ``checkpoint_file``: atomically persist the folded history after every
    generation (controller 0 writes; the file is identical whichever
    controller would write it, by the divergence guarantee) and RESUME from
    it on restart — the multi-controller analog of ``fmin``'s
    ``trials_save_file`` (the reference's distributed driver gets this from
    mongod's durability; SURVEY.md §5 checkpoint row).  A resumed run
    continues the exact trial sequence of an uninterrupted one: generation
    seeds depend only on ``(seed, generation)``, checkpoints land on
    generation boundaries, and the fold digest is replayed from the saved
    rows (the post-resume checksum equals the uninterrupted run's).

    .. warning:: **Pickle trust** — checkpoints are loaded with
       ``pickle.load``, so resuming from a tampered ``checkpoint_file``
       executes arbitrary code.  This matches the repo-wide
       ``trials_save_file``/filestore pickle convention (and the
       reference's), but ``checkpoint_file`` is *documented* to live on a
       filesystem shared by every controller, which widens the writer set:
       restrict write access on that path to the controller processes (see
       docs/DESIGN.md "Observability & trust").

    ``obs``: run-telemetry config (``None`` → environment, a path → JSONL
    stream, or an ``ObsConfig``/``RunObs``).  Records per-generation spans,
    allgather latency, checkpoint save/load timing, and — on
    :class:`ControllerDivergence` — a full context dump of the disagreeing
    checksums.  In a multi-process runtime each controller writes its OWN
    stream (``run.jsonl`` → ``run.p<i>.jsonl``, run_id tagged ``-p<i>``);
    render them as one cross-controller view (allgather skew, per-controller
    phase breakdown, divergence correlation) with::

        python -m hyperopt_tpu.obs.report --merge run.p0.jsonl run.p1.jsonl

    ``fleet_dir``: run as one controller of an ELASTIC fleet instead (no
    ``jax.distributed`` required): generation ownership moves from
    positional bucketing onto filestore shard leases rooted at this
    directory, controllers may join/leave at any time, a survivor reclaims
    a dead controller's shard after ``lease_ttl`` seconds, and a fleet
    resumed at a DIFFERENT size replays the store to a bitwise-identical
    history (``n_shards`` pins the work-shard structure — see
    :mod:`~hyperopt_tpu.parallel.fleet` and docs/DESIGN.md §15).

    ``allgather_timeout`` (collective mode; or
    ``HYPEROPT_TPU_ALLGATHER_TIMEOUT``): bound every cross-controller
    collective by a monotonic deadline.  On expiry the driver checkpoints
    the last checksum-verified generation and raises
    :class:`FleetDegraded` instead of hanging in a collective whose peer
    died — restart the surviving fleet (any size) with the same
    ``checkpoint_file`` to resume bitwise.
    """
    if fleet_dir is not None:
        from .fleet import fleet_fmin

        return fleet_fmin(
            fn, space, max_evals, fleet_dir, batch=batch, seed=seed,
            cfg=cfg, n_startup=n_startup, n_shards=n_shards,
            lease_ttl=lease_ttl, checkpoint_file=checkpoint_file, obs=obs)
    if allgather_timeout is None:
        from .._env import parse_allgather_timeout

        allgather_timeout = parse_allgather_timeout()
    single = _force_single or jax.process_count() == 1
    if single:
        pid, P = 0, 1
    else:
        pid, P = jax.process_index(), jax.process_count()
        from jax.experimental import multihost_utils
    if isinstance(obs, RunObs) and P > 1 and (
            obs.config.jsonl_path or obs.config.flight_path
            or obs.config.http_port or obs.config.devmem_period is not None):
        # a pre-built bundle must ALSO split per controller — N processes
        # appending to its one stream would interleave records under one
        # untagged run_id, exactly what the merge view cannot attribute,
        # and N processes' crash dumps would clobber one flight file.
        # Rebuild from its config with the tagged paths/run_id instead —
        # and disarm the parent bundle's process-global hooks first, or
        # its un-split flight target / stall sink would still collect
        # every controller's output into the one shared file, and its
        # already-bound scrape server would squat the base port the
        # rebuilt controller-0 bundle needs (plus keep serving the
        # detached parent registries)
        if obs.http is not None:
            obs.http.stop()
        if obs.devmem is not None:
            obs.devmem.stop()
        if obs._flight_target is not None:
            obs.flight.remove_target(obs._flight_target)
        elif obs.config.flight_path:
            # explicit flight paths are persistent targets (not tracked in
            # _flight_target) — still unsplit at this point, so drop the
            # shared one before the per-controller rebuild re-arms
            obs.flight.remove_target(obs.config.flight_path)
        if obs.watchdog is not None:
            obs.watchdog.detach_sink(obs.sink)
            obs.watchdog.release()
        obs = RunObs(
            dataclasses.replace(
                obs.config,
                jsonl_path=(controller_stream_path(obs.config.jsonl_path,
                                                   pid)
                            if obs.config.jsonl_path else None),
                flight_path=(controller_stream_path(obs.config.flight_path,
                                                    pid)
                             if obs.config.flight_path else None),
                http_port=_controller_port(obs.config.http_port, pid)),
            run_id=f"{obs.run_id}-p{pid}")
    elif not isinstance(obs, RunObs):
        config = ObsConfig.resolve(obs)
        if P > 1 and config.http_port:
            # one scrape server PER CONTROLLER, port offset by process
            # index (controllers sharing a host would otherwise collide
            # and fail open) — obs.top scrapes each run.p<i> server
            config = dataclasses.replace(
                config, http_port=_controller_port(config.http_port, pid))
        if P > 1 and config.jsonl_path:
            # one stream PER CONTROLLER (run.jsonl -> run.p<i>.jsonl),
            # run_id tagged with the process index: concurrent writers on
            # one shared file would interleave, and the merged post-mortem
            # needs to attribute every record to its controller anyway.
            # Render them as one timeline with
            #   python -m hyperopt_tpu.obs.report --merge run.p0.jsonl ...
            config = dataclasses.replace(
                config,
                jsonl_path=controller_stream_path(config.jsonl_path, pid))
        if P > 1 and config.flight_path:
            # same per-controller split for crash dumps (see above)
            config = dataclasses.replace(
                config,
                flight_path=controller_stream_path(config.flight_path, pid))
        run_id = f"{config.run_id or 'mh'}-p{pid}" if P > 1 else None
        obs = RunObs(config, run_id=run_id)
    if P > 1:
        # no-op without a sink; identifies this stream in the merge view
        obs.event("controller", pid=pid, n_processes=P)

    cs = compile_space(space)
    labels = cs.labels
    n_dev = len(jax.devices())
    if batch is None:
        batch = n_dev
    cfg = dict(cfg or {})
    # cfg["compile_cache"] wires the persistent XLA compilation cache (the
    # multihost analog of fmin's compile_cache= kwarg); it is NOT a kernel
    # parameter, so pop it before cfg feeds run_params / jit cache keys
    from .._env import enable_persistent_compilation_cache

    enable_persistent_compilation_cache(cfg.pop("compile_cache", None))
    cfg = dict(_default_cfg(batch), **cfg)
    if n_startup is None:
        n_startup = max(batch, 20)

    saved = None
    if checkpoint_file is not None:
        import os
        import pickle

        if os.path.exists(checkpoint_file):
            # trust boundary: see the docstring's pickle-trust warning
            t0 = time.perf_counter()
            with open(checkpoint_file, "rb") as f:
                saved = pickle.load(f)
            obs.histogram("checkpoint.load_sec").observe(
                time.perf_counter() - t0)
    # a bitwise resume requires the identical run parameters: generation
    # seeds depend on (seed, gen), gen boundaries on batch, the
    # startup/posterior switch on n_startup, and the proposals on cfg
    run_params = {"labels": list(labels), "batch": int(batch),
                  "seed": int(seed), "n_startup": int(n_startup),
                  "cfg": sorted(cfg.items())}
    if saved is not None:
        for k, v in run_params.items():
            if saved["run_params"][k] != v:
                raise ValueError(
                    f"checkpoint {checkpoint_file} was written with "
                    f"{k}={saved['run_params'][k]!r}; this run has {k}={v!r}"
                    " — bitwise resume requires identical run parameters")
        if saved["n_done"] % batch and saved["n_done"] < max_evals:
            raise ValueError(
                f"checkpoint ends in a partial final generation "
                f"(n_done={saved['n_done']}, batch={batch}): the original "
                "run completed at its own max_evals, and a completed run "
                "cannot be extended bitwise — delete the checkpoint to "
                "start a fresh run")

    cap = 128
    while cap < max(max_evals, saved["n_done"] if saved else 0):
        cap *= 2
    hist = {
        "losses": np.full(cap, np.inf, np.float32),
        "has_loss": np.zeros(cap, bool),
        "vals": {l: np.zeros(cap, np.float32) for l in labels},
        "active": {l: np.zeros(cap, bool) for l in labels},
    }
    # raw per-trial losses as evaluated (NaN for raised trials, ±inf if the
    # objective returned it) — the digest folds THESE, and the checkpoint
    # must replay them bit-exactly; hist only keeps the sanitized form
    raw_losses = np.full(cap, np.nan, np.float32)

    # the proposal kernels: a plain local vmap in single mode, the
    # global-mesh sharded program otherwise (bitwise-identical outputs —
    # the mesh test asserts it)
    from . import sharding
    from .._env import parse_hist_dtype

    # device-resident history storage dtype (HYPEROPT_TPU_HIST_DTYPE):
    # bf16 halves the resident bytes; kernels upcast on read and the fold
    # accumulates in f32, so the checkpoint (host numpy, always f32) and
    # the digest are unaffected.  int8/fp8 (ISSUE 19) degrade to bf16 on
    # this path — the multihost fold compresses by plain astype, and an
    # astype(int8) would TRUNCATE values, not affine-encode them
    from .. import quant

    hist_dtype = str(quant.mirror_float_dtype(parse_hist_dtype()))
    if single:
        mesh = None
        shard_hist = False
        propose_fn = jax.jit(jax.vmap(tpe.build_propose(cs, cfg),
                                      in_axes=(None, 0)))
        sample_fn = jax.jit(jax.vmap(cs.sample_flat))
    else:
        from . import multihost

        mesh = multihost.global_mesh()
        # past the per-chip threshold the history axis shards over the
        # global mesh — each chip then holds cap / n_devices rows instead
        # of a full replicated copy (ROADMAP item 2: the HBM wall)
        shard_hist = sharding.should_shard_history(cap, mesh)
        # packed=True: one [batch, L] buffer -> ONE cross-host collective
        # per generation instead of one per label
        propose_sharded = sharding.suggest_batch_sharded(
            cs, cfg, mesh, packed=True, shard_history=shard_hist)
        sample_fn = jax.jit(jax.vmap(cs.sample_flat))
        obs.gauge("suggest.shards").set(n_dev)
        obs.gauge("suggest.hist_sharded").set(int(shard_hist))

    # DEVICE-RESIDENT history mirror: built once (replicated on the global
    # mesh in multihost mode), then advanced per generation by a DONATED
    # in-place scatter of just that generation's rows
    # (sharding.build_history_fold) — replacing the old cap-sized
    # replicate-the-whole-pytree upload every generation.  The numpy
    # ``hist`` stays the host source of truth (checkpoints pickle FROM it,
    # never from device buffers — the host-materialization boundary), so a
    # failed donated fold just drops the mirror and rebuilds.
    mirror = {"dev": None, "synced": 0}
    wire_fmt = payload_mod.wire_format()

    def device_history(n_now):
        L_n = len(labels)
        if mirror["dev"] is not None and mirror["synced"] < n_now:
            s, e = mirror["synced"], n_now
            k = e - s  # <= batch by construction (one fold per generation)
            vals_rows = np.zeros((batch, L_n), np.float32)
            act_rows = np.zeros((batch, L_n), bool)
            lo = np.zeros(batch, np.float32)
            hl = np.zeros(batch, bool)
            idx = np.full(batch, cap, np.int32)  # padding: dropped in-trace
            for j, l in enumerate(labels):
                vals_rows[:k, j] = hist["vals"][l][s:e]
                act_rows[:k, j] = hist["active"][l][s:e]
            lo[:k] = hist["losses"][s:e]
            hl[:k] = hist["has_loss"][s:e]
            idx[:k] = np.arange(s, e, dtype=np.int32)
            args = (vals_rows, act_rows, lo, hl, idx)
            if not single:
                args = tuple(multihost.replicate_global(a, mesh)
                             for a in args)
            try:
                # mesh-aware fold: the scatter lands directly in the
                # (possibly capacity-sharded) resident layout — never via
                # an intermediate replicated cap-sized copy
                mirror["dev"] = sharding.build_history_fold(
                    labels, mesh=mesh, shard_history=shard_hist)(
                    mirror["dev"], *args)
                mirror["synced"] = e
                obs.counter("mirror.incremental_folds").inc()
            except Exception:
                # the donated input is gone either way; rebuild from host
                mirror["dev"] = None
        if mirror["dev"] is None:
            if single:
                dt = jnp.dtype(hist_dtype)
                mirror["dev"] = jax.tree.map(
                    lambda x: (jnp.asarray(x).astype(dt)
                               if np.issubdtype(np.asarray(x).dtype,
                                                np.floating)
                               else jnp.asarray(x)), hist)
            else:
                from jax.sharding import PartitionSpec as _P

                spec = (_P((sharding.TRIALS_AXIS, sharding.CAND_AXIS))
                        if shard_hist else None)
                mirror["dev"] = multihost.replicate_global(
                    hist, mesh, spec=spec, dtype=jnp.dtype(hist_dtype))
            mirror["synced"] = n_now
            obs.counter("mirror.full_uploads").inc()
        return mirror["dev"]

    def local_keys(gseed):
        return jax.vmap(
            lambda i: jax.random.fold_in(jax.random.PRNGKey(gseed), i)
        )(jnp.arange(batch, dtype=jnp.uint32))

    def gather_packed(mat):
        """GLOBALLY-SHARDED ``[batch, L]`` packed proposals -> per-label
        host arrays on every process, via ONE allgather.  (Locally-computed
        arrays — the startup sampler — are already whole on every process
        and must NOT be allgathered: process_allgather concatenates local
        arrays.)"""
        # pre/post marks around the collective: a stall whose last driver
        # heartbeat is {"point": "proposals", "mark": "pre"} IS a hung
        # allgather — the post-mortem names the blocked collective
        obs.heartbeat("driver.allgather", point="proposals", mark="pre")
        chaos.point("allgather", metrics=obs.metrics)
        t0 = time.perf_counter()
        full = np.asarray(_timed_gather(
            lambda: multihost_utils.process_allgather(mat, tiled=True),
            allgather_timeout, "proposals", obs,
            lambda: _save_checkpoint(force=True),
        )).reshape(batch, len(labels))
        obs.histogram("allgather.proposals_sec").observe(
            time.perf_counter() - t0)
        obs.heartbeat("driver.allgather", point="proposals", mark="post")
        return {l: full[:, j] for j, l in enumerate(labels)}

    digest = hashlib.sha256()
    n_done = 0
    gen = 0
    if saved is not None:
        n_done = saved["n_done"]
        gen = n_done // batch
        hist["losses"][:n_done] = saved["losses"]
        hist["has_loss"][:n_done] = saved["has_loss"]
        raw_losses[:n_done] = saved["raw_losses"]
        for l in labels:
            hist["vals"][l][:n_done] = saved["vals"][l]
            hist["active"][l][:n_done] = saved["active"][l]
        # replay the fold digest so the divergence checksum (and the final
        # result checksum) match an uninterrupted run bitwise.  One
        # vectorized update: the live fold writes, per row, the f32 raw
        # loss then each label's f32 value — exactly a row-major
        # [n_done, 1+L] f32 matrix
        if n_done:
            rows = np.concatenate(
                [np.asarray(saved["raw_losses"], np.float32)[:, None]]
                + [np.asarray(saved["vals"][l], np.float32)[:, None]
                   for l in labels], axis=1)
            digest.update(np.ascontiguousarray(rows, np.float32).tobytes())
    if not single and checkpoint_file is not None:
        # resume agreement: only controller 0 writes the checkpoint, so a
        # per-host disk (or NFS lag) could hand each controller a different
        # resume point — mismatched generation counters mean mismatched
        # collective schedules, i.e. a silent deadlock.  Fail loudly
        # instead: every controller must have loaded identical state.
        # Gated on checkpoint_file: without one, n_done is always 0 and the
        # digest always fresh, so the collective could only ever agree —
        # pure overhead per fmin_multihost call (ADVICE.md round 5).
        obs.counter("resume_agreement_checks").inc()
        obs.heartbeat("driver.allgather", point="resume", mark="pre")
        chaos.point("allgather", metrics=obs.metrics)
        t0 = time.perf_counter()
        state8 = np.frombuffer(digest.digest()[:8], np.uint64)[0]
        mine = jnp.asarray(np.asarray([n_done, state8], np.uint64))
        all_s = np.asarray(_timed_gather(
            lambda: multihost_utils.process_allgather(mine),
            allgather_timeout, "resume", obs, lambda: None,
        )).reshape(P, 2)
        obs.histogram("allgather.resume_sec").observe(
            time.perf_counter() - t0)
        obs.heartbeat("driver.allgather", point="resume", mark="post")
        if not (all_s == all_s[0]).all():
            obs.event("resume_disagreement", n_done=int(n_done),
                      states=[[int(x) for x in row] for row in all_s])
            raise ValueError(
                f"controllers disagree on the resume state {all_s.tolist()}"
                " — checkpoint_file must live on a filesystem shared by"
                " every controller")

    def _save_checkpoint(force=False, upto=None):
        """Atomic generation-boundary snapshot; controller 0 writes (every
        controller holds an identical history — that is the divergence
        guarantee this driver enforces).  ``force=True`` lets ANY
        controller write on the degrade path (a timed-out collective may
        mean controller 0 is the dead one); ``upto`` caps the snapshot at
        the last checksum-VERIFIED trial count when the current
        generation's verification never completed.

        Host-materialization boundary: the snapshot is built from the
        numpy ``hist`` exclusively — never from the device-resident mirror,
        whose buffers may be donated/aliased by the in-place generation
        fold and are not picklable state.  Returns True when a snapshot
        was written (the degrade path's FleetDegraded message reports
        whether an operator actually has a checkpoint to resume from)."""
        if checkpoint_file is None or (pid != 0 and not force):
            return False
        import pickle

        from ..filestore import _atomic_write

        chaos.point("checkpoint", metrics=obs.metrics)
        n = n_done if upto is None else upto
        state = {
            "run_params": run_params,
            "n_done": n,
            "losses": hist["losses"][:n].copy(),
            "has_loss": hist["has_loss"][:n].copy(),
            "raw_losses": raw_losses[:n].copy(),
            "vals": {l: hist["vals"][l][:n].copy() for l in labels},
            "active": {l: hist["active"][l][:n].copy() for l in labels},
        }
        t0 = time.perf_counter()
        _atomic_write(checkpoint_file, pickle.dumps(state))
        obs.histogram("checkpoint.save_sec").observe(
            time.perf_counter() - t0)
        return True

    while n_done < max_evals:
        obs.heartbeat("driver.gen", gen=gen, n_done=n_done)
        chaos.point("gen", metrics=obs.metrics)
        # generation-boundary HBM sample: each controller samples its OWN
        # devices; obs.report --merge aggregates the per-controller streams
        obs.devmem_sample()
        B = min(batch, max_evals - n_done)
        gseed = _gen_seed(seed, gen)
        # generation annotation (obs/profiler.py): a device capture
        # overlapping this generation's propose shows its kernels
        # attributed to (generation, controller) on the device timeline
        with obs.annotate("driver.gen", step=gen, gen=gen,
                          n_done=n_done, pid=pid), \
                obs.span("propose", gen=gen):
            if n_done < n_startup:
                # deterministic in (gseed, index): every process computes
                # the whole startup batch locally, no exchange needed
                out = sample_fn(local_keys(gseed))
                flats = {l: np.asarray(out[l]) for l in labels}
            elif single:
                out = propose_fn(device_history(n_done), local_keys(gseed))
                flats = {l: np.asarray(out[l]) for l in labels}
            else:
                keys = multihost.global_key_batch(gseed, batch, mesh)
                flats = gather_packed(
                    propose_sharded(device_history(n_done), keys))

        def flat_j(j):
            """Host-typed flat sample (int families come back exact off the
            packed f32 arrays — same coercion as rand.unpack_flats)."""
            return {
                l: (int(round(float(flats[l][j]))) if cs.params[l].is_int
                    else float(flats[l][j]))
                for l in labels
            }

        # evaluate MY shard (round-robin by global position in the batch);
        # the active masks of conditional params are computed HERE, for my
        # shard only, and ride the result exchange — every controller used
        # to recompute them for the whole batch during the fold
        L_n = len(labels)
        my_js = [j for j in range(B) if j % P == pid]
        my_losses = np.full(len(my_js), np.nan, np.float32)
        my_active = np.zeros((len(my_js), L_n), bool)
        with obs.span("evaluate", gen=gen, n_local=len(my_js)):
            for k, j in enumerate(my_js):
                flat = flat_j(j)
                act = cs.active_flat(flat)
                my_active[k] = [bool(act[l]) for l in labels]
                try:
                    my_losses[k] = float(fn(cs.assemble(flat)))
                except Exception:
                    # failed trial: no loss, stays typical
                    my_losses[k] = np.nan
                    obs.counter("trials.failed").inc()
        if single:
            losses = my_losses
            active_rows = my_active
        else:
            # pad to the max shard width so allgather shapes agree, encode
            # as ONE lean wire buffer per controller (losses as a narrow
            # f32 column, active/evaluated flags as uint8 bitfields — see
            # payload.py; HYPEROPT_TPU_PAYLOAD=f32 selects the wide debug
            # rows), then reassemble in global order: j = p + k*P
            width = (B + P - 1) // P
            pl = np.full(width, np.nan, np.float32)
            pl[: len(my_js)] = my_losses
            pa = np.zeros((width, L_n), bool)
            pa[: len(my_js)] = my_active
            ev = np.zeros(width, bool)
            ev[: len(my_js)] = True
            wire = payload_mod.to_wire(pl, pa, ev, wire_fmt)
            obs.gauge("payload.bytes_per_controller").set(int(wire.nbytes))
            obs.heartbeat("driver.allgather", point="results", mark="pre",
                          gen=gen)
            chaos.point("allgather", metrics=obs.metrics)
            t0 = time.perf_counter()
            wire_dev = jnp.asarray(wire)
            gathered = np.asarray(_timed_gather(
                lambda: multihost_utils.process_allgather(wire_dev),
                allgather_timeout, "results", obs,
                lambda: _save_checkpoint(force=True),
            )).reshape(P, width, wire.shape[1])
            obs.histogram("allgather.results_sec").observe(
                time.perf_counter() - t0)
            obs.heartbeat("driver.allgather", point="results", mark="post",
                          gen=gen)
            losses = np.full(B, np.nan, np.float32)
            active_rows = np.zeros((B, L_n), bool)
            for p in range(P):
                l_p, a_p, ev_p = payload_mod.from_wire(gathered[p], L_n,
                                                       wire_fmt)
                js = np.arange(p, B, P)
                assert ev_p[: len(js)].all(), "padding row folded as real"
                losses[js] = l_p[: len(js)]
                active_rows[js] = a_p[: len(js)]

        # deterministic fold, global trial-id order (shared with the wire
        # formats' bitwise-equality test: payload.fold_generation is THE
        # fold, whatever encoding delivered the rows)
        with obs.span("fold", gen=gen):
            payload_mod.fold_generation(
                hist, raw_losses, n_done, labels,
                {l: flats[l][:B] for l in labels}, losses, active_rows)
            _digest_generation(digest, labels, flats, losses, B)
        n_done += B
        gen += 1
        obs.counter("generations").inc()
        # headline gauges for the live scrape/top surface (dict stores)
        obs.counter("trials.completed").inc(B)
        done_live = hist["has_loss"][:n_done]
        if done_live.any():
            obs.gauge("best_loss").set(float(
                hist["losses"][:n_done][done_live].min()))
        # divergence checksum: every controller must have folded the same
        # bytes in the same order
        if not single:
            h = int.from_bytes(digest.digest()[:8], "big")
            obs.heartbeat("driver.allgather", point="checksum", mark="pre",
                          gen=gen)
            chaos.point("allgather", metrics=obs.metrics)
            t0 = time.perf_counter()
            h_dev = jnp.asarray(np.uint64(h))
            all_h = np.asarray(_timed_gather(
                lambda: multihost_utils.process_allgather(h_dev),
                allgather_timeout, "checksum", obs,
                # this generation is folded but NOT verified: degrade to
                # the last checksum-verified boundary
                lambda: _save_checkpoint(force=True, upto=n_done - B),
            ))
            obs.histogram("allgather.checksum_sec").observe(
                time.perf_counter() - t0)
            obs.heartbeat("driver.allgather", point="checksum", mark="post",
                          gen=gen)
            if not np.all(all_h == all_h.reshape(-1)[0]):
                # post-mortem context dump: everything a human needs to see
                # WHICH controller diverged and on what data, persisted to
                # the JSONL stream before the raise tears the process down
                obs.event(
                    "controller_divergence",
                    pid=pid, n_done=int(n_done), gen=int(gen),
                    checksums=[hex(int(x)) for x in all_h.reshape(-1)],
                    last_gen_losses=[float(x) for x in losses],
                    batch=int(B),
                )
                obs.counter("divergences").inc()
                raise ControllerDivergence(
                    f"history checksums diverged after {n_done} trials: "
                    f"{[hex(int(x)) for x in all_h.reshape(-1)]}")
        # persist only checksum-verified generations
        _save_checkpoint()

    live = hist["has_loss"][:n_done]
    losses_all = hist["losses"][:n_done]
    if not live.any():
        raise AllTrialsFailed(
            f"all {n_done} trials failed (objective raised on every call)")
    best_i = int(np.argmin(np.where(live, losses_all, np.inf)))
    best_flat = {
        l: (int(round(float(hist["vals"][l][best_i])))
            if cs.params[l].is_int else float(hist["vals"][l][best_i]))
        for l in labels
    }
    obs.finish()  # flush the metrics snapshot to an armed JSONL stream
    return MultihostResult(
        best=cs.assemble(best_flat),
        best_loss=float(losses_all[best_i]),
        n_evals=n_done,
        losses=losses_all.copy(),
        vals={l: hist["vals"][l][:n_done].copy() for l in labels},
        checksum=digest.hexdigest(),
        active={l: hist["active"][l][:n_done].copy() for l in labels},
        _cs=cs,
    )
