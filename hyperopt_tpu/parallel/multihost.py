"""Multi-host / multi-process distribution over ICI + DCN.

Parity target: the reference scales across machines with MongoDB polling —
``hyperopt/mongoexp.py`` (sym: MongoJobs.reserve, MongoWorker) has N worker
hosts racing to claim trial docs from one mongod (SURVEY.md §2.2 "collective
communication backend" row and §5 "distributed comm" row).  The TPU-native
equivalent is a **multi-controller JAX job**: every host runs the same
program, ``jax.distributed.initialize`` forms one global runtime, and the
proposal/evaluation arrays are sharded over a global ``Mesh`` whose
collectives ride ICI within a slice and DCN across slices.  Trial-history
state is replicated (it is tiny); the trial-batch and candidate axes shard.

This module is the thin wiring layer: idempotent ``initialize`` with
environment fallbacks, a global mesh helper, and deterministic global key
batches every process can construct without communication.  The sharded
kernels themselves (``sharding.suggest_batch_sharded``,
``sharding.propose_sharded_candidates``) are process-count-agnostic — under
a multi-process runtime the same jitted programs place their shards on other
hosts' devices and XLA inserts the cross-host collectives.

Tested (tests/test_multihost.py) the way the reference tests mongo
distribution — real local processes, no fakes: two jax processes form one
8-device CPU mesh and must produce bitwise-identical proposals to a
single-process run.
"""

from __future__ import annotations

import os

import numpy as np

import jax
import jax.numpy as jnp

__all__ = [
    "initialize",
    "is_initialized",
    "global_mesh",
    "global_key_batch",
    "replicate_global",
    "process_index",
    "process_count",
]

_initialized = False


def initialize(coordinator_address=None, num_processes=None, process_id=None,
               local_device_ids=None, **kwargs):
    """Join (or form) the multi-process JAX runtime.  Idempotent.

    Arguments fall back to the standard environment variables
    (``JAX_COORDINATOR_ADDRESS``, ``JAX_NUM_PROCESSES``, ``JAX_PROCESS_ID``),
    and on Cloud TPU pods everything may be omitted — ``jax.distributed``
    autodetects from the TPU metadata server.  Call before any other jax use
    (backend topology is fixed at first device access).
    """
    global _initialized
    if _initialized:
        return
    if coordinator_address is None:
        coordinator_address = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if num_processes is None:
        num_processes = os.environ.get("JAX_NUM_PROCESSES")
        num_processes = int(num_processes) if num_processes is not None else None
    if process_id is None:
        process_id = os.environ.get("JAX_PROCESS_ID")
        process_id = int(process_id) if process_id is not None else None
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
        **kwargs,
    )
    _initialized = True


def is_initialized():
    return _initialized


def process_index():
    return jax.process_index()


def process_count():
    return jax.process_count()


def global_mesh(n_cand_shards=1):
    """A ``(trials, cand)`` mesh over ALL global devices (every process's
    chips).  Must be constructed identically on every process — jax.devices()
    returns the same global order everywhere."""
    from . import sharding

    return sharding.make_mesh(len(jax.devices()), n_cand_shards=n_cand_shards)


def replicate_global(tree, mesh, spec=None, dtype=None):
    """Place a host-value pytree onto every device of a (possibly
    multi-process) global mesh — replicated by default, or laid out per
    ``spec`` (a ``PartitionSpec``; the driver passes the capacity-axis
    spec when the resident history shards).  The value must be identical
    on every process — true by construction for trial history, which every
    controller folds deterministically.  ``jax.make_array_from_callback``
    assembles the global array from each process's addressable shards, the
    multi-controller-safe equivalent of ``sharding.place_history``'s
    single-process ``device_put``.  ``dtype`` compresses float leaves to
    the storage dtype on the way (the bf16 resident-history path)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..obs.watchdog import beat as _wd_beat

    sh = NamedSharding(mesh, P() if spec is None else spec)
    dt = np.dtype(dtype) if dtype is not None else None

    def put(x):
        x = np.asarray(x)
        if dt is not None and jnp.issubdtype(x.dtype, jnp.floating):
            x = x.astype(dt)
        return jax.make_array_from_callback(x.shape, sh, lambda idx: x[idx])

    # liveness mark before handing the history to the runtime: device_put
    # onto a multi-process mesh can block on a peer that never arrives
    _wd_beat("multihost.replicate", mark="pre")
    out = jax.tree.map(put, tree)
    _wd_beat("multihost.replicate", mark="post")
    return out


def global_key_batch(seed, batch, mesh, axis=None):
    """A globally-sharded ``[batch, 2]`` array of per-trial PRNG keys (raw
    uint32 words, the format the proposal kernels vmap over).

    Every process computes only its addressable shards, via
    ``jax.make_array_from_callback`` — no cross-host traffic.  Key
    derivation is ``fold_in(PRNGKey(seed), index)``, deterministic in the
    global index, so the assembled global array is identical to what a
    single process would build (the multihost test asserts this).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from . import sharding as _sh

    if axis is None:
        axis = (_sh.TRIALS_AXIS, _sh.CAND_AXIS)
    base = jax.random.PRNGKey(seed)
    host_keys = np.asarray(
        jax.vmap(lambda i: jax.random.fold_in(base, i))(
            jnp.arange(batch, dtype=jnp.uint32))
    )  # [batch, 2], batch-dim sharded, key words replicated
    spec = NamedSharding(mesh, P(axis))
    return jax.make_array_from_callback(
        host_keys.shape, spec, lambda index: host_keys[index])
