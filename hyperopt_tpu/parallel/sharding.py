"""Mesh sharding for the TPE proposal kernels.

Two axes scale in an HPO workload (SURVEY.md §5 "long-context" row):

* the **trial batch** — how many new trials are proposed per step
  (the reference serializes these; MongoTrials/SparkTrials parallelize only
  the *evaluation*), and
* the **candidate axis** — ``n_EI_candidates`` posterior draws per proposal
  (fixed at 24 in the reference).

``suggest_batch_sharded`` shards the first over a mesh axis (pure data
parallelism: per-trial RNG keys are split across devices, history is
replicated, no cross-device traffic).  ``propose_sharded_candidates`` shards
the second with ``jax.shard_map``: each device draws and EI-scores a local
candidate slice, then an ``all_gather`` of per-device (best EI, best value)
pairs resolves the global argmax — collectives ride ICI, the dense analog of
a sequence-parallel reduction.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..algos import tpe

__all__ = [
    "make_mesh",
    "suggest_batch_sharded",
    "propose_sharded_candidates",
    "replicate_history",
    "build_history_fold",
]

TRIALS_AXIS = "trials"
CAND_AXIS = "cand"

# labels tuple -> donated jitted generation fold (shape specialization is
# jit's own cache; bounded because spaces are few per process)
_fold_cache = {}


def build_history_fold(labels):
    """One DONATED device program scattering a generation's rows into the
    replicated history pytree **in place**:

        fold(hist, vals_rows[W, L], active_rows[W, L], losses[W], has[W],
             idx[W]) -> hist'

    This is what lets the multihost driver keep the padded history
    device-resident across generations: instead of re-replicating the full
    cap-sized pytree every generation (cap × (5 bytes + 5/label) over the
    host↔device link), only the generation's W rows travel and the scatter
    aliases the donated buffers.  Padding rows carry ``idx = cap`` and are
    dropped in-trace (``mode='drop'``), so the program shape is stable at
    the batch width.  Callers must thread the RETURNED pytree forward —
    the donated argument is invalid after dispatch (same contract as
    ``PaddedHistory.device_state(donate=True)``).
    """
    labels = tuple(labels)
    fn = _fold_cache.get(labels)
    if fn is None:

        def fold(hist, vals_rows, active_rows, losses, has, idx):
            return {
                "losses": hist["losses"].at[idx].set(losses, mode="drop"),
                "has_loss": hist["has_loss"].at[idx].set(has, mode="drop"),
                "vals": {
                    l: hist["vals"][l].at[idx].set(vals_rows[:, j],
                                                   mode="drop")
                    for j, l in enumerate(labels)
                },
                "active": {
                    l: hist["active"][l].at[idx].set(active_rows[:, j],
                                                     mode="drop")
                    for j, l in enumerate(labels)
                },
            }

        fn = _fold_cache[labels] = jax.jit(fold, donate_argnums=(0,))
    return fn


def make_mesh(n_devices=None, n_cand_shards=1):
    """A ``(trials, cand)`` mesh over the first ``n_devices`` devices.

    ``n_cand_shards`` devices along the candidate axis, the rest along the
    trial-batch axis.  With the defaults this is a pure data-parallel mesh.
    """
    devs = jax.devices()
    n = len(devs) if n_devices is None else n_devices
    if n % n_cand_shards:
        raise ValueError(f"{n} devices not divisible by n_cand_shards={n_cand_shards}")
    arr = np.array(devs[:n]).reshape(n // n_cand_shards, n_cand_shards)
    return Mesh(arr, (TRIALS_AXIS, CAND_AXIS))


def replicate_history(history, mesh):
    """Place the padded-history pytree fully replicated on the mesh."""
    rep = NamedSharding(mesh, P())
    return jax.tree.map(lambda x: jax.device_put(jnp.asarray(x), rep), history)


def suggest_batch_sharded(cs, cfg, mesh, packed=False):
    """Data-parallel batched proposal: keys sharded over every mesh device,
    history replicated.  Returns ``fn(history, keys) -> {label: [batch]}``
    — or, with ``packed=True``, ``-> [batch, L]`` (``rand.pack_labels``
    order), the one-buffer form: a multi-controller driver can then
    exchange a whole generation with a SINGLE cross-host collective instead
    of one per label (collective launch latency dominates [batch]-sized
    transfers over DCN).

    Mathematically identical to the unsharded ``vmap`` (each proposal is
    independent), so results match a single-device run bitwise — the dryrun
    asserts exactly that.
    """
    from ..algos import rand

    propose = jax.vmap(tpe.build_propose(cs, cfg), in_axes=(None, 0))
    key_sharding = NamedSharding(mesh, P((TRIALS_AXIS, CAND_AXIS)))
    rep = NamedSharding(mesh, P())
    hist_shardings = jax.tree.map(lambda _: rep, {
        "losses": 0, "has_loss": 0,
        "vals": {l: 0 for l in cs.labels},
        "active": {l: 0 for l in cs.labels},
    })
    if packed:
        fn = lambda h, k: rand.pack_labels(cs, propose(h, k))  # noqa: E731
        out_sharding = key_sharding  # [batch, L]: batch axis sharded
    else:
        fn = propose
        out_sharding = {l: key_sharding for l in cs.labels}
    return jax.jit(
        fn,
        in_shardings=(hist_shardings, key_sharding),
        out_shardings=out_sharding,
    )


def propose_sharded_candidates(cs, cfg, mesh, packed=False):
    """One proposal with the candidate axis sharded over ``mesh``'s ``cand``
    axis via ``shard_map``.  ``packed=True`` returns a ``[1, L]`` buffer
    (``rand.pack_labels`` order) so the host fetches ONE transfer instead
    of one per label.

    Each device fits the same below/above Parzen models (history replicated),
    draws ``n_EI_candidates / n_shards`` candidates with a device-folded key,
    EI-scores them locally, and contributes its (best EI, best value) to an
    ``all_gather``; the global argmax picks the winner.  Scales
    ``n_EI_candidates`` past single-chip memory/latency limits (the
    sequence-parallel analog for HPO: SURVEY.md §2.2 last row).
    """
    n_shards = mesh.shape[CAND_AXIS]
    n_cand = cfg["n_EI_candidates"]
    if n_cand % n_shards:
        raise ValueError(f"n_EI_candidates={n_cand} not divisible by {n_shards} shards")
    local_cfg = dict(cfg, n_EI_candidates=n_cand // n_shards)
    scored = tpe.build_propose_with_scores(cs, local_cfg)

    def local_best(history, key):
        """Per-device: local candidates + local EI max (runs inside shard_map).
        Reuses the shared scored-proposal kernel (incl. its grouped uniform
        pipeline) with a shard-folded key — the only sharding-specific code
        is the fold and the [1]-shaped packaging for the all-gather."""
        shard = jax.lax.axis_index(CAND_AXIS)
        key = jax.random.fold_in(key, shard)
        out = scored(history, key)
        best_ei = {l: ei[None] for l, (_, ei) in out.items()}
        best_val = {l: val[None] for l, (val, _) in out.items()}
        return best_ei, best_val

    def propose(history, key):
        ei_g, val_g = jax.shard_map(
            local_best,
            mesh=mesh,
            in_specs=(P(), P()),
            out_specs=(P(CAND_AXIS), P(CAND_AXIS)),
        )(history, key)
        # ei_g/val_g: [n_shards] per label; global argmax over shards
        out = {l: val_g[l][jnp.argmax(ei_g[l])] for l in cs.labels}
        if packed:
            from ..algos import rand

            return rand.pack_labels(cs, {l: out[l][None] for l in cs.labels})
        return out

    return jax.jit(propose)
