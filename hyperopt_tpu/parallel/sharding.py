"""Mesh sharding for the TPE proposal kernels.

Two axes scale in an HPO workload (SURVEY.md §5 "long-context" row):

* the **trial batch** — how many new trials are proposed per step
  (the reference serializes these; MongoTrials/SparkTrials parallelize only
  the *evaluation*), and
* the **candidate axis** — ``n_EI_candidates`` posterior draws per proposal
  (fixed at 24 in the reference).

``suggest_batch_sharded`` shards the first over a mesh axis (pure data
parallelism: per-trial RNG keys are split across devices, history replicated
— or, past :func:`hist_shard_threshold`, sharded along the capacity axis so
per-chip HBM holds ``cap / n_shards`` rows).  ``propose_sharded_candidates``
shards the second with ``shard_map``: each device draws and EI-scores a
local candidate slice, contributes its top-k (EI, value) pairs to an
``all_gather``, and a global top-k/softmax select resolves each proposal —
collectives ride ICI, the dense analog of a sequence-parallel reduction.

The **partition-rule table** (:data:`SUGGEST_PARTITION_RULES`, applied by
:func:`match_partition_rules` — the regex → PartitionSpec pytree pattern) is
the single source of truth for how every leaf of the fused tell+ask
program's arguments lands on the mesh; ``tpe._get_suggest_jit`` and the
driver's history fold both compile against it via ``jit`` with explicit
``NamedSharding``s (``shard_map`` fallback on jax builds without
``in_shardings`` support), with ``donate_argnums`` preserved so the PR-4
zero-copy invariants hold on the sharded path.
"""

from __future__ import annotations

import logging
import re

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.5 exports it at top level
    shard_map = jax.shard_map
except AttributeError:  # 0.4.x: the experimental home
    from jax.experimental.shard_map import shard_map

from ..algos import tpe

__all__ = [
    "make_mesh",
    "suggest_mesh",
    "suggest_batch_sharded",
    "propose_sharded_candidates",
    "replicate_history",
    "place_history",
    "build_history_fold",
    "match_partition_rules",
    "suggest_partition_rules",
    "suggest_shardings",
    "suggest_batched_shardings",
    "hist_shard_threshold",
    "should_shard_history",
]

logger = logging.getLogger(__name__)

TRIALS_AXIS = "trials"
CAND_AXIS = "cand"

# (labels, mesh geometry, shard_history, dtypes) -> donated jitted
# generation fold (shape specialization is jit's own cache; bounded because
# spaces are few per process)
_fold_cache = {}


# ---------------------------------------------------------------------------
# partition-rule table (SNIPPETS.md [1]: regex over leaf paths -> spec)
# ---------------------------------------------------------------------------


def _leaf_path_str(path):
    """``jax.tree_util`` key path -> a "/"-joined name regexes match on."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:  # pragma: no cover - future key kinds
            parts.append(str(k))
    return "/".join(parts)


def match_partition_rules(rules, tree):
    """Map every leaf of ``tree`` to the PartitionSpec of the first rule
    whose regex matches its "/"-joined key path (the
    ``match_partition_rules`` pattern of SNIPPETS.md [1]).  Leaf VALUES are
    ignored — only the tree structure and key names matter — so callers
    hand in a cheap name-shaped skeleton, not real arrays.  An unmatched
    leaf raises: a silently-replicated buffer is exactly the HBM-wall bug
    this table exists to prevent."""
    def spec_for(path, _leaf):
        name = _leaf_path_str(path)
        for rule, spec in rules:
            if re.search(rule, name) is not None:
                return spec
        raise ValueError(f"no partition rule matches leaf {name!r}")

    return jax.tree_util.tree_map_with_path(spec_for, tree)


def suggest_partition_rules(shard_history=False, axes=None, study_axis=False):
    """The rule table for the fused tell+ask program (and the generation
    fold): leaf path regex → PartitionSpec.

    * the candidate/proposal batch axis (``ids``, ``packed``, diagnostics)
      ALWAYS shards over the mesh ``axes`` (default :data:`CAND_AXIS` —
      the 1-D suggest mesh; the driver's 2-D global mesh passes both);
    * every ``PaddedHistory`` leaf (``vals/*``, ``active/*``, ``losses``,
      ``has_loss``) replicates below :func:`hist_shard_threshold` and
      shards its capacity axis above it;
    * scalar-ish side inputs (``rows``, ``seed_words``, fold row buffers)
      replicate — they are O(batch), not O(cap).

    ``study_axis=True`` is the MULTI-STUDY cohort layout (ISSUE 9,
    ``tpe.build_suggest_batched``): every leaf — history stack, tell rows,
    seed words, ids, packed proposals — carries a LEADING study axis, and
    that axis shards over the mesh.  Per-study math is device-local under
    study sharding (each device owns whole studies), so cohort proposals
    stay bit-identical to the replicated layout at the same seeds.
    """
    axes = (CAND_AXIS,) if axes is None else tuple(axes)
    batch = P(axes)
    if study_axis:
        # the study axis leads EVERY cohort-program leaf; shard them all
        return (
            (r"^hist/", batch),
            (r"^(rows|seed_words|ids|packed|stats|splits)$", batch),
        )
    hist = P(axes) if shard_history else P()
    return (
        (r"^hist/(vals|active)/", hist),
        (r"^hist/(losses|has_loss)$", hist),
        (r"^(rows|seed_words)$", P()),
        (r"^(vals_rows|active_rows|fold_losses|fold_has|fold_idx)$", P()),
        (r"^ids$", batch),
        (r"^(packed|stats|splits)$", batch),
    )


def _hist_skeleton(labels):
    """Name-shaped skeleton of the padded-history pytree (leaf values are
    placeholders; only paths matter to the rule table)."""
    return {
        "losses": 0, "has_loss": 0,
        "vals": {l: 0 for l in labels},
        "active": {l: 0 for l in labels},
    }


def suggest_shardings(mesh, labels, shard_history=False, diag=False):
    """``(in_shardings, out_shardings)`` for the fused tell+ask program
    ``run(history, rows, seed_words, ids) -> (history', packed[, stats,
    splits])``, built from :func:`suggest_partition_rules` via
    :func:`match_partition_rules`."""
    rules = suggest_partition_rules(shard_history)
    hist = _hist_skeleton(labels)
    in_tree = {"hist": hist, "rows": 0, "seed_words": 0, "ids": 0}
    out_tree = {"hist": hist, "packed": 0}
    if diag:
        out_tree.update(stats=0, splits=0)
    in_specs = match_partition_rules(rules, in_tree)
    out_specs = match_partition_rules(rules, out_tree)
    ns = lambda spec: NamedSharding(mesh, spec)  # noqa: E731
    in_sh = (jax.tree.map(ns, in_specs["hist"]), ns(in_specs["rows"]),
             ns(in_specs["seed_words"]), ns(in_specs["ids"]))
    outs = [jax.tree.map(ns, out_specs["hist"]), ns(out_specs["packed"])]
    if diag:
        outs += [ns(out_specs["stats"]), ns(out_specs["splits"])]
    return in_sh, tuple(outs)


def suggest_batched_shardings(mesh, labels):
    """``(in_shardings, out_shardings)`` for the multi-study cohort
    program ``run(hist_stack, rows, seed_words, ids) -> (hist_stack',
    packed)`` (``tpe.build_suggest_batched``): the leading study axis of
    every leaf shards over ``mesh`` per
    :func:`suggest_partition_rules(study_axis=True)`."""
    rules = suggest_partition_rules(study_axis=True, axes=mesh.axis_names)
    hist = _hist_skeleton(labels)
    in_tree = {"hist": hist, "rows": 0, "seed_words": 0, "ids": 0}
    out_tree = {"hist": hist, "packed": 0}
    in_specs = match_partition_rules(rules, in_tree)
    out_specs = match_partition_rules(rules, out_tree)
    ns = lambda spec: NamedSharding(mesh, spec)  # noqa: E731
    in_sh = (jax.tree.map(ns, in_specs["hist"]), ns(in_specs["rows"]),
             ns(in_specs["seed_words"]), ns(in_specs["ids"]))
    out_sh = (jax.tree.map(ns, out_specs["hist"]), ns(out_specs["packed"]))
    return in_sh, out_sh


def shard_map_suggest_fallback(run, mesh, diag=False):
    """``shard_map`` expression of the fused tell+ask program for jax
    builds whose ``jit`` lacks ``in_shardings`` (SNIPPETS.md [3]: prefer
    pjit with explicit shardings, fall back to map-style ``shard_map``).
    History and rows replicate; the batch axis (``ids``/outputs) maps over
    :data:`CAND_AXIS`.  Every shard applies the same row fold, so the
    replicated history output is shard-invariant by construction
    (``check_rep=False``: the scatter's replication is not provable to the
    rep checker)."""
    out_specs = (P(), P(CAND_AXIS))
    if diag:
        out_specs = out_specs + (P(CAND_AXIS), P(CAND_AXIS))
    return shard_map(run, mesh=mesh,
                     in_specs=(P(), P(), P(), P(CAND_AXIS)),
                     out_specs=out_specs, check_rep=False)


def hist_shard_threshold():
    """Capacity at which the history axis starts sharding (env-tunable:
    ``HYPEROPT_TPU_HIST_SHARD_MIN``)."""
    from .._env import parse_hist_shard_min

    return parse_hist_shard_min()


def should_shard_history(cap, mesh):
    """True when ``cap`` crosses the per-chip threshold AND divides the
    mesh evenly (power-of-two caps over power-of-two meshes always do)."""
    n = int(np.prod(list(mesh.shape.values())))
    return n > 1 and cap >= hist_shard_threshold() and cap % n == 0


# ---------------------------------------------------------------------------
# history placement + the donated generation fold
# ---------------------------------------------------------------------------


def build_history_fold(labels, mesh=None, shard_history=False):
    """One DONATED device program scattering a generation's rows into the
    history pytree **in place**:

        fold(hist, vals_rows[W, L], active_rows[W, L], losses[W], has[W],
             idx[W]) -> hist'

    This is what lets the multihost driver keep the padded history
    device-resident across generations: instead of re-replicating the full
    cap-sized pytree every generation (cap × (5 bytes + 5/label) over the
    host↔device link), only the generation's W rows travel and the scatter
    aliases the donated buffers.  Padding rows carry ``idx = cap`` and are
    dropped in-trace (``mode='drop'``), so the program shape is stable at
    the batch width.  Callers must thread the RETURNED pytree forward —
    the donated argument is invalid after dispatch (same contract as
    ``PaddedHistory.device_state(donate=True)``).

    With ``mesh`` the fold compiles with explicit ``NamedSharding``s from
    the partition-rule table: the scatter lands directly in the SHARDED
    layout (``shard_history=True``) or the mesh-replicated one — never via
    an intermediate replicated copy of the cap-sized pytree.
    """
    labels = tuple(labels)
    geom = (None if mesh is None else
            (tuple(mesh.shape.items()), tuple(d.id for d in mesh.devices.flat)))
    key = (labels, geom, bool(shard_history))
    fn = _fold_cache.get(key)
    if fn is None:

        def fold(hist, vals_rows, active_rows, losses, has, idx):
            return {
                "losses": hist["losses"].at[idx].set(
                    losses.astype(hist["losses"].dtype), mode="drop"),
                "has_loss": hist["has_loss"].at[idx].set(has, mode="drop"),
                "vals": {
                    l: hist["vals"][l].at[idx].set(
                        vals_rows[:, j].astype(hist["vals"][l].dtype),
                        mode="drop")
                    for j, l in enumerate(labels)
                },
                "active": {
                    l: hist["active"][l].at[idx].set(active_rows[:, j],
                                                     mode="drop")
                    for j, l in enumerate(labels)
                },
            }

        if mesh is None:
            fn = jax.jit(fold, donate_argnums=(0,))
        else:
            rules = suggest_partition_rules(shard_history,
                                            axes=mesh.axis_names)
            tree = {"hist": _hist_skeleton(labels), "vals_rows": 0,
                    "active_rows": 0, "fold_losses": 0, "fold_has": 0,
                    "fold_idx": 0}
            specs = match_partition_rules(rules, tree)
            ns = lambda s: NamedSharding(mesh, s)  # noqa: E731
            hist_sh = jax.tree.map(ns, specs["hist"])
            fn = jax.jit(
                fold,
                in_shardings=(hist_sh, ns(specs["vals_rows"]),
                              ns(specs["active_rows"]),
                              ns(specs["fold_losses"]),
                              ns(specs["fold_has"]), ns(specs["fold_idx"])),
                out_shardings=hist_sh,
                donate_argnums=(0,),
            )
        _fold_cache[key] = fn
    return fn


def make_mesh(n_devices=None, n_cand_shards=1):
    """A ``(trials, cand)`` mesh over the first ``n_devices`` devices.

    ``n_cand_shards`` devices along the candidate axis, the rest along the
    trial-batch axis.  With the defaults this is a pure data-parallel mesh.
    """
    devs = jax.devices()
    n = len(devs) if n_devices is None else n_devices
    if n % n_cand_shards:
        raise ValueError(f"{n} devices not divisible by n_cand_shards={n_cand_shards}")
    arr = np.array(devs[:n]).reshape(n // n_cand_shards, n_cand_shards)
    return Mesh(arr, (TRIALS_AXIS, CAND_AXIS))


# geometry -> 1-D suggest mesh (meshes hash by device objects; cache keeps
# the fused program's jit cache key stable across asks)
_suggest_mesh_cache = {}


def suggest_mesh(n_devices=None):
    """A flat 1-D ``(cand,)`` mesh over the first ``n_devices`` local
    devices — the mesh the FUSED tell+ask program shards over (its one
    batch axis is the candidate/proposal batch).  ``n_devices=None`` or
    ``-1`` means all local devices; cached per geometry."""
    devs = jax.devices()
    n = len(devs) if n_devices in (None, -1) else min(int(n_devices),
                                                      len(devs))
    key = tuple(d.id for d in devs[:n])
    m = _suggest_mesh_cache.get(key)
    if m is None:
        m = _suggest_mesh_cache[key] = Mesh(np.array(devs[:n]), (CAND_AXIS,))
    return m


def place_history(history, mesh, shard_history=False, dtype=None):
    """Place the padded-history pytree on ``mesh`` per the partition-rule
    table: replicated by default, capacity-axis sharded with
    ``shard_history=True``.  ``dtype`` (a jnp float dtype) compresses the
    float leaves (``vals``, ``losses``) to the storage dtype on the way —
    the bf16 resident-history path; bool masks stay bool."""
    rules = suggest_partition_rules(shard_history, axes=mesh.axis_names)
    specs = match_partition_rules(rules, {"hist": _hist_skeleton(
        list(history["vals"]))})["hist"]

    def put(x, spec):
        x = jnp.asarray(x)
        # itemsize > 1: an int8/fp8 QUANTIZED leaf (ISSUE 19) holds affine
        # codes, not values — an astype here would silently decode-corrupt
        # them; quantized leaves place as-is (their dtype IS the storage)
        if (dtype is not None and jnp.issubdtype(x.dtype, jnp.floating)
                and x.dtype.itemsize > 1):
            x = x.astype(dtype)
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree.map(put, dict(history), specs)


def replicate_history(history, mesh):
    """Place the padded-history pytree fully replicated on the mesh."""
    return place_history(history, mesh, shard_history=False)


def suggest_batch_sharded(cs, cfg, mesh, packed=False, shard_history=False,
                          qparams=None):
    """Data-parallel batched proposal: keys sharded over every mesh device,
    history replicated — or capacity-axis sharded with
    ``shard_history=True`` (per-chip HBM then holds ``cap / n_devices``
    rows; XLA inserts the gathers the Parzen fit needs).  Returns
    ``fn(history, keys) -> {label: [batch]}`` — or, with ``packed=True``,
    ``-> [batch, L]`` (``rand.pack_labels`` order), the one-buffer form: a
    multi-controller driver can then exchange a whole generation with a
    SINGLE cross-host collective instead of one per label (collective
    launch latency dominates [batch]-sized transfers over DCN).

    Mathematically identical to the unsharded ``vmap`` (each proposal is
    independent), so results match a single-device run bitwise — the dryrun
    asserts exactly that.
    """
    from ..algos import rand

    propose = jax.vmap(tpe.build_propose(cs, cfg, qparams=qparams),
                       in_axes=(None, 0))
    key_sharding = NamedSharding(mesh, P((TRIALS_AXIS, CAND_AXIS)))
    hist_spec = (P((TRIALS_AXIS, CAND_AXIS)) if shard_history else P())
    rep = NamedSharding(mesh, hist_spec)
    hist_shardings = jax.tree.map(lambda _: rep, _hist_skeleton(cs.labels))
    if packed:
        fn = lambda h, k: rand.pack_labels(cs, propose(h, k))  # noqa: E731
        out_sharding = key_sharding  # [batch, L]: batch axis sharded
    else:
        fn = propose
        out_sharding = {l: key_sharding for l in cs.labels}
    return jax.jit(
        fn,
        in_shardings=(hist_shardings, key_sharding),
        out_shardings=out_sharding,
    )


def propose_sharded_candidates(cs, cfg, mesh, packed=False, batch=None,
                               topk=4, qparams=None):
    """Proposals with the CANDIDATE axis sharded over ``mesh``'s ``cand``
    axis via ``shard_map``.  ``batch=None`` keeps the legacy one-proposal
    signature ``fn(history, key) -> {label: scalar}`` (``[1, L]`` packed);
    ``batch=B`` returns ``fn(history, keys[B]) -> {label: [B]}``
    (``[B, L]`` packed) — a full sharded batch of proposals, each scored
    over the whole distributed candidate pool.

    Each device fits the same below/above Parzen models, draws
    ``ceil(n_EI_candidates / n_shards)`` candidates with a device-folded
    key, EI-scores them locally, and contributes its top-``k`` (EI, value)
    pairs to an ``all_gather``; the final select over the gathered
    ``n_shards * k`` pool follows ``cfg["ei_select"]`` — hard argmax
    (exactly the global argmax: the winner is necessarily some shard's
    local top-1) or Gumbel-max softmax over the pooled top candidates (the
    batch-diversity policy of ``tpe._select_candidate``, here restricted
    to the gathered pool).  Scales ``n_EI_candidates`` past single-chip
    memory/latency limits (the sequence-parallel analog for HPO:
    SURVEY.md §2.2 last row).

    ``n_EI_candidates`` need NOT divide the shard count: the local batch
    pads up to the next multiple and padded candidates score ``-inf`` EI,
    so they never win (ISSUE 6 satellite — this used to raise
    ``ValueError``).
    """
    from ..spaces import label_hash

    n_shards = mesh.shape[CAND_AXIS]
    n_cand = int(cfg["n_EI_candidates"])
    n_local = -(-n_cand // n_shards)  # ceil: pad instead of erroring
    k = int(min(topk, n_local))
    local_cfg = dict(cfg, n_EI_candidates=n_local)
    scored = tpe.build_propose_candidates(cs, local_cfg, qparams=qparams)
    single = batch is None
    B = 1 if single else int(batch)
    neg_inf = jnp.float32(-jnp.inf)

    # per-label prior draws for the ε-prior mix (the same exploration
    # floor _mix_prior gives the single-chip kernels: with prob prior_eps
    # a proposal is replaced by a fresh search-space draw, so the batch
    # never collapses onto posterior modes once the posterior sharpens)
    eps = float(cfg.get("prior_eps", 0.0))
    prior_draws = {}
    for _l in cs.labels:
        _dist = cs.params[_l].dist
        if _dist.family in ("categorical", "randint"):
            _pp = jnp.asarray(tpe._prior_probs(_dist))
            _off = (int(_dist.params[0]) if _dist.family == "randint" else 0)
            prior_draws[_l] = (
                lambda kp, pp=_pp, off=_off:
                (tpe._prior_draw_discrete(kp, pp) + off).astype(jnp.float32))
        else:
            _parz = tpe._parzen_from(_dist)
            prior_draws[_l] = (
                lambda kp, parz=_parz: tpe._prior_draw_numeric(kp, *parz))

    def local_topk(history, keys):
        """Per-device: local candidates + local top-k (runs inside
        shard_map).  Reuses the shared raw-candidate kernel with a
        shard-folded key; candidates whose GLOBAL index falls past
        ``n_EI_candidates`` are padding — their EI masks to -inf before
        the top-k so the pad never wins."""
        shard = jax.lax.axis_index(CAND_AXIS)
        valid = (shard * n_local + jnp.arange(n_local)) < n_cand

        def one(key):
            out = scored(history, jax.random.fold_in(key, shard))
            ei_k, val_k = {}, {}
            for l, (samples, ei) in out.items():
                ei = jnp.where(valid, ei, neg_inf)
                top_ei, top_i = jax.lax.top_k(ei, k)
                onehot = (top_i[:, None]
                          == jnp.arange(n_local)[None, :]).astype(jnp.float32)
                ei_k[l] = top_ei
                val_k[l] = onehot @ samples.astype(jnp.float32)
            return ei_k, val_k

        return jax.vmap(one)(keys)  # {label: [B, k]} pairs

    # batched mode shards the PROPOSAL axis over the mesh's trials axis
    # too (each trials-group handles its B / n_trial_shards slice) —
    # replicating the whole batch across trials-groups would redo the same
    # proposals n_trial_shards times over.  The caller pads B to a
    # multiple of the full device count, which divides by construction.
    n_trial_shards = dict(mesh.shape).get(TRIALS_AXIS, 1)
    if not single and B % max(n_trial_shards, 1):
        raise ValueError(
            f"batch={B} not divisible by the mesh's {n_trial_shards} "
            f"trial shards (pad with rand.pad_ids_to_multiple)")
    batch_spec = (P(TRIALS_AXIS)
                  if (not single and n_trial_shards > 1) else P())
    out_row = batch_spec[0] if len(batch_spec) else None

    def propose(history, keys):
        if single:
            keys = keys[None]
        ei_g, val_g = shard_map(
            local_topk,
            mesh=mesh,
            in_specs=(P(), batch_spec),
            out_specs=(P(out_row, CAND_AXIS), P(out_row, CAND_AXIS)),
        )(history, keys)
        # ei_g/val_g: [B, n_shards * k] per label; global select per
        # proposal over the pooled shard top-k.  Keys fold per label
        # (label_hash, the single-chip kernels' contract) so softmax
        # Gumbel noise stays independent across labels, and the ε-prior
        # mix reuses _mix_prior's fold constants (0x9B10B draw, 0xE9510
        # gate) so the exploration floor matches the unsharded policy.
        def select(key, ei_b, val_b):
            out_b = {}
            for l in cs.labels:
                k_l = jax.random.fold_in(key, label_hash(l))
                v = tpe._select_candidate(k_l, val_b[l], ei_b[l], cfg)[0]
                if eps > 0.0:
                    xp = prior_draws[l](jax.random.fold_in(k_l, 0x9B10B))
                    take = jax.random.uniform(
                        jax.random.fold_in(k_l, 0xE9510), ()) < eps
                    v = jnp.where(take, jnp.asarray(xp, v.dtype), v)
                out_b[l] = v
            return out_b

        out = jax.vmap(select)(keys, ei_g, val_g)
        if single:
            out = {l: out[l][0] for l in cs.labels}
            if packed:
                from ..algos import rand

                return rand.pack_labels(cs, {l: out[l][None]
                                             for l in cs.labels})
            return out
        if packed:
            from ..algos import rand

            return rand.pack_labels(cs, out)
        return out

    return jax.jit(propose)
