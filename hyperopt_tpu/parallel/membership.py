"""Leased work shards: elastic generation ownership over a shared filesystem.

Parity target: the MongoWorker/SparkTrials durability role of
``hyperopt/mongoexp.py`` §L4 — N processes racing to claim work items from
one durable store — applied to the SPMD driver's *generation shards*
instead of individual trial docs.  ``fmin_multihost``'s collective path
buckets trials positionally (``j % process_count == process_index``), which
welds fleet membership to the ``jax.distributed`` runtime: one controller
lost mid-generation leaves every survivor deadlocked in
``process_allgather``.  This module moves generation ownership into
filestore-style **leases** so membership becomes elastic:

* a generation's ``B`` trials split into ``n_shards`` fixed shards —
  trial ``j`` belongs to shard ``j % n_shards``, and its id re-buckets
  deterministically from ``(seed, generation, shard)`` alone: the shard
  structure is pinned in the run params, NOT derived from the fleet size,
  so a fleet of any size (including a resumed fleet of a *different*
  size) evaluates the identical trial→shard map and folds the identical
  history (docs/DESIGN.md §15 has the re-bucketing math);
* a controller **claims** a shard by exclusive-create of a lease file
  (the ``os.rename`` atomic-claim idiom of ``filestore.reserve``, with
  ``O_EXCL`` in place of rename because there is no source doc to move);
* the claim is **heartbeated** by mtime while the shard evaluates, and a
  lease older than ``lease_ttl`` with no published result is **reclaimed**
  by any survivor (rename-to-private-name first, so two reclaimers cannot
  double-free — the same claim-the-claim discipline as
  ``filestore._sweep_orphan_claims``);
* the shard's **result** is published by one atomic write; result
  presence is the terminal state.  Because proposals and evaluation are
  deterministic, a lost-lease double evaluation publishes byte-identical
  blobs — at-least-once execution composes with last-write-wins into
  exactly-once *semantics*, no fencing needed.

Layout under a store root (the store's ``attachments/`` also collects
flight dumps from every controller via ``FileStore.arm_flight``, so a
killed controller's last moments stay readable through
``FileStore.read_flight_dumps()``)::

    <root>/fleet/
      params.json            run params, write-once (joiners verify equality)
      members/<owner>        membership heartbeat files (mtime = liveness)
      gen00000/
        shard3.lease         exclusive-create claim; mtime heartbeat
        shard3.result.pkl    published result rows (atomic write, terminal)
        checksum.<owner>     per-controller fold digest (divergence audit)

Clocks: lease/member aging uses file **mtime** (wall clock — the only
clock processes on a shared filesystem share; same tradeoff as
``filestore`` heartbeats), while every in-process wait uses monotonic
deadlines.  Fake-clock tests age leases with ``os.utime``.
"""

from __future__ import annotations

import json
import logging
import os
import time

from ..filestore import FileStore, _atomic_write, _claim_suffix
from ..obs import get_metrics

__all__ = ["FleetMembership", "EpochLeases", "shard_trials",
           "n_occupied_shards", "publish_params_once", "rotate_for_owner"]

logger = logging.getLogger(__name__)

_FLEET_DIR = "fleet"
_MEMBERS_DIR = "members"
_LEASE_SUFFIX = ".lease"
_RESULT_SUFFIX = ".result.pkl"


def shard_trials(B, n_shards, shard):
    """Global batch positions owned by ``shard`` in a ``B``-trial
    generation: ``{j : j % n_shards == shard}``.  The trial id of position
    ``j`` in generation ``g`` is ``g * batch + j`` — both maps depend only
    on pinned run params, never on fleet size (the re-bucketing
    invariant)."""
    return [j for j in range(int(B)) if j % int(n_shards) == int(shard)]


def n_occupied_shards(B, n_shards):
    """How many shards of a ``B``-trial generation are non-empty (a short
    final generation occupies only the first ``B`` shards)."""
    return min(int(n_shards), int(B))


def _safe(owner):
    return str(owner).replace(":", "-").replace(os.sep, "-")


def publish_params_once(path, params, what="store"):
    """Write-once params file: the first caller publishes ``params`` at
    ``path`` atomically-exclusively, every later caller verifies
    equality.  Atomic-exclusive publish: write a private tmp
    COMPLETELY, then ``os.link`` it into place — exactly one linker
    wins, and a loser (or any concurrent joiner) can only ever read a
    fully-written file.  A bare O_EXCL-create-then-write would let a
    simultaneous joiner read the empty/partial file and die on a false
    params mismatch.  Returns True for the first writer, False for a
    verified joiner; raises ValueError on a mismatch (params are
    write-once — every joiner must present identical params)."""
    blob = json.dumps(params, sort_keys=True, default=str)
    tmp = f"{path}.tmp.{_claim_suffix()}"
    with open(tmp, "w") as f:
        f.write(blob)
    try:
        os.link(tmp, path)
        return True
    except FileExistsError:
        with open(path) as f:
            existing = f.read()
        if existing != blob:
            raise ValueError(
                f"{what} was created with params {existing}; this "
                f"process has {blob} — every joiner must present "
                "identical params")
        return False
    finally:
        try:
            os.remove(tmp)
        except FileNotFoundError:
            pass


def rotate_for_owner(items, owner):
    """Deterministic per-owner rotation of ``items`` so concurrent
    claimers start at different offsets (less contention) while any
    single survivor still visits every item.  Stable across processes
    for one owner; NOT Python ``hash()`` (salted)."""
    items = list(items)
    if not items:
        return items
    h = sum(ord(c) for c in str(owner)) % len(items)
    return items[h:] + items[:h]


class FleetMembership:
    """One controller's handle on the lease plane of a fleet store."""

    def __init__(self, root, owner=None, lease_ttl=15.0, metrics=None,
                 member_ttl=None):
        self.store = FileStore(root)
        self.owner = owner or f"{os.uname().nodename}:{os.getpid()}"
        self.lease_ttl = float(lease_ttl)
        self.member_ttl = float(member_ttl if member_ttl is not None
                                else max(3 * self.lease_ttl, self.lease_ttl))
        self.metrics = metrics if metrics is not None else get_metrics("fleet")
        self._fleet = os.path.join(self.store.root, _FLEET_DIR)
        os.makedirs(os.path.join(self._fleet, _MEMBERS_DIR), exist_ok=True)
        self._held = set()  # (gen, shard) leases this member currently holds

    # -- run params (write-once, joiners verify) --------------------------

    def ensure_params(self, params):
        """First member writes ``params.json``; every later (or resumed,
        possibly differently-sized) fleet must present IDENTICAL params —
        the lease plane's analog of the checkpoint run-params check, and
        the guard behind bitwise replay at any fleet size."""
        return publish_params_once(
            os.path.join(self._fleet, "params.json"), params,
            what=f"fleet store {self.store.root}")

    # -- membership records (observability; liveness by mtime) ------------

    def _member_path(self, owner=None):
        return os.path.join(self._fleet, _MEMBERS_DIR,
                            _safe(owner or self.owner))

    def join(self):
        """Register this controller (and arm its flight recorder into the
        store, so a chaos kill leaves forensics behind)."""
        _atomic_write(self._member_path(),
                      json.dumps({"owner": self.owner,
                                  "joined": time.time()}).encode())
        self.store.arm_flight(self.owner)
        self.metrics.counter("fleet.joins").inc()
        self.metrics.gauge("fleet.members").set(len(self.live_members()))

    def heartbeat_member(self):
        try:
            os.utime(self._member_path(), None)
        except FileNotFoundError:  # swept or never joined: re-join
            _atomic_write(self._member_path(),
                          json.dumps({"owner": self.owner,
                                      "joined": time.time()}).encode())

    def leave(self):
        try:
            os.remove(self._member_path())
        except FileNotFoundError:
            pass
        self.metrics.gauge("fleet.members").set(len(self.live_members()))

    def live_members(self):
        """Owners whose member record heartbeated within ``member_ttl``
        (a dead controller simply ages out — leaving is optional)."""
        d = os.path.join(self._fleet, _MEMBERS_DIR)
        now = time.time()
        out = []
        for fname in sorted(os.listdir(d)):
            try:
                age = now - os.path.getmtime(os.path.join(d, fname))
            except FileNotFoundError:
                continue
            if age <= self.member_ttl:
                out.append(fname)
        return out

    # -- shard leases ------------------------------------------------------

    def _gen_dir(self, gen):
        path = os.path.join(self._fleet, f"gen{int(gen):05d}")
        os.makedirs(path, exist_ok=True)
        return path

    def _lease_path(self, gen, shard):
        return os.path.join(self._gen_dir(gen),
                            f"shard{int(shard)}{_LEASE_SUFFIX}")

    def _result_path(self, gen, shard):
        return os.path.join(self._gen_dir(gen),
                            f"shard{int(shard)}{_RESULT_SUFFIX}")

    def try_claim(self, gen, shard):
        """Atomically claim one shard: ``O_CREAT|O_EXCL`` — exactly one
        creator wins (the ``reserve`` rename analog).  A shard whose
        result already exists is never claimed."""
        if os.path.exists(self._result_path(gen, shard)):
            return False
        path = self._lease_path(gen, shard)
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            self.metrics.counter("lease.contention").inc()
            return False
        with os.fdopen(fd, "w") as f:
            f.write(f"{self.owner}\n{time.time()}\n")
        self._held.add((int(gen), int(shard)))
        self.metrics.counter("lease.claims").inc()
        return True

    def heartbeat_shard(self, gen, shard):
        """Refresh a held lease's mtime (called between trial evaluations;
        a lease older than ``lease_ttl`` is fair game for reclaim).  The
        touch is best-effort: a reclaimed-from-under-us lease means a
        survivor took over — our eventual publish is byte-identical."""
        try:
            os.utime(self._lease_path(gen, shard), None)
            self.metrics.counter("lease.heartbeats").inc()
        except FileNotFoundError:
            pass

    def lease_mtimes(self, gen, shards):
        """Current lease mtime per shard (None when unleased) — the fleet
        barrier's liveness signal: an advancing mtime means a live holder
        is heartbeating through a long evaluation and the barrier must
        keep waiting rather than degrade."""
        out = []
        for s in shards:
            try:
                out.append(os.path.getmtime(self._lease_path(gen, s)))
            except FileNotFoundError:
                out.append(None)
        return out

    def release(self, gen, shard):
        self._held.discard((int(gen), int(shard)))
        try:
            os.remove(self._lease_path(gen, shard))
        except FileNotFoundError:
            pass

    def reclaim_stale(self, gen, n_shards):
        """Free leases older than ``lease_ttl`` whose shard has no result
        (the holder died mid-evaluation, or stalled past the TTL — either
        way a survivor may re-run the shard; determinism makes the re-run
        idempotent).  Claim-the-claim first: rename to a private name so
        two concurrent reclaimers cannot both free one lease.  Returns the
        number of shards freed."""
        n = 0
        now = time.time()
        for shard in range(int(n_shards)):
            path = self._lease_path(gen, shard)
            if os.path.exists(self._result_path(gen, shard)):
                # published: the lease (if any) is a leftover, not a claim
                if os.path.exists(path):
                    self.release(gen, shard)
                continue
            try:
                age = now - os.path.getmtime(path)
            except FileNotFoundError:
                continue
            if age < self.lease_ttl:
                continue
            mine = f"{path}.reclaim.{_claim_suffix()}"
            try:
                os.rename(path, mine)
            except FileNotFoundError:
                continue  # another reclaimer (or the holder's release) won
            try:
                with open(mine) as f:
                    dead_owner = f.readline().strip()
            except OSError:
                dead_owner = "?"
            os.remove(mine)
            n += 1
            self.metrics.counter("lease.reclaims").inc()
            logger.warning(
                "reclaimed stale shard lease gen=%s shard=%s (holder %s, "
                "%.1fs old)", gen, shard, dead_owner, age)
            self.store.events.emit(
                "shard_reclaimed", f"g{gen}s{shard}", gen=int(gen),
                shard=int(shard), holder=dead_owner, age_sec=age)
        return n

    # -- shard results (terminal state) ------------------------------------

    def publish(self, gen, shard, blob):
        """Atomically publish a shard's result and drop the lease.  Safe
        under duplicate evaluation: deterministic evaluation ⇒ identical
        ``blob`` ⇒ last-write-wins is a no-op."""
        _atomic_write(self._result_path(gen, shard), blob)
        self.metrics.counter("shard.published").inc()
        self.release(gen, shard)

    def read_result(self, gen, shard):
        path = self._result_path(gen, shard)
        try:
            with open(path, "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def missing_shards(self, gen, n_shards):
        """Occupied shards of ``gen`` that have no published result yet."""
        gen_dir = self._gen_dir(gen)
        have = {fname for fname in os.listdir(gen_dir)
                if fname.endswith(_RESULT_SUFFIX)}
        return [s for s in range(int(n_shards))
                if f"shard{s}{_RESULT_SUFFIX}" not in have]

    def claim_order(self, shards):
        """Deterministic per-owner rotation of ``shards`` so a fleet's
        members start claiming at different offsets (less contention)
        while any single survivor still visits every shard."""
        return rotate_for_owner(shards, self.owner)

    # -- divergence audit --------------------------------------------------

    def write_checksum(self, gen, digest_hex):
        _atomic_write(os.path.join(self._gen_dir(gen),
                                   f"checksum.{_safe(self.owner)}"),
                      str(digest_hex).encode())

    def read_checksums(self, gen):
        """{owner: digest} for every controller that folded ``gen``."""
        d = self._gen_dir(gen)
        out = {}
        for fname in sorted(os.listdir(d)):
            if not fname.startswith("checksum."):
                continue
            try:
                with open(os.path.join(d, fname)) as f:
                    out[fname[len("checksum."):]] = f.read().strip()
            except OSError:
                continue
        return out


# ---------------------------------------------------------------------------
# long-lived epoch leases (ISSUE 12)
# ---------------------------------------------------------------------------


class EpochLeases:
    """Long-lived, epoch-fenced leases over one directory — the
    generalization of the per-generation shard lease above for ownership
    that OUTLIVES any single unit of work (the serving fleet's
    study-shard keyspace).  Three differences from the ``gen/shard``
    lease:

    * **no terminal state** — there is no ``result.pkl`` that retires a
      lease; ownership ends only by explicit :meth:`release` or by
      stale :meth:`reclaim`;
    * **a durable per-name epoch counter** — every successful claim
      bumps ``<name>.epoch`` (atomically, under the just-won ``O_EXCL``
      exclusivity, so bumps never race) and the claim returns the new
      epoch.  The epoch is the fencing token downstream state is named
      by: the serving fleet writes one WAL file per (shard, epoch), so
      a reclaimed-from-under-us holder's late appends land in a file no
      replay will ever read — journals never interleave;
    * **owner-verified mutation** — :meth:`heartbeat` and
      :meth:`release` verify the lease body still names THIS owner and
      epoch before touching the file, so a holder that lost its lease
      to reclaim can never refresh (or free) the new holder's claim.

    The claim/reclaim discipline itself is unchanged: ``O_CREAT|O_EXCL``
    claim (exactly one creator wins), mtime heartbeat, rename-first
    stale reclaim (claim-the-claim — two reclaimers cannot double-free).
    Clocks follow the module convention: aging uses file mtime (the
    only clock a shared filesystem gives every process), fake-clock
    tests age leases with ``os.utime``.
    """

    def __init__(self, root, owner, lease_ttl=15.0, metrics=None):
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)
        self.owner = str(owner)
        self.lease_ttl = float(lease_ttl)
        self.metrics = metrics if metrics is not None else get_metrics("fleet")
        self.held = {}  # name -> epoch this owner currently holds

    def _lease_path(self, name):
        return os.path.join(self.root, f"{name}{_LEASE_SUFFIX}")

    def _epoch_path(self, name):
        return os.path.join(self.root, f"{name}.epoch")

    def read_epoch(self, name):
        """The last epoch ever claimed for ``name`` (0 = never)."""
        try:
            with open(self._epoch_path(name)) as f:
                return int(f.read().strip() or 0)
        except (OSError, ValueError):
            return 0

    def holder(self, name):
        """The lease body ``{owner, epoch, ts}`` of ``name``'s current
        claim, or None (unleased / torn mid-claim)."""
        try:
            with open(self._lease_path(name)) as f:
                rec = json.loads(f.read())
            return rec if isinstance(rec, dict) else None
        except (OSError, ValueError):
            return None

    def try_claim(self, name):
        """Atomically claim ``name``: exactly one ``O_EXCL`` creator
        wins and gets the bumped epoch back (None = lost the race).
        The epoch bump is serialized BY the claim itself — nobody else
        can win the O_EXCL while this lease file exists, and reclaim
        renames it away before the next claim — so epochs are strictly
        monotonic per name across any claim/crash/reclaim history."""
        path = self._lease_path(name)
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            self.metrics.counter("lease.contention").inc()
            return None
        epoch = self.read_epoch(name) + 1
        _atomic_write(self._epoch_path(name), str(epoch).encode())
        with os.fdopen(fd, "w") as f:
            f.write(json.dumps({"owner": self.owner, "epoch": epoch,
                                "ts": time.time()}))
        self.held[name] = epoch
        self.metrics.counter("lease.claims").inc()
        return epoch

    def verify_held(self, name):
        """True while the on-disk lease still names this owner at the
        epoch it claimed.  False means the lease was reclaimed (or
        released) from under us — the caller must stop serving the
        name; its epoch-named state is already fenced off."""
        want = self.held.get(name)
        if want is None:
            return False
        rec = self.holder(name)
        if (rec is None or rec.get("owner") != self.owner
                or rec.get("epoch") != want):
            self.held.pop(name, None)
            return False
        return True

    def heartbeat(self, name):
        """Refresh a held lease's mtime; returns False (and forgets the
        hold) when the lease was reclaimed from under us — unlike the
        gen/shard lease, a long-lived holder MUST notice, because it is
        still serving."""
        if not self.verify_held(name):
            return False
        try:
            os.utime(self._lease_path(name), None)
            self.metrics.counter("lease.heartbeats").inc()
        except FileNotFoundError:
            self.held.pop(name, None)
            return False
        return True

    def release(self, name):
        """Drop a held lease (the graceful-drain path).  Owner-verified:
        releasing a lease someone else re-claimed would free THEIR
        ownership.  The verify-then-remove pair is not atomic — a holder
        stalled PAST the TTL could, in the instant between the two,
        lose a reclaim race and delete the next claimant's file; the
        epoch fence self-heals it (the claimant's next verification
        fails and the shard re-adopts one epoch later), and making it
        atomic would need a cross-process lock on every lease op."""
        if not self.verify_held(name):
            return False
        self.held.pop(name, None)
        try:
            os.remove(self._lease_path(name))
        except FileNotFoundError:
            pass
        return True

    def reclaim(self, names):
        """Free leases older than ``lease_ttl`` (holder stopped
        heartbeating: dead, or stalled past the TTL — its epoch fences
        its late writes either way).  Rename-first, exactly as
        :meth:`FleetMembership.reclaim_stale`: two concurrent reclaimers
        free each lease at most once.  Returns the freed names — the
        caller claims them (bumping the epoch) before adopting any
        state."""
        freed = []
        now = time.time()
        for name in names:
            path = self._lease_path(name)
            try:
                age = now - os.path.getmtime(path)
            except FileNotFoundError:
                continue
            if age < self.lease_ttl:
                continue
            mine = f"{path}.reclaim.{_claim_suffix()}"
            try:
                os.rename(path, mine)
            except FileNotFoundError:
                continue  # another reclaimer (or the holder) won
            try:
                with open(mine) as f:
                    dead = (json.loads(f.read() or "{}") or {}).get(
                        "owner", "?")
            except (OSError, ValueError):
                dead = "?"
            os.remove(mine)
            freed.append(name)
            self.metrics.counter("lease.reclaims").inc()
            logger.warning("reclaimed stale epoch lease %s (holder %s, "
                           "%.1fs old)", name, dead, age)
        return freed

    def unleased(self, names):
        """The subset of ``names`` with no live lease file (claimable)."""
        return [n for n in names
                if not os.path.exists(self._lease_path(n))]
