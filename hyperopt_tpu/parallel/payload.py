"""Lean wire formats for the multi-controller per-generation exchange.

Each generation, every controller evaluates its round-robin shard of the
proposal batch and must ship the results — raw losses plus the per-label
active masks of conditional params — to every other controller
(``driver.fmin_multihost``).  The naive encoding is float32 rows
``[vals... are already known globally, so: active(L) as 0/1 floats, loss,
evaluated flag]`` = ``4 * (L + 2)`` bytes per trial.  The lean encoding
packs the ``L + 1`` boolean flags into a uint8 bitfield and keeps the loss
as its own narrow f32 column:

* ``f32`` rows: ``[W, L + 2]`` float32 — ``4L + 8`` bytes/trial
* ``u8`` rows:  4 loss bytes + ``ceil((L+1)/8)`` mask bytes/trial

For an 8-label space that is 40 → 6 bytes per trial (>6x; ≥2x for any L).
Collective payloads over DCN are latency-dominated at these sizes, but the
format also bounds memory on thousand-wide generations and the fold is
pinned bit-identical between the two encodings
(tests/test_pipeline.py::test_payload_fold_bitwise), so the lean form is
the default.  ``HYPEROPT_TPU_PAYLOAD=f32`` selects the wide debug rows
(same homogeneous-endianness assumption — both formats byte-view f32).

Both forms serialize to ONE uint8 buffer per controller (``to_wire``) so a
generation costs a single allgather whatever the format.  Byte views of
f32 assume a homogeneous (little-endian in practice) controller fleet —
the same assumption ``jax.distributed`` itself makes about array bytes.

The ``evaluated`` flag marks real rows: controller shards pad to a common
width so allgather shapes agree, and padding rows must never fold (their
loss bytes are arbitrary).
"""

from __future__ import annotations

import os

import numpy as np

__all__ = [
    "wire_format",
    "mask_nbytes",
    "row_nbytes",
    "to_wire",
    "from_wire",
    "fold_generation",
]


def wire_format(env=None):
    """``"u8"`` (default, lean) or ``"f32"`` (wide debug rows), from
    ``HYPEROPT_TPU_PAYLOAD``."""
    env = os.environ if env is None else env
    fmt = env.get("HYPEROPT_TPU_PAYLOAD", "u8").strip().lower() or "u8"
    if fmt not in ("u8", "f32"):
        raise ValueError(
            f"HYPEROPT_TPU_PAYLOAD must be 'u8' or 'f32', got {fmt!r}")
    return fmt


def mask_nbytes(L):
    """Bitfield bytes per trial: L active bits + 1 evaluated bit."""
    return (L + 1 + 7) // 8


def row_nbytes(L, fmt="u8"):
    """Wire bytes per trial row."""
    if fmt == "f32":
        return 4 * (L + 2)
    return 4 + mask_nbytes(L)


def to_wire(losses, active, evaluated, fmt="u8"):
    """Encode one controller's padded result shard as a ``uint8 [W,
    row_nbytes]`` buffer (ONE collective per generation).

    ``losses``: f32 [W] raw losses (NaN = failed trial; arbitrary on
    padding rows); ``active``: bool [W, L]; ``evaluated``: bool [W] —
    False marks padding rows appended to equalize shard widths.
    """
    losses = np.ascontiguousarray(losses, np.float32)
    active = np.asarray(active, bool)
    evaluated = np.asarray(evaluated, bool)
    W, L = active.shape
    if fmt == "f32":
        rows = np.empty((W, L + 2), np.float32)
        rows[:, :L] = active  # 0/1 floats — the wide legacy encoding
        rows[:, L] = losses
        rows[:, L + 1] = evaluated
        return np.ascontiguousarray(rows).view(np.uint8).reshape(
            W, 4 * (L + 2))
    bits = np.zeros((W, L + 1), bool)
    bits[:, :L] = active
    bits[:, L] = evaluated
    out = np.empty((W, row_nbytes(L, "u8")), np.uint8)
    out[:, :4] = losses.view(np.uint8).reshape(W, 4)
    out[:, 4:] = np.packbits(bits, axis=1)
    return out


def from_wire(buf, L, fmt="u8"):
    """Invert :func:`to_wire`: ``(losses f32 [W], active bool [W, L],
    evaluated bool [W])``.  Loss bytes round-trip exactly (bit pattern,
    incl. NaN payloads — the fold digest depends on it)."""
    buf = np.ascontiguousarray(buf, np.uint8)
    W = buf.shape[0]
    if fmt == "f32":
        rows = buf.reshape(W, -1).view(np.float32).reshape(W, L + 2)
        return (rows[:, L].copy(), rows[:, :L] != 0.0, rows[:, L + 1] != 0.0)
    losses = buf[:, :4].copy().view(np.float32).reshape(W)
    bits = np.unpackbits(buf[:, 4:], axis=1, count=L + 1).astype(bool)
    return losses, bits[:, :L], bits[:, L]


def fold_generation(hist, raw_losses, start, labels, flats, losses,
                    active_rows):
    """Fold one generation's results into the padded numpy history, global
    trial-id order — the ONE fold both wire formats (and the single-process
    path) share, so "bitwise-identical fold" is true by construction and
    pinned by test on top.

    ``hist``: the driver's padded SoA dict; ``raw_losses``: the raw
    as-evaluated loss array (digest replay source); ``flats``: ``{label:
    f32 [B]}`` packed proposals (globally known); ``losses``: f32 [B] raw;
    ``active_rows``: bool [B, L] in ``labels`` order.
    """
    B = len(losses)
    end = start + B
    ok = np.isfinite(losses)
    hist["losses"][start:end] = np.where(ok, losses, np.inf).astype(np.float32)
    hist["has_loss"][start:end] = ok
    raw_losses[start:end] = losses
    for j, l in enumerate(labels):
        hist["vals"][l][start:end] = np.asarray(flats[l], np.float32)
        hist["active"][l][start:end] = active_rows[:, j]
