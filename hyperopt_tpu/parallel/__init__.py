"""Parallel / distributed execution: mesh sharding and async trial evaluation.

The reference's parallelism is embarrassingly-parallel trial evaluation over
MongoDB workers or Spark executors (``hyperopt/mongoexp.py`` sym: MongoTrials,
``hyperopt/spark.py`` sym: SparkTrials).  The TPU-native equivalents
(SURVEY.md §2.2):

* ``sharding`` — the two scaling axes of HPO, sharded over a
  ``jax.sharding.Mesh``: the **trial batch** (data-parallel ``vmap`` over new
  ids, one shard per device) and the **candidate axis** (``shard_map`` over
  ``n_EI_candidates`` with an all-gather EI argmax — the sequence-parallel
  analog).
* ``executor`` — host-side async trial evaluation behind the reference's
  ``Trials.asynchronous`` protocol (``ExecutorTrials``: worker pool for
  arbitrary objectives, one vmapped device call per queue for traceable
  ones).
* ``multihost`` — the ``jax.distributed`` wiring (global mesh, replication,
  deterministic global key batches).
* ``driver`` — the end-to-end SPMD multi-controller ``fmin_multihost``:
  global proposals, per-controller evaluation shards, deterministic folds,
  divergence checksum (the MongoTrials.fmin + MongoWorker analog).
* ``membership`` / ``fleet`` — the elastic, preemption-native form of the
  same driver (``fmin_multihost(fleet_dir=...)``): generation ownership as
  filestore shard LEASES, controllers joining/leaving freely, survivors
  reclaiming dead controllers' shards, and bitwise replay at any fleet
  size (ISSUE 8 / ROADMAP item 4 — the reliability half of production
  scale).
"""

from . import executor, sharding  # noqa: F401
from .executor import ExecutorTrials  # noqa: F401
from .driver import fmin_multihost, MultihostResult, ControllerDivergence  # noqa: F401
from .membership import FleetMembership, shard_trials  # noqa: F401
