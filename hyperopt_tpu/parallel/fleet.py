"""Elastic, preemption-native fleet driver: ``fmin_multihost`` over leased
work shards instead of ``jax.distributed`` collectives.

The collective driver (``driver.fmin_multihost``) is bitwise-deterministic
but membership-static: its result exchange is ``process_allgather``, so a
controller lost mid-generation leaves every survivor blocked in a
collective that will never complete.  This module runs the SAME algorithm —
same proposals, same fold order, same digest, bitwise-identical history —
with the exchange moved onto the filestore lease plane
(:mod:`~hyperopt_tpu.parallel.membership`):

* every controller computes the full generation's proposals locally
  (deterministic in ``(seed, generation, history)`` — replicated compute
  buys zero coordination);
* evaluation ownership is **leased per shard** (``j % n_shards``); a
  controller claims, heartbeats, evaluates and publishes shard results as
  atomic blobs in the store;
* a survivor **reclaims** a dead controller's stale lease and re-runs the
  shard — determinism makes the duplicate publish byte-identical, so
  at-least-once execution folds into an exactly-once history;
* the generation barrier is "every occupied shard has a published
  result", which any fleet size (including ONE survivor) can satisfy —
  controllers may join or leave at any point, not just between
  generations, because mid-generation state lives in the store, not in a
  collective schedule;
* the store doubles as the checkpoint: a controller (re)starting on a
  populated store replays completed generations by reading published
  shard blobs instead of evaluating, so a resumed fleet of a *different*
  size reaches a bitwise-identical history (``run_params`` — including
  ``n_shards`` — are pinned write-once in the store and verified by every
  joiner).

The divergence checksum survives the redesign: each controller publishes
its cumulative fold digest per generation (``checksum.<owner>``) and
cross-checks every other controller's — a mismatch raises
:class:`~hyperopt_tpu.parallel.driver.ControllerDivergence` exactly as the
allgathered digest does in collective mode.

Chaos sites (``hyperopt_tpu.chaos``): ``gen`` at each generation start,
``claim`` before each lease claim, ``trial`` before each objective call,
``publish`` before each shard publish, ``checkpoint`` before each
checkpoint write.  Disarmed, every site is one attribute check.
"""

from __future__ import annotations

import hashlib
import pickle
import time

import numpy as np

import jax
import jax.numpy as jnp

from .. import chaos
from ..exceptions import AllTrialsFailed, FleetDegraded
from ..obs import ObsConfig, RunObs
from ..spaces import compile_space
from ..algos import tpe
from . import payload as payload_mod
from .membership import FleetMembership, n_occupied_shards, shard_trials

__all__ = ["fleet_fmin"]


def fleet_fmin(fn, space, max_evals, fleet_dir, batch=None, seed=0, cfg=None,
               n_startup=None, n_shards=None, lease_ttl=15.0,
               checkpoint_file=None, obs=None, owner=None,
               poll_interval=0.05, barrier_timeout=600.0):
    """Minimize ``fn`` over ``space`` as one controller of an elastic
    fleet rooted at ``fleet_dir``.  Run any number of these concurrently
    (separate plain processes — no ``jax.distributed`` runtime required);
    each returns the same :class:`~.driver.MultihostResult`, bitwise
    identical to ``fmin_multihost(..., _force_single=True)`` at the same
    ``(seed, batch, cfg)``.

    ``n_shards`` fixes the generation's work-shard count (default
    ``min(batch, 8)``) and is pinned in the store's ``params.json`` — the
    re-bucketing invariant that lets a resumed fleet of a different size
    replay bitwise.  ``lease_ttl`` is the heartbeat staleness bound after
    which survivors reclaim a dead controller's shard.
    ``barrier_timeout`` (monotonic deadline) bounds the wait for a
    generation to complete; on expiry the controller checkpoints what is
    verified and raises :class:`FleetDegraded` instead of hanging.
    """
    from .driver import (ControllerDivergence, MultihostResult, _default_cfg,
                         _digest_generation, _gen_seed)
    from .._env import (enable_persistent_compilation_cache, parse_hist_dtype)

    if not isinstance(obs, RunObs):
        obs = RunObs(ObsConfig.resolve(obs))

    cs = compile_space(space)
    labels = cs.labels
    if batch is None:
        batch = len(jax.devices())
    cfg = dict(cfg or {})
    enable_persistent_compilation_cache(cfg.pop("compile_cache", None))
    cfg = dict(_default_cfg(batch), **cfg)
    if n_startup is None:
        n_startup = max(batch, 20)
    if n_shards is None:
        n_shards = max(1, min(int(batch), 8))
    n_shards = int(n_shards)

    run_params = {"labels": list(labels), "batch": int(batch),
                  "seed": int(seed), "n_startup": int(n_startup),
                  "cfg": sorted(cfg.items()), "n_shards": n_shards}

    member = FleetMembership(fleet_dir, owner=owner, lease_ttl=lease_ttl,
                             metrics=obs.metrics)
    member.ensure_params(run_params)
    member.join()
    obs.event("fleet_controller", owner=member.owner, n_shards=n_shards,
              lease_ttl=lease_ttl)

    saved = None
    if checkpoint_file is not None:
        import os

        if os.path.exists(checkpoint_file):
            # trust boundary: same pickle-trust warning as the collective
            # driver's checkpoint_file (docs/DESIGN.md "Observability &
            # trust") — the fleet store adds params.json verification on
            # top, but the snapshot itself is a pickle
            t0 = time.perf_counter()
            with open(checkpoint_file, "rb") as f:
                saved = pickle.load(f)
            obs.histogram("checkpoint.load_sec").observe(
                time.perf_counter() - t0)
        if saved is not None:
            for k, v in run_params.items():
                if saved["run_params"].get(k) != v:
                    raise ValueError(
                        f"checkpoint {checkpoint_file} was written with "
                        f"{k}={saved['run_params'].get(k)!r}; this run has "
                        f"{k}={v!r} — bitwise resume requires identical "
                        "run parameters")
            if saved["n_done"] % batch and saved["n_done"] < max_evals:
                raise ValueError(
                    f"checkpoint ends in a partial final generation "
                    f"(n_done={saved['n_done']}, batch={batch}): a completed "
                    "run cannot be extended bitwise — delete the checkpoint "
                    "to start a fresh run")

    cap = 128
    while cap < max(max_evals, saved["n_done"] if saved else 0):
        cap *= 2
    hist = {
        "losses": np.full(cap, np.inf, np.float32),
        "has_loss": np.zeros(cap, bool),
        "vals": {l: np.zeros(cap, np.float32) for l in labels},
        "active": {l: np.zeros(cap, bool) for l in labels},
    }
    raw_losses = np.full(cap, np.nan, np.float32)

    propose_fn = jax.jit(jax.vmap(tpe.build_propose(cs, cfg),
                                  in_axes=(None, 0)))
    sample_fn = jax.jit(jax.vmap(cs.sample_flat))
    # int8/fp8 degrade to bf16 here: this path compresses by plain astype
    # (no affine-code read boundary is wired into the fleet kernels)
    from .. import quant

    hist_dt = quant.mirror_float_dtype(parse_hist_dtype())

    def device_history():
        # full upload per generation, compressed to the storage dtype the
        # same way the collective single path does (bitwise parity): the
        # fleet path optimizes survivability, not HBM traffic
        return jax.tree.map(
            lambda x: (jnp.asarray(x).astype(hist_dt)
                       if np.issubdtype(np.asarray(x).dtype, np.floating)
                       else jnp.asarray(x)), hist)

    def local_keys(gseed):
        return jax.vmap(
            lambda i: jax.random.fold_in(jax.random.PRNGKey(gseed), i)
        )(jnp.arange(batch, dtype=jnp.uint32))

    digest = hashlib.sha256()
    n_done = 0
    gen = 0
    if saved is not None:
        n_done = saved["n_done"]
        gen = n_done // batch
        hist["losses"][:n_done] = saved["losses"]
        hist["has_loss"][:n_done] = saved["has_loss"]
        raw_losses[:n_done] = saved["raw_losses"]
        for l in labels:
            hist["vals"][l][:n_done] = saved["vals"][l]
            hist["active"][l][:n_done] = saved["active"][l]
        if n_done:
            rows = np.concatenate(
                [np.asarray(saved["raw_losses"], np.float32)[:, None]]
                + [np.asarray(saved["vals"][l], np.float32)[:, None]
                   for l in labels], axis=1)
            digest.update(np.ascontiguousarray(rows, np.float32).tobytes())

    def _save_checkpoint():
        """Atomic generation-boundary snapshot.  Unlike the collective
        driver there is no distinguished controller 0 — membership is
        elastic — so EVERY controller writes; the bytes are identical by
        the divergence guarantee, so last-write-wins is a no-op."""
        if checkpoint_file is None:
            return
        from ..filestore import _atomic_write

        chaos.point("checkpoint", metrics=obs.metrics)
        state = {
            "run_params": run_params,
            "n_done": n_done,
            "losses": hist["losses"][:n_done].copy(),
            "has_loss": hist["has_loss"][:n_done].copy(),
            "raw_losses": raw_losses[:n_done].copy(),
            "vals": {l: hist["vals"][l][:n_done].copy() for l in labels},
            "active": {l: hist["active"][l][:n_done].copy() for l in labels},
        }
        t0 = time.perf_counter()
        _atomic_write(checkpoint_file, pickle.dumps(state))
        obs.histogram("checkpoint.save_sec").observe(
            time.perf_counter() - t0)

    L_n = len(labels)

    def flat_j(flats, j):
        return {
            l: (int(round(float(flats[l][j]))) if cs.params[l].is_int
                else float(flats[l][j]))
            for l in labels
        }

    def evaluate_shard(flats, gen, shard, js):
        """Evaluate one claimed shard, heartbeating the lease between
        trials (a single trial longer than the TTL may be reclaimed and
        re-run elsewhere — the duplicate publish is byte-identical)."""
        losses_s = np.full(len(js), np.nan, np.float32)
        active_s = np.zeros((len(js), L_n), bool)
        for k, j in enumerate(js):
            chaos.point("trial", metrics=obs.metrics)
            flat = flat_j(flats, j)
            act = cs.active_flat(flat)
            active_s[k] = [bool(act[l]) for l in labels]
            try:
                losses_s[k] = float(fn(cs.assemble(flat)))
            except Exception:
                losses_s[k] = np.nan
                obs.counter("trials.failed").inc()
            member.heartbeat_shard(gen, shard)
        return losses_s, active_s

    while n_done < max_evals:
        obs.heartbeat("driver.gen", gen=gen, n_done=n_done,
                      owner=member.owner)
        obs.devmem_sample()
        chaos.point("gen", metrics=obs.metrics)
        member.heartbeat_member()
        B = min(batch, max_evals - n_done)
        S_gen = n_occupied_shards(B, n_shards)
        gseed = _gen_seed(seed, gen)
        with obs.annotate("driver.gen", step=gen, gen=gen, n_done=n_done), \
                obs.span("propose", gen=gen):
            if n_done < n_startup:
                out = sample_fn(local_keys(gseed))
            else:
                out = propose_fn(device_history(), local_keys(gseed))
            flats = {l: np.asarray(out[l]) for l in labels}

        # evaluate-or-adopt until every occupied shard has a result: claim
        # missing shards, reclaim stale leases, poll — bounded by a
        # MONOTONIC deadline (NTP steps must not shrink the barrier).
        # The deadline measures LIVENESS, not generation wall time: it
        # re-arms whenever the barrier observes progress — a shard
        # publishing, a reclaim, or a missing shard's lease mtime
        # advancing (a live holder heartbeating through a long objective).
        # A fleet evaluating 10-minute trials must never degrade while
        # someone is visibly working; only a barrier where NOTHING moves
        # for barrier_timeout seconds (a stalled-but-never-stale holder,
        # or external store mutation) is declared degraded.
        deadline = time.monotonic() + barrier_timeout
        barrier_view = None
        with obs.span("evaluate", gen=gen):
            while True:
                missing = member.missing_shards(gen, S_gen)
                if not missing:
                    break
                progressed = False
                for s in member.claim_order(missing):
                    chaos.point("claim", metrics=obs.metrics)
                    if not member.try_claim(gen, s):
                        continue
                    progressed = True
                    js = shard_trials(B, n_shards, s)
                    losses_s, active_s = evaluate_shard(flats, gen, s, js)
                    blob = pickle.dumps(
                        {"shard": int(s), "js": js, "losses": losses_s,
                         "active": active_s}, protocol=4)
                    chaos.point("publish", metrics=obs.metrics)
                    member.publish(gen, s, blob)
                if progressed:
                    deadline = time.monotonic() + barrier_timeout
                    continue
                if member.reclaim_stale(gen, S_gen):
                    deadline = time.monotonic() + barrier_timeout
                    continue
                view = (tuple(missing),
                        tuple(member.lease_mtimes(gen, missing)))
                if view != barrier_view:
                    barrier_view = view
                    deadline = time.monotonic() + barrier_timeout
                if time.monotonic() >= deadline:
                    _save_checkpoint()
                    obs.event("fleet_barrier_timeout", gen=gen,
                              missing=list(missing))
                    raise FleetDegraded(
                        f"generation {gen} incomplete after "
                        f"{barrier_timeout:.0f}s (shards {missing} leased "
                        "but never published and never went stale); "
                        "verified history checkpointed — restart the fleet "
                        "(any size) on the same store to resume bitwise")
                member.heartbeat_member()
                time.sleep(poll_interval)

        # assemble the generation in global trial-id order from the
        # published blobs (mine and everyone else's look identical)
        losses = np.full(B, np.nan, np.float32)
        active_rows = np.zeros((B, L_n), bool)
        for s in range(S_gen):
            blob = member.read_result(gen, s)
            if blob is None:  # result swept between barrier and read?
                raise FleetDegraded(
                    f"shard result gen={gen} shard={s} vanished after the "
                    "barrier — the fleet store is being mutated externally")
            rec = pickle.loads(blob)
            js = np.asarray(rec["js"], int)
            losses[js] = rec["losses"]
            active_rows[js] = rec["active"]

        with obs.span("fold", gen=gen):
            payload_mod.fold_generation(
                hist, raw_losses, n_done, labels,
                {l: flats[l][:B] for l in labels}, losses, active_rows)
            _digest_generation(digest, labels, flats, losses, B)
        n_done += B
        gen += 1
        obs.counter("generations").inc()
        obs.counter("trials.completed").inc(B)
        done_live = hist["has_loss"][:n_done]
        if done_live.any():
            obs.gauge("best_loss").set(float(
                hist["losses"][:n_done][done_live].min()))

        # divergence audit: publish my cumulative digest, cross-check every
        # controller that folded this generation (dead controllers simply
        # never wrote one — absence is not divergence)
        my_sum = digest.hexdigest()
        member.write_checksum(gen - 1, my_sum)
        others = member.read_checksums(gen - 1)
        bad = {o: c for o, c in others.items() if c != my_sum}
        if bad:
            obs.event("controller_divergence", owner=member.owner,
                      n_done=int(n_done), gen=int(gen - 1),
                      mine=my_sum, others=bad)
            obs.counter("divergences").inc()
            raise ControllerDivergence(
                f"fleet history checksums diverged after {n_done} trials: "
                f"mine={my_sum} theirs={bad}")
        _save_checkpoint()

    live = hist["has_loss"][:n_done]
    losses_all = hist["losses"][:n_done]
    if not live.any():
        raise AllTrialsFailed(
            f"all {n_done} trials failed (objective raised on every call)")
    best_i = int(np.argmin(np.where(live, losses_all, np.inf)))
    best_flat = {
        l: (int(round(float(hist["vals"][l][best_i])))
            if cs.params[l].is_int else float(hist["vals"][l][best_i]))
        for l in labels
    }
    member.leave()
    obs.finish()
    return MultihostResult(
        best=cs.assemble(best_flat),
        best_loss=float(losses_all[best_i]),
        n_evals=n_done,
        losses=losses_all.copy(),
        vals={l: hist["vals"][l][:n_done].copy() for l in labels},
        checksum=digest.hexdigest(),
        active={l: hist["active"][l][:n_done].copy() for l in labels},
        _cs=cs,
    )
