"""Asynchronous trial evaluation — the Mongo/Spark-backend analog.

Parity targets: ``hyperopt/mongoexp.py`` (sym: MongoTrials, MongoJobs.reserve,
MongoWorker.run_one) and ``hyperopt/spark.py`` (sym: SparkTrials).  The
reference moves ``Domain.evaluate`` across a process/cluster boundary via DB
polling (Mongo) or driver→executor RPC (Spark); the single-claim guarantee is
Mongo's atomic ``find_one_and_update``.

Here the boundary is a host-side worker pool feeding the one JAX process
that owns the accelerator (single-controller model — SURVEY.md §5 "race
detection" row):

* ``ExecutorTrials`` is a ``Trials`` with ``asynchronous=True``: inserting
  NEW trials dispatches evaluation onto a ``ThreadPoolExecutor``.  Claiming
  NEW→RUNNING happens under one lock (the atomic-claim analog; a test
  asserts no double-claim).  Workers write results, flip DONE/ERROR and bump
  ``refresh_time`` (the heartbeat analog); ``fmin``'s poll loop sees state
  changes exactly as it would see Mongo state changes.
* With ``traceable=True`` the pool evaluates a whole queue of trials as ONE
  vmapped device call (``Domain.make_batch_eval``) — the TPU-native form of
  trial parallelism the reference cannot express: instead of N processes
  each computing one objective, one XLA program computes N.

The domain reaches workers the same way Mongo workers get it: a cloudpickle
blob stored by ``FMinIter`` under ``attachments['FMinIter_Domain']``
(misc.cmd = ('domain_attachment', 'FMinIter_Domain')).
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..obs import EventLog, MetricsRegistry
from ..obs.watchdog import beat as _wd_beat
from ..retry import RetryPolicy
from ..obs.events import (
    TRIAL_CANCELLED,
    TRIAL_CLAIMED,
    TRIAL_FINISHED,
    TRIAL_NEW,
)
from ..base import (
    JOB_STATE_CANCEL,
    JOB_STATE_DONE,
    JOB_STATE_ERROR,
    JOB_STATE_NEW,
    JOB_STATE_RUNNING,
    STATUS_FAIL,
    STATUS_OK,
    Ctrl,
    Trials,
    coarse_utcnow,
    spec_from_misc,
)

__all__ = ["ExecutorTrials"]

logger = logging.getLogger(__name__)

# each pool instance gets its own metrics namespace (executor-1, -2, ...) so
# two concurrent backends in one process don't mix queue gauges
_instance_ids = itertools.count(1)


class ExecutorTrials(Trials):
    """Trials whose evaluation runs on a worker pool (asynchronous=True)."""

    asynchronous = True
    poll_interval_secs = 0.05  # in-process pool: poll fast (FMinIter reads this)

    @property
    def default_max_queue_len(self):
        """FMinIter queues at least this many outstanding suggestions so the
        pool stays saturated (the SparkTrials-parallelism analog)."""
        return self.n_workers

    def __init__(self, n_workers=4, traceable=False, timeout=None,
                 retry=None, exp_key=None, refresh=True):
        self.n_workers = int(n_workers)
        self.traceable = bool(traceable)
        # per-trial budget (the SparkTrials(timeout=) analog): a RUNNING
        # trial past its deadline is moved to JOB_STATE_CANCEL by the
        # driver's poll loop; the orphaned worker thread's eventual result is
        # discarded.  Python threads can't be killed — cancellation is a
        # state-level guarantee (fmin never waits on it), not a CPU reclaim,
        # matching Spark's job-group cancel semantics at the trial-doc level.
        # Deadlines are MONOTONIC-clock, stamped at claim time (ISSUE 8):
        # wall-clock arithmetic on book_time meant an NTP step or a
        # suspended host could mass-cancel every healthy in-flight trial.
        self.timeout = timeout
        # per-trial retry policy (retry.py): a raising objective is re-run
        # in place with jittered exponential backoff, the attempt count
        # recorded in misc['attempts'] — None/0 keeps the old
        # fail-immediately behavior
        self.retry = RetryPolicy.coerce(retry)
        self._deadlines = {}  # tid -> monotonic cancel deadline
        self._monotonic = time.monotonic  # injectable for fake-clock tests
        self._sleep = time.sleep
        self._lock = threading.RLock()
        self._pool = None
        self._domain_cache = None
        self._batch_eval_cache = None
        self._dispatched = set()  # tids already submitted to the pool
        # obs: queue/utilization gauges + lifecycle events for this pool
        # (in-memory ring; the durable analog lives in FileStore).  The
        # registry is per-instance and deliberately NOT globally registered:
        # readers reach it via `trials.metrics`, and registering every pool
        # (plus every unpickle) would grow the process-global table forever
        self.metrics = MetricsRegistry(f"executor-{next(_instance_ids)}")
        self.metrics.gauge("n_workers").set(self.n_workers)
        self.obs_events = EventLog()
        self._busy = 0
        super().__init__(exp_key=exp_key, refresh=refresh)

    # -- obs plumbing ------------------------------------------------------

    def _worker_busy(self, delta):
        """Track pool utilization: busy-worker gauge + cumulative busy
        seconds (divide by wall x n_workers for utilization)."""
        with self._lock:
            self._busy += delta
            self.metrics.gauge("busy_workers").set(self._busy)

    # -- pool / domain plumbing -------------------------------------------

    def _get_pool(self):
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=self.n_workers, thread_name_prefix="hyperopt-worker"
            )
        return self._pool

    def _get_domain(self):
        """Unpickle the domain attachment once (MongoWorker.run_one analog)."""
        if self._domain_cache is None:
            blob = self.attachments.get("FMinIter_Domain")
            if blob is None:
                return None
            if isinstance(blob, (bytes, bytearray)):
                import cloudpickle

                self._domain_cache = cloudpickle.loads(bytes(blob))
            else:
                self._domain_cache = blob
        return self._domain_cache

    # -- claim / evaluate --------------------------------------------------

    def _claim(self, trial):
        """Atomically move NEW -> RUNNING (MongoJobs.reserve analog).
        The cancel deadline is stamped HERE, from the monotonic clock —
        claim time is the only moment both the budget and the clock are
        known to be fresh."""
        with self._lock:
            if trial["state"] != JOB_STATE_NEW:
                return False
            trial["state"] = JOB_STATE_RUNNING
            trial["book_time"] = coarse_utcnow()
            trial["owner"] = threading.current_thread().name
            if self.timeout is not None:
                self._deadlines[trial["tid"]] = (
                    self._monotonic() + self.timeout)
        self.obs_events.emit(TRIAL_CLAIMED, trial["tid"],
                             owner=trial["owner"])
        return True

    def _finish(self, trial, result=None, error=None):
        with self._lock:
            # the monotonic deadline dies with the trial whatever the
            # outcome — only live RUNNING docs are budget-tracked
            self._deadlines.pop(trial["tid"], None)
            if trial["state"] == JOB_STATE_CANCEL:
                self.metrics.counter("results.discarded").inc()
                return  # timed out meanwhile: the late result is discarded
            # write result BEFORE state: the driver thread reads docs without
            # this lock, and must never observe DONE with a stale result
            if error is not None:
                trial["misc"]["error"] = (str(type(error)), str(error))
                trial["state"] = JOB_STATE_ERROR
            else:
                trial["result"] = result
                trial["state"] = JOB_STATE_DONE
            trial["refresh_time"] = coarse_utcnow()
        sec = None
        if trial.get("book_time") is not None:
            sec = (trial["refresh_time"] - trial["book_time"]).total_seconds()
            self.metrics.histogram("trial_sec").observe(sec)
        if error is not None:
            self.metrics.counter("trials.errors").inc()
            self.obs_events.emit(TRIAL_FINISHED, trial["tid"],
                                 status="error", sec=sec)
        else:
            self.metrics.counter("trials.completed").inc()
            self.obs_events.emit(TRIAL_FINISHED, trial["tid"],
                                 status=(result or {}).get("status", "ok"),
                                 sec=sec)

    def checkpoint_trial(self, doc):
        """Ctrl.checkpoint hook: stamp the partial result under the lock so
        the driver thread never reads a half-written doc (docs are shared
        in-process; the stamp is the persistence)."""
        with self._lock:
            doc["refresh_time"] = coarse_utcnow()

    def _cancel_timed_out(self):
        """RUNNING → CANCEL for trials past their MONOTONIC deadline
        (SparkTrials timeout policy: hyperopt/spark.py sym: _FMinState
        timeout handling).  Runs under the driver's poll cadence.

        Deadlines are stamped at claim time from ``time.monotonic`` — the
        old wall-clock ``now - book_time`` arithmetic meant an NTP step or
        a laptop resume could instantly "age" every healthy RUNNING trial
        past its budget and mass-cancel them.  A RUNNING trial with no
        recorded deadline (resumed from a checkpoint: monotonic values are
        meaningless across processes/boots) is granted a fresh full budget
        on first sight rather than cancelled on a clock it never saw."""
        if self.timeout is None:
            return
        with self._lock:
            now_mono = self._monotonic()
            now = coarse_utcnow()
            for t in self._dynamic_trials:
                if t["state"] != JOB_STATE_RUNNING or t.get("book_time") is None:
                    continue
                deadline = self._deadlines.get(t["tid"])
                if deadline is None:
                    self._deadlines[t["tid"]] = now_mono + self.timeout
                    continue
                if now_mono >= deadline:
                    t["state"] = JOB_STATE_CANCEL
                    # merge, don't overwrite: a Ctrl.checkpoint partial
                    # result must survive cancellation
                    t["result"] = {**(t.get("result") or {}), "status": STATUS_FAIL}
                    t["misc"]["error"] = (
                        "Cancelled",
                        f"trial exceeded per-trial timeout {self.timeout}s",
                    )
                    t["refresh_time"] = now
                    self._deadlines.pop(t["tid"], None)
                    self.metrics.counter("trials.timeouts").inc()
                    self.obs_events.emit(TRIAL_CANCELLED, t["tid"],
                                         reason="trial_timeout")
                    logger.warning("trial %s cancelled after %ss timeout",
                                   t["tid"], self.timeout)

    def cancel_unfinished(self):
        """Move every NEW/RUNNING trial to CANCEL — called by FMinIter when
        the fmin-level timeout expires so the driver never blocks on a hung
        in-flight objective (hyperopt/spark.py: job-group cancellation)."""
        with self._lock:
            for t in self._dynamic_trials:
                if t["state"] in (JOB_STATE_NEW, JOB_STATE_RUNNING):
                    t["state"] = JOB_STATE_CANCEL
                    t["result"] = {**(t.get("result") or {}), "status": STATUS_FAIL}
                    t["misc"]["error"] = ("Cancelled", "fmin timeout")
                    t["refresh_time"] = coarse_utcnow()
                    self._deadlines.pop(t["tid"], None)
                    self.metrics.counter("trials.cancelled").inc()
                    self.obs_events.emit(TRIAL_CANCELLED, t["tid"],
                                         reason="fmin_timeout")

    def _run_one(self, trial):
        """Evaluate one claimed trial (MongoWorker.run_one analog), with
        the per-trial retry policy: a raising objective re-runs in place
        after a jittered exponential backoff, up to ``retry.max_retries``
        extra attempts, the attempt count recorded in
        ``misc['attempts']``.  A trial cancelled (timeout / fmin timeout)
        between attempts is NOT retried — the state-level cancel guarantee
        outranks the retry budget."""
        domain = self._get_domain()
        if domain is None or not self._claim(trial):
            return
        self._worker_busy(+1)
        # per-trial progress beats feed the stall watchdog: an objective
        # hung past "start" with no "finish" shows up by name in the
        # stall report's last-heartbeat table
        _wd_beat("executor.trial", tid=trial["tid"], mark="start")
        t0 = time.perf_counter()
        try:
            spec = spec_from_misc(trial["misc"])
            attempt = 0
            while True:
                with self._lock:
                    if trial["state"] != JOB_STATE_RUNNING:
                        # cancelled during the backoff sleep (trial or
                        # fmin timeout): the doc is already terminal —
                        # re-evaluating would burn a full objective run
                        # whose result _finish must then discard
                        self.metrics.counter("results.discarded").inc()
                        break
                trial["misc"]["attempts"] = attempt + 1
                try:
                    result = domain.evaluate(
                        spec, Ctrl(self, current_trial=trial))
                except Exception as e:  # crash must not kill the driver
                    with self._lock:
                        cancelled = trial["state"] != JOB_STATE_RUNNING
                    if cancelled or not self.retry.retries_left(attempt + 1):
                        logger.error("async job exception: %s", e)
                        self._finish(trial, error=e)
                        break
                    delay = self.retry.delay(attempt, key=trial["tid"])
                    self.metrics.counter("trials.retries").inc()
                    self.metrics.histogram("retry.backoff_sec").observe(delay)
                    logger.warning(
                        "trial %s attempt %d failed (%s); retrying in %.2fs",
                        trial["tid"], attempt + 1, e, delay)
                    self._sleep(delay)
                    attempt += 1
                else:
                    self._finish(trial, result=result)
                    break
        finally:
            self.metrics.counter("worker_busy_sec").inc(
                time.perf_counter() - t0)
            self._worker_busy(-1)
            _wd_beat("executor.trial", tid=trial["tid"], mark="finish")

    def _run_batch(self, trials_batch):
        """Evaluate a queue of trials as ONE vmapped device program."""
        domain = self._get_domain()
        if domain is None:
            return
        claimed = [t for t in trials_batch if self._claim(t)]
        if not claimed:
            return
        self._worker_busy(+1)
        _wd_beat("executor.batch", n=len(claimed), mark="start")
        t0 = time.perf_counter()
        self.metrics.counter("batch_evals").inc()
        try:
            try:
                import jax.numpy as jnp

                if self._batch_eval_cache is None:
                    self._batch_eval_cache = domain.make_batch_eval()
                labels = domain.cs.labels
                specs = [spec_from_misc(t["misc"]) for t in claimed]
                flat_batch = {
                    l: jnp.asarray(
                        np.array([s.get(l, 0.0) for s in specs], np.float32)
                        if not domain.cs.params[l].is_int
                        else np.array([int(s.get(l, 0)) for s in specs], np.int32)
                    )
                    for l in labels
                }
                losses = np.asarray(self._batch_eval_cache(flat_batch), np.float64)
            except Exception as e:
                logger.error("batched async eval exception: %s", e)
                for t in claimed:
                    self._finish(t, error=e)
                return
            for t, loss in zip(claimed, losses):
                if np.isfinite(loss):
                    self._finish(t, result={"loss": float(loss), "status": STATUS_OK})
                else:
                    self._finish(t, error=ValueError(f"non-finite loss {loss}"))
        finally:
            self.metrics.counter("worker_busy_sec").inc(
                time.perf_counter() - t0)
            self._worker_busy(-1)
            _wd_beat("executor.batch", n=len(claimed), mark="finish")

    # -- Trials overrides --------------------------------------------------

    def _dispatch(self, docs):
        """Submit NEW, not-yet-dispatched docs to the pool exactly once.

        Docs inserted before the domain attachment exists are left
        undispatched; ``refresh()`` picks them up later (the Mongo-worker
        poll-again analog) — so each doc is submitted once, not O(all-NEW)
        per insert/refresh.
        """
        if not docs or self._get_domain() is None:
            return
        with self._lock:
            todo = [
                d
                for d in docs
                if d["state"] == JOB_STATE_NEW and d["tid"] not in self._dispatched
            ]
            self._dispatched.update(d["tid"] for d in todo)
        if not todo:
            return
        self.metrics.counter("dispatched").inc(len(todo))
        pool = self._get_pool()
        if self.traceable and len(todo) > 1:
            pool.submit(self._run_batch, todo)
        else:
            for trial in todo:
                pool.submit(self._run_one, trial)

    def insert_trial_docs(self, docs):
        with self._lock:
            tids = super().insert_trial_docs(docs)
            inserted = self._dynamic_trials[-len(docs):] if len(docs) else []
        for d in inserted:
            self.obs_events.emit(TRIAL_NEW, d["tid"])
        self._dispatch(inserted)
        return tids

    def refresh(self):
        self._cancel_timed_out()
        with self._lock:
            super().refresh()
            pending = [
                d
                for d in self._dynamic_trials
                if d["state"] == JOB_STATE_NEW and d["tid"] not in self._dispatched
            ]
            n_queued = sum(
                1 for d in self._dynamic_trials
                if d["state"] in (JOB_STATE_NEW, JOB_STATE_RUNNING)
            )
        self.metrics.gauge("queue_depth").set(n_queued)
        self._dispatch(pending)

    def delete_all(self):
        with self._lock:
            self._dispatched = set()
            super().delete_all()

    def count_by_state_unsynced(self, arg):
        self._cancel_timed_out()
        with self._lock:
            return super().count_by_state_unsynced(arg)

    def shutdown(self, wait=True):
        if self._pool is not None:
            # cancel_futures: queued-but-unstarted work is dropped; running
            # threads (possibly hung user objectives) are not joined when
            # wait=False — their results land in already-terminal docs and
            # are discarded by _finish
            self._pool.shutdown(wait=wait, cancel_futures=not wait)
            self._pool = None

    # pickle: drop pool/lock/caches along with base-class exclusions
    def __getstate__(self):
        state = super().__getstate__()
        state["_pool"] = None
        state["_lock"] = None
        state["_domain_cache"] = None
        state["_batch_eval_cache"] = None
        # a resumed process has no workers yet: NEW docs must redispatch there
        state["_dispatched"] = set()
        # monotonic deadlines are meaningless in another process/boot:
        # _cancel_timed_out re-stamps resumed RUNNING trials on first sight
        state["_deadlines"] = {}
        state["_monotonic"] = None
        state["_sleep"] = None
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.RLock()
        self._monotonic = time.monotonic
        self._sleep = time.sleep
        # checkpoints written by older versions predate these attributes
        self.__dict__.setdefault("_dispatched", set())
        self.__dict__.setdefault("_deadlines", {})
        self.__dict__.setdefault("retry", RetryPolicy(0))
        self.__dict__.setdefault(
            "metrics", MetricsRegistry(f"executor-{next(_instance_ids)}"))
        self.__dict__.setdefault("obs_events", EventLog())
        self.__dict__.setdefault("_busy", 0)
