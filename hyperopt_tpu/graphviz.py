"""Graphviz DOT rendering of a search space.

Parity target: ``hyperopt/graphviz.py`` (sym: dot_hyperparameters).  The
reference walks the pyll Apply graph; here the static Expr tree is walked
directly.  (A package submodule cannot shadow the top-level PyPI
``graphviz`` package under absolute imports, so the reference-parity name
is safe; ``graphviz_mod`` remains as a back-compat alias.)
"""

from __future__ import annotations

from .spaces import Choice, Container, Dist, Expr, Literal, Op, Param, as_expr

__all__ = ["dot_hyperparameters"]


def _esc(s) -> str:
    return str(s).replace('"', r"\"")


def dot_hyperparameters(expr) -> str:
    """DOT source for the space's expression tree
    (graphviz.py sym: dot_hyperparameters)."""
    expr = as_expr(expr)
    lines = ["digraph {"]
    counter = [0]

    def node(label, shape="ellipse"):
        name = f"n{counter[0]}"
        counter[0] += 1
        lines.append(f'  {name} [label="{_esc(label)}" shape={shape}];')
        return name

    def rec(e: Expr) -> str:
        if isinstance(e, Literal):
            return node(repr(e.value), shape="box")
        if isinstance(e, Param):
            d: Dist = e.dist
            me = node(f"{e.label}\\n{d.family}{tuple(round(p, 4) for p in d.params)}",
                      shape="doubleoctagon")
            return me
        if isinstance(e, Choice):
            me = node(f"choice {e.label}", shape="diamond")
            for i, opt in enumerate(e.options):
                child = rec(opt)
                lines.append(f'  {me} -> {child} [label="{i}"];')
            return me
        if isinstance(e, Op):
            me = node(e.op)
            for a in e.args:
                lines.append(f"  {me} -> {rec(a)};")
            return me
        if isinstance(e, Container):
            me = node(e.kind, shape="box3d")
            for k, c in zip(e.keys, e.children):
                child = rec(c)
                edge_label = f' [label="{_esc(k)}"]' if k else ""
                lines.append(f"  {me} -> {child}{edge_label};")
            return me
        raise TypeError(f"not a space expression: {e!r}")

    rec(expr)
    lines.append("}")
    return "\n".join(lines)
