"""Progress reporting (hyperopt/progress.py sym: tqdm_progress_callback,
no_progress_callback)."""

from __future__ import annotations

import contextlib

__all__ = ["tqdm_progress_callback", "no_progress_callback",
           "get_progress_callback", "format_postfix"]


def format_postfix(best_loss, obs=None):
    """The live progress-bar postfix: best loss, plus the run's latest
    search-health gauges ("EI p50 …  dup …") when an armed obs bundle has
    recorded at least one health ask, plus the HBM watermark ("hbm 62%")
    when device-memory telemetry is armed (``HYPEROPT_TPU_DEVMEM``).
    Disarmed runs render exactly the historical ``best loss: <x>``
    string."""
    s = f"best loss: {best_loss:.6g}"
    if obs is not None and getattr(obs, "sink", None) is not None:
        from .obs.health import live_health_postfix

        extra = live_health_postfix(obs)
        if extra:
            s += "  " + extra
    devmem = getattr(obs, "devmem", None) if obs is not None else None
    if devmem is not None:
        frac, peak = devmem.watermark()
        if frac is not None:
            s += f"  hbm {frac * 100:.0f}%"
        elif peak is not None:
            s += f"  hbm peak {peak / (1 << 20):.0f}MiB"
    return s


class _NullProgress:
    """No-op progress context with the tqdm-ish surface FMinIter uses."""

    postfix = ""

    def update(self, n=1):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


@contextlib.contextmanager
def no_progress_callback(initial=0, total=None):
    yield _NullProgress()


@contextlib.contextmanager
def tqdm_progress_callback(initial=0, total=None):
    try:
        from tqdm import tqdm
    except ImportError:  # pragma: no cover
        with no_progress_callback(initial, total) as ctx:
            yield ctx
        return

    from .std_out_err_redirect_tqdm import std_out_err_redirect_tqdm

    class _Tqdm:
        def __init__(self, bar):
            self.bar = bar

        @property
        def postfix(self):
            return self.bar.postfix

        @postfix.setter
        def postfix(self, s):
            self.bar.set_postfix_str(s, refresh=False)

        def update(self, n=1):
            if n:
                self.bar.update(n)

    total_ = None if total in (None, float("inf")) else int(total)
    # objective prints are routed through tqdm.write so they don't shred
    # the bar (reference: std_out_err_redirect_tqdm.py used the same way)
    with std_out_err_redirect_tqdm() as orig_stdout:
        with tqdm(initial=initial, total=total_, dynamic_ncols=True,
                  file=orig_stdout) as bar:
            yield _Tqdm(bar)


def get_progress_callback(show_progressbar):
    if callable(show_progressbar) and not isinstance(show_progressbar, bool):
        return show_progressbar
    return tqdm_progress_callback if show_progressbar else no_progress_callback
