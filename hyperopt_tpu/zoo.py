"""Benchmark domain zoo — shared fixtures for tests and bench.py.

Parity target: ``hyperopt/tests/test_domains.py`` (sym: quadratic1,
q1_lognormal, q1_choice, n_arms, distractor, gauss_wave, gauss_wave2, branin,
many_dists) — the reference keeps these in its test tree; here they live in
the package so ``bench.py`` and ``__graft_entry__`` reuse them.

Each entry is a ``DomainZoo`` record: a search space, an objective over the
structured sample, the known optimum (when analytic), and a ``traceable``
flag — True when the objective is pure jnp math, so it can run under
``jit``/``vmap``/``lax.scan`` (the on-device fmin and batched-eval paths).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable

import jax.numpy as jnp

from . import hp

__all__ = ["DomainZoo", "ZOO", "branin", "hartmann6", "rosenbrock",
           "StudyMixItem", "make_study_mix"]


@dataclasses.dataclass(frozen=True)
class DomainZoo:
    name: str
    space: Any
    objective: Callable
    loss_target: float  # a loss an OK optimizer reaches within ~100 evals
    optimum: float | None = None
    traceable: bool = False


def branin(x, y):
    """Branin-Hoo (BASELINE config #2); global min ≈ 0.397887."""
    a = 1.0
    b = 5.1 / (4.0 * math.pi**2)
    c = 5.0 / math.pi
    r = 6.0
    s = 10.0
    t = 1.0 / (8.0 * math.pi)
    return a * (y - b * x**2 + c * x - r) ** 2 + s * (1 - t) * jnp.cos(x) + s


def hartmann6(x):
    """6-D Hartmann (BASELINE config #3); global min ≈ -3.32237."""
    alpha = jnp.array([1.0, 1.2, 3.0, 3.2])
    A = jnp.array(
        [
            [10, 3, 17, 3.5, 1.7, 8],
            [0.05, 10, 17, 0.1, 8, 14],
            [3, 3.5, 1.7, 10, 17, 8],
            [17, 8, 0.05, 10, 0.1, 14],
        ],
        jnp.float32,
    )
    P = 1e-4 * jnp.array(
        [
            [1312, 1696, 5569, 124, 8283, 5886],
            [2329, 4135, 8307, 3736, 1004, 9991],
            [2348, 1451, 3522, 2883, 3047, 6650],
            [4047, 8828, 8732, 5743, 1091, 381],
        ],
        jnp.float32,
    )
    inner = jnp.sum(A * (jnp.asarray(x) - P) ** 2, axis=1)
    return -jnp.sum(alpha * jnp.exp(-inner))


def rosenbrock(xs):
    xs = jnp.asarray(xs)
    return jnp.sum(100.0 * (xs[1:] - xs[:-1] ** 2) ** 2 + (1.0 - xs[:-1]) ** 2)


def _quadratic1():
    return DomainZoo(
        name="quadratic1",
        space={"x": hp.uniform("x", -5, 5)},
        objective=lambda d: (d["x"] - 3.0) ** 2,
        loss_target=0.1,
        optimum=0.0,
        traceable=True,
    )


def _q1_lognormal():
    return DomainZoo(
        name="q1_lognormal",
        space={"x": hp.qlognormal("x", 0.0, 2.0, 1.0)},
        objective=lambda d: jnp.maximum(-(d["x"] ** 2), -100.0),
        loss_target=-9.0,
        optimum=-100.0,
        traceable=True,
    )


def _q1_choice():
    return DomainZoo(
        name="q1_choice",
        space=hp.choice(
            "case",
            [{"x": hp.uniform("x1", -5, 5)}, {"x": hp.uniform("x2", -10, -3)}],
        ),
        objective=lambda d: (d["x"] + 2.0) ** 2,
        loss_target=0.5,
        optimum=0.0,
    )


def _n_arms(n=2):
    return DomainZoo(
        name="n_arms",
        space=hp.choice("arm", list(range(n))),
        objective=lambda arm: 0.0 if arm == 0 else 1.0,
        loss_target=0.0,
        optimum=0.0,
    )


def _distractor():
    # global min at x=3 (deep narrow), distractor basin at x=-3 (wide shallow)
    def obj(d):
        x = d["x"]
        f = -math.exp(-((x - 3.0) ** 2)) - 1.2 * math.exp(-0.05 * (x + 3.0) ** 2)
        return f

    return DomainZoo(
        name="distractor",
        space={"x": hp.uniform("x", -15, 15)},
        objective=obj,
        loss_target=-1.1,
        optimum=None,
    )


def _gauss_wave():
    """Sinusoid under a Gaussian envelope
    (hyperopt/tests/test_domains.py sym: gauss_wave) — a smooth global basin
    with high-frequency ripple; TPE must not get stuck on a local ripple."""

    def obj(d):
        x = d["x"]
        return -math.exp(-((x / 8.0) ** 2)) * math.cos(x)

    return DomainZoo(
        name="gauss_wave",
        space={"x": hp.uniform("x", -20, 20)},
        objective=obj,
        loss_target=-0.8,
        optimum=-1.0,
    )


def _gauss_wave2():
    def obj(d):
        x = d["x"]
        t = d["hf"]
        f = math.sin(x) * (1.0 if t == "sin" else 0.0) + 0.1 * x**2
        return f

    return DomainZoo(
        name="gauss_wave2",
        space={
            "x": hp.uniform("x", -20, 20),
            "hf": hp.choice("hf", ["sin", "flat"]),
        },
        objective=obj,
        loss_target=0.0,
    )


def _branin_domain():
    # pure-jnp objective: returns a 0-d jax array, which Domain.evaluate
    # accepts on host and which traces under jit/vmap/lax.scan (the
    # batched-eval and on-device fmin paths rely on `traceable=True` being
    # literally true)
    return DomainZoo(
        name="branin",
        space={"x": hp.uniform("x", -5, 10), "y": hp.uniform("y", 0, 15)},
        objective=lambda d: branin(d["x"], d["y"]),
        loss_target=0.9,
        optimum=0.397887,
        traceable=True,
    )


def _hartmann6_domain():
    return DomainZoo(
        name="hartmann6",
        space={f"x{i}": hp.uniform(f"x{i}", 0, 1) for i in range(6)},
        objective=lambda d: hartmann6(jnp.stack([d[f"x{i}"] for i in range(6)])),
        loss_target=-2.0,
        optimum=-3.32237,
        traceable=True,
    )


def _rosenbrock4():
    return DomainZoo(
        name="rosenbrock4",
        space={f"x{i}": hp.uniform(f"x{i}", -2, 2) for i in range(4)},
        objective=lambda d: rosenbrock(jnp.stack([d[f"x{i}"] for i in range(4)])),
        loss_target=30.0,
        optimum=0.0,
        traceable=True,
    )


def _many_dists():
    """One of every hp.* family incl. nested choice
    (hyperopt/tests/test_domains.py sym: many_dists)."""
    space = {
        "a": hp.choice("a", [0, 1, 2]),
        "b": hp.randint("b", 10),
        "c": hp.uniform("c", 4, 7),
        "d": hp.loguniform("d", -2, 0),
        "e": hp.quniform("e", 0, 10, 3),
        "f": hp.qloguniform("f", 0, 3, 2),
        "g": hp.normal("g", 4, 7),
        "h": hp.lognormal("h", -2, 2),
        "i": hp.qnormal("i", 0, 10, 2),
        "j": hp.qlognormal("j", 0, 2, 1),
        "k": hp.pchoice("k", [(0.1, 0), (0.9, 1)]),
        "z": hp.choice(
            "z", [{"m": hp.uniform("m", -1, 1)}, {"n": hp.uniformint("n", 1, 5)}]
        ),
    }

    def obj(d):
        z = d["z"]
        zv = z.get("m", 0.0) + z.get("n", 0)
        return (
            abs(d["c"] - 5.0)
            + 0.1 * abs(d["g"])
            + 0.01 * (d["a"] + d["b"] + d["e"] + d["k"])
            + 0.001 * (d["d"] + d["f"] + d["h"] + d["i"] + abs(d["j"]) + zv)
        )

    return DomainZoo(name="many_dists", space=space, objective=obj, loss_target=2.5)


def _hartmann6_host(x):
    """Host-numpy Hartmann6 for non-traceable (interactive-loop) domains —
    keeps per-eval cost off the accelerator dispatch path."""
    import numpy as np

    alpha = np.array([1.0, 1.2, 3.0, 3.2])
    A = np.array(
        [
            [10, 3, 17, 3.5, 1.7, 8],
            [0.05, 10, 17, 0.1, 8, 14],
            [3, 3.5, 1.7, 10, 17, 8],
            [17, 8, 0.05, 10, 0.1, 14],
        ]
    )
    P = 1e-4 * np.array(
        [
            [1312, 1696, 5569, 124, 8283, 5886],
            [2329, 4135, 8307, 3736, 1004, 9991],
            [2348, 1451, 3522, 2883, 3047, 6650],
            [4047, 8828, 8732, 5743, 1091, 381],
        ]
    )
    inner = np.sum(A * (np.asarray(x) - P) ** 2, axis=1)
    return float(-np.sum(alpha * np.exp(-inner)))


def _hr_conditional():
    """BASELINE config #3: mixed conditional space — ``hp.choice`` dispatches
    between Hartmann6 (6 uniform dims) and a 20-D Rosenbrock whose scale is
    an ``hp.loguniform``; TPE must learn both the branch preference and the
    per-branch posteriors (all via activation masks, SURVEY.md §7.4)."""
    import numpy as np

    space = hp.choice(
        "family",
        [
            {
                "kind": "hartmann",
                "xs": [hp.uniform(f"h{i}", 0, 1) for i in range(6)],
            },
            {
                "kind": "rosen",
                "xs": [hp.uniform(f"r{i}", -2, 2) for i in range(20)],
                "scale": hp.loguniform("r_scale", -3, 1),
            },
        ],
    )

    def obj(d):
        if d["kind"] == "hartmann":
            return _hartmann6_host(d["xs"])
        xs = np.asarray(d["xs"]) * d["scale"]
        return float(
            np.sum(100.0 * (xs[1:] - xs[:-1] ** 2) ** 2 + (1.0 - xs[:-1]) ** 2)
        )

    # hartmann branch reaches < -1 quickly; rosen floor is ~0 → a competent
    # optimizer should commit to the hartmann branch within ~100 evals
    return DomainZoo(name="hr_conditional", space=space, objective=obj, loss_target=-1.0)


_ML_N, _ML_DIM, _ML_FOLDS = 512, 16, 4


def _ml_dataset():
    """The shared synthetic binary-classification task for the ML domains:
    deterministic (numpy rng 42), 16 features, label noise.  PURE NUMPY and
    built lazily on first objective call — jax ops here would initialize the
    backend at import (hangs when the ambient tunnel is broken) or cache
    escaping tracers when first touched under a trace."""
    import functools

    @functools.lru_cache(maxsize=1)
    def build():
        import numpy as np

        n, dim, folds = _ML_N, _ML_DIM, _ML_FOLDS
        rng = np.random.default_rng(42)
        w_true = rng.standard_normal(dim).astype(np.float32)
        X = rng.standard_normal((n, dim)).astype(np.float32)
        margin = X @ w_true / np.sqrt(dim)
        y = (margin + 0.6 * rng.standard_normal(n) > 0).astype(np.float32)
        return X.reshape(folds, n // folds, dim), y.reshape(folds, n // folds)

    return build


_ml_data = _ml_dataset()


def _ml_logreg_cv():
    """BASELINE config #4 analog: a REAL machine-learning objective — 4-fold
    cross-validated logistic regression trained by gradient descent, all pure
    jnp (the sklearn SVM/RF-on-MNIST role, rebuilt traceable so thousands of
    trials vmap/shard onto the accelerator instead of forking sklearn
    processes).  Data is synthetic-but-fixed: a deterministic key generates a
    16-feature binary task with label noise, so every trial everywhere sees
    the same dataset.  Hyperparameters: learning rate (log), L2 (log),
    momentum (uniform) — the classic conditioning/regularization trade-off;
    the CV loss surface has a genuine basin (lr too high diverges, L2 too
    high underfits)."""
    import jax
    from jax import lax

    dim, folds, steps = _ML_DIM, _ML_FOLDS, 120
    _data = _ml_data  # shared lazily-built dataset (see _ml_dataset)

    def _nll(w, b, Xs, ys):
        z = Xs @ w + b
        s = 2.0 * ys - 1.0
        return jnp.mean(jnp.log1p(jnp.exp(-s * z)))

    def _train_fold(i, lr, l2, mom):
        Xf, yf = _data()
        va_x, va_y = Xf[i], yf[i]
        tr_x = jnp.concatenate([Xf[j] for j in range(folds) if j != i])
        tr_y = jnp.concatenate([yf[j] for j in range(folds) if j != i])

        def loss_fn(params):
            w, b = params
            return _nll(w, b, tr_x, tr_y) + l2 * jnp.sum(w**2)

        def step(carry, _):
            (w, b), (vw, vb) = carry
            gw, gb = jax.grad(loss_fn)((w, b))
            vw = mom * vw - lr * gw
            vb = mom * vb - lr * gb
            return ((w + vw, b + vb), (vw, vb)), None

        init = ((jnp.zeros(dim), jnp.float32(0.0)),
                (jnp.zeros(dim), jnp.float32(0.0)))
        ((w, b), _), _ = lax.scan(step, init, None, length=steps)
        return _nll(w, b, va_x, va_y)

    def obj(d):
        lr, l2, mom = d["lr"], d["l2"], d["momentum"]
        # folds are a static unroll (4 iterations), each a lax.scan train loop
        loss = jnp.mean(jnp.stack([_train_fold(i, lr, l2, mom)
                                   for i in range(folds)]))
        # a diverged run (lr high enough that the weights blow up to
        # inf/NaN) must surface as a FINITE terrible loss, not NaN: NaN
        # raises InvalidLoss and fails the trial, punching holes in the
        # posterior exactly where TPE most needs "this region is bad"
        # evidence (and tripping every all-finite-losses pin).  50 is
        # ~100x the task's tuned CV logloss — ranked worse than any real
        # configuration, cheap for the EI split to learn from.
        return jnp.where(jnp.isfinite(loss), loss, jnp.float32(50.0))

    return DomainZoo(
        name="ml_logreg_cv",
        space={
            "lr": hp.loguniform("lr", math.log(1e-4), math.log(10.0)),
            "l2": hp.loguniform("l2", math.log(1e-6), math.log(1.0)),
            "momentum": hp.uniform("momentum", 0.0, 0.98),
        },
        objective=obj,
        loss_target=0.45,  # well-tuned CV logloss on this task
        traceable=True,
    )


def _ml_model_select_cv():
    """BASELINE config #4, full shape: MODEL-FAMILY SELECTION (the sklearn
    "SVM vs RandomForest" analog) with per-family hyperparameters, all
    traceable.  ``hp.choice`` dispatches between an L2 logistic regression
    and a one-hidden-layer MLP (fixed width — shapes must be static under
    jit); the traced union-merge assembly (spaces.CompiledSpace.assemble)
    exposes both branches' hyperparameters and the objective gates on the
    selector, so TPE learns the family preference AND each family's
    posterior through activation masks.  Uses _ml_logreg_cv's dataset."""
    import jax
    from jax import lax

    base = ZOO["ml_logreg_cv"]

    dim, folds, steps, hidden = _ML_DIM, _ML_FOLDS, 120, 32
    _data = _ml_data  # SAME dataset as ml_logreg_cv (shared _ml_dataset)

    def _nll(logits, ys):
        s = 2.0 * ys - 1.0
        return jnp.mean(jnp.log1p(jnp.exp(-s * logits)))

    def _train(i, params0, forward, lr, l2):
        Xf, yf = _data()
        va_x, va_y = Xf[i], yf[i]
        tr_x = jnp.concatenate([Xf[j] for j in range(folds) if j != i])
        tr_y = jnp.concatenate([yf[j] for j in range(folds) if j != i])

        def loss_fn(params):
            reg = sum(jnp.sum(p**2) for p in jax.tree.leaves(params))
            return _nll(forward(params, tr_x), tr_y) + l2 * reg

        def step(params, _):
            g = jax.grad(loss_fn)(params)
            return jax.tree.map(lambda p, gg: p - lr * gg, params, g), None

        params, _ = lax.scan(step, params0, None, length=steps)
        return _nll(forward(params, va_x), va_y)

    def _cv_logreg(lr, l2):
        fwd = lambda p, X: X @ p[0] + p[1]
        p0 = (jnp.zeros(dim), jnp.float32(0.0))
        return jnp.mean(jnp.stack([_train(i, p0, fwd, lr, l2)
                                   for i in range(folds)]))

    def _cv_mlp(lr, l2, w_scale):
        def fwd(p, X):
            (W1, b1, W2, b2) = p
            h = jnp.tanh(X @ W1 + b1)
            return h @ W2 + b2

        k = jax.random.PRNGKey(7)
        k1, k2 = jax.random.split(k)
        p0 = (w_scale * jax.random.normal(k1, (dim, hidden)) / jnp.sqrt(dim),
              jnp.zeros(hidden),
              w_scale * jax.random.normal(k2, (hidden,)) / jnp.sqrt(hidden),
              jnp.float32(0.0))
        return jnp.mean(jnp.stack([_train(i, p0, fwd, lr, l2)
                                   for i in range(folds)]))

    space = hp.choice("model", [
        {"m": 0,
         "lr_lin": hp.loguniform("lr_lin", math.log(1e-4), math.log(10.0)),
         "l2_lin": hp.loguniform("l2_lin", math.log(1e-6), math.log(1.0))},
        {"m": 1,
         "lr_mlp": hp.loguniform("lr_mlp", math.log(1e-4), math.log(1.0)),
         "l2_mlp": hp.loguniform("l2_mlp", math.log(1e-6), math.log(1.0)),
         "w_scale": hp.loguniform("w_scale", math.log(0.1), math.log(3.0))},
    ])

    def obj(d):
        if isinstance(d.get("m"), int):  # host path: only the live branch
            if d["m"] == 0:
                return _cv_logreg(d["lr_lin"], d["l2_lin"])
            return _cv_mlp(d["lr_mlp"], d["l2_mlp"], d["w_scale"])
        # traced path: union structure — evaluate both families, gate on m
        # (all-branch evaluation is the XLA conditional-space doctrine)
        loss_lin = _cv_logreg(d["lr_lin"], d["l2_lin"])
        loss_mlp = _cv_mlp(d["lr_mlp"], d["l2_mlp"], d["w_scale"])
        return jnp.where(jnp.asarray(d["m"]) == 0, loss_lin, loss_mlp)

    return DomainZoo(
        name="ml_model_select_cv",
        space=space,
        objective=obj,
        loss_target=base.loss_target,
        traceable=True,
    )


def _hpob_surrogate():
    """HPO-B-style tabular surrogate (BASELINE config #5 / SURVEY §6).

    The real HPO-B benchmark evaluates hyperparameter configs against
    surrogates (XGBoost regressors) fit on OpenML HPO logs; the dataset
    cannot be downloaded in this offline environment, so this domain keeps
    the workload's SHAPE — a cheap tabular surrogate over a realistic mixed
    ML search space, evaluated millions of times — with a seeded random-MLP
    surrogate: a fixed 2-hidden-layer tanh network over the normalized
    config vector (log-scaled learning rate / weight decay, linear dropout
    and momentum, quantized depth, one-hot optimizer choice).  The
    landscape is smooth, anisotropic and multimodal (random tanh features
    superpose many ridges), deterministic everywhere (weights from numpy
    rng(77), built lazily like ``_ml_dataset``), and pure jnp — so it
    vmaps/shards onto the accelerator at the 10k-trials-per-generation
    scale the real benchmark is used at.
    """
    import functools

    hidden = 64

    @functools.lru_cache(maxsize=1)
    def weights():
        import numpy as np

        rng = np.random.default_rng(77)
        # feature vector: 5 numeric (normalized to [0,1]) + 4 one-hot
        fdim = 9
        W1 = rng.standard_normal((fdim, hidden)).astype(np.float32) * 1.8
        b1 = rng.uniform(-1, 1, hidden).astype(np.float32)
        W2 = rng.standard_normal((hidden, hidden)).astype(np.float32) / np.sqrt(hidden)
        b2 = rng.uniform(-1, 1, hidden).astype(np.float32)
        w3 = rng.standard_normal(hidden).astype(np.float32) / np.sqrt(hidden)
        return W1, b1, W2, b2, w3

    def obj(d):
        W1, b1, W2, b2, w3 = weights()
        lr = (jnp.log(jnp.asarray(d["lr"], jnp.float32)) + 9.2) / 9.2  # [1e-4, 1] -> [0,1]
        wd = (jnp.log(jnp.asarray(d["weight_decay"], jnp.float32)) + 13.8) / 13.8
        do = jnp.asarray(d["dropout"], jnp.float32) / 0.9
        mom = jnp.asarray(d["momentum"], jnp.float32)
        depth = (jnp.asarray(d["depth"], jnp.float32) - 1.0) / 7.0
        opt = jnp.asarray(d["optimizer"], jnp.int32)
        onehot = (opt == jnp.arange(4)).astype(jnp.float32)
        x = jnp.concatenate([jnp.stack([lr, wd, do, mom, depth]), onehot])
        h = jnp.tanh(x @ jnp.asarray(W1) + jnp.asarray(b1))
        h = jnp.tanh(h @ jnp.asarray(W2) + jnp.asarray(b2))
        return jnp.dot(h, jnp.asarray(w3))

    space = {
        "lr": hp.loguniform("lr", math.log(1e-4), 0.0),
        "weight_decay": hp.loguniform("weight_decay", math.log(1e-6), 0.0),
        "dropout": hp.quniform("dropout", 0.0, 0.9, 0.1),
        "momentum": hp.uniform("momentum", 0.0, 1.0),
        "depth": hp.uniformint("depth", 1, 8),
        "optimizer": hp.choice("optimizer", [0, 1, 2, 3]),
    }
    # measured on CPU: prior best-of-10k -0.615, random best@100 ~ -0.51,
    # TPE mean best@100 -0.59 — the target separates TPE from random
    return DomainZoo(name="hpob_surrogate", space=space, objective=obj,
                     loss_target=-0.55, traceable=True)


@dataclasses.dataclass(frozen=True)
class StudyMixItem:
    """One study of the standing multi-study workload: a zoo domain plus
    the study-level serving parameters (seed, budget, startup count)."""

    name: str
    domain: "DomainZoo"
    seed: int
    budget: int
    n_startup_jobs: int


#: the domains the standing mix cycles through — chosen for heterogeneous
#: spaces (1-D uniform, 2-D, 6-D, mixed discrete HPO-B surrogate) so a mix
#: always exercises several cohorts at once, and all cheap to evaluate
_MIX_DOMAINS = ("quadratic1", "branin", "hartmann6", "rosenbrock4",
                "hpob_surrogate")
_MIX_BUDGETS = (20, 30, 40, 60, 80)


def make_study_mix(n, seed0=0):
    """The standing multi-study workload (ISSUE 9 satellite): ``n``
    heterogeneous studies cycling through the HPO-B surrogate and the
    analytic zoo domains with varied budgets and per-study seeds.

    Used by the multi-study tests, ``bench.py``'s ``multi_study`` stage
    and ``scripts/service_smoke.py`` — one definition so "1k concurrent
    studies" means the same workload everywhere.  Deterministic in
    ``(n, seed0)``.
    """
    mix = []
    for i in range(int(n)):
        dom = ZOO[_MIX_DOMAINS[i % len(_MIX_DOMAINS)]]
        mix.append(StudyMixItem(
            name=f"{dom.name}#{i}",
            domain=dom,
            seed=int(seed0) + i,
            budget=_MIX_BUDGETS[(i // len(_MIX_DOMAINS)) % len(_MIX_BUDGETS)],
            n_startup_jobs=5,
        ))
    return mix


ZOO = {
    d.name: d
    for d in (
        _quadratic1(),
        _q1_lognormal(),
        _q1_choice(),
        _n_arms(),
        _distractor(),
        _gauss_wave(),
        _gauss_wave2(),
        _branin_domain(),
        _hartmann6_domain(),
        _rosenbrock4(),
        _many_dists(),
        _hr_conditional(),
        _ml_logreg_cv(),
        _hpob_surrogate(),
    )
}
ZOO["ml_model_select_cv"] = _ml_model_select_cv()
