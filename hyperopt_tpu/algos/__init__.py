"""Suggest algorithms behind the ``algo=`` plugin boundary.

The plugin signature is preserved from the reference
(``hyperopt/rand.py``/``tpe.py`` sym: suggest):

    suggest(new_ids, domain, trials, seed, **kwargs) -> [trial docs]

so ``functools.partial(tpe.suggest, gamma=..., n_EI_candidates=...)`` keeps
working as the configuration mechanism (SURVEY.md §5 "Config / flag system").
"""

from . import rand  # noqa: F401

# Optional algo modules are imported if present so a partial checkout of the
# algos package never breaks `import hyperopt_tpu` (round-1 regression).
# Only "this exact module does not exist" is tolerated; a genuine import
# failure *inside* an existing module must surface, not silently demote the
# default optimizer to random search.
for _name in ("tpe", "anneal", "mix", "atpe"):
    try:
        globals()[_name] = __import__(f"{__name__}.{_name}", fromlist=[_name])
    except ModuleNotFoundError as e:
        if e.name != f"{__name__}.{_name}":
            raise
del _name
