"""Suggest algorithms behind the ``algo=`` plugin boundary.

The plugin signature is preserved from the reference
(``hyperopt/rand.py``/``tpe.py`` sym: suggest):

    suggest(new_ids, domain, trials, seed, **kwargs) -> [trial docs]

so ``functools.partial(tpe.suggest, gamma=..., n_EI_candidates=...)`` keeps
working as the configuration mechanism (SURVEY.md §5 "Config / flag system").
"""

from . import anneal, mix, rand, tpe  # noqa: F401
