"""Mixture-of-suggesters.

Parity target: ``hyperopt/mix.py`` (sym: suggest): per new id, draw one
sub-suggester from a categorical over ``p_suggest = [(p, suggest_fn), ...]``
and delegate.  Used e.g. to blend random exploration into TPE.
"""

from __future__ import annotations

import numpy as np

__all__ = ["suggest"]


def suggest(new_ids, domain, trials, seed, p_suggest):
    """``p_suggest``: list of ``(probability, suggest_fn)`` pairs summing
    to 1 (hyperopt/mix.py sym: suggest)."""
    ps = np.asarray([p for p, _ in p_suggest], dtype=float)
    if not np.isclose(ps.sum(), 1.0, atol=1e-6):
        raise ValueError(f"p_suggest probabilities sum to {ps.sum()}, expected 1")
    # full-width seed: masking to 31 bits would collapse seeds differing only
    # in high words to identical mix streams (cf. rand.seed_to_key)
    rng = np.random.default_rng(int(seed))
    docs = []
    for new_id in new_ids:
        idx = int(rng.choice(len(ps), p=ps))
        _, sub = p_suggest[idx]
        docs.extend(sub([new_id], domain, trials, int(rng.integers(2**31 - 1))))
    return docs
