"""Tree-structured Parzen Estimator — the jitted TPU hot path.

Parity target: ``hyperopt/tpe.py`` (sym: suggest, adaptive_parzen_normal,
linear_forgetting_weights, GMM1, GMM1_lpdf, LGMM1, LGMM1_lpdf,
ap_split_trials, broadcast_best, build_posterior, _default_*).

TPU-first redesign (SURVEY.md §7.1):

* The reference rebuilds a pyll posterior *graph* on every call and interprets
  it with ``rec_eval`` — O(#trials) numpy work per suggestion, one candidate
  batch of 24.  Here the whole posterior — below/above split, adaptive-Parzen
  fit for every hyperparameter, candidate sampling, mixture log-pdfs and the
  EI argmax — is ONE jitted function of ``(history arrays, key)``.  Structure
  (labels, distribution families, static params) is baked in at trace time;
  only the padded history arrays are data.
* Truncated GMM sampling is **inverse-CDF** (component choice reweighted by
  per-component truncated mass, then ``ndtri`` on a uniform in the truncated
  CDF interval) instead of the reference's rejection resampling loop — exact
  same distribution, but bounded, branchless and vmappable.
* Variable-length observation sets become fixed-capacity arrays + boolean
  masks (``Trials.padded_history``), so shapes are stable and the kernel
  recompiles only when the power-of-two capacity bucket grows.
* Multiple ``new_ids`` are proposed by ``vmap`` over folded RNG keys; the
  candidate axis scales to thousands (the reference is fixed at 24).

Behavioral parity is *distributional*, not bitwise: jax.random (threefry) ≠
numpy MT19937, and truncation-by-inversion ≠ truncation-by-rejection sample
paths.  Statistical tests (tests/test_tpe.py) check lpdf normalization,
sampler/lpdf agreement and optimizer performance, mirroring the reference's
own test doctrine (SURVEY.md §4).
"""

from __future__ import annotations

import functools
import math

import numpy as np

import jax
import jax.numpy as jnp
from jax.scipy.special import logsumexp, ndtri

from ..spaces import Dist, label_hash
from ..utils import LRUCache
from . import rand

__all__ = [
    "EPS",
    "suggest",
    "suggest_async",
    "suggest_sharded",
    "build_suggest_batched",
    "build_suggest_batched_wide",
    "cohort_cache_stats",
    "cohort_cache_contains",
    "cohort_key",
    "cohort_key_wide",
    "jit_cache_stats",
    "widened_profile",
    "widened_params",
    "build_propose_wide",
    "adaptive_parzen_normal",
    "linear_forgetting_weights",
    "normal_cdf",
    "lognormal_cdf",
    "gmm1_sample",
    "gmm1_lpdf",
    "lgmm1_sample",
    "lgmm1_lpdf",
    "categorical_posterior",
    "split_below_above",
    "build_propose",
    "build_propose_with_scores",
    "build_propose_candidates",
]

# -- reference defaults (hyperopt/tpe.py ≈L20-40, sym: _default_*) -----------
EPS = 1e-12
_default_prior_weight = 1.0
_default_n_startup_jobs = 20
_default_n_EI_candidates = 24
_default_gamma = 0.25
_default_linear_forgetting = 25

# f32-safe clip for inverse-CDF inputs (SURVEY.md §7.4: keep ndtri away from
# {0,1}); 1e-7 is ~16 ulp at 1.0 in float32.
_U_TINY = 1e-7


# ---------------------------------------------------------------------------
# scalar cdf helpers (hyperopt/tpe.py sym: normal_cdf, lognormal_cdf)
# ---------------------------------------------------------------------------


def normal_cdf(x, mu, sigma):
    z = (x - mu) / (jnp.sqrt(2.0) * sigma)
    return 0.5 * (1.0 + jax.lax.erf(z))


def lognormal_cdf(x, mu, sigma):
    """CDF at x>=0 of exp(N(mu, sigma)); 0 for x<=0."""
    x = jnp.maximum(x, 0.0)
    safe = jnp.maximum(x, EPS)
    return jnp.where(x > 0, normal_cdf(jnp.log(safe), mu, sigma), 0.0)


def _normal_logpdf(x, mu, sigma):
    return -0.5 * ((x - mu) / sigma) ** 2 - jnp.log(sigma) - 0.5 * jnp.log(2.0 * jnp.pi)


# ---------------------------------------------------------------------------
# adaptive Parzen fit (hyperopt/tpe.py sym: adaptive_parzen_normal,
# linear_forgetting_weights)
# ---------------------------------------------------------------------------


def linear_forgetting_weights(obs_mask, LF):
    """Per-slot forgetting weight, insertion order (tpe.py sym:
    linear_forgetting_weights).

    The reference ramps the oldest ``N-LF`` observations linearly from ``1/N``
    to 1 and keeps the newest ``LF`` at weight 1 (``np.linspace(1/N, 1,
    N-LF)`` + ones).  Here: positions are cumsum ranks over the boolean mask,
    so padding slots cost nothing and shapes stay static.
    """
    obs_mask = obs_mask.astype(jnp.float32)
    n = jnp.sum(obs_mask)
    pos = jnp.cumsum(obs_mask) - 1.0  # rank among live obs, insertion order
    n_ramp = n - LF
    denom = jnp.maximum(n_ramp - 1.0, 1.0)
    ramp = 1.0 / jnp.maximum(n, 1.0) + pos * (1.0 - 1.0 / jnp.maximum(n, 1.0)) / denom
    w = jnp.where(pos >= n_ramp, 1.0, ramp)
    w = jnp.where(n <= LF, 1.0, w)
    return w * obs_mask


def adaptive_parzen_normal(obs, obs_mask, prior_weight, prior_mu, prior_sigma, LF):
    """Fit the adaptive Parzen estimator (tpe.py sym: adaptive_parzen_normal).

    Returns ``(weights, mus, sigmas)`` of static length ``cap+1`` — the obs
    (masked) plus the prior component, sorted by location.  Semantics follow
    the reference: the prior is inserted at its sorted position with
    ``sigma=prior_sigma`` and weight ``prior_weight``; each observation's
    sigma is its larger neighbor gap in the sorted order, clipped to
    ``[prior_sigma / min(100, 1 + m), prior_sigma]`` with ``m`` the number of
    live components; observation weights use linear forgetting; weights are
    normalized to sum to 1.  (The reference's special-cased 1-observation
    branch — obs sigma = prior_sigma/2 — is subsumed by the general clip.)

    Dead (padding) slots get weight 0, mu=prior_mu, sigma=prior_sigma so no
    NaN/Inf can leak into downstream kernels.
    """
    cap = obs.shape[0]
    obs_mask = obs_mask.astype(bool)
    m_obs = jnp.sum(obs_mask)          # live observations
    m = m_obs + 1                      # live components incl. prior

    lfw = linear_forgetting_weights(obs_mask, LF)  # already masked
    big = jnp.float32(jnp.finfo(jnp.float32).max)
    vals_c = jnp.concatenate([jnp.where(obs_mask, obs, big), jnp.array([prior_mu])])
    wts_c = jnp.concatenate([lfw, jnp.array([jnp.float32(prior_weight)])])
    prior_c = jnp.concatenate([jnp.zeros(cap, bool), jnp.array([True])])

    order = jnp.argsort(vals_c)
    svals = vals_c[order]
    swts = wts_c[order]
    sprior = prior_c[order]

    idx = jnp.arange(cap + 1)
    prev_gap = svals - jnp.concatenate([svals[:1], svals[:-1]])
    next_gap = jnp.concatenate([svals[1:], svals[-1:]]) - svals
    prev_ok = (idx >= 1) & (idx < m)
    next_ok = idx < (m - 1)
    neg = jnp.float32(-1.0)
    sigma = jnp.maximum(
        jnp.where(prev_ok, prev_gap, neg), jnp.where(next_ok, next_gap, neg)
    )
    # single live component (prior only, m==1): no neighbor info -> prior
    # sigma.  With m>1 a zero gap (duplicate observations) stays 0 and is
    # raised to minsigma by the clip below — NOT to prior_sigma, else the
    # below-model could never concentrate on repeated good values.
    sigma = jnp.where(m == 1, prior_sigma, jnp.maximum(sigma, 0.0))

    maxsigma = jnp.float32(prior_sigma)
    minsigma = prior_sigma / jnp.minimum(100.0, 1.0 + m.astype(jnp.float32))
    sigma = jnp.clip(sigma, minsigma, maxsigma)
    sigma = jnp.where(sprior, prior_sigma, sigma)

    live = idx < m
    svals = jnp.where(live, svals, prior_mu)
    sigma = jnp.where(live, sigma, prior_sigma)
    swts = jnp.where(live, swts, 0.0)
    swts = swts / jnp.sum(swts)
    return swts, svals, sigma


# ---------------------------------------------------------------------------
# truncated GMM sample + lpdf (hyperopt/tpe.py sym: GMM1, GMM1_lpdf,
# LGMM1, LGMM1_lpdf) — inverse-CDF truncation instead of rejection
# ---------------------------------------------------------------------------


def _trunc_masses(weights, mus, sigmas, low, high):
    """Per-component in-bounds CDF mass and the mixture acceptance prob
    (the reference's ``p_accept``).  ``low``/``high`` are STATIC Python
    floats (±inf for unbounded) so truncation branches resolve at trace
    time — `jnp.float32(x)` inside a trace would produce a Tracer and break
    `math.isinf` checks."""
    alpha = normal_cdf(low, mus, sigmas) if math.isfinite(low) else jnp.zeros_like(mus)
    beta = normal_cdf(high, mus, sigmas) if math.isfinite(high) else jnp.ones_like(mus)
    mass = jnp.clip(beta - alpha, 0.0, 1.0)
    p_accept = jnp.sum(weights * mass)
    return alpha, beta, mass, p_accept


def gmm1_sample(key, weights, mus, sigmas, low, high, q, n_samples):
    """Draw ``n_samples`` from the truncated (optionally quantized) mixture.

    Reference (tpe.py sym: GMM1) truncates by rejection-resampling; here the
    component is drawn from weights reweighted by per-component truncated
    mass, then the sample is ``mu + sigma * ndtri(U(alpha, beta))`` — the
    exact truncated-mixture law, no loops.

    TPU note: the component draw is inverse-CDF over the (tiny) component
    table — a ``u > cdf`` compare-and-sum — and the per-sample (mu, sigma,
    alpha, beta) lookup is a one-hot matmul on the MXU.  Both replace
    per-sample gathers and the gumbel-max categorical, which dominated the
    kernel's device time (gathers serialize badly on TPU; measured ~1.4x
    whole-kernel win on v5e).
    """
    low, high = float(low), float(high)
    if q is None and math.isfinite(low) and math.isfinite(high):
        # the dominant (hp.uniform) case shares the traced-bounds kernel
        # with the grouped pipeline — one copy of the math
        return _gmm1_sample_bounded(key, weights, mus, sigmas, low, high,
                                    n_samples)
    alpha, beta, mass, _ = _trunc_masses(weights, mus, sigmas, low, high)
    w_trunc = weights * mass
    cdf = jnp.cumsum(w_trunc)
    cdf = cdf / jnp.maximum(cdf[-1], EPS)
    k_comp, k_u = jax.random.split(key)
    u_comp = jax.random.uniform(k_comp, (n_samples,))
    # component index = #{cdf entries < u}: zero-mass components have a
    # zero-width cdf step and are never selected (measure-zero ties aside)
    comp = jnp.sum(u_comp[:, None] > cdf[None, :], axis=1)
    comp = jnp.minimum(comp, weights.shape[0] - 1)
    onehot = (comp[:, None] == jnp.arange(weights.shape[0])[None, :]).astype(
        jnp.float32
    )
    table = jnp.stack([mus, sigmas, alpha, beta], axis=1)  # [m, 4]
    picked = onehot @ table  # [n_samples, 4] — MXU, not gather
    mu_s, sigma_s, a_s, b_s = picked[:, 0], picked[:, 1], picked[:, 2], picked[:, 3]
    u0 = jax.random.uniform(k_u, (n_samples,))
    u = a_s + u0 * (b_s - a_s)
    u = jnp.clip(u, _U_TINY, 1.0 - _U_TINY)
    x = mu_s + sigma_s * ndtri(u)
    if math.isfinite(low):
        x = jnp.maximum(x, low)
    if math.isfinite(high):
        # clamp strictly inside the half-open [low, high) support: a sample
        # clamped to exactly `high` would score lpdf -inf under both models
        # and poison the EI argmax with NaN
        x = jnp.minimum(x, float(np.nextafter(np.float32(high), np.float32(low))))
    if q is not None:
        x = jnp.round(x / q) * q
    return x


def gmm1_lpdf(x, weights, mus, sigmas, low, high, q):
    """Log-density of the truncated (quantized) mixture at ``x``
    (tpe.py sym: GMM1_lpdf).  Quantized case integrates each bin
    ``[x-q/2, x+q/2] ∩ [low, high]`` via cdf differences.

    TPU layout note: the [components, samples] orientation keeps the long
    sample axis minor (fully tiled into 128-wide lanes); a [samples, m]
    array with m ≈ cap+1 pads the minor dim up to 128 and wastes about half
    the VPU (measured ~1.2x whole-kernel win on v5e)."""
    low, high = float(low), float(high)
    if q is None and math.isfinite(low) and math.isfinite(high):
        return _gmm1_lpdf_bounded(x, weights, mus, sigmas, low, high)
    _, _, _, p_accept = _trunc_masses(weights, mus, sigmas, low, high)
    xT = x[None, :]  # [1, n] against [m, 1] components: samples stay minor
    if q is None:
        comp = jnp.log(jnp.maximum(weights, EPS))[:, None] + _normal_logpdf(
            xT, mus[:, None], sigmas[:, None]
        )
        comp = jnp.where(weights[:, None] > 0, comp, -jnp.inf)
        out = logsumexp(comp, axis=0) - jnp.log(jnp.maximum(p_accept, EPS))
        inb = jnp.ones(x.shape, bool)
        if math.isfinite(low):
            inb = inb & (x >= low)
        if math.isfinite(high):
            inb = inb & (x < high)
        return jnp.where(inb, out, -jnp.inf)
    ub = xT + q / 2
    lb = xT - q / 2
    if math.isfinite(high):
        ub = jnp.minimum(ub, high)
    if math.isfinite(low):
        lb = jnp.maximum(lb, low)
    prob = jnp.sum(
        weights[:, None]
        * (normal_cdf(ub, mus[:, None], sigmas[:, None])
           - normal_cdf(lb, mus[:, None], sigmas[:, None])),
        axis=0,
    )
    return jnp.log(jnp.maximum(prob, EPS)) - jnp.log(jnp.maximum(p_accept, EPS))


def lgmm1_sample(key, weights, mus, sigmas, low, high, q, n_samples):
    """Truncated lognormal mixture draw (tpe.py sym: LGMM1): the underlying
    normal is truncated to the log-space interval ``[low, high]``, the sample
    is its exp, optionally quantized in value space."""
    z = gmm1_sample(key, weights, mus, sigmas, low, high, None, n_samples)
    x = jnp.exp(z)
    if q is not None:
        x = jnp.round(x / q) * q
    return x


def lgmm1_lpdf(x, weights, mus, sigmas, low, high, q):
    """Log-density of the truncated lognormal mixture (tpe.py sym:
    LGMM1_lpdf).  ``low/high`` are log-space truncation bounds; the quantized
    case integrates value-space bins via ``lognormal_cdf`` with the lower
    edge clamped at 0 (the reference's qlognormal-includes-zero case)."""
    low, high = float(low), float(high)
    _, _, _, p_accept = _trunc_masses(weights, mus, sigmas, low, high)
    if q is None:
        safe = jnp.maximum(x, EPS)
        logx = jnp.log(safe)
        comp = jnp.log(jnp.maximum(weights, EPS))[:, None] + _normal_logpdf(
            logx[None, :], mus[:, None], sigmas[:, None]
        )
        comp = jnp.where(weights[:, None] > 0, comp, -jnp.inf)
        out = logsumexp(comp, axis=0) - logx - jnp.log(jnp.maximum(p_accept, EPS))
        inb = x > 0
        if math.isfinite(low):
            inb = inb & (logx >= low)
        if math.isfinite(high):
            inb = inb & (logx < high)
        return jnp.where(inb, out, -jnp.inf)
    xT = x[None, :]
    ub = xT + q / 2
    lb = jnp.maximum(xT - q / 2, 0.0)
    if math.isfinite(high):
        ub = jnp.minimum(ub, math.exp(high))
    if math.isfinite(low):
        lb = jnp.maximum(lb, math.exp(low))
    prob = jnp.sum(
        weights[:, None]
        * (lognormal_cdf(ub, mus[:, None], sigmas[:, None])
           - lognormal_cdf(lb, mus[:, None], sigmas[:, None])),
        axis=0,
    )
    return jnp.log(jnp.maximum(prob, EPS)) - jnp.log(jnp.maximum(p_accept, EPS))


# ---------------------------------------------------------------------------
# categorical / randint posterior (tpe.py sym: ap_categorical_sampler)
# ---------------------------------------------------------------------------


def categorical_posterior(obs, obs_mask, prior_p, prior_weight, LF):
    """Pseudocount-smoothed posterior over ``K = len(prior_p)`` buckets:
    ``counts(weighted by linear forgetting) + K * prior_weight * prior_p``,
    normalized (tpe.py sym: ap_categorical_sampler)."""
    K = prior_p.shape[0]
    lfw = linear_forgetting_weights(obs_mask, LF)
    onehot = jax.nn.one_hot(obs.astype(jnp.int32), K, dtype=jnp.float32)
    counts = jnp.sum(onehot * lfw[:, None], axis=0)
    pseudo = counts + K * prior_weight * prior_p
    return pseudo / jnp.sum(pseudo)


# ---------------------------------------------------------------------------
# below/above split (tpe.py sym: ap_split_trials)
# ---------------------------------------------------------------------------


def split_below_above(losses, has_loss, gamma, LF):
    """Boolean masks of the best ``n_below = min(ceil(gamma*sqrt(N)), LF)``
    trials vs. the rest, over trials that reported a loss."""
    cap = losses.shape[0]
    N = jnp.sum(has_loss)
    n_below = jnp.minimum(
        jnp.ceil(gamma * jnp.sqrt(N.astype(jnp.float32))), jnp.float32(LF)
    ).astype(jnp.int32)
    big = jnp.float32(jnp.finfo(jnp.float32).max)
    order = jnp.argsort(jnp.where(has_loss, losses, big))
    rank = jnp.zeros(cap, jnp.int32).at[order].set(jnp.arange(cap, dtype=jnp.int32))
    below = (rank < n_below) & has_loss
    above = has_loss & ~below
    return below, above


# ---------------------------------------------------------------------------
# per-family proposal (tpe.py sym: ap_uniform_sampler .. build_posterior)
# ---------------------------------------------------------------------------


def _parzen_from(dist: Dist):
    """Static (prior_mu, prior_sigma, low, high, q, log_space, obs_transform)
    for the numeric families (tpe.py sym: ap_*_sampler registry)."""
    fam, p = dist.family, dist.params
    inf = float("inf")
    if fam == "uniform":
        low, high = p
        return 0.5 * (low + high), high - low, low, high, None, False
    if fam == "quniform":
        low, high, q = p
        return 0.5 * (low + high), high - low, low, high, q, False
    if fam == "uniformint":
        # reference lowers hp.uniformint to quniform(low-0.5, high+0.5, q=1)
        low, high = p[0] - 0.5, p[1] + 0.5
        return 0.5 * (low + high), high - low, low, high, 1.0, False
    if fam == "loguniform":
        low, high = p  # log-space bounds
        return 0.5 * (low + high), high - low, low, high, None, True
    if fam == "qloguniform":
        low, high, q = p
        return 0.5 * (low + high), high - low, low, high, q, True
    if fam == "normal":
        mu, sigma = p
        return mu, sigma, -inf, inf, None, False
    if fam == "qnormal":
        mu, sigma, q = p
        return mu, sigma, -inf, inf, q, False
    if fam == "lognormal":
        mu, sigma = p
        return mu, sigma, -inf, inf, None, True
    if fam == "qlognormal":
        mu, sigma, q = p
        return mu, sigma, -inf, inf, q, True
    raise ValueError(f"no parzen prior for family {dist.family!r}")


def _stack_parzen_statics(parz):
    """Stack per-label ``_parzen_from`` tuples into the statics arrays
    the grouped pipelines consume — the ONE place that owns the
    placeholder conventions (unbounded groups never read low/high, so
    0.0 keeps the stacked arrays finite; unquantized labels carry
    q=1.0).  Shared by ``build_propose_with_scores`` (closed-over
    constants) and ``widened_params`` (runtime inputs): the widened
    kernel is pinned bitwise against the grouped path, so the two must
    never drift."""
    return {
        "prior_mu": np.asarray([p[0] for p in parz], np.float32),
        "prior_sigma": np.asarray([p[1] for p in parz], np.float32),
        "low": np.asarray(
            [p[2] if math.isfinite(p[2]) else 0.0 for p in parz],
            np.float32),
        "high": np.asarray(
            [p[3] if math.isfinite(p[3]) else 0.0 for p in parz],
            np.float32),
        "q": np.asarray(
            [p[4] if p[4] is not None else 1.0 for p in parz],
            np.float32),
        "islog": np.asarray([p[5] for p in parz], bool),
    }


def _prior_probs(dist: Dist) -> np.ndarray:
    """Static prior bucket probabilities for the discrete families."""
    if dist.family == "categorical":
        p = np.asarray(dist.params, np.float32)
        return p / p.sum()
    if dist.family == "randint":
        low, high = dist.params
        K = int(high) - int(low)
        return np.full(K, 1.0 / K, np.float32)
    raise ValueError(f"not a discrete family: {dist.family!r}")


def _select_candidate(key, samples, ei, cfg):
    """Pick ONE candidate from the EI scores.

    ``cfg["ei_select"]``:

    * ``"argmax"`` (default) — the reference's sequential semantics
      (tpe.py sym: broadcast_best): exploit the best-scoring candidate.
    * ``"softmax"`` — draw ``i ∝ softmax(EI / ei_tau)`` via the Gumbel-max
      trick.  Sequential TPE gets feedback after every proposal, so a hard
      argmax is right; a 10k-wide *batch* shares ONE posterior, and a hard
      argmax collapses every proposal onto the same marginal mode (measured:
      generations got WORSE than prior sampling, BENCH_r04
      ``parallel_trials_10k_tpe``).  Each vmapped proposal carries its own
      key, so stochastic selection spreads the batch across the whole EI
      landscape while still favoring high-EI regions — diversity exactly
      where the posterior is uncertain.

    ``cfg["prior_eps"]`` (handled by the callers): with that probability a
    proposal is replaced by a fresh draw from the search-space prior, so the
    above-model keeps seeing typical points and exploration never collapses
    even once the posterior is sharp (the batch analog of the reference's
    prior component inside the Parzen mixture).
    """
    if cfg.get("ei_select", "argmax") == "softmax":
        tau = float(cfg.get("ei_tau", 1.0))
        u = jax.random.uniform(
            jax.random.fold_in(key, 0x5E1EC7), ei.shape,
            minval=_U_TINY, maxval=1.0 - _U_TINY,
        )
        gumbel = -jnp.log(-jnp.log(u))
        i = jnp.argmax(ei / tau + gumbel)
    else:
        i = jnp.argmax(ei)
    return samples[i], ei[i]


def _mix_prior(key, cfg, val, ei_sel, draw, score):
    """With probability ``cfg['prior_eps']``, replace the selected candidate
    with a fresh search-space prior draw, scored under the same below/above
    models (see ``_select_candidate``'s docstring for why).  The RNG
    contract lives HERE and only here: ``fold_in(key, 0x9B10B)`` feeds the
    draw and ``fold_in(key, 0xE9510)`` the take-gate, so the grouped and
    per-label kernels stay draw-for-draw identical (the agreement tests
    depend on it).  ``draw(kp) -> scalar``; ``score(xs[1]) -> EI[1]``.

    Returns ``(value, EI, take)`` — the bool ``take`` flag feeds the
    health diagnostics (prior-fallback frequency); callers on the plain
    path drop it and XLA dead-code-eliminates it."""
    eps = float(cfg.get("prior_eps", 0.0))
    if eps <= 0.0:
        return val, ei_sel, jnp.zeros((), bool)
    xp = draw(jax.random.fold_in(key, 0x9B10B))
    ei_p = score(xp[None])[0]
    take = jax.random.uniform(jax.random.fold_in(key, 0xE9510), ()) < eps
    return jnp.where(take, xp, val), jnp.where(take, ei_p, ei_sel), take


def _diag_stats(samples, ei, ei_sel, wb, below_mask, prior_mass, LF, take,
                discrete=False):
    """Per-label HEALTH_STATS vector (obs/health.py sym: HEALTH_STATS) —
    EI quantiles, selected-candidate EI rank, duplicate-candidate rate,
    posterior shape (effective component count, prior-mass fraction) and
    the ε-prior take flag.

    Pure post-processing of arrays the proposal already computed: consumes
    NO RNG and leaves the selected value untouched, so the diagnostics
    variant of a kernel proposes bit-identically to the plain one
    (tests/test_health.py pins armed == disarmed trial sequences).

    ``wb``: the below model's normalized component weights (mixture
    components for numeric labels, posterior bucket probabilities for
    discrete ones); ``prior_mass``: the prior's unnormalized pseudocount
    mass (``prior_weight`` numeric, ``K * prior_weight`` discrete).
    """
    n = ei.shape[0]
    s = jnp.sort(ei)

    def q(p):
        return s[min(n - 1, int(round(p * (n - 1))))]

    sel_rank = jnp.sum(ei > ei_sel).astype(jnp.float32)
    if n > 1:
        sv = jnp.sort(samples.astype(jnp.float32))
        gaps = sv[1:] - sv[:-1]
        if discrete:
            dup = jnp.sum(gaps == 0.0) / (n - 1)
        else:
            scale = jnp.maximum(sv[-1] - sv[0], EPS)
            dup = jnp.sum(gaps <= 1e-6 * scale) / (n - 1)
    else:
        dup = jnp.float32(0.0)
    eff = 1.0 / jnp.maximum(jnp.sum(wb * wb), EPS)
    obs_mass = jnp.sum(linear_forgetting_weights(below_mask, LF))
    pm = jnp.float32(prior_mass)
    prior_frac = pm / jnp.maximum(obs_mass + pm, EPS)
    return jnp.stack([
        q(0.10), q(0.50), q(0.90), s[-1],
        sel_rank, dup.astype(jnp.float32), eff.astype(jnp.float32),
        prior_frac, take.astype(jnp.float32),
    ])


def _prior_draw_numeric(key, prior_mu, prior_sigma, low, high, q, log_space):
    """One draw from the search-space PRIOR of a numeric family (the
    distribution ``rand.suggest`` samples): uniform over finite bounds,
    ``N(mu, sigma)`` for the unbounded normal families; exp for log-space
    families, then quantization.  ``low``/``high`` must be STATIC floats —
    the per-label kernel's contract (the grouped pipeline draws inline with
    its own static ``bounded`` flag; see ``_propose_numeric_group``)."""
    low, high = float(low), float(high)  # a traced bound raises here, loudly
    if math.isfinite(low) and math.isfinite(high):
        u = jax.random.uniform(key, (), minval=0.0, maxval=1.0 - _U_TINY)
        z = low + u * (high - low)
    else:
        z = prior_mu + prior_sigma * jax.random.normal(key, ())
    x = jnp.exp(z) if log_space else z
    if q is not None:
        x = jnp.round(x / q) * q
    return x


def _pallas_armed():
    """Hand-scheduled EI is opt-in via ``HYPEROPT_TPU_MEGAKERNEL`` (or the
    deprecated ``HYPEROPT_TPU_PALLAS=1`` alias): the un-quantized numeric
    EI score routes through ``megakernel.ei_diff`` — the large-component
    regime where the jnp path's ``[m, n]`` intermediate stops fitting VMEM
    (docs/DESIGN.md §25 "when hand-scheduling pays").  Checked at TRACE
    time; callers that cache traced programs must fold this flag into
    their cache key."""
    from .._env import parse_megakernel, parse_pallas

    return parse_pallas() or parse_megakernel() != "off"


def _ei_pallas(samples, log_space, wb, mb, sb, wa, ma, sa, low, high):
    """EI = lpdf_below − lpdf_above via ``megakernel.ei_diff`` for the
    un-quantized families.  The kernel computes the raw two-mixture
    log-density difference; the truncation normalizers (``log p_accept``)
    are scalars applied here, and the per-sample Jacobian of the log-space
    density cancels in the difference — so this matches the jnp path's
    math exactly (up to fp reassociation; tests pin 1e-4 agreement)."""
    from .. import megakernel

    x = jnp.log(jnp.maximum(samples, EPS)) if log_space else samples
    _, _, _, pb = _trunc_masses(wb, mb, sb, low, high)
    _, _, _, pa = _trunc_masses(wa, ma, sa, low, high)
    return (megakernel.ei_diff(x, wb, mb, sb, wa, ma, sa)
            - jnp.log(jnp.maximum(pb, EPS)) + jnp.log(jnp.maximum(pa, EPS)))


def _propose_numeric(key, dist, vals, below_mask, above_mask, cfg,
                     diag=False, raw=False):
    """Sample candidates from the below model, score EI = llik_below −
    llik_above, return ``(selected candidate, its EI)`` (tpe.py sym:
    broadcast_best; selection policy: ``_select_candidate``).  The EI score
    is what cross-shard argmax reductions consume (parallel/sharding.py).
    ``diag=True`` appends the per-label health stats vector
    (``_diag_stats``) — same proposal, one extra output.  ``raw=True``
    returns the whole ``(samples, ei)`` candidate pool pre-selection —
    what the sharded candidate axis pools across devices before its own
    masked top-k select."""
    prior_mu, prior_sigma, low, high, q, log_space = _parzen_from(dist)
    obs = vals
    if log_space:
        obs = jnp.log(jnp.maximum(vals, EPS))
    fit = functools.partial(
        adaptive_parzen_normal,
        prior_weight=cfg["prior_weight"],
        prior_mu=jnp.float32(prior_mu),
        prior_sigma=jnp.float32(prior_sigma),
        LF=cfg["LF"],
    )
    wb, mb, sb = fit(obs, below_mask)
    wa, ma, sa = fit(obs, above_mask)
    n_cand = cfg["n_EI_candidates"]
    if log_space:
        samples = lgmm1_sample(key, wb, mb, sb, low, high, q, n_cand)
    else:
        samples = gmm1_sample(key, wb, mb, sb, low, high, q, n_cand)
    if q is None and _pallas_armed():
        ei = _ei_pallas(samples, log_space, wb, mb, sb, wa, ma, sa, low,
                        high)
    else:
        lpdf = lgmm1_lpdf if log_space else gmm1_lpdf
        ll_b = lpdf(samples, wb, mb, sb, low, high, q)
        ll_a = lpdf(samples, wa, ma, sa, low, high, q)
        ei = ll_b - ll_a
    ei = jnp.where(jnp.isnan(ei), -jnp.inf, ei)  # -inf − -inf must never win
    if raw:
        return samples, ei
    val, ei_sel = _select_candidate(key, samples, ei, cfg)
    lpdf = lgmm1_lpdf if log_space else gmm1_lpdf
    out, ei_out, take = _mix_prior(
        key, cfg, val, ei_sel,
        lambda kp: _prior_draw_numeric(kp, prior_mu, prior_sigma, low, high,
                                       q, log_space),
        lambda xs: (lpdf(xs, wb, mb, sb, low, high, q)
                    - lpdf(xs, wa, ma, sa, low, high, q)),
    )
    if not diag:
        return out, ei_out
    stats = _diag_stats(samples, ei, ei_sel, wb, below_mask,
                        cfg["prior_weight"], cfg["LF"], take)
    return out, ei_out, stats


def _gmm1_sample_bounded(key, weights, mus, sigmas, low, high, n_samples):
    """``gmm1_sample`` for the finite-bounds, unquantized case with bounds
    that may be TRACED scalars (the grouped pipeline vmaps over labels, so
    ``low``/``high`` are in-trace values, not Python floats).  The static
    path delegates here so the kernel math exists exactly once; samples
    clamp to ``nextafter(high, low)`` — strictly inside the half-open
    support, else a sample at exactly ``high`` scores lpdf -inf under both
    models and poisons the EI argmax with NaN."""
    low = jnp.asarray(low, jnp.float32)
    high = jnp.asarray(high, jnp.float32)
    alpha = normal_cdf(low, mus, sigmas)
    beta = normal_cdf(high, mus, sigmas)
    mass = jnp.clip(beta - alpha, 0.0, 1.0)
    w_trunc = weights * mass
    cdf = jnp.cumsum(w_trunc)
    cdf = cdf / jnp.maximum(cdf[-1], EPS)
    k_comp, k_u = jax.random.split(key)
    u_comp = jax.random.uniform(k_comp, (n_samples,))
    comp = jnp.sum(u_comp[:, None] > cdf[None, :], axis=1)
    comp = jnp.minimum(comp, weights.shape[0] - 1)
    onehot = (comp[:, None] == jnp.arange(weights.shape[0])[None, :]).astype(
        jnp.float32
    )
    table = jnp.stack([mus, sigmas, alpha, beta], axis=1)
    picked = onehot @ table
    mu_s, sigma_s, a_s, b_s = picked[:, 0], picked[:, 1], picked[:, 2], picked[:, 3]
    u0 = jax.random.uniform(k_u, (n_samples,))
    u = a_s + u0 * (b_s - a_s)
    u = jnp.clip(u, _U_TINY, 1.0 - _U_TINY)
    x = mu_s + sigma_s * ndtri(u)
    return jnp.clip(x, low, jnp.nextafter(high, low))


def _gmm1_lpdf_bounded(x, weights, mus, sigmas, low, high):
    """``gmm1_lpdf`` (q=None) with traced finite bounds; formula-identical
    to the static-bounds path."""
    alpha = normal_cdf(low, mus, sigmas)
    beta = normal_cdf(high, mus, sigmas)
    p_accept = jnp.sum(weights * jnp.clip(beta - alpha, 0.0, 1.0))
    comp = jnp.log(jnp.maximum(weights, EPS))[:, None] + _normal_logpdf(
        x[None, :], mus[:, None], sigmas[:, None]
    )
    comp = jnp.where(weights[:, None] > 0, comp, -jnp.inf)
    out = logsumexp(comp, axis=0) - jnp.log(jnp.maximum(p_accept, EPS))
    inb = (x >= low) & (x < high)
    return jnp.where(inb, out, -jnp.inf)


def _gmm1_sample_unbounded(key, weights, mus, sigmas, n_samples):
    """``gmm1_sample`` for the unbounded (normal/lognormal prior) families,
    expressed with no bound inputs so a GROUP of such labels can vmap over
    traced (mu, sigma) statics.  Draw-for-draw identical to the static
    general path with ``low=-inf, high=+inf`` (there, alpha=0/beta=1 make
    ``u = a + u0*(b-a)`` collapse to ``u0`` exactly)."""
    cdf = jnp.cumsum(weights)
    cdf = cdf / jnp.maximum(cdf[-1], EPS)
    k_comp, k_u = jax.random.split(key)
    u_comp = jax.random.uniform(k_comp, (n_samples,))
    comp = jnp.sum(u_comp[:, None] > cdf[None, :], axis=1)
    comp = jnp.minimum(comp, weights.shape[0] - 1)
    onehot = (comp[:, None] == jnp.arange(weights.shape[0])[None, :]).astype(
        jnp.float32
    )
    picked = onehot @ jnp.stack([mus, sigmas], axis=1)
    u = jnp.clip(jax.random.uniform(k_u, (n_samples,)), _U_TINY, 1.0 - _U_TINY)
    return picked[:, 0] + picked[:, 1] * ndtri(u)


def _gmm1_lpdf_unbounded(x, weights, mus, sigmas):
    """``gmm1_lpdf`` (q=None, no truncation) with traced component params;
    formula-identical to the static path at infinite bounds (p_accept =
    sum(weights))."""
    comp = jnp.log(jnp.maximum(weights, EPS))[:, None] + _normal_logpdf(
        x[None, :], mus[:, None], sigmas[:, None]
    )
    comp = jnp.where(weights[:, None] > 0, comp, -jnp.inf)
    return logsumexp(comp, axis=0) - jnp.log(jnp.maximum(jnp.sum(weights), EPS))


def _q_lpdf_group(x, weights, mus, sigmas, lo, hi, q, islog, bounded,
                  has_log=True):
    """Quantized-bin log-density with TRACED statics, matching the static
    ``gmm1_lpdf``/``lgmm1_lpdf`` q-paths bin for bin: each value-space bin
    ``[x-q/2, x+q/2]`` is integrated by cdf differences — normal cdf on the
    (traced-)bounded support for linear families, lognormal cdf with the
    lower edge clamped at 0 for log families; ``islog`` selects per label.
    ``bounded`` and ``has_log`` are static per GROUP (the quantized normal
    families have no truncation and p_accept = sum(weights); a group with
    no log labels skips the dead lognormal branch entirely)."""
    xT = x[None, :]
    ub, lb = xT + q / 2, xT - q / 2
    ubn, lbn = (jnp.minimum(ub, hi), jnp.maximum(lb, lo)) if bounded else (ub, lb)
    pn = jnp.sum(
        weights[:, None]
        * (normal_cdf(ubn, mus[:, None], sigmas[:, None])
           - normal_cdf(lbn, mus[:, None], sigmas[:, None])),
        axis=0,
    )
    if has_log:
        lbl = jnp.maximum(lb, 0.0)
        ubl, lbl = ((jnp.minimum(ub, jnp.exp(hi)), jnp.maximum(lbl, jnp.exp(lo)))
                    if bounded else (ub, lbl))
        pl = jnp.sum(
            weights[:, None]
            * (lognormal_cdf(ubl, mus[:, None], sigmas[:, None])
               - lognormal_cdf(lbl, mus[:, None], sigmas[:, None])),
            axis=0,
        )
        prob = jnp.where(islog, pl, pn)
    else:
        prob = pn
    if bounded:
        alpha = normal_cdf(lo, mus, sigmas)
        beta = normal_cdf(hi, mus, sigmas)
        p_accept = jnp.sum(weights * jnp.clip(beta - alpha, 0.0, 1.0))
    else:
        p_accept = jnp.sum(weights)
    return jnp.log(jnp.maximum(prob, EPS)) - jnp.log(jnp.maximum(p_accept, EPS))


def _propose_numeric_group(keys, obs, below, above, statics, cfg,
                           quantized, bounded, has_log=True, diag=False):
    """One vmapped proposal pipeline for a whole GROUP of numeric labels
    sharing a (quantized?, bounded?) shape.

    Per-label, this is the same math as ``_propose_numeric`` — same key
    derivation, same Parzen fit, same sampler and EI — but expressed ONCE
    and vmapped over the label axis instead of unrolled per label, so the
    traced program (and its XLA compile time) stays constant as the label
    count grows (round-4 grouped only ``hp.uniform``; round 5 extends to
    every numeric family, with q/log/bounds as traced statics and the
    quantized/bounded branch structure static per group).  The Parzen fit,
    sampling, EI selection and eps-prior mixing all run in z-space (log
    space for log families; the Jacobian term of the log-space density
    cancels inside ``EI = ll_below − ll_above``, so EI scores match the
    per-label path exactly); quantization happens in value space as in the
    static kernels.  Tests assert per-family agreement with the unrolled
    path."""

    def one(key, obs_l, b_l, a_l, pmu, psig, lo, hi, q, islog):
        def to_value(z):
            """z-space -> value space (identity for linear labels; skipped
            statically when the group has no log labels)."""
            return jnp.where(islog, jnp.exp(z), z) if has_log else z

        obs_z = (jnp.where(islog, jnp.log(jnp.maximum(obs_l, EPS)), obs_l)
                 if has_log else obs_l)
        fit = functools.partial(
            adaptive_parzen_normal,
            prior_weight=cfg["prior_weight"],
            prior_mu=pmu,
            prior_sigma=psig,
            LF=cfg["LF"],
        )
        wb, mb, sb = fit(obs_z, b_l)
        wa, ma, sa = fit(obs_z, a_l)
        n_cand = cfg["n_EI_candidates"]
        if bounded:
            z = _gmm1_sample_bounded(key, wb, mb, sb, lo, hi, n_cand)
        else:
            z = _gmm1_sample_unbounded(key, wb, mb, sb, n_cand)
        if quantized:
            sel = jnp.round(to_value(z) / q) * q

            def score(xs):
                return (_q_lpdf_group(xs, wb, mb, sb, lo, hi, q, islog,
                                      bounded, has_log)
                        - _q_lpdf_group(xs, wa, ma, sa, lo, hi, q, islog,
                                        bounded, has_log))
        elif bounded:
            sel = z

            def score(xs):
                return (_gmm1_lpdf_bounded(xs, wb, mb, sb, lo, hi)
                        - _gmm1_lpdf_bounded(xs, wa, ma, sa, lo, hi))
        else:
            sel = z

            def score(xs):
                return (_gmm1_lpdf_unbounded(xs, wb, mb, sb)
                        - _gmm1_lpdf_unbounded(xs, wa, ma, sa))

        ei = score(sel)
        ei = jnp.where(jnp.isnan(ei), -jnp.inf, ei)
        val, ei_sel = _select_candidate(key, sel, ei, cfg)

        def draw(kp):
            if bounded:
                u = jax.random.uniform(kp, (), minval=0.0,
                                       maxval=1.0 - _U_TINY)
                zp = lo + u * (hi - lo)
            else:
                zp = pmu + psig * jax.random.normal(kp, ())
            return jnp.round(to_value(zp) / q) * q if quantized else zp

        val, ei_out, take = _mix_prior(key, cfg, val, ei_sel, draw, score)
        if not quantized:
            val = to_value(val)
        if not diag:
            return val, ei_out
        stats = _diag_stats(sel, ei, ei_sel, wb, b_l, cfg["prior_weight"],
                            cfg["LF"], take)
        return val, ei_out, stats

    return jax.vmap(one)(
        keys, obs, below, above,
        statics["prior_mu"], statics["prior_sigma"],
        statics["low"], statics["high"], statics["q"], statics["islog"],
    )


def _propose_discrete_group(keys, obs, below, above, prior_ps, offsets, cfg,
                            diag=False):
    """Vmapped ``_propose_discrete`` for a GROUP of discrete labels sharing
    one bucket count K (the static shape); prior probabilities and randint
    offsets ride the label axis as traced statics."""
    K = prior_ps.shape[1]

    def one(key, obs_l, b_l, a_l, prior_p, offset):
        obs_i = obs_l.astype(jnp.int32) - offset
        pb = categorical_posterior(obs_i, b_l, prior_p, cfg["prior_weight"],
                                   cfg["LF"])
        pa = categorical_posterior(obs_i, a_l, prior_p, cfg["prior_weight"],
                                   cfg["LF"])
        n_cand = cfg["n_EI_candidates"]
        cdf = jnp.cumsum(pb)
        cdf = cdf / jnp.maximum(cdf[-1], EPS)
        u = jax.random.uniform(key, (n_cand,))
        samples = jnp.minimum(jnp.sum(u[:, None] > cdf[None, :], axis=1), K - 1)
        onehot = (samples[:, None] == jnp.arange(K)[None, :]).astype(jnp.float32)
        logs = onehot @ jnp.stack(
            [jnp.log(jnp.maximum(pb, EPS)), jnp.log(jnp.maximum(pa, EPS))],
            axis=1,
        )
        ei = logs[:, 0] - logs[:, 1]
        ei = jnp.where(jnp.isnan(ei), -jnp.inf, ei)
        val, ei_sel = _select_candidate(key, samples, ei, cfg)
        val, ei_out, take = _mix_prior(
            key, cfg, val, ei_sel,
            functools.partial(_prior_draw_discrete, prior_p=prior_p),
            lambda xs: ((xs[:, None] == jnp.arange(K)[None, :]).astype(
                jnp.float32)
                @ (jnp.log(jnp.maximum(pb, EPS))
                   - jnp.log(jnp.maximum(pa, EPS)))),
        )
        if not diag:
            return val + offset, ei_out
        stats = _diag_stats(samples, ei, ei_sel, pb, b_l,
                            K * cfg["prior_weight"], cfg["LF"], take,
                            discrete=True)
        return val + offset, ei_out, stats

    return jax.vmap(one)(keys, obs, below, above, prior_ps, offsets)


def _prior_draw_discrete(kp, prior_p):
    """One inverse-cdf bucket draw from the discrete prior."""
    K = prior_p.shape[0]
    cdfp = jnp.cumsum(prior_p)
    cdfp = cdfp / jnp.maximum(cdfp[-1], EPS)
    up = jax.random.uniform(kp, ())
    return jnp.minimum(jnp.sum(up > cdfp), K - 1)


def _propose_discrete(key, dist, vals, below_mask, above_mask, cfg,
                      diag=False, raw=False):
    prior_p = jnp.asarray(_prior_probs(dist))
    offset = 0
    if dist.family == "randint":
        offset = int(dist.params[0])
    obs = vals.astype(jnp.int32) - offset
    pb = categorical_posterior(obs, below_mask, prior_p, cfg["prior_weight"], cfg["LF"])
    pa = categorical_posterior(obs, above_mask, prior_p, cfg["prior_weight"], cfg["LF"])
    n_cand = cfg["n_EI_candidates"]
    # inverse-CDF bucket draw + one-hot lookup (same gather-free idiom as
    # gmm1_sample: per-sample gathers from a small table serialize on TPU)
    K = prior_p.shape[0]
    cdf = jnp.cumsum(pb)
    cdf = cdf / jnp.maximum(cdf[-1], EPS)
    u = jax.random.uniform(key, (n_cand,))
    samples = jnp.minimum(jnp.sum(u[:, None] > cdf[None, :], axis=1), K - 1)
    onehot = (samples[:, None] == jnp.arange(K)[None, :]).astype(jnp.float32)
    # clamp the logs: a zero-probability bucket would make the one-hot
    # matmul compute 0 * -inf = NaN for EVERY candidate.  The clamp never
    # actually binds: categorical_posterior smooths with ``+ K *
    # prior_weight * prior_p``, so every bucket's posterior is at least
    # ``K * prior_weight * min(prior_p) / total`` ≫ EPS for any real prior
    # (test_tpe.py::test_categorical_posterior_floor asserts the bound) —
    # it is a NaN guard for hostile priors only, not a reweighting of ties
    logs = onehot @ jnp.stack(
        [jnp.log(jnp.maximum(pb, EPS)), jnp.log(jnp.maximum(pa, EPS))], axis=1
    )
    ei = logs[:, 0] - logs[:, 1]
    ei = jnp.where(jnp.isnan(ei), -jnp.inf, ei)
    if raw:
        return samples + offset, ei
    val, ei_sel = _select_candidate(key, samples, ei, cfg)
    val, ei_out, take = _mix_prior(
        key, cfg, val, ei_sel,
        functools.partial(_prior_draw_discrete, prior_p=prior_p),
        lambda xs: ((xs[:, None] == jnp.arange(K)[None, :]).astype(jnp.float32)
                    @ (jnp.log(jnp.maximum(pb, EPS))
                       - jnp.log(jnp.maximum(pa, EPS)))),
    )
    if not diag:
        return val + offset, ei_out
    stats = _diag_stats(samples, ei, ei_sel, pb, below_mask,
                        prior_p.shape[0] * cfg["prior_weight"], cfg["LF"],
                        take, discrete=True)
    return val + offset, ei_out, stats


def _read_vals(history, label, qparams=None):
    """f32 view of one label's vals column — THE kernel read boundary for
    compressed history (docs/DESIGN.md §13/§25): float storage (f32/bf16)
    upcasts, int8/fp8 codes affine-decode with the label's baked
    ``(scale, zero, islog)``.  The branch is decided at TRACE time from
    the leaf dtype, so a degraded (bf16) history and an armed (quantized)
    one compile distinct-but-correct programs from the same builder."""
    v = jnp.asarray(history["vals"][label])
    if qparams is not None:
        from .. import quant

        if quant.quant_dtype_name(v.dtype) is not None:
            return quant.dequantize(v, qparams[label])
    return v.astype(jnp.float32)


def _quant_qparams(cs, hist_dtype):
    """Per-label qparams for a RESOLVED storage name (None unless
    ``hist_dtype`` is int8/fp8) — deterministic from (space, name), which
    is why jit cache keys only need the name, not the values."""
    if hist_dtype is None:
        return None
    from .. import quant

    if not quant.is_quant_name(hist_dtype):
        return None
    return quant.space_qparams(cs, hist_dtype)


def build_propose_with_scores(cs, cfg, group=True, diagnostics=False,
                              qparams=None):
    """Compile one proposal step returning per-label ``(value, EI score)``.

    ``diagnostics=True`` builds the health-instrumented variant:
    ``propose(history, key) -> (out, diag)`` where ``diag`` carries the
    per-label HEALTH_STATS vectors plus the below/above split sizes (see
    ``_diag_stats`` / obs/health.py).  Proposals are bit-identical to the
    plain variant — the diagnostics are pure post-processing, no extra RNG
    — but the traced program differs, so armed and disarmed asks live
    under separate jit cache keys and the disarmed hot path never pays
    for the instrumentation.

    The EI scores feed cross-shard argmax reductions
    (``parallel/sharding.py``); ``build_propose`` below drops them for the
    plain ask path.  ``group=True`` (default) routes labels through vmapped
    per-GROUP pipelines instead of unrolling a copy of the kernel per label
    — same math and same per-label RNG keys, but the traced program size
    (and XLA compile time) stops growing with the label count.  Groups:
    numeric labels sharing a (quantized?, bounded?) branch shape (q, log
    flag and bounds become traced statics; round 4 grouped only
    ``hp.uniform``, measured 39.7 s → 21.7 s cold on a 28-label space), and
    discrete labels sharing a bucket count K.  A family with a single label
    keeps the per-label kernel (a width-1 vmap saves nothing).
    ``group=False`` forces the per-label path (used by the agreement
    tests); ``group="all"`` routes EVERY label through the grouped
    pipelines, singleton families included (width-1 vmaps) — the
    label-layout the widened cohort kernel (:func:`build_propose_wide`)
    is pinned bitwise against."""
    by_gkey = {}
    if group:
        for l in cs.labels:
            dist = cs.params[l].dist
            if dist.family in ("categorical", "randint"):
                gkey = ("disc", len(_prior_probs(dist)))
            else:
                _, _, low, high, q, _ = _parzen_from(dist)
                gkey = ("num", q is not None,
                        math.isfinite(low) and math.isfinite(high))
            by_gkey.setdefault(gkey, []).append(l)
        if group != "all":
            by_gkey = {k: ls for k, ls in by_gkey.items() if len(ls) >= 2}
    grouped = {l for ls in by_gkey.values() for l in ls}

    numeric_groups = []  # (labels, quantized, bounded, statics)
    disc_groups = []     # (labels, prior_ps[G, K], offsets[G])
    for gkey, ls in by_gkey.items():
        if gkey[0] == "disc":
            prior_ps = np.stack([_prior_probs(cs.params[l].dist) for l in ls])
            offsets = np.asarray(
                [int(cs.params[l].dist.params[0])
                 if cs.params[l].dist.family == "randint" else 0
                 for l in ls], np.int32)
            disc_groups.append((ls, jnp.asarray(prior_ps), jnp.asarray(offsets)))
        else:
            _, quantized, bounded = gkey
            parz = [_parzen_from(cs.params[l].dist) for l in ls]
            statics = {k: jnp.asarray(v)
                       for k, v in _stack_parzen_statics(parz).items()}
            has_log = any(p[5] for p in parz)
            numeric_groups.append((ls, quantized, bounded, has_log, statics))

    def propose(history, key):
        # f32 accumulation boundary: the resident history may be stored in
        # a compressed dtype (HYPEROPT_TPU_HIST_DTYPE=bf16); every kernel
        # consumes it upcast to float32 so the Parzen fit / EI math is
        # unchanged — only the HBM-resident bytes shrink
        losses = jnp.asarray(history["losses"]).astype(jnp.float32)
        has_loss = jnp.asarray(history["has_loss"])
        below, above = split_below_above(losses, has_loss, cfg["gamma"], cfg["LF"])
        out = {}
        stats = {}

        def stacked(ls):
            keys = jnp.stack([
                jax.random.fold_in(key, label_hash(l)) for l in ls
            ])
            obs = jnp.stack([_read_vals(history, l, qparams) for l in ls])
            act = jnp.stack([jnp.asarray(history["active"][l]) for l in ls])
            return keys, obs, below[None, :] & act, above[None, :] & act

        for ls, quantized, bounded, has_log, statics in numeric_groups:
            res = _propose_numeric_group(
                *stacked(ls), statics, cfg, quantized, bounded, has_log,
                diag=diagnostics)
            for i, l in enumerate(ls):
                out[l] = (res[0][i], res[1][i])
                if diagnostics:
                    stats[l] = res[2][i]
        for ls, prior_ps, offsets in disc_groups:
            res = _propose_discrete_group(
                *stacked(ls), prior_ps, offsets, cfg, diag=diagnostics)
            for i, l in enumerate(ls):
                out[l] = (res[0][i], res[1][i])
                if diagnostics:
                    stats[l] = res[2][i]
        for label in cs.labels:
            if label in grouped:
                continue
            info = cs.params[label]
            vals = _read_vals(history, label, qparams)
            active = jnp.asarray(history["active"][label])
            k = jax.random.fold_in(key, label_hash(label))
            b = below & active
            a = above & active
            if info.dist.family in ("categorical", "randint"):
                res = _propose_discrete(k, info.dist, vals, b, a, cfg,
                                        diag=diagnostics)
            else:
                res = _propose_numeric(k, info.dist, vals, b, a, cfg,
                                       diag=diagnostics)
            out[label] = res[:2] if diagnostics else res
            if diagnostics:
                stats[label] = res[2]
        if diagnostics:
            return out, {"stats": stats,
                         "n_below": jnp.sum(below).astype(jnp.int32),
                         "n_above": jnp.sum(above).astype(jnp.int32)}
        return out

    return propose


def build_propose(cs, cfg, group=True, qparams=None):
    """Compile one proposal step for a CompiledSpace.

    Returns a pure function ``propose(history, key) -> {label: value}``:
    the full TPE posterior for every hyperparameter, evaluated jointly in one
    XLA program — the jitted replacement for the reference's per-call
    ``build_posterior`` graph surgery + ``rec_eval`` interpretation
    (tpe.py sym: build_posterior, suggest).  See
    ``build_propose_with_scores`` for the grouped-pipeline details.
    """
    scored = build_propose_with_scores(cs, cfg, group=group, qparams=qparams)

    def propose(history, key):
        return {l: v for l, (v, _) in scored(history, key).items()}

    return propose


def build_propose_candidates(cs, cfg, qparams=None):
    """Compile the RAW candidate pool: ``propose(history, key) -> {label:
    (samples[n_EI_candidates], ei[n_EI_candidates])}`` — the
    selection-free variant of :func:`build_propose_with_scores`.

    This is what the sharded candidate axis consumes
    (``parallel/sharding.py``): each device draws and scores a LOCAL pool
    with this kernel, then masks padding candidates and selects across
    devices AFTER an all-gather of per-shard top-k — the select cannot
    live inside the per-device kernel.  Per-label kernels (not the grouped
    pipeline): the sharded path runs few labels against very wide
    candidate axes, the regime where per-label trace size is irrelevant
    and the hand-scheduled EI opt-in (``HYPEROPT_TPU_MEGAKERNEL``, or the
    deprecated ``HYPEROPT_TPU_PALLAS=1``) applies."""

    def propose(history, key):
        losses = jnp.asarray(history["losses"]).astype(jnp.float32)
        has_loss = jnp.asarray(history["has_loss"])
        below, above = split_below_above(losses, has_loss, cfg["gamma"],
                                         cfg["LF"])
        out = {}
        for label in cs.labels:
            info = cs.params[label]
            vals = _read_vals(history, label, qparams)
            active = jnp.asarray(history["active"][label])
            k = jax.random.fold_in(key, label_hash(label))
            b = below & active
            a = above & active
            if info.dist.family in ("categorical", "randint"):
                out[label] = _propose_discrete(k, info.dist, vals, b, a,
                                               cfg, raw=True)
            else:
                out[label] = _propose_numeric(k, info.dist, vals, b, a,
                                              cfg, raw=True)
        return out

    return propose


# (space signature, cfg) -> fused tell+ask program; LRU-bounded — every
# entry pins a compiled XLA executable
_suggest_jit_cache = LRUCache(32)


def _apply_rows(labels, history, rows, qparams=None):
    """Fold packed trial rows (see ``PaddedHistory._pack_row``) into the
    history arrays in-trace.  Padding rows carry an out-of-bounds index and
    are dropped by ``mode='drop'``.  One VECTORIZED scatter per array (the
    row indices are distinct by construction — every real row targets its
    own trial slot): the traced program size is independent of the row
    bucket, so the bucket can be a single fixed size and the fused
    tell+ask program compiles exactly once per space."""
    L = len(labels)
    idx = rows[:, 2 * L + 2].astype(jnp.int32)  # [K]

    # .astype(leaf dtype): rows arrive f32; a compressed (bf16) resident
    # history takes the scatter in its own storage dtype.  An int8/fp8
    # leaf instead takes the AFFINE ENCODE (quant.quantize) — the rows
    # hold snapped grid values (PaddedHistory.append), so in-trace encode
    # and host encode agree bitwise.
    def vcol(l, j):
        leaf = history["vals"][l]
        if qparams is not None:
            from .. import quant

            qname = quant.quant_dtype_name(leaf.dtype)
            if qname is not None:
                return leaf.at[idx].set(
                    quant.quantize(rows[:, j], qparams[l], qname),
                    mode="drop")
        return leaf.at[idx].set(rows[:, j].astype(leaf.dtype), mode="drop")

    return {
        "vals": {l: vcol(l, j) for j, l in enumerate(labels)},
        "active": {
            l: history["active"][l].at[idx].set(rows[:, L + j] > 0.5, mode="drop")
            for j, l in enumerate(labels)
        },
        "losses": history["losses"].at[idx].set(
            rows[:, 2 * L].astype(history["losses"].dtype), mode="drop"),
        "has_loss": history["has_loss"].at[idx].set(rows[:, 2 * L + 1] > 0.5,
                                                    mode="drop"),
    }


def _donation_enabled():
    """Buffer donation of the history pytree into the fused tell+ask
    program (in-place scatter instead of a cap-sized copy per tick).
    ``HYPEROPT_TPU_NO_DONATION=1`` opts out for backends where XLA cannot
    alias the update (donation is then silently a copy anyway, but the
    flag also silences per-call unusable-donation warnings)."""
    import os

    return os.environ.get("HYPEROPT_TPU_NO_DONATION",
                          "").strip().lower() in ("", "0", "false", "no")


def _get_suggest_jit(domain, cfg_key, cfg, diag=False, donate=True,
                     mesh=None, shard_history=False, hist_dtype=None):
    """The fused tell+ask program:
    ``run(history, rows, seed_words[2], ids[B]) -> (history', packed[B, L])``.

    One device program per ask→tell iteration: it folds the just-completed
    trials (``rows``) into the device-resident history, then proposes for
    every queued id.  Key derivation is traced in too — host-side
    ``PRNGKey``/``fold_in`` calls are each their own device dispatch, and on
    a tunneled accelerator every extra program costs tens of ms of
    completion latency (the round-2 interactive-loop bottleneck).

    ``diag=True`` (an armed obs run) compiles the health-instrumented
    variant under its OWN cache key, additionally returning the packed
    per-label stats ``[B, L, |HEALTH_STATS|]`` and split sizes ``[B, 2]``.
    The disarmed key and program are byte-identical to the plain build, so
    arming a run never perturbs an unarmed run's cache or hot path.

    ``donate=True`` (default) jits with ``donate_argnums=(0,)``: the
    history pytree is donated, so ``_apply_rows``'s scatters alias the
    input buffers in place and no tick materializes a cap-sized copy of
    the padded history (callers MUST thread the returned history handle
    forward — ``PaddedHistory.device_state(donate=True)`` /
    ``commit_device`` enforce that with a stale-handle guard).

    ``mesh`` (a ``sharding.suggest_mesh``) compiles the SAME traced
    program with explicit ``NamedSharding``s from the partition-rule table
    (``sharding.suggest_shardings``): the proposal batch axis (``ids``,
    ``packed``, diagnostics) shards over the mesh always; the history axis
    shards too when ``shard_history=True`` (``hist_cap`` past the per-chip
    threshold).  ``donate_argnums`` is preserved, so the no-cap-copy
    invariant (``DONATION_GATE``) holds on the sharded path — the in-place
    scatter aliases per-shard buffers.  Per-proposal math is device-local
    under batch sharding, so sharded proposals are BIT-IDENTICAL to the
    single-chip program at the same seed (pinned across mesh shapes
    {1, 2, 4, 8}).
    """
    cs = domain.cs
    key = ((cs.signature(), cfg_key, "health") if diag
           else (cs.signature(), cfg_key))
    if not donate:
        key = key + ("nodonate",)
    if _pallas_armed():
        # the pallas opt-in changes the traced program: its cache entry
        # must not shadow (or be shadowed by) the jnp build
        key = key + ("pallas",)
    qparams = _quant_qparams(cs, hist_dtype)
    if qparams is not None:
        # the quantized build decodes/encodes codes in-trace; qparams are
        # deterministic from (space, name), so the name alone keys it
        key = key + ("quant", str(hist_dtype))
    if mesh is not None:
        geom = (tuple(mesh.shape.items()),
                tuple(d.id for d in mesh.devices.flat))
        key = key + ("mesh", geom, bool(shard_history))
    fn = _suggest_jit_cache.get(key)
    if fn is None:
        if diag:
            scored = build_propose_with_scores(cs, cfg, diagnostics=True,
                                               qparams=qparams)

            def propose_diag(history, k):
                out, d = scored(history, k)
                vals = {l: v for l, (v, _) in out.items()}
                stats = jnp.stack([d["stats"][l] for l in cs.labels])
                split = jnp.stack([d["n_below"], d["n_above"]])
                return vals, stats, split

            def run(history, rows, seed_words, ids):
                hist = _apply_rows(cs.labels, history, rows, qparams)
                key = jax.random.fold_in(
                    jax.random.PRNGKey(seed_words[0]), seed_words[1]
                )
                keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(ids)
                vals, stats, splits = jax.vmap(
                    propose_diag, in_axes=(None, 0))(hist, keys)
                return hist, rand.pack_labels(cs, vals), stats, splits

        else:
            propose = build_propose(cs, cfg, qparams=qparams)

            def run(history, rows, seed_words, ids):
                hist = _apply_rows(cs.labels, history, rows, qparams)
                key = jax.random.fold_in(
                    jax.random.PRNGKey(seed_words[0]), seed_words[1]
                )
                keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(ids)
                out = jax.vmap(propose, in_axes=(None, 0))(hist, keys)
                return hist, rand.pack_labels(cs, out)

        donate_kw = {"donate_argnums": (0,)} if donate else {}
        if mesh is None:
            fn = jax.jit(run, **donate_kw)
        else:
            from ..parallel import sharding as _sh

            in_sh, out_sh = _sh.suggest_shardings(
                mesh, cs.labels, shard_history=shard_history, diag=diag)
            try:
                fn = jax.jit(run, in_shardings=in_sh,
                             out_shardings=out_sh, **donate_kw)
            except TypeError:  # pragma: no cover - ancient jax builds
                # explicit-shardings jit unavailable: shard_map fallback
                # (SNIPPETS.md [3] doctrine — map-style data parallelism
                # over the batch axis, history replicated; donation is
                # best-effort through the outer jit)
                fn = jax.jit(_sh.shard_map_suggest_fallback(run, mesh,
                                                            diag=diag),
                             **donate_kw)
        _suggest_jit_cache.put(key, fn)
    return fn


def _seed_words(seed):
    """(low 32 bits, high 32 bits) of an integer seed, for in-trace key
    derivation matching ``rand.seed_to_key``'s full-width semantics."""
    seed = int(seed)
    return np.asarray([seed & 0xFFFFFFFF, (seed >> 32) & 0xFFFFFFFF], np.uint32)


# ---------------------------------------------------------------------------
# multi-study batched suggest (ISSUE 9): the fused tell+ask program vmapped
# over a STUDY axis, so thousands of small concurrent studies share one
# device dispatch instead of owning the mesh one at a time
# ---------------------------------------------------------------------------

# (space signature, cfg, cohort shape, layout) -> compiled cohort program.
# A separate LRU from _suggest_jit_cache: cohort programs are specialized on
# the (n_studies, cap, ids width) slot shape, and the scheduler reports this
# cache's hit/miss rates as the ``suggest.cohort_cache`` metrics — study
# churn that re-traces per ask wave shows up here, not as silent recompiles.
_cohort_jit_cache = LRUCache(16)


def cohort_cache_stats():
    """Hit/miss/size counters of the cohort-program LRU (the scheduler
    publishes these as ``suggest.cohort_cache.*`` gauges after each tick)."""
    return _cohort_jit_cache.stats()


def jit_cache_stats():
    """Hit/miss/size counters of the SINGLE-STUDY fused tell+ask program
    LRU (``_suggest_jit_cache``) — the compile plane (ISSUE 14) exposes
    these as ``service.compile.jit_cache.*`` gauges so cache behavior is
    visible on the scrape plane, not just the cohort path's."""
    return _suggest_jit_cache.stats()


def cohort_cache_contains(key):
    """Non-mutating membership probe of the cohort-program LRU: no hit or
    miss is counted and the entry's recency is untouched.  The compile
    plane's readiness check uses this — a readiness PROBE must not make
    the probed entry look hot (or cold) to the eviction policy."""
    return _cohort_jit_cache.contains(key)


def cohort_key(cs, cfg, n_studies, cap, n_ids, donate=True, mesh=None,
               hist_dtype=None):
    """The cohort-program LRU key :func:`build_suggest_batched` will use
    for these build parameters — factored out so the compile plane can
    ask "is this program compiled?" without building anything.
    ``hist_dtype`` is the cohort's RESOLVED storage name (the quantized
    build is a different traced program); when the megakernel is armed
    for this space, the key carries that too — so the PR 13 bank warms
    the program that will actually serve."""
    key = (cs.signature(), tuple(sorted(cfg.items())), "cohort",
           int(n_studies), int(cap), int(n_ids), bool(donate))
    if _pallas_armed():
        key = key + ("pallas",)
    from .. import quant

    if hist_dtype is not None and quant.is_quant_name(hist_dtype):
        key = key + ("quant", str(hist_dtype))
    from .. import megakernel

    if megakernel.armed(cs):
        key = key + ("megakernel", megakernel.mode())
    if mesh is not None:
        key = key + ("mesh", tuple(mesh.shape.items()),
                     tuple(d.id for d in mesh.devices.flat))
    return key


def cohort_key_wide(profile, cfg, n_studies, cap, n_ids, donate=True):
    """The LRU key of the WIDENED cohort program
    (:func:`build_suggest_batched_wide`): keyed on the space's widened
    PROFILE, not its exact signature — every space sharing the profile
    shares this one compiled program (the whole point of widening)."""
    return (tuple(profile), tuple(sorted(cfg.items())), "wide",
            int(n_studies), int(cap), int(n_ids), bool(donate))


def build_suggest_batched(cs, cfg, n_studies, cap, n_ids, donate=True,
                          mesh=None, hist_dtype=None):
    """Compile the STUDY-BATCHED fused tell+ask program:

        run(hist_stack, rows_stack, seed_words[S, 2], ids[S, B])
            -> (hist_stack', packed[S, B, L])

    where every padded-history leaf carries a leading study axis
    (``losses[S, cap]``, ``vals[l][S, cap]``, ...) and ``rows_stack`` is
    ``[S, K, 2L+3]`` — per-study pending tell rows in the
    ``PaddedHistory._pack_row`` layout.  The body is EXACTLY the
    single-study program of :func:`_get_suggest_jit` ``vmap``-ped over the
    study axis: same row fold, same in-trace key derivation
    (``fold_in(PRNGKey(seed_words[0]), seed_words[1])`` then per-id
    ``fold_in``), same grouped proposal pipeline — so each study's
    proposals are bit-identical to the ones an independent sequential
    ``fmin`` would produce at the same per-study seed (tier-1 pinned).

    Every study in a cohort must share the space (``cs``), the capacity
    bucket ``cap`` and the id width ``B`` — that is the scheduler's cohort
    contract (``service/scheduler.py`` packs studies into fixed-shape
    slots precisely so these are static).  ``donate=True`` donates the
    stacked history, so the per-tick fold is an in-place scatter over the
    whole cohort (no S×cap copy per wave).  ``mesh`` shards the study
    axis over local devices via the partition-rule table
    (``sharding.suggest_partition_rules(study_axis=True)``) with donation
    preserved — ``n_studies`` must then divide the mesh's device count
    total.

    ``hist_dtype`` is the cohort's RESOLVED storage name: int8/fp8 builds
    the quantized program (codes decoded/encoded in-trace; see
    ``_read_vals``/``_apply_rows``).  With ``HYPEROPT_TPU_MEGAKERNEL``
    armed for this space, the whole tick builds as the fused Pallas
    megakernel instead (``megakernel.build_cohort``) — same signature,
    same donation, cached under the same LRU via :func:`cohort_key` so
    the compile plane's bank/warming covers it; a lowering failure falls
    back to this jnp program (warn-once counter) and re-keys plain.
    """
    key = cohort_key(cs, cfg, n_studies, cap, n_ids, donate=donate,
                     mesh=mesh, hist_dtype=hist_dtype)
    fn = _cohort_jit_cache.get(key)
    if fn is not None:
        return fn
    qparams = _quant_qparams(cs, hist_dtype)
    from .. import megakernel

    if megakernel.armed(cs):
        fn = megakernel.build_cohort(cs, cfg, n_studies, cap, n_ids,
                                     donate=donate, mesh=mesh,
                                     qparams=qparams)
        if fn is not None:
            _cohort_jit_cache.put(key, fn)
            return fn
        # lowering failed: megakernel just disarmed itself for this space
        # (warn-once + suggest.megakernel.fallback counter); recompute the
        # now-plain key so the jnp build lands where later asks look
        return build_suggest_batched(cs, cfg, n_studies, cap, n_ids,
                                     donate=donate, mesh=mesh,
                                     hist_dtype=hist_dtype)
    if fn is None:
        propose = build_propose(cs, cfg, qparams=qparams)
        labels = cs.labels

        def one(history, rows, seed_words, ids):
            hist = _apply_rows(labels, history, rows, qparams)
            k = jax.random.fold_in(
                jax.random.PRNGKey(seed_words[0]), seed_words[1]
            )
            keys = jax.vmap(lambda i: jax.random.fold_in(k, i))(ids)
            out = jax.vmap(propose, in_axes=(None, 0))(hist, keys)
            return hist, rand.pack_labels(cs, out)

        run = jax.vmap(one)
        donate_kw = {"donate_argnums": (0,)} if donate else {}
        if mesh is None:
            fn = jax.jit(run, **donate_kw)
        else:
            from ..parallel import sharding as _sh

            in_sh, out_sh = _sh.suggest_batched_shardings(mesh, labels)
            fn = jax.jit(run, in_shardings=in_sh, out_shardings=out_sh,
                         **donate_kw)
        _cohort_jit_cache.put(key, fn)
    return fn


# ---------------------------------------------------------------------------
# widened cohort programs (ISSUE 14): distinct-but-compatible spaces share
# ONE compiled program.  The per-label statics the grouped pipelines already
# stack (prior mu/sigma, bounds, q, log flag, label hashes) are lifted from
# closed-over constants to RUNTIME inputs, and the per-label dict layout is
# replaced by a positional [W, ...] slot layout whose pytree carries no
# label names — so the traced program (and its XLA executable) depends only
# on the space's widened PROFILE: the multiset of (quantized?, bounded?)
# numeric shapes and discrete bucket counts, each padded to a power-of-two
# slot width.  Padding slots are inert: every per-slot computation is a
# vmap lane, so a padded slot can never perturb a real label's proposal —
# the space-padding extension of the pinned capacity-invariance contract
# (padding rows are fully masked there; padding LANES are fully discarded
# here).  Label names still reach the kernel — as runtime ``label_hash``
# words feeding the same per-label ``fold_in`` — so proposals stay
# bit-identical per label no matter which compatible space compiled the
# program first.
# ---------------------------------------------------------------------------


def _pow2_up(n):
    b = 1
    while b < n:
        b *= 2
    return b


#: parzen statics of the inert padding slot: a uniform(0, 1) label with no
#: observations — finite everywhere, and its lane's output is discarded
_PAD_PARZEN = (0.5, 1.0, 0.0, 1.0, None, False)


def widened_profile(cs):
    """``(profile, slots)`` of a CompiledSpace, or ``None`` when the space
    cannot widen (conditional parameters — their activation masks couple
    labels, so the independent-lane argument above does not apply; such
    spaces fall back to exact-signature programs).

    ``profile`` is the hashable program identity: a sorted tuple of group
    entries ``("num", quantized, bounded, W)`` / ``("disc", K, W)`` with
    ``W`` the pow2-padded slot width.  ``slots`` lists each group's REAL
    labels in ``cs.labels`` order (the canonical slot assignment —
    padding occupies the group's tail)."""
    if any(info.conditions for info in cs.params.values()):
        return None
    groups = {}
    for l in cs.labels:
        d = cs.params[l].dist
        if d.family in ("categorical", "randint"):
            gkey = ("disc", len(_prior_probs(d)))
        else:
            _, _, low, high, q, _ = _parzen_from(d)
            gkey = ("num", q is not None,
                    math.isfinite(low) and math.isfinite(high))
        groups.setdefault(gkey, []).append(l)
    profile, slots = [], []
    for gkey in sorted(groups):
        ls = groups[gkey]
        profile.append(gkey + (_pow2_up(len(ls)),))
        slots.append(tuple(ls))
    return tuple(profile), tuple(slots)


def widened_params(cs, profile, slots, qparams=None):
    """The runtime parameter pytree of one space under a widened profile:
    per group, the stacked per-slot statics the grouped kernels consume
    (plus the ``label_hash`` words), padded to the profile's slot width
    with the inert entries.  Host numpy — tiny arrays, converted at
    dispatch.

    Every group also carries the per-slot quant code
    (``qscale``/``qzero``/``qlog``; identity ``(1, 0, False)`` when the
    space's history is not quantized) — runtime inputs, so compatible
    spaces with DIFFERENT codes still share one compiled program; the
    wide kernels only touch them when the history leaf dtype is int8/fp8
    (dead inputs otherwise, DCE'd by XLA)."""
    out = []
    for entry, ls in zip(profile, slots):
        Wg = entry[-1]
        pad = Wg - len(ls)
        hashes = [label_hash(l) for l in ls] + [0] * pad
        qp = [(qparams[l] if qparams is not None and l in qparams
               else (1.0, 0.0, False)) for l in ls]
        qp += [(1.0, 0.0, False)] * pad
        qarrs = {
            "qscale": np.asarray([p[0] for p in qp], np.float32),
            "qzero": np.asarray([p[1] for p in qp], np.float32),
            "qlog": np.asarray([p[2] for p in qp], bool),
        }
        if entry[0] == "disc":
            K = entry[1]
            ps = [_prior_probs(cs.params[l].dist) for l in ls]
            ps += [np.full(K, 1.0 / K, np.float32)] * pad
            offs = [int(cs.params[l].dist.params[0])
                    if cs.params[l].dist.family == "randint" else 0
                    for l in ls] + [0] * pad
            out.append({
                "hash": np.asarray(hashes, np.uint32),
                "p": np.stack(ps).astype(np.float32),
                "off": np.asarray(offs, np.int32),
                **qarrs,
            })
        else:
            parz = [_parzen_from(cs.params[l].dist) for l in ls]
            parz += [_PAD_PARZEN] * pad
            out.append({"hash": np.asarray(hashes, np.uint32),
                        **_stack_parzen_statics(parz), **qarrs})
    return tuple(out)


def _dequant_wide(vals, wparams):
    """f32 view of the positional ``[W, cap]`` (or ``[W', cap]`` slice-
    concatenated) vals stack: affine-decode when the stack holds int8/fp8
    codes, plain upcast otherwise.  Per-slot ``(scale, zero, islog)``
    come concatenated from the group entries — slot order is profile
    order, exactly the stack's row order."""
    from .. import quant

    if quant.quant_dtype_name(vals.dtype) is None:
        return jnp.asarray(vals).astype(jnp.float32)
    scale = jnp.concatenate([jnp.asarray(gp["qscale"]) for gp in wparams])
    zero = jnp.concatenate([jnp.asarray(gp["qzero"]) for gp in wparams])
    islog = jnp.concatenate([jnp.asarray(gp["qlog"]) for gp in wparams])
    t = vals.astype(jnp.float32) * scale[:, None] + zero[:, None]
    # clamp the dead exp branch: where() evaluates both sides, and a
    # linear slot's t can be large enough to overflow exp into inf
    return jnp.where(islog[:, None], jnp.exp(jnp.minimum(t, 80.0)), t)


def build_propose_wide(profile, cfg):
    """One proposal step over the positional slot layout:
    ``propose(history, wparams, key) -> values[W]`` where ``history`` is
    ``{"vals": [W, cap], "active": [W, cap], "losses": [cap],
    "has_loss": [cap]}`` and ``wparams`` is :func:`widened_params`' tuple.

    Per slot this is EXACTLY the grouped pipeline of
    :func:`build_propose_with_scores` (``group="all"``) — the same group
    kernels, the same per-label keys, the same statics values (as traced
    inputs instead of baked constants) — so a real slot's proposal is
    bit-identical to the unwidened grouped path (pinned by test).
    ``has_log`` is statically True for every numeric group: a linear
    slot's ``jnp.where(islog, ...)`` selects the linear value exactly, so
    the dead log branch never perturbs it — that staticness is what lets
    log and linear spaces share one program."""
    def propose(history, wparams, key):
        losses = jnp.asarray(history["losses"]).astype(jnp.float32)
        has_loss = jnp.asarray(history["has_loss"])
        below, above = split_below_above(losses, has_loss, cfg["gamma"],
                                         cfg["LF"])
        vals = _dequant_wide(jnp.asarray(history["vals"]), wparams)
        act = jnp.asarray(history["active"])
        outs = []
        off = 0
        for entry, gp in zip(profile, wparams):
            Wg = entry[-1]
            sl = slice(off, off + Wg)
            off += Wg
            keys = jax.vmap(
                lambda h: jax.random.fold_in(key, h))(gp["hash"])
            obs = vals[sl]
            b = below[None, :] & act[sl]
            a = above[None, :] & act[sl]
            if entry[0] == "disc":
                v, _ = _propose_discrete_group(keys, obs, b, a, gp["p"],
                                               gp["off"], cfg)
            else:
                _, quantized, bounded, _ = entry
                statics = {k: gp[k] for k in
                           ("prior_mu", "prior_sigma", "low", "high",
                            "q", "islog")}
                v, _ = _propose_numeric_group(keys, obs, b, a, statics,
                                              cfg, quantized, bounded,
                                              has_log=True)
            outs.append(jnp.asarray(v, jnp.float32))
        return jnp.concatenate(outs)

    return propose


def _apply_rows_wide(W, history, rows, wparams=None):
    """:func:`_apply_rows` over the positional slot layout: ``rows`` is
    ``[K, 2W+3]`` (slot-ordered val columns, slot-ordered active columns,
    loss, has_loss, trial index) and the scatters write the same values
    to the same (slot, trial) cells as the per-label dict path.  An
    int8/fp8 vals stack takes the affine ENCODE instead of an astype,
    with the per-slot code streamed from ``wparams`` (see
    :func:`_dequant_wide`)."""
    from .. import quant

    idx = rows[:, 2 * W + 2].astype(jnp.int32)  # [K]
    vrows = rows[:, :W].T  # [W, K] f32 slot-major
    qname = quant.quant_dtype_name(history["vals"].dtype)
    if qname is not None and wparams is not None:
        scale = jnp.concatenate([jnp.asarray(gp["qscale"])
                                 for gp in wparams])
        zero = jnp.concatenate([jnp.asarray(gp["qzero"]) for gp in wparams])
        islog = jnp.concatenate([jnp.asarray(gp["qlog"]) for gp in wparams])
        t = jnp.where(islog[:, None],
                      jnp.log(jnp.maximum(vrows, quant.EPS)), vrows)
        q = jnp.clip((t - zero[:, None]) / scale[:, None], -127.0, 127.0)
        if qname == "int8":
            q = jnp.round(q)
        vset = q.astype(history["vals"].dtype)
    else:
        vset = vrows.astype(history["vals"].dtype)
    return {
        "vals": history["vals"].at[:, idx].set(vset, mode="drop"),
        "active": history["active"].at[:, idx].set(
            rows[:, W:2 * W].T > 0.5, mode="drop"),
        "losses": history["losses"].at[idx].set(
            rows[:, 2 * W].astype(history["losses"].dtype), mode="drop"),
        "has_loss": history["has_loss"].at[idx].set(
            rows[:, 2 * W + 1] > 0.5, mode="drop"),
    }


def build_suggest_batched_wide(profile, cfg, n_studies, cap, n_ids,
                               donate=True):
    """The WIDENED study-batched fused tell+ask program:

        run(hist_stack, rows_stack, seed_words[S, 2], ids[S, B], wparams)
            -> (hist_stack', packed[S, B, W])

    ``hist_stack`` leaves carry a leading study axis over the positional
    slot layout (``vals[S, W, cap]``, ``losses[S, cap]``, ...);
    ``wparams`` (study-invariant — every study in a cohort shares the
    space) rides unbatched.  The body is :func:`build_propose_wide`
    under the same fold/key-derivation/vmap structure as
    :func:`build_suggest_batched`; cached in the same cohort LRU under
    :func:`cohort_key_wide` — keyed on the PROFILE, so every compatible
    space reuses the entry.  No mesh variant: widened cohorts serve
    single-device (the cold-start plane's regime is many small diverse
    spaces, not one sharded giant)."""
    key = cohort_key_wide(profile, cfg, n_studies, cap, n_ids,
                          donate=donate)
    fn = _cohort_jit_cache.get(key)
    if fn is None:
        propose = build_propose_wide(profile, cfg)
        W = sum(entry[-1] for entry in profile)

        def one(history, rows, seed_words, ids, wparams):
            hist = _apply_rows_wide(W, history, rows, wparams)
            k = jax.random.fold_in(
                jax.random.PRNGKey(seed_words[0]), seed_words[1]
            )
            keys = jax.vmap(lambda i: jax.random.fold_in(k, i))(ids)
            out = jax.vmap(lambda kk: propose(hist, wparams, kk))(keys)
            return hist, out

        run = jax.vmap(one, in_axes=(0, 0, 0, 0, None))
        donate_kw = {"donate_argnums": (0,)} if donate else {}
        fn = jax.jit(run, **donate_kw)
        _cohort_jit_cache.put(key, fn)
    return fn


# ---------------------------------------------------------------------------
# the plugin entry point (tpe.py sym: suggest)
# ---------------------------------------------------------------------------


def suggest_async(
    new_ids,
    domain,
    trials,
    seed,
    prior_weight=_default_prior_weight,
    n_startup_jobs=_default_n_startup_jobs,
    n_EI_candidates=_default_n_EI_candidates,
    gamma=_default_gamma,
    linear_forgetting=_default_linear_forgetting,
    ei_select="argmax",
    ei_tau=1.0,
    prior_eps=0.0,
    verbose=False,
):
    """Dispatch one fused tell+ask program and return a
    :class:`~hyperopt_tpu.algos.rand.AskHandle` whose ``result()`` performs
    the packed readback and builds the trial docs.

    The dispatch side does everything history-related: it folds the
    just-completed trials into the DONATED device mirror (zero-copy
    in-place scatter; see ``_get_suggest_jit``) and commits the program's
    returned history handle immediately, so by the time the handle is
    awaited the trials object is already consistent.  Only the proposal
    buffer rides the future — exactly the piece the pipelined ``fmin``
    loop overlaps with objective evaluation (``lookahead=N``).
    """
    if not len(new_ids):
        return rand.AskHandle([], lambda: [])
    if len(trials.trials) < n_startup_jobs:
        return rand.suggest_async(new_ids, domain, trials, seed)

    cfg = {
        "prior_weight": float(prior_weight),
        "n_EI_candidates": int(n_EI_candidates),
        "gamma": float(gamma),
        "LF": int(linear_forgetting),
        "ei_select": str(ei_select),
        "ei_tau": float(ei_tau),
        "prior_eps": float(prior_eps),
    }
    cfg_key = tuple(sorted(cfg.items()))
    ph = trials.history_object(domain.cs.labels)
    # arm (or degrade) the int8/fp8 history code before any device state
    # exists — a no-op unless HYPEROPT_TPU_HIST_DTYPE is a quant name
    ph.ensure_qparams(domain.cs)

    # ONE device program (fold completed trials + propose whole queue) and
    # one single-buffer readback; the updated history stays device-resident
    # and the fold scatters into the DONATED input buffers in place.  ids
    # pad to a power-of-two bucket (extras discarded on host) so the
    # program shape — and hence the XLA compile — is stable across queue
    # ramp-up/drain batch sizes.
    #
    # An armed obs run (FMinIter sets trials.obs_health when its sink is
    # live) runs the health-instrumented variant instead: same proposals,
    # plus a small diagnostics buffer fetched alongside the packed values.
    # Disarmed runs take the plain branch — same cache key, same program,
    # same single readback as before the health layer existed.
    health = getattr(trials, "obs_health", None)
    donate = _donation_enabled()
    # HYPEROPT_TPU_SHARD arms the mesh-sharded fused program: the proposal
    # batch shards over local devices (history too, past the per-chip cap
    # threshold) — unset, the single-chip program is byte-identical to
    # previous rounds
    from .._env import parse_shard

    n_shard = parse_shard()
    mesh = None
    shard_hist = False
    if n_shard is not None:
        from ..parallel import sharding as _sh

        mesh = _sh.suggest_mesh(n_shard)
        shard_hist = _sh.should_shard_history(ph.cap, mesh)
    run = _get_suggest_jit(domain, cfg_key, cfg, diag=health is not None,
                           donate=donate, mesh=mesh,
                           shard_history=shard_hist,
                           hist_dtype=ph.hist_dtype)
    ids = rand.pad_ids_sticky(domain, new_ids)
    dev, rows = ph.device_state(donate=donate)
    if mesh is not None:
        n_dev = int(mesh.devices.size)
        # the batch axis must divide the mesh; pad with the last id
        # (extras discarded on host, per-id keys make pads harmless)
        ids = rand.pad_ids_to_multiple(ids, n_dev)
        # steady state this is a no-op (committed handles already carry
        # the mesh layout and device_put short-circuits); the first
        # sharded tick — or a post-growth re-upload — pays one placement
        # copy, after which donation aliases per-shard buffers in place
        dev = _sh.place_history(dev, mesh, shard_history=shard_hist)
        m = getattr(trials, "obs_metrics", None)
        if m is not None:
            m.gauge("suggest.shards").set(n_dev)
            m.gauge("suggest.cand_per_shard").set(
                (len(ids) // n_dev) * cfg["n_EI_candidates"])
            m.gauge("suggest.hist_sharded").set(int(shard_hist))
    args = (dev, rows, _seed_words(seed), ids)
    if health is not None:
        from ..obs import health as _health_mod
        from ..obs.devmem import register_owner

        # lower-only cost capture: reads the cost table, consumes no buffers
        _health_mod.capture_jit_cost(run, args, "suggest.tpe")
        # tag the packed proposal readback buffer for the devmem census
        # (armed runs only — the disarmed ask path stays byte-identical)
        register_owner("candidates", (len(ids), len(domain.cs.labels)))
    try:
        out = run(*args)
    except BaseException:
        # the donated input may already be invalid and no updated handle
        # exists: drop the mirror so the next ask rebuilds from host
        ph.abandon_device()
        raise
    ph.commit_device(out[0])

    if health is None:
        mat = out[1]

        def finish():
            flats = rand.unpack_flats(domain.cs, mat, len(new_ids))
            return rand.flat_to_new_trial_docs(domain, trials, new_ids, flats)

    else:
        _, mat, stats, splits = out

        def finish():
            from ..obs import health as _health_mod

            _health_mod.record_tpe_health(
                health, domain.cs.labels,
                np.asarray(stats)[: len(new_ids)],
                np.asarray(splits)[: len(new_ids)])
            flats = rand.unpack_flats(domain.cs, mat, len(new_ids))
            return rand.flat_to_new_trial_docs(domain, trials, new_ids, flats)

    return rand.AskHandle(new_ids, finish)


def suggest(new_ids, domain, trials, seed, **kwargs):
    """Propose new trials by TPE (hyperopt/tpe.py sym: suggest).

    Signature-compatible with the reference plugin boundary, incl.
    ``functools.partial(tpe.suggest, gamma=..., n_EI_candidates=...)`` tuning.
    The first ``n_startup_jobs`` trials delegate to random search; after that
    every proposal is one jitted device program, vmapped over ``new_ids``.

    ``ei_select``/``ei_tau``/``prior_eps`` are TPU-batch extensions with no
    reference analog (the reference proposes one trial at a time):
    stochastic EI selection and ε-prior mixing keep a WIDE ``new_ids`` batch
    diverse when every proposal shares one posterior — see
    ``_select_candidate``.  The defaults reproduce reference semantics.

    This is ``suggest_async`` (dispatch) + an immediate ``result()``
    (readback) — bit-identical proposals, one code path.
    """
    return suggest_async(new_ids, domain, trials, seed, **kwargs).result()


# (space sig, cfg, mesh geometry, kind) -> jitted fn; LRU-bounded like
# _suggest_jit_cache
_sharded_jit_cache = LRUCache(32)


def suggest_sharded(
    mesh=None,
    n_cand_shards=1,
    n_startup_jobs=_default_n_startup_jobs,
    ei_select=None,
    **tpe_kwargs,
):
    """Build an ``algo=`` callable whose TPE proposals run SHARDED over a
    device mesh — the user-facing entry to ``parallel/sharding.py``'s
    kernels (the reference's user-facing parallelism is
    ``SparkTrials(parallelism=P)``, hyperopt/spark.py sym: SparkTrials;
    here the parallel resource is a ``jax.sharding.Mesh``).

        fmin(obj, space, algo=tpe.suggest_sharded(n_cand_shards=2),
             max_evals=100, max_queue_len=8, ...)

    Two sharded axes, picked per call:

    * queue batches (``len(new_ids) > 1``) shard the TRIAL axis — each
      device proposes for its slice of the batch (ids pad to a power of
      two, then up to a multiple of the mesh's device count, so tail
      batches always shard evenly).  With ``n_cand_shards > 1`` the whole
      batch additionally scores over the DISTRIBUTED candidate pool
      (``sharding.propose_sharded_candidates(batch=B)``: per-shard top-k
      all-gathered, pooled select).
    * single proposals with ``n_cand_shards > 1`` shard the CANDIDATE axis
      via ``shard_map`` + all-gather top-k select (`n_EI_candidates` split
      across devices; counts that do not divide pad and mask).

    ``mesh=None`` builds a mesh over all visible devices at first use (so
    the factory can be called before jax initializes).  ``ei_select``
    defaults to ``"softmax"`` for batched calls (a shared-posterior batch
    needs diversity — see ``_select_candidate``) and ``"argmax"`` for
    single proposals.  Startup trials delegate to random search as usual.
    """
    state = {"mesh": mesh}
    # kwargs use tpe.suggest's public names; 'linear_forgetting' maps to the
    # kernel cfg's 'LF'.  Unknown names raise HERE, at factory time — a
    # typo'd kwarg silently swallowed into the jit cache key would run a
    # different optimizer than requested.
    _kw_map = {"prior_weight": "prior_weight",
               "n_EI_candidates": "n_EI_candidates",
               "gamma": "gamma",
               "linear_forgetting": "LF",
               "ei_tau": "ei_tau",
               "prior_eps": "prior_eps"}
    unknown = set(tpe_kwargs) - set(_kw_map)
    if unknown:
        raise TypeError(f"suggest_sharded: unknown kwargs {sorted(unknown)} "
                        f"(accepts {sorted(_kw_map)})")
    cfg_over = {_kw_map[k]: v for k, v in tpe_kwargs.items()}

    def algo(new_ids, domain, trials, seed):
        from ..parallel import sharding as _sh

        if not len(new_ids):
            return []
        if len(trials.trials) < n_startup_jobs:
            return rand.suggest(new_ids, domain, trials, seed)
        if state["mesh"] is None:
            state["mesh"] = _sh.make_mesh(n_cand_shards=n_cand_shards)
        m = state["mesh"]

        batched = len(new_ids) > 1
        select = ei_select if ei_select is not None else (
            "softmax" if batched else "argmax")
        cfg = {
            "prior_weight": _default_prior_weight,
            "n_EI_candidates": _default_n_EI_candidates,
            "gamma": _default_gamma,
            "LF": _default_linear_forgetting,
            "ei_select": select,
            **cfg_over,
        }
        cs = domain.cs
        geom = (tuple(m.shape.items()), tuple(d.id for d in m.devices.flat))
        # batched + candidate shards: every proposal in the batch scores
        # over the DISTRIBUTED candidate pool (round-6
        # propose_sharded_candidates growth) — the program is specialized
        # on the padded batch width, so that width joins the cache key
        cand_batched = batched and int(m.shape[_sh.CAND_AXIS]) > 1
        padded = None
        if batched:
            # pad to a power of two, then up to a multiple of the mesh's
            # device count: in_shardings require the batch axis divisible
            # by the mesh (a tail queue batch of 3 on an 8-device mesh
            # would otherwise abort the run)
            n_dev = int(np.prod(list(m.shape.values())))
            padded = rand.pad_ids_to_multiple(
                rand.pad_ids_sticky(domain, new_ids), n_dev)
        ph = trials.history_object(cs.labels)
        ph.ensure_qparams(cs)
        qparams = _quant_qparams(cs, ph.hist_dtype)
        # _pallas_armed() changes the traced program (build_propose_
        # candidates' EI path), so the flag joins the cache key — as does
        # the resolved storage name (the quantized build decodes in-trace)
        cache_key = (cs.signature(), tuple(sorted(cfg.items())), geom,
                     batched, len(padded) if cand_batched else None,
                     _pallas_armed(),
                     ph.hist_dtype if qparams is not None else None)
        fn = _sharded_jit_cache.get(cache_key)
        if fn is None:
            if cand_batched:
                fn = _sh.propose_sharded_candidates(cs, cfg, m, packed=True,
                                                    batch=len(padded),
                                                    qparams=qparams)
            elif batched:
                fn = _sh.suggest_batch_sharded(cs, cfg, m, packed=True,
                                               qparams=qparams)
            else:
                fn = _sh.propose_sharded_candidates(cs, cfg, m, packed=True,
                                                    qparams=qparams)
            _sharded_jit_cache.put(cache_key, fn)

        hv = ph.device_view()
        hist = {k: hv[k] for k in ("losses", "has_loss", "vals", "active")}
        hist_dev = _sh.replicate_history(hist, m)
        base = rand.seed_to_key(seed)
        if batched:
            keys = rand.fold_ids(base, padded)
            mat = fn(hist_dev, keys)  # [B_pad, L] packed, batch-sharded
            flats = rand.unpack_flats(cs, np.asarray(mat), len(new_ids))
        else:
            key = rand.fold_ids(base, new_ids)[0]
            mat = fn(hist_dev, key)  # [1, L] packed: ONE readback
            flats = rand.unpack_flats(cs, np.asarray(mat), 1)
        return rand.flat_to_new_trial_docs(domain, trials, new_ids, flats)

    return algo
