"""Adaptive TPE — per-call prediction of TPE's hyper-hyperparameters.

Parity target: ``hyperopt/atpe.py`` (sym: ATPEOptimizer, suggest) +
``hyperopt/atpe_models/*``.  The reference ships ~1900 LoC driving a set of
**pre-trained lightgbm models** that map (search-space features, trial-history
features) → TPE tuning (gamma, n_EI_candidates, secondary cutoffs, …), the
models having been fit offline on thousands of HPO runs.

Those binary model files are not reproducible here (no network, no lightgbm
training data), so this module keeps the reference's *architecture* —
featurize the space, featurize the history, predict the TPE
hyper-hyperparameters, delegate to ``tpe.suggest`` with the prediction — but
replaces the learned lightgbm regressors with a transparent analytic
predictor whose rules encode the same relationships the ATPE paper reports
(gamma ↑ when the loss landscape looks flat, candidate count ↑ with
dimensionality, forgetting window tied to history length).  The predictor is
a pure function of two feature dicts, so a learned model can be dropped in
later without touching the plugin surface.

Differences from the reference are deliberate and documented here rather
than hidden: prediction is rule-based, not lightgbm; the feature set is the
subset that is well-defined for the compiled-space IR.
"""

from __future__ import annotations

import math

import numpy as np

from . import tpe

__all__ = [
    "featurize_space",
    "featurize_trials",
    "predict_tpe_params",
    "suggest",
    "ATPEOptimizer",
]

_LOG_FAMILIES = {"loguniform", "qloguniform", "lognormal", "qlognormal"}
_DISCRETE_FAMILIES = {"categorical", "randint", "uniformint"}


def featurize_space(cs):
    """Search-space features (atpe.py sym: Hyperparameter feature extraction).

    All derivable from the static param table — the analog of what the
    reference computes from ``expr_to_config``.
    """
    infos = list(cs.params.values())
    n = len(infos)
    n_cond = sum(1 for i in infos if i.conditions)
    return {
        "n_params": n,
        "n_conditional": n_cond,
        "frac_conditional": n_cond / max(n, 1),
        "frac_log": sum(1 for i in infos if i.dist.family in _LOG_FAMILIES) / max(n, 1),
        "frac_discrete": sum(
            1 for i in infos if i.dist.family in _DISCRETE_FAMILIES
        ) / max(n, 1),
        "max_cond_depth": max((len(i.conditions) for i in infos), default=0),
    }


def featurize_trials(trials):
    """History features: size, spread and recent-progress signals — plus the
    total eval budget when the driver surfaced one (``fmin`` sets
    ``trials.max_evals_hint``; the reference's suggest protocol has no
    budget channel, and its aTPE paying no attention to the remaining
    budget is exactly what the round-4 verdict flagged)."""
    losses = np.asarray(
        [l for l in trials.losses() if l is not None], dtype=np.float64
    )
    n = len(losses)
    feats = {"n_trials": n, "loss_spread": 0.0, "recent_improvement": 1.0,
             "fail_frac": 0.0,
             "budget": getattr(trials, "max_evals_hint", None)}
    statuses = trials.statuses()
    if statuses:
        feats["fail_frac"] = sum(1 for s in statuses if s == "fail") / len(statuses)
    if n >= 4:
        lo, hi = np.min(losses), np.max(losses)
        med = np.median(losses)
        # spread of the bulk relative to the best–median gap: ~0 on a flat
        # landscape (every trial similar), large when the best stand out
        feats["loss_spread"] = float((med - lo) / (hi - lo + 1e-12))
        half = n // 2
        best_old = np.min(losses[:half])
        best_new = np.min(losses[half:])
        denom = abs(best_old) + (hi - lo) + 1e-12
        feats["recent_improvement"] = float(
            np.clip((best_old - best_new) / denom, 0.0, 1.0)
        )
    return feats


def _quantize(x, step):
    return float(np.round(x / step) * step)


def _pow2_bucket(x, lo, hi):
    """Round to the nearest power of two within [lo, hi]."""
    x = float(np.clip(x, lo, hi))
    return int(2 ** int(round(math.log2(x))))


def predict_tpe_params(space_feats, trial_feats):
    """Map features → TPE tuning (the lightgbm-ensemble analog; see module
    docstring for why this is analytic).  Returns kwargs for ``tpe.suggest``.

    Every output is quantized to a coarse bucket: the fused suggest kernel
    (tpe._get_suggest_jit) is cached per (space, cfg), so a continuously
    varying cfg would force a full retrace+compile on every call and grow
    the jit cache without bound.  Buckets keep the number of distinct
    compiled kernels per run small (~a dozen) while preserving the
    adaptive behavior at the granularity that matters.
    """
    d = space_feats["n_params"]
    n = trial_feats["n_trials"]

    # gamma: the reference default is 0.25.  Flat landscape / little recent
    # progress → widen the 'below' set (more exploration); strong recent
    # progress with clear structure → sharpen it.  The adjustment clips at
    # 0.35: a 75-eval ablation on branin measured gamma=0.45 costing ~20%
    # of final loss (plateau detection fires even when the run is sitting
    # IN the optimum basin), while 0.30-0.35 stayed ahead of the default.
    gamma = 0.25
    gamma *= 1.0 + 0.8 * (1.0 - trial_feats["recent_improvement"]) * (
        1.0 - trial_feats["loss_spread"]
    )
    gamma *= 1.0 - 0.4 * trial_feats["recent_improvement"]
    gamma = _quantize(np.clip(gamma, 0.15, 0.35), 0.05)

    # candidate count: scale with DIMENSIONALITY only — cheap on an
    # accelerator (vmapped axis), so err high; the reference caps at ~24
    # only because numpy pays per candidate.  (An earlier history-length
    # ramp was measured hurting low-dim domains: on branin a mid-run jump
    # from 32 to 64 candidates over-exploited the argmax by ~25% of final
    # loss.)  Power-of-two bucket.
    n_ei = _pow2_bucket(24 * math.sqrt(max(d, 1)), 32, 512)

    # linear forgetting: keep the window proportional to history once the
    # run is long, never below the reference default.  25-wide buckets.
    lf = int(np.clip(_quantize(n // 2, 25), 25, 200))

    # startup: more dimensions need more seeding, conditional spaces more
    # still (each branch needs observations).  (Not part of the kernel cfg —
    # only compared against len(trials) — but bucket anyway for stability.)
    n_startup = int(
        np.clip(_quantize(10 + 2 * d * (1 + space_feats["frac_conditional"]), 5), 15, 60)
    )
    # budget awareness (round-5 verdict #4): random startup must never eat
    # more than ~a fifth of a known eval budget — on a 75-eval run the old
    # rule could spend 60 evals exploring and leave 15 for TPE.
    budget = trial_feats.get("budget")
    if budget:
        n_startup = min(n_startup, max(10, int(budget) // 5))

    # prior weight: down-weight the prior a little on log-scaled spaces where
    # the uniform-in-log prior is broad relative to useful regions.
    prior_weight = float(np.clip(_quantize(1.0 - 0.3 * space_feats["frac_log"], 0.1), 0.6, 1.0))

    return {
        "gamma": gamma,
        "n_EI_candidates": n_ei,
        "linear_forgetting": lf,
        "n_startup_jobs": n_startup,
        "prior_weight": prior_weight,
    }


class ATPEOptimizer:
    """Object form mirroring the reference's class (atpe.py sym:
    ATPEOptimizer); holds overrides and exposes ``suggest``."""

    def __init__(self, **overrides):
        self.overrides = overrides

    def recommend(self, domain, trials):
        params = predict_tpe_params(
            featurize_space(domain.cs), featurize_trials(trials)
        )
        params.update(self.overrides)
        return params

    def suggest(self, new_ids, domain, trials, seed):
        return tpe.suggest(new_ids, domain, trials, seed,
                           **self.recommend(domain, trials))


def suggest(new_ids, domain, trials, seed, **overrides):
    """Adaptive-TPE plugin entry point (hyperopt/atpe.py sym: suggest);
    signature-compatible with the ``algo=`` boundary, tunable via
    ``functools.partial`` like every other suggester."""
    return ATPEOptimizer(**overrides).suggest(new_ids, domain, trials, seed)
