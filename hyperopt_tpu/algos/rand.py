"""Random-search suggester on ``jax.random``.

Parity target: ``hyperopt/rand.py`` (sym: suggest, suggest_batch).  The
reference seeds a fresh numpy RandomState per new id and interprets the
vectorized pyll program; here each new id folds into a threefry key and the
compiled space's jitted ``sample_flat`` draws every parameter in one traced
program (batched across ids via ``vmap``).
"""

from __future__ import annotations

import numpy as np

import jax

__all__ = ["suggest", "suggest_batch", "flat_to_new_trial_docs", "seed_to_key", "fold_ids"]


def seed_to_key(seed):
    """Full-width threefry key from an integer seed.

    The low 32 bits seed the key and any higher bits fold in separately, so
    seeds differing only above bit 31 (common with rstate-derived 64-bit
    seeds) produce distinct streams instead of silently colliding.
    """
    seed = int(seed)
    key = jax.random.PRNGKey(seed & 0xFFFFFFFF)
    hi = (seed >> 32) & 0xFFFFFFFF
    if hi:
        key = jax.random.fold_in(key, hi)
    return key


def fold_ids(key, new_ids):
    """One derived key per new id (full 32-bit id range)."""
    return jax.vmap(lambda i: jax.random.fold_in(key, i))(
        jax.numpy.asarray([int(i) & 0xFFFFFFFF for i in new_ids], jax.numpy.uint32)
    )


def flat_to_new_trial_docs(domain, trials, new_ids, flats):
    """Build reference-shaped trial docs from flat per-label samples.

    ``flats``: list of {label: host scalar}.  Inactive conditional params get
    empty idxs/vals (the sparse doc form of hyperopt/vectorize.py).
    """
    rval = []
    for new_id, flat in zip(new_ids, flats):
        active = domain.cs.active_flat(flat)
        idxs = {}
        vals = {}
        for label, info in domain.cs.params.items():
            if active[label]:
                v = flat[label]
                v = int(v) if info.is_int else float(v)
                idxs[label] = [new_id]
                vals[label] = [v]
            else:
                idxs[label] = []
                vals[label] = []
        misc = {"tid": new_id, "cmd": ("domain_attachment", "FMinIter_Domain"),
                "idxs": idxs, "vals": vals}
        if domain.workdir is not None:
            misc["workdir"] = domain.workdir
        rval.extend(
            trials.new_trial_docs([new_id], [None], [domain.new_result()], [misc])
        )
    return rval


def _flat_to_host(flat):
    return {k: np.asarray(v).item() for k, v in flat.items()}


def suggest(new_ids, domain, trials, seed):
    """Draw one prior sample per new id (hyperopt/rand.py sym: suggest)."""
    key = seed_to_key(seed)
    flats = []
    for new_id in new_ids:
        k = jax.random.fold_in(key, int(new_id) & 0xFFFFFFFF)
        flats.append(_flat_to_host(domain.cs.sample_flat_jit(k)))
    return flat_to_new_trial_docs(domain, trials, new_ids, flats)


def suggest_batch(new_ids, domain, trials, seed):
    """Vectorized variant: one vmapped device program for all ids."""
    key = seed_to_key(seed)
    keys = fold_ids(key, new_ids)
    batch = jax.jit(jax.vmap(domain.cs.sample_flat))(keys)
    host = {k: np.asarray(v) for k, v in batch.items()}
    flats = [{k: host[k][i].item() for k in host} for i in range(len(new_ids))]
    return flat_to_new_trial_docs(domain, trials, new_ids, flats)
