"""Random-search suggester on ``jax.random``.

Parity target: ``hyperopt/rand.py`` (sym: suggest, suggest_batch).  The
reference seeds a fresh numpy RandomState per new id and interprets the
vectorized pyll program; here each new id folds into a threefry key and the
compiled space's jitted ``sample_flat`` draws every parameter in one traced
program (batched across ids via ``vmap``).
"""

from __future__ import annotations

import numpy as np

import jax

__all__ = ["suggest", "suggest_batch", "flat_to_new_trial_docs"]


def flat_to_new_trial_docs(domain, trials, new_ids, flats):
    """Build reference-shaped trial docs from flat per-label samples.

    ``flats``: list of {label: host scalar}.  Inactive conditional params get
    empty idxs/vals (the sparse doc form of hyperopt/vectorize.py).
    """
    rval = []
    for new_id, flat in zip(new_ids, flats):
        active = domain.cs.active_flat(flat)
        idxs = {}
        vals = {}
        for label, info in domain.cs.params.items():
            if active[label]:
                v = flat[label]
                v = int(v) if info.is_int else float(v)
                idxs[label] = [new_id]
                vals[label] = [v]
            else:
                idxs[label] = []
                vals[label] = []
        misc = {"tid": new_id, "cmd": ("domain_attachment", "FMinIter_Domain"),
                "idxs": idxs, "vals": vals}
        if domain.workdir is not None:
            misc["workdir"] = domain.workdir
        rval.extend(
            trials.new_trial_docs([new_id], [None], [domain.new_result()], [misc])
        )
    return rval


def _flat_to_host(flat):
    return {k: np.asarray(v).item() for k, v in flat.items()}


def suggest(new_ids, domain, trials, seed):
    """Draw one prior sample per new id (hyperopt/rand.py sym: suggest)."""
    key = jax.random.PRNGKey(int(seed) & 0x7FFFFFFF)
    flats = []
    for new_id in new_ids:
        k = jax.random.fold_in(key, int(new_id) & 0x7FFFFFFF)
        flats.append(_flat_to_host(domain.cs.sample_flat_jit(k)))
    return flat_to_new_trial_docs(domain, trials, new_ids, flats)


def suggest_batch(new_ids, domain, trials, seed):
    """Vectorized variant: one vmapped device program for all ids."""
    key = jax.random.PRNGKey(int(seed) & 0x7FFFFFFF)
    keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(
        jax.numpy.asarray([int(i) & 0x7FFFFFFF for i in new_ids])
    )
    batch = jax.jit(jax.vmap(domain.cs.sample_flat))(keys)
    host = {k: np.asarray(v) for k, v in batch.items()}
    flats = [{k: host[k][i].item() for k in host} for i in range(len(new_ids))]
    return flat_to_new_trial_docs(domain, trials, new_ids, flats)
