"""Random-search suggester on ``jax.random``.

Parity target: ``hyperopt/rand.py`` (sym: suggest, suggest_batch).  The
reference seeds a fresh numpy RandomState per new id and interprets the
vectorized pyll program; here each new id folds into a threefry key and the
compiled space's jitted ``sample_flat`` draws every parameter in one traced
program (batched across ids via ``vmap``).
"""

from __future__ import annotations

import numpy as np

import jax

from ..utils import LRUCache

__all__ = ["suggest", "suggest_async", "suggest_batch", "AskHandle",
           "pad_ids_to_multiple",
           "flat_to_new_trial_docs", "seed_to_key",
           "fold_ids", "pad_ids_pow2", "pad_ids_sticky"]


class AskHandle:
    """One dispatched ask: the suggest program is already in flight on the
    accelerator; :meth:`result` performs the (blocking) readback and builds
    the reference-shaped trial docs.

    This is the seam the pipelined host loop overlaps on: ``fmin``'s
    ``lookahead=N`` dispatches the next batch's handle before evaluating
    the current trials, and only awaits it when the objective actually
    needs the values.  Dispatch-then-immediate-``result()`` is the plain
    synchronous ask, bit-identical to calling ``suggest`` directly.
    """

    def __init__(self, new_ids, finish):
        self.new_ids = list(new_ids)
        self._finish = finish
        self._docs = None

    def result(self):
        """Block on the packed proposal buffer and return the trial docs
        (idempotent)."""
        if self._finish is not None:
            self._docs = self._finish()
            self._finish = None
        return self._docs


def seed_to_key(seed):
    """Full-width threefry key from an integer seed.

    The low 32 bits seed the key and any higher bits fold in separately, so
    seeds differing only above bit 31 (common with rstate-derived 64-bit
    seeds) produce distinct streams instead of silently colliding.
    """
    seed = int(seed)
    key = jax.random.PRNGKey(seed & 0xFFFFFFFF)
    # unconditional fold keeps this bit-identical to the in-trace derivation
    # used by the suggesters' fused kernels (tpe._get_suggest_jit)
    return jax.random.fold_in(key, (seed >> 32) & 0xFFFFFFFF)


def fold_ids(key, new_ids):
    """One derived key per new id (full 32-bit id range)."""
    return jax.vmap(lambda i: jax.random.fold_in(key, i))(
        jax.numpy.asarray([int(i) & 0xFFFFFFFF for i in new_ids], jax.numpy.uint32)
    )


def flat_to_new_trial_docs(domain, trials, new_ids, flats):
    """Build reference-shaped trial docs from flat per-label samples.

    ``flats``: list of {label: host scalar}.  Inactive conditional params get
    empty idxs/vals (the sparse doc form of hyperopt/vectorize.py).
    """
    rval = []
    for new_id, flat in zip(new_ids, flats):
        active = domain.cs.active_flat(flat)
        idxs = {}
        vals = {}
        for label, info in domain.cs.params.items():
            if active[label]:
                v = flat[label]
                v = int(v) if info.is_int else float(v)
                idxs[label] = [new_id]
                vals[label] = [v]
            else:
                idxs[label] = []
                vals[label] = []
        misc = {"tid": new_id, "cmd": ("domain_attachment", "FMinIter_Domain"),
                "idxs": idxs, "vals": vals}
        if domain.workdir is not None:
            misc["workdir"] = domain.workdir
        rval.extend(
            trials.new_trial_docs([new_id], [None], [domain.new_result()], [misc])
        )
    return rval


def pack_labels(cs, out):
    """Stack a ``{label: value[B]}`` kernel output into one ``[B, L]`` f32
    matrix (labels in ``cs.labels`` order).

    A tunneled accelerator pays one host↔device round trip *per fetched
    buffer*; packing makes every suggest readback exactly one transfer.
    Integer families survive the f32 trip exactly (|value| < 2^24).
    """
    import jax.numpy as jnp

    return jnp.stack(
        [jnp.asarray(out[l], jnp.float32) for l in cs.labels], axis=-1
    )


def unpack_flats(cs, mat, n):
    """Invert :func:`pack_labels` on host: ``[n, L]`` matrix → flat dicts."""
    mat = np.asarray(mat)
    return [
        {
            l: (int(round(float(mat[i, j]))) if cs.params[l].is_int
                else float(mat[i, j]))
            for j, l in enumerate(cs.labels)
        }
        for i in range(n)
    ]


def pad_ids_pow2(new_ids, min_bucket=1):
    """Pad a non-empty id batch to a power-of-two ``uint32`` array (at least
    ``min_bucket`` wide) by repeating the last id (callers discard the extra
    outputs via ``unpack_flats(..., n)``).  Suggest-kernel program shapes —
    and hence XLA compiles — stay stable across queue ramp-up/drain batch
    sizes; shared by ``rand.suggest`` and ``tpe.suggest``.  Padding never
    changes the kept proposals: per-id keys derive from the id VALUE, not
    the batch position."""
    ids = [int(i) & 0xFFFFFFFF for i in new_ids]
    B = 1
    while B < max(len(ids), int(min_bucket)):
        B *= 2
    return np.asarray(ids + [ids[-1]] * (B - len(ids)), np.uint32)


def pad_ids_to_multiple(ids, n):
    """Pad an already-bucketed ``uint32`` id array up to a multiple of
    ``n`` (a mesh's device count) by repeating the last id — sharded
    programs need the batch axis divisible by the mesh; a tail queue batch
    of 3 on an 8-device mesh would otherwise abort the run.  Extras are
    discarded on host (``unpack_flats(..., n)``) and never change the kept
    proposals: per-id keys derive from the id VALUE, not the position."""
    n = int(n)
    if n <= 1 or len(ids) % n == 0:
        return ids
    B = -(-len(ids) // n) * n
    return np.concatenate([ids, np.full(B - len(ids), ids[-1], np.uint32)])


def pad_ids_sticky(domain, new_ids):
    """``pad_ids_pow2`` with a per-domain sticky floor: the bucket never
    shrinks below the widest batch this domain has already compiled, so a
    queue-drain tail (e.g. 2 ids after steady batches of 4) reuses the
    existing program instead of paying a full XLA compile for a narrower
    copy of the same kernel.  ``FMinIter`` seeds the floor from
    ``max_queue_len`` so even the first ramp-up batch compiles the steady
    shape."""
    padded = pad_ids_pow2(new_ids, getattr(domain, "_ids_bucket", 1))
    domain._ids_bucket = len(padded)
    return padded


# space signature -> jitted batched prior sampler; LRU-bounded — every entry
# pins a compiled XLA executable
_sample_jit_cache = LRUCache(32)


def _get_sample_jit(domain):
    """Cached ``run(seed_words[2], ids[B]) -> packed [B, L]`` with the
    PRNG-key derivation traced in — one device dispatch and one readback
    per suggest call regardless of batch size (host-side PRNGKey/fold_in
    calls each cost a round trip on a tunneled accelerator).  Keyed by
    space signature so fresh Domains reuse the compiled kernel."""
    cs = domain.cs
    key = cs.signature()
    fn = _sample_jit_cache.get(key)
    if fn is None:
        sample_flat = cs.sample_flat

        def run(seed_words, ids):
            k = jax.random.fold_in(
                jax.random.PRNGKey(seed_words[0]), seed_words[1]
            )
            keys = jax.vmap(lambda i: jax.random.fold_in(k, i))(ids)
            return pack_labels(cs, jax.vmap(sample_flat)(keys))

        fn = jax.jit(run)
        _sample_jit_cache.put(key, fn)
    return fn


def suggest_async(new_ids, domain, trials, seed):
    """Dispatch the batched prior-sample program and return an
    :class:`AskHandle`; the readback (and doc building) happens in its
    ``result()``.  ``suggest`` below is dispatch + immediate result."""
    if not len(new_ids):
        return AskHandle([], lambda: [])
    seed = int(seed)
    seed_words = np.asarray([seed & 0xFFFFFFFF, (seed >> 32) & 0xFFFFFFFF], np.uint32)
    mat = _get_sample_jit(domain)(seed_words, pad_ids_sticky(domain, new_ids))

    def finish():
        flats = unpack_flats(domain.cs, mat, len(new_ids))
        health = getattr(trials, "obs_health", None)
        if health is not None and len(flats) >= 2:
            from ..obs.health import record_proposal_health

            record_proposal_health(health, "rand", domain.cs.labels, flats)
        return flat_to_new_trial_docs(domain, trials, new_ids, flats)

    return AskHandle(new_ids, finish)


def suggest(new_ids, domain, trials, seed):
    """Draw one prior sample per new id (hyperopt/rand.py sym: suggest).

    All ids are drawn by one vmapped device program (per-id ``fold_in``
    keys, so the draws are identical whatever the batching).

    Armed obs runs additionally record the cheap search-health subset
    (per-label duplicate rate + proposal spread across the batch) from the
    already-fetched host values — no extra device work, nothing at all
    when disarmed (obs/health.py sym: record_proposal_health)."""
    return suggest_async(new_ids, domain, trials, seed).result()


def suggest_batch(new_ids, domain, trials, seed):
    """Alias of ``suggest`` (hyperopt/rand.py sym: suggest_batch) — the
    serial path is already one batched device program."""
    return suggest(new_ids, domain, trials, seed)
