"""Simulated-annealing-flavored suggester.

Parity target: ``hyperopt/anneal.py`` (sym: AnnealSuggest, suggest;
defaults ``avg_best_idx=2.0``, ``shrink_coef=0.1``).

Semantics preserved from the reference:

* Each proposal anchors on a previously observed **good** trial: per
  hyperparameter, trials where that parameter was active and a loss was
  recorded are ranked by loss, and the anchor rank is drawn geometrically
  with mean ``avg_best_idx`` (so rank 0 — the best — is most likely).
* The prior distribution is then **shrunk** around the anchor value by
  ``s(T) = 1 / (1 + T * shrink_coef)`` where ``T`` is the number of active
  observations: uniform-family widths scale by ``s``, normal-family sigmas
  scale by ``s``, and discrete posteriors mix ``(1-s)·onehot(anchor) +
  s·prior``.  With no observations ``s = 1`` and the proposal is a prior
  draw.

TPU-first: the whole proposal — per-label ranking, geometric anchor draw,
shrunk-distribution sampling for every family — is one jitted function of
the padded history arrays, vmapped over new ids (same harness as TPE via
``algobase.SuggestAlgo``).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..spaces import label_hash
from .algobase import SuggestAlgo
from .tpe import EPS, _parzen_from, _prior_probs

__all__ = ["AnnealSuggest", "suggest"]

_default_avg_best_idx = 2.0
_default_shrink_coef = 0.1


def _geometric_rank(key, u_mean, n):
    """Rank ~ Geometric with mean ``u_mean``, clipped to [0, n-1]."""
    # P(rank >= r) = (1 - p)^r with p = 1/u_mean
    p = 1.0 / u_mean
    u = jax.random.uniform(key, minval=EPS, maxval=1.0)
    r = jnp.floor(jnp.log(u) / math.log(1.0 - p + 1e-12)).astype(jnp.int32)
    return jnp.clip(r, 0, jnp.maximum(n - 1, 0))


def _anchor(key, vals, obs_mask, losses, avg_best_idx):
    """(anchor value, T) — value of the geometrically-ranked best active
    trial; arbitrary (weight-irrelevant) when T == 0."""
    big = jnp.float32(jnp.finfo(jnp.float32).max)
    masked = jnp.where(obs_mask, losses, big)
    order = jnp.argsort(masked)
    T = jnp.sum(obs_mask.astype(jnp.int32))
    r = _geometric_rank(key, avg_best_idx, T)
    return vals[order[r]], T


def _shrink(T, shrink_coef):
    return 1.0 / (1.0 + T.astype(jnp.float32) * shrink_coef)


class AnnealSuggest(SuggestAlgo):
    """hyperopt/anneal.py sym: AnnealSuggest."""

    # armed obs runs tag this suggester's health records / cost gauges
    # "anneal" (the cheap dup-rate + spread subset; algobase.__call__)
    obs_name = "anneal"

    def __init__(self, avg_best_idx=_default_avg_best_idx,
                 shrink_coef=_default_shrink_coef):
        super().__init__(avg_best_idx=float(avg_best_idx),
                         shrink_coef=float(shrink_coef))

    def build(self, cs, cfg):
        avg_best_idx = cfg["avg_best_idx"]
        shrink_coef = cfg["shrink_coef"]

        def propose_label(key, info, vals, obs_mask, losses):
            fam = info.dist.family
            k_anchor, k_draw = jax.random.split(key)

            if fam in ("categorical", "randint"):
                prior_p = jnp.asarray(_prior_probs(info.dist))
                offset = int(info.dist.params[0]) if fam == "randint" else 0
                a, T = _anchor(k_anchor, vals.astype(jnp.int32) - offset,
                               obs_mask, losses, avg_best_idx)
                s = _shrink(T, shrink_coef)
                onehot = jax.nn.one_hot(a, prior_p.shape[0], dtype=jnp.float32)
                p = (1.0 - s) * onehot + s * prior_p
                return jax.random.categorical(k_draw, jnp.log(p)) + offset

            prior_mu, prior_sigma, low, high, q, log_space = _parzen_from(info.dist)
            obs = jnp.log(jnp.maximum(vals, EPS)) if log_space else vals
            a, T = _anchor(k_anchor, obs, obs_mask, losses, avg_best_idx)
            s = _shrink(T, shrink_coef)
            a = jnp.where(T > 0, a, prior_mu)

            if math.isfinite(low) and math.isfinite(high):
                # uniform family: width (high-low)*s centered on the anchor,
                # slid (not clipped) to stay inside [low, high] so the
                # proposal density stays uniform over a full-width window
                width = (high - low) * s
                lo = jnp.clip(a - width / 2, low, high - width)
                x = jax.random.uniform(k_draw, minval=0.0, maxval=1.0) * width + lo
            else:
                # normal family: sigma shrinks by s
                x = a + prior_sigma * s * jax.random.normal(k_draw)
            if log_space:
                x = jnp.exp(x)
            if q is not None:
                x = jnp.round(x / q) * q
            return x

        def propose(history, key):
            losses = jnp.asarray(history["losses"])
            has_loss = jnp.asarray(history["has_loss"])
            out = {}
            for label in cs.labels:
                info = cs.params[label]
                vals = jnp.asarray(history["vals"][label])
                active = jnp.asarray(history["active"][label])
                k = jax.random.fold_in(key, label_hash(label))
                out[label] = propose_label(k, info, vals, active & has_loss, losses)
            return out

        return propose


suggest = AnnealSuggest()
