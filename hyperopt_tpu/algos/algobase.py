"""Shared scaffolding for suggest algorithms.

Parity target: ``hyperopt/algobase.py`` (sym: SuggestAlgo, ExprEvaluator).
The reference's ``SuggestAlgo`` walks the vectorized pyll graph with
per-node-type dispatch; in the compiled-space design there is no graph to
walk — the static ``ParamInfo`` table plays that role — so the base class
here owns the *runtime* plumbing shared by suggesters instead: padded
history retrieval, per-id RNG key folding, jit caching per config, and
emission of reference-shaped trial documents.

A suggester subclasses ``SuggestAlgo``, implements ``build(cs, cfg)``
returning a pure ``propose(history, key) -> {label: value}``, and gains a
reference-compatible ``__call__(new_ids, domain, trials, seed)``.
"""

from __future__ import annotations

import numpy as np

import jax

from . import rand

__all__ = ["SuggestAlgo"]


class SuggestAlgo:
    """Base class turning a jitted per-proposal kernel into a
    ``suggest(new_ids, domain, trials, seed)`` plugin."""

    #: subclasses: number of observed trials below which we delegate to rand
    n_startup_jobs = 0

    def __init__(self, **cfg):
        self.cfg = cfg

    # -- to be provided by subclasses -------------------------------------

    def build(self, cs, cfg):
        """Return ``propose(history, key) -> {label: value}`` (pure, jittable)."""
        raise NotImplementedError

    # -- shared runtime ----------------------------------------------------

    def _get_jit(self, domain, cfg):
        cache_attr = f"_algo_cache_{type(self).__name__}"
        cache = getattr(domain, cache_attr, None)
        if cache is None:
            cache = {}
            setattr(domain, cache_attr, cache)
        key = tuple(sorted(cfg.items()))
        fn = cache.get(key)
        if fn is None:
            fn = jax.jit(jax.vmap(self.build(domain.cs, cfg), in_axes=(None, 0)))
            cache[key] = fn
        return fn

    def __call__(self, new_ids, domain, trials, seed, **overrides):
        cfg = dict(self.cfg, **overrides)
        n_startup = cfg.pop("n_startup_jobs", self.n_startup_jobs)
        if len(trials.trials) < n_startup:
            return rand.suggest(new_ids, domain, trials, seed)
        history = trials.padded_history(domain.cs.labels)
        hist_arrays = {
            "losses": history["losses"],
            "has_loss": history["has_loss"],
            "vals": history["vals"],
            "active": history["active"],
        }
        propose = self._get_jit(domain, cfg)
        keys = rand.fold_ids(rand.seed_to_key(seed), new_ids)
        batch = propose(hist_arrays, keys)
        host = {k: np.asarray(v) for k, v in batch.items()}
        flats = [{k: host[k][i].item() for k in host} for i in range(len(new_ids))]
        return rand.flat_to_new_trial_docs(domain, trials, new_ids, flats)
