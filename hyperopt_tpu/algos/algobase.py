"""Shared scaffolding for suggest algorithms.

Parity target: ``hyperopt/algobase.py`` (sym: SuggestAlgo, ExprEvaluator).
The reference's ``SuggestAlgo`` walks the vectorized pyll graph with
per-node-type dispatch; in the compiled-space design there is no graph to
walk — the static ``ParamInfo`` table plays that role — so the base class
here owns the *runtime* plumbing shared by suggesters instead: padded
history retrieval, per-id RNG key folding, jit caching per config, and
emission of reference-shaped trial documents.

A suggester subclasses ``SuggestAlgo``, implements ``build(cs, cfg)``
returning a pure ``propose(history, key) -> {label: value}``, and gains a
reference-compatible ``__call__(new_ids, domain, trials, seed)``.
"""

from __future__ import annotations

import numpy as np

import jax

from . import rand

__all__ = ["SuggestAlgo"]


class SuggestAlgo:
    """Base class turning a jitted per-proposal kernel into a
    ``suggest(new_ids, domain, trials, seed)`` plugin."""

    #: subclasses: number of observed trials below which we delegate to rand
    n_startup_jobs = 0

    #: name used in telemetry records (health JSONL ``algo`` field, device
    #: cost gauges); subclasses override for a human name (anneal does)
    obs_name = None

    def __init__(self, **cfg):
        self.cfg = cfg

    # -- to be provided by subclasses -------------------------------------

    def build(self, cs, cfg):
        """Return ``propose(history, key) -> {label: value}`` (pure, jittable)."""
        raise NotImplementedError

    # -- shared runtime ----------------------------------------------------

    #: module-wide: (algo class, space signature, cfg) -> jitted suggest
    _jit_cache = {}

    def _get_jit(self, domain, cfg):
        """Cached ``run(history, seed_words[2], ids[B]) -> packed [B, L]``
        with key derivation traced in (one dispatch per suggest call).
        Keyed by space signature so fresh Domains reuse compiled kernels."""
        key = (type(self).__name__, domain.cs.signature(), tuple(sorted(cfg.items())))
        fn = SuggestAlgo._jit_cache.get(key)
        if fn is None:
            cs = domain.cs
            propose = self.build(cs, cfg)

            def run(history, seed_words, ids):
                k = jax.random.fold_in(
                    jax.random.PRNGKey(seed_words[0]), seed_words[1]
                )
                keys = jax.vmap(lambda i: jax.random.fold_in(k, i))(ids)
                out = jax.vmap(propose, in_axes=(None, 0))(history, keys)
                return rand.pack_labels(cs, out)

            fn = SuggestAlgo._jit_cache[key] = jax.jit(run)
        return fn

    def __call__(self, new_ids, domain, trials, seed, **overrides):
        cfg = dict(self.cfg, **overrides)
        n_startup = cfg.pop("n_startup_jobs", self.n_startup_jobs)
        if len(trials.trials) < n_startup:
            return rand.suggest(new_ids, domain, trials, seed)
        history = trials.padded_history(domain.cs.labels)
        hist_arrays = {
            "losses": history["losses"],
            "has_loss": history["has_loss"],
            "vals": history["vals"],
            "active": history["active"],
        }
        # quantized mirrors (ISSUE 19): generic suggesters consume plain
        # floats — decode any int8/fp8 affine-coded leaf at this read
        # boundary (one dequant per leaf; f32 out), so subclass kernels
        # never see storage codes.  Histories that never armed qparams
        # mirror as bf16 and pass through untouched.
        ph = trials.history_object(domain.cs.labels)
        if getattr(ph, "qparams", None) is not None:
            import jax.numpy as jnp

            from .. import quant

            def _decode(l, v):
                v = jnp.asarray(v)
                if quant.quant_dtype_name(v.dtype) is not None:
                    return quant.dequantize(v, ph.qparams[l])
                return v

            hist_arrays["vals"] = {
                l: _decode(l, v) for l, v in hist_arrays["vals"].items()}
        run = self._get_jit(domain, cfg)
        seed = int(seed)
        seed_words = np.asarray(
            [seed & 0xFFFFFFFF, (seed >> 32) & 0xFFFFFFFF], np.uint32
        )
        ids = np.asarray([int(i) & 0xFFFFFFFF for i in new_ids], np.uint32)
        mat = run(hist_arrays, seed_words, ids)
        flats = rand.unpack_flats(domain.cs, mat, len(new_ids))
        # armed obs runs: the cheap health subset (dup rate, spread) from
        # the host values already fetched, plus a one-time FLOP/byte cost
        # capture of the suggest program; strictly nothing when disarmed
        health = getattr(trials, "obs_health", None)
        if health is not None:
            from ..obs import health as health_mod

            name = self.obs_name or type(self).__name__.lower()
            health_mod.capture_jit_cost(
                run, (hist_arrays, seed_words, ids), f"algo.{name}")
            if len(flats) >= 2:
                health_mod.record_proposal_health(
                    health, name, domain.cs.labels, flats)
        return rand.flat_to_new_trial_docs(domain, trials, new_ids, flats)
