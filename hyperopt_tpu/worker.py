"""Standalone evaluation worker over a FileStore.

Parity target: ``hyperopt/mongoexp.py`` (sym: MongoWorker.run_one ≈L800-1000,
main_worker / main_worker_helper — the ``hyperopt-mongo-worker`` CLI).  A
worker process loops: reclaim stale claims → atomically reserve one NEW job →
unpickle the Domain from the store's ``FMinIter_Domain`` attachment →
evaluate with a heartbeat thread bumping ``refresh_time`` → write DONE/ERROR.
Exits after ``--max-consecutive-failures`` consecutive errors or
``--reserve-timeout`` seconds without work, exactly like the reference CLI.

Run as ``hyperopt-tpu-worker --store DIR`` (console script) or
``python -m hyperopt_tpu.worker --store DIR``.
"""

from __future__ import annotations

import argparse
import logging
import os
import socket
import sys
import threading
import time

from . import chaos
from .base import Ctrl, JOB_STATE_NEW, JOB_STATE_RUNNING, spec_from_misc
from .filestore import FileStore, FileTrials, ReserveTimeout
from .obs.watchdog import beat as _wd_beat, get_watchdog
from .retry import RetryPolicy

__all__ = ["FileWorker", "main"]

logger = logging.getLogger(__name__)


class FileWorker:
    """One worker loop bound to a store (mongoexp.py sym: MongoWorker)."""

    def __init__(self, store_root, poll_interval=0.25, heartbeat_interval=2.0,
                 stale_after=30.0, workdir=None, retry=None):
        self.store = FileStore(store_root)
        self.store_root = store_root
        self.poll_interval = float(poll_interval)
        self.heartbeat_interval = float(heartbeat_interval)
        self.stale_after = float(stale_after)
        self.workdir = workdir
        # per-trial retry policy (retry.py): flaky objectives re-run in
        # place with jittered backoff while the heartbeat thread keeps the
        # claim fresh; None/0 keeps the fail-immediately reference behavior
        self.retry = RetryPolicy.coerce(retry)
        self.owner = f"{socket.gethostname()}:{os.getpid()}"
        self._domain = None
        # forensics: a SIGTERM'd/crashed worker dumps its flight ring into
        # the store's attachments (flight.<owner>.jsonl) — the driver can
        # post-mortem every worker that ever died on this store
        self.flight_dump = self.store.arm_flight(self.owner)
        # a worker IS a live run for its whole process lifetime: without
        # the retain, the run-scoped watchdog would never consider this
        # process active and stall detection would silently no-op here
        wd = get_watchdog()
        if wd is not None:
            wd.retain()

    def _get_domain(self):
        if self._domain is None:
            blob = self.store.get_attachment("FMinIter_Domain")
            if blob is None:
                return None
            import cloudpickle

            self._domain = cloudpickle.loads(blob)
        return self._domain

    def run_one(self, reserve_timeout=None):
        """Reserve and evaluate one job (mongoexp.py sym: MongoWorker.run_one).
        Raises ReserveTimeout if nothing could be claimed in time (a
        MONOTONIC deadline: an NTP step must not expire the poll early)."""
        deadline = (None if reserve_timeout is None
                    else time.monotonic() + reserve_timeout)
        while True:
            _wd_beat("worker.poll", owner=self.owner)
            try:
                self.store.reclaim_stale(self.stale_after)
                doc = self.store.reserve(self.owner)
            except OSError as e:
                # transient store I/O failure (NFS blip, chaos-injected):
                # a poll loop that dies on one bad write defeats the whole
                # reclaim story — log, back off a beat, poll again
                logger.warning("store I/O error while polling: %s", e)
                doc = None
            if doc is not None:
                break
            if deadline is not None and time.monotonic() >= deadline:
                raise ReserveTimeout(f"no job within {reserve_timeout}s")
            time.sleep(self.poll_interval)

        domain = self._get_domain()
        if domain is None:
            # job exists but the driver hasn't attached the domain yet: put
            # the claim back and wait
            doc["state"] = JOB_STATE_NEW
            doc["owner"] = None
            self.store.write_doc(doc)
            try:
                os.remove(self.store._path(JOB_STATE_RUNNING, doc["tid"]))
            except FileNotFoundError:
                pass
            time.sleep(self.poll_interval)
            return False

        stop = threading.Event()

        def beat():
            while not stop.wait(self.heartbeat_interval):
                try:
                    self.store.heartbeat(doc)
                except OSError as e:
                    # a failed heartbeat WRITE (chaos-injected or a real
                    # NFS blip) must not kill the beat loop: a skipped
                    # beat is recoverable (worst case a stale reclaim
                    # re-runs deterministic work), a silently-dead beat
                    # thread guarantees the reclaim
                    logger.warning("heartbeat write failed for %s: %s",
                                   doc["tid"], e)
                # the store heartbeat proves the THREAD is alive; this one
                # tells the stall watchdog which trial the worker is inside
                _wd_beat("worker.trial", tid=doc["tid"], owner=self.owner)

        hb = threading.Thread(target=beat, daemon=True,
                              name=f"hyperopt-heartbeat-{doc['tid']}")
        hb.start()
        error = None
        result = None
        try:
            spec = spec_from_misc(doc["misc"])
            trials = FileTrials(self.store_root, refresh=False)
            ctrl = Ctrl(trials, current_trial=doc)
            attempt = 0
            while True:
                # per-trial retry loop (retry.py): the heartbeat thread
                # stays up across attempts and backoff sleeps, so the
                # claim never goes stale while the trial is being retried;
                # the attempt count rides the doc into the terminal state
                doc["misc"]["attempts"] = attempt + 1
                chaos.point("trial", metrics=self.store.metrics)
                try:
                    result = domain.evaluate(spec, ctrl)
                    error = None
                    break
                except Exception as e:
                    error = e
                    if not self.retry.retries_left(attempt + 1):
                        break
                    delay = self.retry.delay(
                        attempt, key=f"{self.owner}:{doc['tid']}")
                    self.store.metrics.counter("trials.retries").inc()
                    self.store.metrics.histogram(
                        "retry.backoff_sec").observe(delay)
                    logger.warning(
                        "job %s attempt %d failed (%s); retrying in %.2fs",
                        doc["tid"], attempt + 1, e, delay)
                    time.sleep(delay)
                    attempt += 1
        finally:
            # the heartbeat must be fully stopped on EVERY exit path —
            # including an objective exception or a raise from
            # spec/ctrl construction — BEFORE finish() removes
            # running/<tid>.pkl: a still-beating thread could pass its
            # existence check and resurrect the file, which a concurrent
            # reclaim_stale would later move back to NEW and re-evaluate a
            # finished (or deterministic-failure) trial
            stop.set()
            hb.join(timeout=30)
        if hb.is_alive():
            # a heartbeat write is stalled (e.g. hung NFS): finishing now
            # would re-open the resurrect race the join exists to close.
            # Leave the claim; reclaim_stale re-queues it once stale.
            logger.error("job %s: heartbeat thread stuck; leaving claim for "
                         "stale reclaim", doc["tid"])
            return False
        from .exceptions import StoreFullError

        attempt = 0
        while True:
            try:
                if error is not None:
                    logger.error("job %s failed: %s", doc["tid"], error)
                    self.store.finish(doc, error=error)
                    return False
                self.store.finish(doc, result=result)
                return True
            except StoreFullError as e:
                # a full disk is transient (ISSUE 15: the serving side
                # is compacting/GCing): back off and retry the terminal
                # write instead of dropping a finished result on the
                # floor — the evaluation is the expensive part
                if not self.retry.retries_left(attempt + 1):
                    logger.warning(
                        "store full finishing job %s after %d retries: "
                        "%s (claim left for stale/orphan recovery)",
                        doc["tid"], attempt, e)
                    return False
                delay = self.retry.delay(
                    attempt, key=f"enospc:{self.owner}:{doc['tid']}")
                self.store.metrics.counter("store.enospc_retries").inc()
                logger.warning("store full finishing job %s; retrying "
                               "in %.2fs (%s)", doc["tid"], delay, e)
                time.sleep(delay)
                attempt += 1
                continue
            except OSError as e:
                # the terminal write failed (NFS blip, chaos-injected):
                # the claim (running doc or orphaned *.finish.* rename)
                # is exactly what the stale-reclaim/orphan-sweep
                # machinery recovers — surviving here beats taking the
                # worker down with the store
                logger.warning("store I/O error finishing job %s: %s "
                               "(claim left for stale/orphan recovery)",
                               doc["tid"], e)
                return False


def main(argv=None):
    """CLI entry point (mongoexp.py sym: main_worker)."""
    p = argparse.ArgumentParser(prog="hyperopt-tpu-worker")
    p.add_argument("--store", required=True, help="FileStore directory")
    p.add_argument("--poll-interval", type=float, default=0.25)
    p.add_argument("--heartbeat-interval", type=float, default=2.0)
    p.add_argument("--stale-after", type=float, default=30.0,
                   help="reclaim RUNNING jobs with heartbeats older than this")
    p.add_argument("--max-consecutive-failures", type=int, default=4)
    p.add_argument("--reserve-timeout", type=float, default=120.0,
                   help="exit after this long without claiming a job")
    p.add_argument("--max-jobs", type=int, default=sys.maxsize)
    p.add_argument("--workdir", default=None)
    p.add_argument("--retries", type=int, default=None,
                   help="extra per-trial attempts after a raising objective "
                        "(jittered exponential backoff; default: "
                        "HYPEROPT_TPU_TRIAL_RETRIES or 0)")
    p.add_argument("--retry-base-delay", type=float, default=0.5,
                   help="base backoff before the first retry (doubles per "
                        "attempt, jittered)")
    args = p.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    retry = (RetryPolicy.from_env() if args.retries is None
             else RetryPolicy(max_retries=args.retries,
                              base_delay=args.retry_base_delay))
    worker = FileWorker(
        args.store,
        poll_interval=args.poll_interval,
        heartbeat_interval=args.heartbeat_interval,
        stale_after=args.stale_after,
        workdir=args.workdir,
        retry=retry,
    )
    consecutive_failures = 0
    done = 0
    while done < args.max_jobs:
        try:
            ok = worker.run_one(reserve_timeout=args.reserve_timeout)
        except ReserveTimeout:
            logger.info("reserve timeout; exiting")
            return 0
        if ok:
            consecutive_failures = 0
            done += 1
        else:
            consecutive_failures += 1
            if consecutive_failures >= args.max_consecutive_failures:
                logger.error("too many consecutive failures; exiting")
                return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
