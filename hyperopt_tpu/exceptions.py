"""Exception types.

Parity target: ``hyperopt/exceptions.py`` (sym: AllTrialsFailed, DuplicateLabel,
InvalidTrial, InvalidResultStatus, InvalidLoss, InvalidAnnotatedParameter).
"""


class HyperoptTpuError(Exception):
    """Base class for framework errors."""


class AllTrialsFailed(HyperoptTpuError):
    """Raised by ``Trials.argmin`` / ``fmin`` when no trial reported a loss."""


class DuplicateLabel(HyperoptTpuError):
    """Raised when two hyperparameters in one space share a label."""


class InvalidTrial(HyperoptTpuError):
    """Raised when a trial document does not match the schema."""


class InvalidResultStatus(HyperoptTpuError):
    """Raised when an objective returns an unknown ``status`` string."""


class InvalidLoss(HyperoptTpuError):
    """Raised when an objective's ``loss`` is not a finite float (or None for fail)."""


class InvalidAnnotatedParameter(HyperoptTpuError):
    """Raised when an ``hp.*`` call is malformed (bad label, bad args)."""


class FleetDegraded(HyperoptTpuError):
    """Raised instead of hanging when a multi-controller run cannot make
    progress: a collective (``process_allgather``) exceeded its timeout —
    a peer controller is dead or partitioned — or an elastic-fleet
    generation barrier expired with shards leased but never published.
    The raiser checkpoints the last checksum-verified generation first, so
    the surviving fleet (of ANY size) restarts from the checkpoint/store
    and replays bitwise; this is the "degrade to checkpoint-and-shrink"
    half of the preemption story (docs/DESIGN.md §15)."""


class StoreFullError(OSError):
    """The backing filesystem refused a durable write for lack of space
    (``ENOSPC``/``EDQUOT``) — RETRYABLE: the store-integrity plane
    (docs/DESIGN.md §21) sheds load, compacts WALs and GCs the store,
    and the write succeeds once space frees.  Subclasses ``OSError`` so
    pre-ISSUE-15 handlers that absorb store I/O failures keep working;
    typed so the serving path can answer 507 + ``Retry-After`` instead
    of a generic 500, and the worker/executor retry path can back off
    instead of burning its budget on a full disk."""


class StaleHistoryError(HyperoptTpuError):
    """Raised when a device-resident trial history is touched after its
    buffers were DONATED to a fused tell+ask dispatch and the program's
    returned handle has not been committed back
    (``PaddedHistory.commit_device``).  Without this guard the reuse would
    surface as an opaque XLA invalid-buffer crash deep inside the runtime."""
