"""Per-trial retry policy: jittered exponential backoff with deterministic
jitter.

Parity target: ``hyperopt/mongoexp.py`` leaves transient-failure handling to
the operator (a crashed trial lands in ``error`` state and stays there);
production spot/preemptible fleets need flaky objectives (OOM-killed
subprocess, preempted accelerator, transient NFS error) retried with
backoff instead of burning an evaluation.  One policy object serves every
evaluation path that re-runs work:

* ``worker.FileWorker`` — retries the objective in place while the
  heartbeat thread keeps the claim fresh; the attempt count is recorded in
  the trial doc (``misc['attempts']``) so a post-mortem can tell a
  first-try success from a third-try one.
* ``parallel.executor.ExecutorTrials`` — same loop on the thread-pool path.
* ``filestore.FileStore.reserve`` — a micro-scale instance damps the
  claim-contention storm (many workers racing ``os.rename`` on the same
  NEW docs).

Jitter is DETERMINISTIC in ``(key, attempt)`` — seeded ``random.Random``,
not global randomness — so tests replay exact schedules and two workers
retrying the same trial still spread out (their keys differ by owner).
Delays are wall-clock sleeps; *deadlines* elsewhere use the monotonic
clock (see ``executor._cancel_timed_out``) — backoff cares about duration,
deadlines must survive NTP steps.
"""

from __future__ import annotations

import dataclasses
import os
import random

__all__ = ["RetryPolicy"]


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """``max_retries`` EXTRA attempts after the first (0 = never retry);
    delay before retry ``i`` (0-based) is ``base_delay * 2**i`` capped at
    ``max_delay``, scaled by a deterministic jitter draw into
    ``[(1 - jitter) * d, d]`` (decorrelated "full jitter downward": the
    cap is the worst case, never exceeded)."""

    max_retries: int = 0
    base_delay: float = 0.5
    max_delay: float = 30.0
    jitter: float = 0.5

    def delay(self, attempt, key=0):
        """Backoff before retry number ``attempt`` (0-based), jittered
        deterministically in ``(key, attempt)``."""
        d = min(self.base_delay * (2.0 ** max(0, int(attempt))),
                self.max_delay)
        if not self.jitter:
            return d
        rng = random.Random(f"{key}:{attempt}")
        return d * (1.0 - self.jitter * rng.random())

    def delay_after(self, attempt, key=0, floor=0.0):
        """Backoff before retry ``attempt`` honoring a server hint:
        the jittered exponential delay, raised to ``floor`` when the
        server's ``Retry-After`` asks the client to stay away longer
        (the ask/tell service computes it from live wave latency —
        overriding it downward would re-create the stampede the hint
        exists to spread)."""
        return max(float(floor), self.delay(attempt, key=key))

    def retries_left(self, attempts):
        """True while a trial that has already made ``attempts`` attempts
        may run again (``attempts`` counts the first try)."""
        return attempts <= self.max_retries

    @classmethod
    def coerce(cls, v):
        """``None`` → no-retry policy, an int → that many retries with
        defaults, a policy → itself (the knob every constructor takes)."""
        if v is None:
            return cls(0)
        if isinstance(v, cls):
            return v
        if isinstance(v, int):
            return cls(max_retries=v)
        raise TypeError(f"retry must be None, an int, or RetryPolicy; got {v!r}")

    @classmethod
    def from_env(cls, env=None):
        """``HYPEROPT_TPU_TRIAL_RETRIES=<n>[:<base_delay>]`` → policy (the
        worker-CLI default); unset/invalid → no retries (warn-free: a
        missing knob is the common case, a malformed one falls back to the
        safe default)."""
        env = os.environ if env is None else env
        raw = env.get("HYPEROPT_TPU_TRIAL_RETRIES", "").strip()
        if not raw:
            return cls(0)
        n_s, _, base_s = raw.partition(":")
        try:
            n = int(n_s)
            base = float(base_s) if base_s else 0.5
            if n < 0 or base <= 0:
                raise ValueError
        except ValueError:
            return cls(0)
        return cls(max_retries=n, base_delay=base)
