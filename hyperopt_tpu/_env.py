"""Shared subprocess-environment recipe for forced virtual-CPU JAX.

Used by every harness path that must NOT touch the ambient accelerator
(bench fallback, multi-chip dryrun, sharded-scaling child, worker tests):
the ambient env may carry a site accelerator plugin (keyed off
``PALLAS_AXON_POOL_IPS``) whose broken tunnel hangs backend init
uncatchably, so these paths run in clean subprocesses on virtual CPU
devices.  One definition — the recipe drifted when it was hand-copied
per call site.
"""

from __future__ import annotations

import re

__all__ = ["forced_cpu_env"]


def forced_cpu_env(environ, n_devices=None):
    """Copy ``environ`` with JAX pinned to CPU (and, optionally, an
    ``n_devices``-wide virtual device pool via XLA_FLAGS).

    An existing ``--xla_force_host_platform_device_count`` flag is REPLACED,
    not kept: a child process may need a different pool width than the parent
    that spawned it (e.g. the 8-device dryrun launching 4-device
    multi-controller children)."""
    env = dict(environ)
    env["JAX_PLATFORMS"] = "cpu"
    # the site accelerator plugin (keyed off this var) would otherwise
    # re-register the single real chip instead of virtual CPUs
    env.pop("PALLAS_AXON_POOL_IPS", None)
    if n_devices:
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+", "",
            env.get("XLA_FLAGS", ""),
        ).strip()
        env["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n_devices}".strip()
        )
    return env
