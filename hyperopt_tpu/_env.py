"""Shared subprocess-environment recipe for forced virtual-CPU JAX.

Used by every harness path that must NOT touch the ambient accelerator
(bench fallback, multi-chip dryrun, sharded-scaling child, worker tests):
the ambient env may carry a site accelerator plugin (keyed off
``PALLAS_AXON_POOL_IPS``) whose broken tunnel hangs backend init
uncatchably, so these paths run in clean subprocesses on virtual CPU
devices.  One definition — the recipe drifted when it was hand-copied
per call site.
"""

from __future__ import annotations

import logging
import os
import re

__all__ = [
    "forced_cpu_env",
    "enable_persistent_compilation_cache",
    "parse_obs_http",
    "parse_devmem_period",
    "parse_hist_dtype",
    "parse_shard",
    "parse_hist_shard_min",
    "parse_pallas",
    "parse_megakernel",
    "parse_allgather_timeout",
    "parse_service",
    "parse_service_max_studies",
    "parse_service_max_pending",
    "parse_service_idle_sec",
    "parse_service_wal",
    "parse_service_deadline_ms",
    "parse_service_queue",
    "parse_service_degrade",
    "parse_reqtrace",
    "parse_service_access_log",
    "parse_service_slo",
    "parse_store_watermark",
    "parse_store_gc",
    "parse_load",
    "parse_load_slo",
    "parse_probe",
    "parse_probe_period",
    "parse_probe_slo",
    "parse_tenant",
    "parse_tenant_top_k",
    "parse_tenant_quota",
    "parse_tenant_slo",
]

logger = logging.getLogger(__name__)

# observability env vars follow one convention: unset/0/off disables, a bad
# value WARNS ONCE and disables — telemetry misconfiguration must never take
# down the run it would have observed
_warned_envs = set()


def _warn_once(var, raw, why):
    if var not in _warned_envs:
        _warned_envs.add(var)
        logger.warning("%s=%r is not %s; disabling (observability env "
                       "values warn-and-disable, never raise)", var, raw, why)


def parse_obs_http(env=None):
    """``HYPEROPT_TPU_OBS_HTTP=<port>`` (or ``<host>:<port>`` to bind
    beyond the loopback default) → the value for ``ObsConfig.http_port``,
    or None when unset/disabled/invalid.  ``0`` in the ENVIRONMENT means
    "off" (the kwarg form ``obs_http=0`` means "ephemeral port" — only an
    explicit API caller can usefully ask for a port it must then
    discover)."""
    env = os.environ if env is None else env
    raw = env.get("HYPEROPT_TPU_OBS_HTTP", "").strip()
    if raw.lower() in ("", "0", "off", "false", "no"):
        return None
    host, _, port_s = raw.rpartition(":")
    try:
        port = int(port_s)
    except ValueError:
        _warn_once("HYPEROPT_TPU_OBS_HTTP", raw,
                   "an integer port (or host:port)")
        return None
    if not 1 <= port <= 65535:
        _warn_once("HYPEROPT_TPU_OBS_HTTP", raw, "a port in [1, 65535]")
        return None
    return raw if host else port


# default devmem sample period, owned here so obs/devmem.py and the env
# parser can share it without an import cycle
DEFAULT_DEVMEM_PERIOD_SEC = 10.0


def parse_devmem_period(env=None):
    """``HYPEROPT_TPU_DEVMEM=<seconds>`` → float sample period for the
    device-memory telemetry sampler (``obs/devmem.py``), or None when
    unset/disabled/invalid.  ``1``/``on`` selects the default period."""
    env = os.environ if env is None else env
    raw = env.get("HYPEROPT_TPU_DEVMEM", "").strip()
    if raw.lower() in ("", "0", "off", "false", "no"):
        return None
    if raw.lower() in ("1", "on", "true", "yes"):
        return DEFAULT_DEVMEM_PERIOD_SEC
    try:
        period = float(raw)
    except ValueError:
        _warn_once("HYPEROPT_TPU_DEVMEM", raw, "a sample period in seconds")
        return None
    if not period > 0:
        _warn_once("HYPEROPT_TPU_DEVMEM", raw, "a positive sample period")
        return None
    return period

# -- sharded-suggest / compressed-history knobs (ISSUE 6) -------------------
# These follow the same warn-and-disable convention as the observability
# vars: a bad value must never take down the run it would have tuned.

def parse_hist_dtype(env=None):
    """``HYPEROPT_TPU_HIST_DTYPE=int8|fp8|bf16|f32`` → the DEVICE storage
    dtype name for the padded-history mirror (default f32).  The host
    numpy arrays stay float32 and authoritative either way —
    pickle/checkpoint never see the compressed form; kernels accumulate
    in f32 after an on-read upcast (docs/DESIGN.md §13).

    ``int8``/``fp8`` (ISSUE 19) store affine-quantized history codes
    (``quant.py``; per-label scale/zero derived from the space, losses
    kept bf16) so the same HBM holds 4x the bf16 ``hist_cap``; spaces or
    backends the code cannot represent degrade that history to bf16 with
    a warn-once, never failing an ask (docs/DESIGN.md §25)."""
    env = os.environ if env is None else env
    raw = env.get("HYPEROPT_TPU_HIST_DTYPE", "").strip().lower()
    if raw in ("", "f32", "fp32", "float32"):
        return "float32"
    if raw in ("bf16", "bfloat16"):
        return "bfloat16"
    if raw in ("int8", "i8"):
        return "int8"
    if raw in ("fp8", "f8", "float8", "float8_e4m3fn"):
        return "fp8"
    _warn_once("HYPEROPT_TPU_HIST_DTYPE", raw, "one of int8|fp8|bf16|f32")
    return "float32"


def parse_shard(env=None):
    """``HYPEROPT_TPU_SHARD`` → number of devices the fused tell+ask
    program shards over, or None when disabled.  ``auto``/``on`` (or
    ``all``) means "all local devices" (returned as ``-1``); an integer
    ``k >= 1`` uses exactly the first ``k``.  Disabled (default) keeps the
    single-chip program byte-identical to previous rounds."""
    env = os.environ if env is None else env
    raw = env.get("HYPEROPT_TPU_SHARD", "").strip().lower()
    if raw in ("", "0", "off", "false", "no"):
        return None
    if raw in ("on", "true", "yes", "auto", "all"):
        return -1  # all local devices
    try:
        k = int(raw)
    except ValueError:
        _warn_once("HYPEROPT_TPU_SHARD", raw, "an integer device count "
                   "(or auto/on/off)")
        return None
    if k < 1:
        _warn_once("HYPEROPT_TPU_SHARD", raw, "a positive device count")
        return None
    return k


# default per-chip history-capacity threshold above which the history AXIS
# shards across the mesh (below it, history replicates: the Parzen fit
# wants the whole history anyway and replication avoids the gather)
DEFAULT_HIST_SHARD_MIN = 65536


def parse_hist_shard_min(env=None):
    """``HYPEROPT_TPU_HIST_SHARD_MIN=<cap>`` → capacity threshold at which
    a sharded suggest program also shards the HISTORY axis (per-chip HBM
    then holds ``cap / n_shards`` rows).  Default 65536."""
    env = os.environ if env is None else env
    raw = env.get("HYPEROPT_TPU_HIST_SHARD_MIN", "").strip()
    if not raw:
        return DEFAULT_HIST_SHARD_MIN
    try:
        v = int(raw)
    except ValueError:
        _warn_once("HYPEROPT_TPU_HIST_SHARD_MIN", raw, "an integer capacity")
        return DEFAULT_HIST_SHARD_MIN
    if v < 1:
        _warn_once("HYPEROPT_TPU_HIST_SHARD_MIN", raw, "a positive capacity")
        return DEFAULT_HIST_SHARD_MIN
    return v


def parse_pallas(env=None):
    """``HYPEROPT_TPU_PALLAS=1`` → DEPRECATED alias for
    ``HYPEROPT_TPU_MEGAKERNEL=1`` (ISSUE 19): routes the numeric EI score
    through the hand-scheduled ``megakernel.ei_diff`` pair.  Still safe to
    arm (the kernel falls back to the jnp twin off-TPU), but new deploys
    should set ``HYPEROPT_TPU_MEGAKERNEL``, which fuses the WHOLE ask tick
    rather than the single EI op (docs/DESIGN.md §25).  Warns once."""
    env = os.environ if env is None else env
    raw = env.get("HYPEROPT_TPU_PALLAS", "").strip().lower()
    armed = raw not in ("", "0", "off", "false", "no")
    if armed and "HYPEROPT_TPU_PALLAS" not in _warned_envs:
        _warned_envs.add("HYPEROPT_TPU_PALLAS")
        logger.warning(
            "HYPEROPT_TPU_PALLAS is deprecated (single-op EI kernel); set "
            "HYPEROPT_TPU_MEGAKERNEL=1 for the fused ask-tick kernel "
            "(docs/DESIGN.md §25). Honoring the alias this run.")
    return armed


def parse_megakernel(env=None):
    """``HYPEROPT_TPU_MEGAKERNEL`` → arming mode for the fused ask-tick
    Pallas megakernel (ISSUE 19, ``megakernel.py``):

    * unset / ``0`` / ``off`` → ``"off"`` — the jnp program, byte-identical
      to previous rounds;
    * ``1`` / ``on`` → ``"on"`` — fuse the tick on TPU backends; any
      non-TPU backend or lowering failure falls back to the jnp program
      with a warn-once counter (never fails an ask);
    * ``interpret`` → ``"interpret"`` — run the kernel through the Pallas
      interpreter on any backend (CPU CI exercises the real kernel body;
      orders of magnitude slower — tests only).

    Anything else warns once and stays off."""
    env = os.environ if env is None else env
    raw = env.get("HYPEROPT_TPU_MEGAKERNEL", "").strip().lower()
    if raw in ("", "0", "off", "false", "no"):
        return "off"
    if raw in ("1", "on", "true", "yes"):
        return "on"
    if raw == "interpret":
        return "interpret"
    _warn_once("HYPEROPT_TPU_MEGAKERNEL", raw, "one of 1|0|interpret")
    return "off"


def parse_allgather_timeout(env=None):
    """``HYPEROPT_TPU_ALLGATHER_TIMEOUT=<seconds>`` → monotonic deadline
    for every ``fmin_multihost`` collective (driver.py ``_timed_gather``),
    or None when unset/disabled/invalid.  Armed, a collective whose peer
    died degrades to checkpoint-and-shrink (``FleetDegraded``) instead of
    hanging; disarmed (the default) the collective path is byte-identical
    to previous rounds and starts no threads."""
    env = os.environ if env is None else env
    raw = env.get("HYPEROPT_TPU_ALLGATHER_TIMEOUT", "").strip()
    if raw.lower() in ("", "0", "off", "false", "no"):
        return None
    try:
        sec = float(raw)
    except ValueError:
        _warn_once("HYPEROPT_TPU_ALLGATHER_TIMEOUT", raw,
                   "a timeout in seconds")
        return None
    if not sec > 0:
        _warn_once("HYPEROPT_TPU_ALLGATHER_TIMEOUT", raw,
                   "a positive timeout")
        return None
    return sec


# -- ask/tell service knobs (ISSUE 9) ---------------------------------------
# Same warn-and-disable convention: a bad value must never take down the
# service it would have tuned.


def parse_service(env=None):
    """``HYPEROPT_TPU_SERVICE=<port>`` (or ``<host>:<port>``) → the bind
    value for the ask/tell serving front end (``python -m
    hyperopt_tpu.service.server`` reads it when ``--port`` is absent), or
    None when unset/disabled/invalid.  Same grammar as
    :func:`parse_obs_http` — ``0``/``off`` in the environment means
    disabled; only the CLI's explicit ``--port 0`` asks for an ephemeral
    port it then announces."""
    env = os.environ if env is None else env
    raw = env.get("HYPEROPT_TPU_SERVICE", "").strip()
    if raw.lower() in ("", "0", "off", "false", "no"):
        return None
    host, _, port_s = raw.rpartition(":")
    try:
        port = int(port_s)
    except ValueError:
        _warn_once("HYPEROPT_TPU_SERVICE", raw,
                   "an integer port (or host:port)")
        return None
    if not 1 <= port <= 65535:
        _warn_once("HYPEROPT_TPU_SERVICE", raw, "a port in [1, 65535]")
        return None
    return raw if host else port


def _parse_pos_int(var, default, env=None):
    env = os.environ if env is None else env
    raw = env.get(var, "").strip()
    if not raw:
        return default
    try:
        v = int(raw)
    except ValueError:
        _warn_once(var, raw, "an integer")
        return default
    if v < 1:
        _warn_once(var, raw, "a positive integer")
        return default
    return v


def parse_service_max_studies(env=None):
    """``HYPEROPT_TPU_SERVICE_MAX_STUDIES`` → admission quota: how many
    live studies the scheduler accepts before ``POST /study`` answers 429
    (default 4096)."""
    return _parse_pos_int("HYPEROPT_TPU_SERVICE_MAX_STUDIES", 4096, env)


def parse_service_max_pending(env=None):
    """``HYPEROPT_TPU_SERVICE_MAX_PENDING`` → per-study quota on asked-
    but-untold trials; an ask past it answers 429 instead of letting one
    client starve the cohort (default 64)."""
    return _parse_pos_int("HYPEROPT_TPU_SERVICE_MAX_PENDING", 64, env)


def parse_service_idle_sec(env=None):
    """``HYPEROPT_TPU_SERVICE_IDLE_SEC`` → seconds of inactivity after
    which a study's cohort slot is evicted (the study itself survives and
    re-admits on its next ask; default 600).  Accepts fractions, like the
    ``--idle-sec`` CLI flag; ``0``/``off`` disables idle eviction."""
    env = os.environ if env is None else env
    raw = env.get("HYPEROPT_TPU_SERVICE_IDLE_SEC", "").strip()
    if not raw:
        return 600.0
    if raw.lower() in ("0", "off", "false", "no"):
        return float("inf")  # never evict on idleness
    try:
        sec = float(raw)
    except ValueError:
        _warn_once("HYPEROPT_TPU_SERVICE_IDLE_SEC", raw,
                   "a duration in seconds (or 0/off)")
        return 600.0
    if sec < 0:
        _warn_once("HYPEROPT_TPU_SERVICE_IDLE_SEC", raw,
                   "a non-negative duration")
        return 600.0
    return sec


# -- durable/overload-safe serving knobs (ISSUE 10) -------------------------
# Same warn-and-disable convention: a bad value must never take down the
# service it would have hardened.


def parse_service_wal(env=None):
    """``HYPEROPT_TPU_SERVICE_WAL`` → the write-ahead-journal arming mode
    for the ask/tell service:

    * unset / ``1`` / ``on`` → ``"auto"`` — journal under the store root
      when the scheduler has one (``<store>/service.wal.jsonl``), off
      otherwise (an in-memory scheduler has nowhere durable to resume
      from anyway);
    * ``0`` / ``off`` → ``None`` — never journal, even with a store;
    * anything else → an explicit journal PATH (arms the WAL with or
      without a store; without one, replay regenerates every ask from
      the journal alone).
    """
    env = os.environ if env is None else env
    raw = env.get("HYPEROPT_TPU_SERVICE_WAL", "").strip()
    if raw.lower() in ("", "1", "on", "true", "yes", "auto"):
        return "auto"
    if raw.lower() in ("0", "off", "false", "no"):
        return None
    return raw


DEFAULT_SERVICE_DEADLINE_MS = 30000.0


def parse_service_deadline_ms(env=None):
    """``HYPEROPT_TPU_SERVICE_DEADLINE_MS`` → the server-side default
    request deadline in milliseconds (a request may tighten it with an
    ``X-Deadline-Ms`` header, never loosen it past this).  ``0``/``off``
    disables the default deadline; default 30000."""
    env = os.environ if env is None else env
    raw = env.get("HYPEROPT_TPU_SERVICE_DEADLINE_MS", "").strip()
    if not raw:
        return DEFAULT_SERVICE_DEADLINE_MS
    if raw.lower() in ("0", "off", "false", "no"):
        return None
    try:
        ms = float(raw)
    except ValueError:
        _warn_once("HYPEROPT_TPU_SERVICE_DEADLINE_MS", raw,
                   "a deadline in milliseconds (or 0/off)")
        return DEFAULT_SERVICE_DEADLINE_MS
    if not ms > 0:
        _warn_once("HYPEROPT_TPU_SERVICE_DEADLINE_MS", raw,
                   "a positive deadline")
        return DEFAULT_SERVICE_DEADLINE_MS
    return ms


def parse_service_queue(env=None):
    """``HYPEROPT_TPU_SERVICE_QUEUE`` → the bounded admission queue: how
    many asks may be admitted (queued or in a wave) before new asks shed
    with 429 + ``Retry-After`` (default 256).  Tells shed only past 4x
    this bound — they are cheap and preserve state, so the breaker sheds
    the expensive path first."""
    return _parse_pos_int("HYPEROPT_TPU_SERVICE_QUEUE", 256, env)


DEFAULT_DEGRADE_RECOVER_WAVES = 8


def parse_service_degrade(env=None):
    """``HYPEROPT_TPU_SERVICE_DEGRADE`` → the device-fault degrade
    ladder: ``None`` when disabled (``0``/``off`` — a tick fault then
    fails the asks it served, the pre-ladder behavior), else the number
    of CLEAN waves after which the ladder probes one level back up
    (unset/``on`` → default 8; any positive integer — including ``1``
    — picks the recovery patience directly)."""
    env = os.environ if env is None else env
    raw = env.get("HYPEROPT_TPU_SERVICE_DEGRADE", "").strip()
    if raw.lower() in ("", "on", "true", "yes", "auto"):
        return DEFAULT_DEGRADE_RECOVER_WAVES
    if raw.lower() in ("0", "off", "false", "no"):
        return None
    try:
        n = int(raw)
    except ValueError:
        _warn_once("HYPEROPT_TPU_SERVICE_DEGRADE", raw,
                   "a clean-wave count (or 0/off)")
        return DEFAULT_DEGRADE_RECOVER_WAVES
    if n < 1:
        _warn_once("HYPEROPT_TPU_SERVICE_DEGRADE", raw,
                   "a positive clean-wave count")
        return DEFAULT_DEGRADE_RECOVER_WAVES
    return n


# -- request-scoped observability knobs (ISSUE 11) --------------------------
# Same warn-and-disable convention: a bad value must never take down the
# service it would have observed.


def parse_reqtrace(env=None):
    """``HYPEROPT_TPU_REQTRACE`` → whether the request-trace context
    plane (``obs/reqtrace.py``) is armed.  Default ON — trace ids are
    pure metadata (no threads, never touch proposals), and a serving
    fleet without request correlation is undebuggable.  ``0``/``off``
    disarms everything: no minting, no header, no WAL ``trace`` field
    (the bench ``trace_overhead`` stage measures the armed-vs-disarmed
    per-ask delta)."""
    env = os.environ if env is None else env
    raw = env.get("HYPEROPT_TPU_REQTRACE", "").strip().lower()
    return raw not in ("0", "off", "false", "no")


def parse_service_access_log(env=None):
    """``HYPEROPT_TPU_SERVICE_ACCESS_LOG=<path>`` → JSONL access-log
    path for the ask/tell server (one record per request: method, path,
    status, latency ms, trace id, shed/degrade reason), or None when
    unset/disabled.  Opt-in: the default server keeps its
    ``log_message``-swallowing silence."""
    env = os.environ if env is None else env
    raw = env.get("HYPEROPT_TPU_SERVICE_ACCESS_LOG", "").strip()
    if raw.lower() in ("", "0", "off", "false", "no"):
        return None
    return raw


def parse_service_slo(env=None):
    """``HYPEROPT_TPU_SERVICE_SLO`` → SLO-plane targets for the serving
    front end (``obs/slo.py``), or None when disabled:

    * unset / ``1`` / ``on`` → the default objectives
      (availability 99.9%, 99% of asks under 500ms, ≤5% shed);
    * ``0`` / ``off`` → None — no plane, no gauges, no escalation;
    * a spec string tunes targets:
      ``avail=99.9,ask_p99_ms=250,ask_pct=99,shed=2`` — ``avail`` and
      ``ask_pct`` in percent, ``ask_p99_ms`` the latency threshold in
      milliseconds, ``shed`` the allowed shed percentage.  Unknown or
      malformed tokens warn once and keep that objective's default.
    """
    env = os.environ if env is None else env
    raw = env.get("HYPEROPT_TPU_SERVICE_SLO", "").strip()
    if raw.lower() in ("", "1", "on", "true", "yes", "auto"):
        from .obs.slo import DEFAULT_TARGETS

        return {k: dict(v) for k, v in DEFAULT_TARGETS.items()}
    if raw.lower() in ("0", "off", "false", "no"):
        return None
    from .obs.slo import DEFAULT_TARGETS

    targets = {k: dict(v) for k, v in DEFAULT_TARGETS.items()}
    for token in raw.split(","):
        token = token.strip()
        if not token:
            continue
        key, _, val = token.partition("=")
        key = key.strip().lower()
        try:
            v = float(val)
        except ValueError:
            _warn_once("HYPEROPT_TPU_SERVICE_SLO", token,
                       "a key=number token")
            continue
        if key in ("avail", "availability") and 0 < v < 100:
            targets["availability"]["target"] = v / 100.0
        elif key in ("ask_p99_ms", "ask_ms") and v > 0:
            targets["ask_latency"]["threshold_ms"] = v
        elif key in ("ask_pct",) and 0 < v < 100:
            targets["ask_latency"]["target"] = v / 100.0
        elif key in ("shed",) and 0 <= v < 100:
            # shed=0 means "any shed burns budget" — clamp under 1.0 so
            # the objective stays a valid (0,1) target
            targets["shed_rate"]["target"] = min(0.9999, 1.0 - v / 100.0)
        else:
            _warn_once("HYPEROPT_TPU_SERVICE_SLO", token,
                       "one of avail=/ask_p99_ms=/ask_pct=/shed= with a "
                       "sane value")
    return targets


# -- search-quality observability knobs (ISSUE 16) --------------------------
# Same warn-and-disable convention: a bad value must never take down the
# service it would have observed.


def parse_quality(env=None):
    """``HYPEROPT_TPU_QUALITY`` → whether the search-quality telemetry
    plane (``obs/quality.py``) is armed on the scheduler.  Default ON —
    quality tracking is pure tell-time metadata (no threads, never
    touches proposals, O(1) per tell), and a serving fleet that cannot
    tell "optimizing" from "plateaued" is flying blind.  ``0``/``off``
    disarms everything: no trackers, no gauges, no timeline events (the
    bench ``quality_overhead`` stage measures the armed-vs-disarmed
    per-tell delta)."""
    env = os.environ if env is None else env
    raw = env.get("HYPEROPT_TPU_QUALITY", "").strip().lower()
    return raw not in ("0", "off", "false", "no")


def parse_quality_slo(env=None):
    """``HYPEROPT_TPU_QUALITY_SLO`` → the stagnant-fraction objective the
    quality plane feeds into the server's SLO burn-rate plane, or None
    when disabled:

    * unset / ``1`` / ``on`` → the default ``stagnation`` objective
      (≥90% of live tells land on non-stagnant studies);
    * ``0`` / ``off`` → None — quality telemetry still runs, it just
      does not burn an error budget;
    * ``stagnant=N`` → allow N percent of live tells on stagnant
      studies before burning budget.  Malformed tokens warn once and
      keep the default.
    """
    env = os.environ if env is None else env
    raw = env.get("HYPEROPT_TPU_QUALITY_SLO", "").strip()
    if raw.lower() in ("", "1", "on", "true", "yes", "auto"):
        from .obs.slo import QUALITY_TARGETS

        return {k: dict(v) for k, v in QUALITY_TARGETS.items()}
    if raw.lower() in ("0", "off", "false", "no"):
        return None
    from .obs.slo import QUALITY_TARGETS

    targets = {k: dict(v) for k, v in QUALITY_TARGETS.items()}
    for token in raw.split(","):
        token = token.strip()
        if not token:
            continue
        key, _, val = token.partition("=")
        key = key.strip().lower()
        try:
            v = float(val)
        except ValueError:
            _warn_once("HYPEROPT_TPU_QUALITY_SLO", token,
                       "a key=number token")
            continue
        if key in ("stagnant", "stagnation") and 0 <= v < 100:
            # stagnant=0 means "any stagnant tell burns budget" — clamp
            # under 1.0 so the objective stays a valid (0,1) target
            targets["stagnation"]["target"] = min(0.9999, 1.0 - v / 100.0)
        else:
            _warn_once("HYPEROPT_TPU_QUALITY_SLO", token,
                       "stagnant=<percent>")
    return targets


def parse_load(env=None):
    """``HYPEROPT_TPU_LOAD`` → whether the load & cost attribution
    ledger (``obs/load.py``) is armed on the scheduler.  Default ON —
    attribution is pure wave-time arithmetic (no threads, never touches
    proposals, O(1) per cohort tick), and a fleet that cannot say which
    studies and shards are spending its device time cannot be balanced
    (ROADMAP 5b/5c).  ``0``/``off`` disarms everything: no rows, no
    gauges, no heat-ledger appends (the bench ``load_attribution``
    stage measures the armed-vs-disarmed per-wave delta)."""
    env = os.environ if env is None else env
    raw = env.get("HYPEROPT_TPU_LOAD", "").strip().lower()
    return raw not in ("0", "off", "false", "no")


def parse_load_slo(env=None):
    """``HYPEROPT_TPU_LOAD_SLO`` → the fleet-imbalance objective the
    load ledger feeds into the server's SLO burn-rate plane, or None
    when disabled:

    * unset / ``1`` / ``on`` → the default ``imbalance`` objective
      (≥90% of load observations see heat skew ≤ the skew bound);
    * ``0`` / ``off`` → None — load attribution still runs, it just
      does not burn an error budget;
    * ``skew=N`` → the heat-skew bound (max/mean shard heat) an
      observation must stay under to count balanced (default 3.0;
      must exceed 1.0 — a perfectly balanced fleet sits at 1.0);
    * ``balanced=N`` → allow N percent of observations over the bound
      before burning budget.  Malformed tokens warn once and keep the
      defaults.
    """
    env = os.environ if env is None else env
    raw = env.get("HYPEROPT_TPU_LOAD_SLO", "").strip()
    if raw.lower() in ("", "1", "on", "true", "yes", "auto"):
        from .obs.slo import LOAD_TARGETS

        return {k: dict(v) for k, v in LOAD_TARGETS.items()}
    if raw.lower() in ("0", "off", "false", "no"):
        return None
    from .obs.slo import LOAD_TARGETS

    targets = {k: dict(v) for k, v in LOAD_TARGETS.items()}
    for token in raw.split(","):
        token = token.strip()
        if not token:
            continue
        key, _, val = token.partition("=")
        key = key.strip().lower()
        try:
            v = float(val)
        except ValueError:
            _warn_once("HYPEROPT_TPU_LOAD_SLO", token,
                       "a key=number token")
            continue
        if key == "skew" and v > 1.0:
            targets["imbalance"]["skew_max"] = v
        elif key == "balanced" and 0 <= v < 100:
            targets["imbalance"]["target"] = min(0.9999, 1.0 - v / 100.0)
        else:
            _warn_once("HYPEROPT_TPU_LOAD_SLO", token,
                       "skew=<ratio>1> or balanced=<percent>")
    return targets


# -- blackbox prober knobs (ISSUE 18) ---------------------------------------
# Same warn-and-disable convention — except the arming default, which is
# OFF: the prober is the one obs plane that generates TRAFFIC (synthetic
# canary studies through the real client path), so it must be asked for.


DEFAULT_PROBE_PERIOD_SEC = 30.0


def parse_probe(env=None):
    """``HYPEROPT_TPU_PROBE`` → whether the server arms the blackbox
    prober (``obs/prober.py``) against itself after startup.  Default
    OFF — disarmed means zero threads, zero allocations, no canary
    traffic; ``1``/``on`` arms it (also ``--probe`` on the server CLI,
    which wins over the env)."""
    env = os.environ if env is None else env
    raw = env.get("HYPEROPT_TPU_PROBE", "").strip().lower()
    return raw in ("1", "on", "true", "yes")


def parse_probe_period(env=None):
    """``HYPEROPT_TPU_PROBE_PERIOD=<seconds>`` → the probe cycle cadence
    (default 30s).  One canary study per cycle per target; malformed or
    non-positive values warn once and keep the default."""
    env = os.environ if env is None else env
    raw = env.get("HYPEROPT_TPU_PROBE_PERIOD", "").strip()
    if not raw:
        return DEFAULT_PROBE_PERIOD_SEC
    try:
        v = float(raw)
    except ValueError:
        _warn_once("HYPEROPT_TPU_PROBE_PERIOD", raw, "a number of seconds")
        return DEFAULT_PROBE_PERIOD_SEC
    if v <= 0:
        _warn_once("HYPEROPT_TPU_PROBE_PERIOD", raw, "a positive period")
        return DEFAULT_PROBE_PERIOD_SEC
    return v


def parse_probe_slo(env=None):
    """``HYPEROPT_TPU_PROBE_SLO`` → the blackbox objectives the prober
    feeds into the server's SLO burn-rate plane, or None when disabled:

    * unset / ``1`` / ``on`` → the default ``probe_avail`` /
      ``probe_golden_match`` / ``probe_ask_p99_ms`` objectives —
      client-view signals, deliberately distinct from the server-side
      ``availability``/``ask_latency`` pair so a wedged listener burns
      budget;
    * ``0`` / ``off`` → None — probing still runs and renders verdicts,
      it just does not burn an error budget;
    * ``avail=N`` (percent), ``golden=N`` (percent of cycles that must
      match golden), ``ask_p99_ms=N`` (the latency threshold a probe
      ask must beat).  Malformed tokens warn once and keep defaults.
    """
    env = os.environ if env is None else env
    raw = env.get("HYPEROPT_TPU_PROBE_SLO", "").strip()
    if raw.lower() in ("", "1", "on", "true", "yes", "auto"):
        from .obs.slo import PROBE_TARGETS

        return {k: dict(v) for k, v in PROBE_TARGETS.items()}
    if raw.lower() in ("0", "off", "false", "no"):
        return None
    from .obs.slo import PROBE_TARGETS

    targets = {k: dict(v) for k, v in PROBE_TARGETS.items()}
    for token in raw.split(","):
        token = token.strip()
        if not token:
            continue
        key, _, val = token.partition("=")
        key = key.strip().lower()
        try:
            v = float(val)
        except ValueError:
            _warn_once("HYPEROPT_TPU_PROBE_SLO", token,
                       "a key=number token")
            continue
        if key in ("avail", "availability") and 0 < v <= 100:
            targets["probe_avail"]["target"] = min(0.9999, v / 100.0)
        elif key == "golden" and 0 < v <= 100:
            targets["probe_golden_match"]["target"] = \
                min(0.9999, v / 100.0)
        elif key == "ask_p99_ms" and v > 0:
            targets["probe_ask_p99_ms"]["threshold_ms"] = v
        else:
            _warn_once("HYPEROPT_TPU_PROBE_SLO", token,
                       "one of avail=/golden=/ask_p99_ms= with a sane "
                       "value")
    return targets


# -- cold-start compile plane knobs (ISSUE 14) ------------------------------
# Same warn-and-disable convention: a bad value must never take down the
# serving plane it would have warmed.


DEFAULT_COMPILE_BANK_TOP_N = 8


def parse_compile_plane(env=None):
    """``HYPEROPT_TPU_COMPILE_PLANE`` → arm the cold-start compile plane
    (ISSUE 14): studies whose cohort program is not yet compiled are
    served by flagged ``rand.suggest`` (the WARMING state) while one
    background thread compiles, and a census-driven kernel bank pre-warms
    common keys at server start.  Opt-in (default OFF): the disarmed
    scheduler is byte-identical to the pre-ISSUE-14 path, and arming
    changes the early proposals of brand-new cohort keys (rand until the
    program lands — recorded in the WAL, so replay stays bit-identical
    to the warming run itself)."""
    env = os.environ if env is None else env
    raw = env.get("HYPEROPT_TPU_COMPILE_PLANE", "").strip().lower()
    return raw in ("1", "on", "true", "yes", "auto")


def parse_compile_bank_top_n(env=None):
    """``HYPEROPT_TPU_COMPILE_BANK_TOP_N`` → how many census-ranked
    cohort keys the kernel bank compiles SYNCHRONOUSLY at server start,
    before the listener opens (the rest warm in the background; default
    8).  ``0`` defers everything to the background."""
    env = os.environ if env is None else env
    raw = env.get("HYPEROPT_TPU_COMPILE_BANK_TOP_N", "").strip()
    if not raw:
        return DEFAULT_COMPILE_BANK_TOP_N
    try:
        v = int(raw)
    except ValueError:
        _warn_once("HYPEROPT_TPU_COMPILE_BANK_TOP_N", raw, "an integer")
        return DEFAULT_COMPILE_BANK_TOP_N
    if v < 0:
        _warn_once("HYPEROPT_TPU_COMPILE_BANK_TOP_N", raw,
                   "a non-negative integer")
        return DEFAULT_COMPILE_BANK_TOP_N
    return v


def parse_compile_widen(env=None):
    """``HYPEROPT_TPU_COMPILE_WIDEN`` → widen cohort programs (ISSUE 14):
    compatible spaces (same widened profile — unconditional, same
    multiset of numeric/discrete shapes after pow2 label padding) share
    ONE compiled program, with per-label params and label hashes as
    runtime inputs.  Opt-in (default OFF): widened proposals route every
    label through the grouped pipeline (singleton families included), so
    they match the default path only to the grouped-vs-unrolled
    agreement tolerance — keep the flag stable across restarts of a
    WAL-resumed service."""
    env = os.environ if env is None else env
    raw = env.get("HYPEROPT_TPU_COMPILE_WIDEN", "").strip().lower()
    return raw in ("1", "on", "true", "yes")


# -- replicated serving fleet knobs (ISSUE 12) ------------------------------
# Same warn-and-disable convention: a bad value must never take down the
# fleet it would have partitioned.


DEFAULT_FLEET_SHARDS = 8
DEFAULT_FLEET_LEASE_TTL = 15.0


def parse_fleet_shards(env=None):
    """``HYPEROPT_TPU_FLEET_SHARDS`` → how many study-shards the fleet
    partitions the study keyspace into (default 8).  The shard count is
    a WRITE-ONCE property of a fleet store root (``fleet/params.json``
    pins it; joiners with a different value are refused) — changing it
    would re-bucket every existing study id."""
    return _parse_pos_int("HYPEROPT_TPU_FLEET_SHARDS",
                          DEFAULT_FLEET_SHARDS, env)


def parse_fleet_lease_ttl(env=None):
    """``HYPEROPT_TPU_FLEET_LEASE_TTL`` → seconds without a heartbeat
    after which a replica's study-shard lease is reclaimable by a
    survivor (default 15).  Lower = faster failover, higher = more
    tolerance for long GC/compile pauses; the steward heartbeats every
    ttl/4, so the TTL must comfortably exceed a wave's wall time."""
    env = os.environ if env is None else env
    raw = env.get("HYPEROPT_TPU_FLEET_LEASE_TTL", "").strip()
    if not raw:
        return DEFAULT_FLEET_LEASE_TTL
    try:
        sec = float(raw)
    except ValueError:
        _warn_once("HYPEROPT_TPU_FLEET_LEASE_TTL", raw,
                   "a duration in seconds")
        return DEFAULT_FLEET_LEASE_TTL
    if not sec > 0:
        _warn_once("HYPEROPT_TPU_FLEET_LEASE_TTL", raw,
                   "a positive duration")
        return DEFAULT_FLEET_LEASE_TTL
    return sec


def parse_fleet_addr(env=None):
    """``HYPEROPT_TPU_FLEET_ADDR`` → the URL this replica ADVERTISES in
    the ownership table (what 307 redirects point other clients at), or
    None to advertise the server's own bind URL.  Needed whenever the
    bind address is not the reachable one (0.0.0.0 binds, NAT,
    port-forwarded containers)."""
    env = os.environ if env is None else env
    raw = env.get("HYPEROPT_TPU_FLEET_ADDR", "").strip()
    if raw.lower() in ("", "0", "off", "false", "no"):
        return None
    return raw.rstrip("/")


# -- storage-integrity knobs (ISSUE 15) -------------------------------------

DEFAULT_STORE_WATERMARK = 0.02


def parse_store_watermark(env=None):
    """``HYPEROPT_TPU_STORE_WATERMARK`` → the low-disk threshold that
    trips the space-pressure degrade rung (compact quiescent WALs, run
    bounded store GC, then shed asks with 507 until space returns):

    * unset → the default (free fraction below 0.02);
    * a value in ``(0, 1)`` → minimum free FRACTION of the filesystem;
    * a value ``>= 1`` → minimum free BYTES;
    * ``0`` / ``off`` → disarmed (gauges still publish at scrape time).
    """
    env = os.environ if env is None else env
    raw = env.get("HYPEROPT_TPU_STORE_WATERMARK", "").strip()
    if not raw:
        return DEFAULT_STORE_WATERMARK
    if raw.lower() in ("0", "off", "false", "no"):
        return None
    try:
        v = float(raw)
    except ValueError:
        _warn_once("HYPEROPT_TPU_STORE_WATERMARK", raw,
                   "a free fraction in (0,1), a byte count, or 0/off")
        return DEFAULT_STORE_WATERMARK
    if v <= 0:
        return None
    return v


def parse_store_gc(env=None):
    """``HYPEROPT_TPU_STORE_GC`` → whether the disk-watermark degrade
    rung may run the bounded store GC (settle-superseded doc copies,
    stale tmp files, expired flight dumps, compaction-superseded
    ancestor epoch WALs) before shedding.  Default on; ``0``/``off``
    disables GC (the rung still compacts WALs and sheds)."""
    env = os.environ if env is None else env
    raw = env.get("HYPEROPT_TPU_STORE_GC", "").strip().lower()
    return raw not in ("0", "off", "false", "no")


# -- tenant observatory knobs (ISSUE 20) ------------------------------------


def parse_tenant(env=None):
    """``HYPEROPT_TPU_TENANT`` → whether the tenant observatory
    (``obs/tenant.py``: per-tenant attribution, the weighted-fair wave
    packer, per-tenant SLO objectives) is armed on the scheduler.
    Default ON — like the cost ledger, attribution is pure arithmetic
    on already-measured wave time (no threads, never touches
    proposals), and a multi-tenant edge that cannot say which principal
    is burning the fleet cannot be fair (ROADMAP 5b).  ``0``/``off``
    disarms everything: ``scheduler.tenants is None``, first-come
    packing, no gauges, no per-tenant SLOs."""
    env = os.environ if env is None else env
    raw = env.get("HYPEROPT_TPU_TENANT", "").strip().lower()
    return raw not in ("0", "off", "false", "no")


def parse_tenant_top_k(env=None):
    """``HYPEROPT_TPU_TENANT_TOP_K`` → the tenant ledger's named-row
    bound (top-K by activity; everything past it rolls into the
    ``other`` bucket).  Default 64; must be ≥ 1."""
    from .obs.tenant import DEFAULT_TOP_K

    env = os.environ if env is None else env
    raw = env.get("HYPEROPT_TPU_TENANT_TOP_K", "").strip()
    if not raw:
        return DEFAULT_TOP_K
    try:
        k = int(raw)
    except ValueError:
        _warn_once("HYPEROPT_TPU_TENANT_TOP_K", raw, "a positive integer")
        return DEFAULT_TOP_K
    if k < 1:
        _warn_once("HYPEROPT_TPU_TENANT_TOP_K", raw, "a positive integer")
        return DEFAULT_TOP_K
    return k


def parse_tenant_quota(env=None):
    """``HYPEROPT_TPU_TENANT_QUOTA`` → the per-tenant admission budget:
    the maximum asks ONE tenant may hold admitted (waiting or in a
    wave) at once.  Past it that tenant sheds (429 + ``Retry-After``)
    while others keep admitting — the noisy-neighbor breaker.

    * unset / ``0`` / ``off`` → None (no per-tenant budget; the global
      queue bound still applies);
    * a positive integer → the per-tenant inflight-ask cap.
    """
    env = os.environ if env is None else env
    raw = env.get("HYPEROPT_TPU_TENANT_QUOTA", "").strip()
    if not raw or raw.lower() in ("0", "off", "false", "no"):
        return None
    try:
        q = int(raw)
    except ValueError:
        _warn_once("HYPEROPT_TPU_TENANT_QUOTA", raw,
                   "a positive integer or 0/off")
        return None
    return q if q >= 1 else None


def parse_tenant_slo(env=None):
    """``HYPEROPT_TPU_TENANT_SLO`` → the per-tenant objective targets
    installed for each top-K tenant, or None when disabled:

    * unset / ``1`` / ``on`` → the defaults (:data:`~hyperopt_tpu.obs
      .slo.TENANT_TARGETS`: 99% availability, 99% of asks under 2s,
      ≤10% of offered asks shed — per tenant);
    * ``0`` / ``off`` → None — attribution still runs, tenants just
      do not burn error budgets;
    * ``avail=P`` / ``ask_p=P`` / ``shed=P`` → the target fraction of
      GOOD events per objective (in (0, 1));
    * ``ask_ms=N`` → the per-tenant ask latency threshold in ms.
      Malformed tokens warn once and keep the defaults.
    """
    env = os.environ if env is None else env
    raw = env.get("HYPEROPT_TPU_TENANT_SLO", "").strip()
    if raw.lower() in ("", "1", "on", "true", "yes", "auto"):
        from .obs.slo import TENANT_TARGETS

        return {k: dict(v) for k, v in TENANT_TARGETS.items()}
    if raw.lower() in ("0", "off", "false", "no"):
        return None
    from .obs.slo import TENANT_TARGETS

    targets = {k: dict(v) for k, v in TENANT_TARGETS.items()}
    for token in raw.split(","):
        token = token.strip()
        if not token:
            continue
        key, _, val = token.partition("=")
        key = key.strip().lower()
        try:
            v = float(val)
        except ValueError:
            _warn_once("HYPEROPT_TPU_TENANT_SLO", token,
                       "a key=number token")
            continue
        if key == "avail" and 0.0 < v < 1.0:
            targets["availability"]["target"] = v
        elif key == "ask_p" and 0.0 < v < 1.0:
            targets["ask_p99"]["target"] = v
        elif key == "ask_ms" and v > 0:
            targets["ask_p99"]["threshold_ms"] = v
        elif key == "shed" and 0.0 < v < 1.0:
            targets["shed_rate"]["target"] = v
        else:
            _warn_once("HYPEROPT_TPU_TENANT_SLO", token,
                       "avail/ask_p/shed=<frac in (0,1)> or ask_ms=<ms>")
    return targets


_CACHE_CONFIGURED = False
_EXPLICIT_DIR = None  # the explicit dir currently configured, if any


def enable_persistent_compilation_cache(cache_dir=None):
    """Point jax at an on-disk compilation cache (once per process) unless
    the user already configured one or opted out via
    ``HYPEROPT_TPU_NO_CACHE=1``.

    The TPE/rand suggest kernels cost seconds of XLA compile per space
    (BASELINE.md compile-vs-execute split); with the persistent cache that
    cost is paid once per MACHINE instead of once per process — every later
    "cold" ``fmin`` starts near-warm.  Called lazily by the fmin entry
    points, never at import (mutating global jax config on import would
    surprise embedders).

    ``cache_dir`` (or ``HYPEROPT_TPU_COMPILE_CACHE=<dir>``) pins the cache
    directory EXPLICITLY: no per-machine fingerprint partitioning (the
    caller owns dir hygiene across config changes), and the
    min-compile-time floor drops to 0 so even sub-second kernels cache —
    the setting bench's ``compile_cache`` stage measures cold-vs-warm
    with.  An explicit dir wins over an earlier automatic configuration.
    """
    global _CACHE_CONFIGURED
    opt_out = os.environ.get("HYPEROPT_TPU_NO_CACHE", "").strip().lower()
    if opt_out not in ("", "0", "false", "no"):
        return
    explicit = (str(cache_dir) if cache_dir
                else os.environ.get("HYPEROPT_TPU_COMPILE_CACHE", "").strip()
                or None)
    global _EXPLICIT_DIR
    if _CACHE_CONFIGURED and (explicit is None or explicit == _EXPLICIT_DIR):
        return
    import jax

    if explicit is not None:
        path = os.path.abspath(os.path.expanduser(explicit))
        try:
            os.makedirs(path, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", path)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.0)
            _CACHE_CONFIGURED = True
            _EXPLICIT_DIR = explicit
            return
        except Exception as e:
            # an unwritable EXPLICIT dir must not silently disable caching
            # wholesale: warn once and fall through to the automatic
            # per-machine dir, which is what an unset variable would use
            import logging

            logging.getLogger(__name__).warning(
                "compilation cache dir %s unusable (%s); falling back to "
                "the automatic per-machine cache", path, e)
    if _CACHE_CONFIGURED:
        return
    _CACHE_CONFIGURED = True

    if getattr(jax.config, "jax_compilation_cache_dir", None):
        return  # user (or bench harness) already picked a cache dir
    # partition by configuration fingerprint: XLA's cache key does not cover
    # every host-machine/flag difference, and loading an AOT entry compiled
    # under another configuration logs machine-feature mismatch errors (and
    # can SIGILL).  Processes with different platforms/XLA flags/CPUs get
    # disjoint directories instead of sharing one.
    import hashlib
    import platform

    try:
        with open("/proc/cpuinfo") as f:
            # x86 spells it 'flags', aarch64 'Features'
            cpu = next((ln for ln in f
                        if ln.startswith(("flags", "Features"))), "")
    except OSError:
        cpu = ""
    if not cpu:
        cpu = platform.processor() or platform.machine()
    tag = hashlib.sha1("|".join([
        os.environ.get("JAX_PLATFORMS", ""),
        os.environ.get("XLA_FLAGS", ""),
        jax.__version__,
        cpu,
    ]).encode()).hexdigest()[:10]
    path = os.path.join(os.path.expanduser("~"), ".cache", "hyperopt_tpu",
                        f"xla-{tag}")
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # unwritable HOME etc.: cache is an optimization only
        pass


def forced_cpu_env(environ, n_devices=None):
    """Copy ``environ`` with JAX pinned to CPU (and, optionally, an
    ``n_devices``-wide virtual device pool via XLA_FLAGS).

    An existing ``--xla_force_host_platform_device_count`` flag is REPLACED,
    not kept: a child process may need a different pool width than the parent
    that spawned it (e.g. the 8-device dryrun launching 4-device
    multi-controller children)."""
    env = dict(environ)
    env["JAX_PLATFORMS"] = "cpu"
    # the site accelerator plugin (keyed off this var) would otherwise
    # re-register the single real chip instead of virtual CPUs
    env.pop("PALLAS_AXON_POOL_IPS", None)
    if n_devices:
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+", "",
            env.get("XLA_FLAGS", ""),
        ).strip()
        env["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n_devices}".strip()
        )
    return env
