"""Write-ahead journal for the ask/tell service (the durable half of
ISSUE 10).

The scheduler's in-memory state — which studies exist, where each study's
seed stream is, which asks were issued — dies with the process; even with
``--store`` (per-study :class:`~hyperopt_tpu.filestore.FileTrials`) a
restart forgets every live study.  The journal closes that gap with the
cheapest durable structure that works on the filesystems TPU pods
actually mount (NFS / GCS-fuse): an append-only JSONL file under the
store root, read back through the torn-line-tolerant
:func:`~hyperopt_tpu.obs.trace.iter_jsonl` (a half-written final line —
the normal crash artifact — is skipped, never fatal).

Record kinds (one JSON object per line; every record carries ``kind``
and ``sid``)::

    admit     {spec, seed, kwargs}            study admitted (spec is the
                                              JSON-wire space schema, or
                                              {"zoo": name})
    ask       {tids, seed, algo}              an ask was SERVED: the ids it
                                              issued, the suggest seed it
                                              drew, and the algo that
                                              produced the docs ("tpe",
                                              "rand" for startup/degraded)
    tell      {tid, loss, status}             one result reported
    close     {}                              study closed by the client
    snapshot  {spec, seed, kwargs, rstate,    compaction record: the
               n_asked, n_told, state}        study's registry entry + RNG
                                              position; its trials live in
                                              the FileStore

Ordering and idempotency (the replay argument, DESIGN.md §17): records
append in the order the scheduler applied them, and studies are
independent — a study's proposals depend only on its own ask/tell
history.  Replay therefore walks the journal once, per record:

* ``admit``/``snapshot`` re-create the study (bypassing the admission
  quota — resumed studies are grandfathered; the quota is admission
  control for NEW work, not an excuse to drop journaled state);
* ``ask`` advances the study's seed stream by exactly one draw and
  re-lands any doc the store does not already hold, regenerated through
  the SAME code path that served it (the PR-9 determinism pins make the
  regenerated docs bit-identical — the exactly-once argument the fleet
  uses for duplicate shard publishes);
* ``tell`` applies only if the trial is not already DONE — a duplicate
  (journaled AND settled into the store before the crash) is skipped,
  never double-applied.

fsync is batched per wave: ask records flush+fsync once at the end of
the wave that served them (before any asker unblocks), tell records
before the tell returns.  Compaction (:meth:`StudyJournal.rewrite`)
replaces the file atomically (tmp + ``os.replace``) with one
``snapshot`` record per live study; it runs only when the scheduler has
a store (without one the ask records ARE the trial data) and only at
quiescent points (no wave in flight — a snapshot taken after a pending
ask's seed draw but before its ask record would replay that draw twice).
"""

from __future__ import annotations

import json
import logging
import os
import time

from .. import chaos
from ..obs.trace import iter_jsonl

__all__ = ["StudyJournal", "JournalError", "wal_path_for"]

logger = logging.getLogger(__name__)

#: journal file name under a store root (``wal_path_for``)
WAL_BASENAME = "service.wal.jsonl"


class JournalError(OSError):
    """The journal could not be written.  Raised back through the serving
    path so the failed request errors (client retries) instead of the
    scheduler advancing past state the journal never captured."""


def wal_path_for(store_root):
    """The default journal location for a scheduler persisting into
    ``store_root`` (the WAL shares the store's durability story)."""
    return os.path.join(str(store_root), WAL_BASENAME)


def _fsync_dir(path):
    """fsync the DIRECTORY holding ``path``.  ``os.replace`` makes the
    compacted journal visible atomically, but on ext4-ordered (and most
    journaled) mounts the rename itself is only durable once the parent
    directory entry is flushed — a crash right after the replace could
    otherwise resurrect the pre-compaction journal, whose stale records
    would replay draws the snapshot already accounts for.  Best-effort:
    some filesystems refuse O_RDONLY fsync on directories; losing the
    directory flush there degrades to the pre-ISSUE-12 ordering, never
    to an error on the serving path."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class StudyJournal:
    """Append-side + replay-side of the WAL.  Not thread-safe by itself —
    the scheduler already serializes every mutation under its lock, and
    the journal is only touched there."""

    def __init__(self, path):
        self.path = str(path)
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._fh = None
        self._dirty = False
        self.appends = 0
        self.syncs = 0
        self.compactions = 0

    # -- append side -------------------------------------------------------

    def _handle(self):
        if self._fh is None:
            self._fh = open(self.path, "a", encoding="utf-8")
        return self._fh

    def append(self, rec):
        """One record onto the journal (buffered — call :meth:`sync` at
        the durability point).  Any OSError surfaces as
        :class:`JournalError` so the serving path fails THIS request
        instead of silently losing the record."""
        chaos.io_point("wal")
        try:
            fh = self._handle()
            fh.write(json.dumps(rec, sort_keys=True,
                                separators=(",", ":")) + "\n")
        except OSError as e:
            self._drop_handle()
            raise JournalError(f"journal append failed: {e}") from e
        self._dirty = True
        self.appends += 1

    def sync(self):
        """Flush + fsync everything appended since the last sync (the
        batched per-wave durability point)."""
        if not self._dirty or self._fh is None:
            return
        try:
            self._fh.flush()
            os.fsync(self._fh.fileno())
        except OSError as e:
            self._drop_handle()
            raise JournalError(f"journal fsync failed: {e}") from e
        self._dirty = False
        self.syncs += 1

    def _drop_handle(self):
        fh, self._fh = self._fh, None
        self._dirty = False
        if fh is not None:
            try:
                fh.close()
            except OSError:
                pass

    def close(self):
        try:
            self.sync()
        finally:
            self._drop_handle()

    # -- replay / compaction side -----------------------------------------

    def records(self):
        """Every parseable record, in append order.  Torn lines (the
        crash artifact batched fsync allows at the tail) are skipped by
        ``iter_jsonl`` — a WAL is readable after ANY crash."""
        if not os.path.exists(self.path):
            return
        yield from iter_jsonl(self.path)

    def size_bytes(self):
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0

    def rewrite(self, records):
        """Atomically replace the journal with ``records`` (compaction).
        The append handle reopens on the next :meth:`append`, so a
        concurrent-append-after-compact lands in the NEW file."""
        chaos.io_point("wal")
        self._drop_handle()
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                for rec in records:
                    f.write(json.dumps(rec, sort_keys=True,
                                       separators=(",", ":")) + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        except OSError as e:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise JournalError(f"journal compaction failed: {e}") from e
        # the rename is durable only once the parent directory entry is
        # too (ISSUE 12 satellite — see _fsync_dir)
        _fsync_dir(self.path)
        self.compactions += 1

    # -- record constructors (one place owns the schema) -------------------

    # ``trace`` (ISSUE 11) is the request-trace id that caused the
    # record — pure metadata for the per-study audit timeline.  Replay
    # NEVER reads it (unknown fields were always ignored), so journals
    # written before the field existed — and journals written with
    # tracing disarmed — resume bit-identically (pinned by test).

    @staticmethod
    def admit_rec(study_id, spec, seed, kwargs, trace=None):
        rec = {"kind": "admit", "sid": study_id, "spec": spec,
               "seed": int(seed), "kwargs": dict(kwargs), "ts": time.time()}
        if trace is not None:
            rec["trace"] = str(trace)
        return rec

    @staticmethod
    def ask_rec(study_id, tids, seed, algo, trace=None, req=None):
        rec = {"kind": "ask", "sid": study_id,
               "tids": [int(t) for t in tids], "seed": int(seed),
               "algo": str(algo), "ts": time.time()}
        if trace is not None:
            rec["trace"] = str(trace)
        if req is not None:
            # the client's ask-idempotency token (ISSUE 12): replay
            # rebuilds the served-request map from it so a retried ask
            # answers the same tids across crashes and shard migrations
            rec["req"] = str(req)
        return rec

    @staticmethod
    def tell_rec(study_id, tid, loss, status, trace=None):
        rec = {"kind": "tell", "sid": study_id, "tid": int(tid),
               "loss": None if loss is None else float(loss),
               "status": status, "ts": time.time()}
        if trace is not None:
            rec["trace"] = str(trace)
        return rec

    @staticmethod
    def close_rec(study_id, trace=None):
        rec = {"kind": "close", "sid": study_id, "ts": time.time()}
        if trace is not None:
            rec["trace"] = str(trace)
        return rec

    @staticmethod
    def snapshot_rec(study):
        """Compaction record for one study: registry entry + exact RNG
        position (``numpy`` Generator state is a JSON-clean dict of
        bigints) so replay resumes the seed stream mid-flight."""
        rec = {
            "kind": "snapshot", "sid": study.study_id,
            "spec": study.space_spec, "seed": study.seed,
            "kwargs": study.admit_kwargs,
            "rstate": study.rstate.bit_generator.state,
            "n_asked": study.n_asked, "n_told": study.n_told,
            "state": study.state, "ts": time.time(),
        }
        if study.served_reqs:
            # compaction must not break ask idempotency: the retry
            # window spans a drain/migration (pre-field snapshots
            # replay fine — the map just starts empty)
            rec["served"] = dict(study.served_reqs)
        return rec
