"""Write-ahead journal for the ask/tell service (the durable half of
ISSUE 10).

The scheduler's in-memory state — which studies exist, where each study's
seed stream is, which asks were issued — dies with the process; even with
``--store`` (per-study :class:`~hyperopt_tpu.filestore.FileTrials`) a
restart forgets every live study.  The journal closes that gap with the
cheapest durable structure that works on the filesystems TPU pods
actually mount (NFS / GCS-fuse): an append-only JSONL file under the
store root, read back through the torn-line-tolerant
:func:`~hyperopt_tpu.obs.trace.iter_jsonl` (a half-written final line —
the normal crash artifact — is skipped, never fatal).

Record kinds (one JSON object per line; every record carries ``kind``
and ``sid``)::

    admit     {spec, seed, kwargs}            study admitted (spec is the
                                              JSON-wire space schema, or
                                              {"zoo": name})
    ask       {tids, seed, algo}              an ask was SERVED: the ids it
                                              issued, the suggest seed it
                                              drew, and the algo that
                                              produced the docs ("tpe",
                                              "rand" for startup/degraded)
    tell      {tid, loss, status}             one result reported
    close     {}                              study closed by the client
    snapshot  {spec, seed, kwargs, rstate,    compaction record: the
               n_asked, n_told, state}        study's registry entry + RNG
                                              position; its trials live in
                                              the FileStore
    quarantine {reason}                       the study's journal state was
                                              found corrupt (ISSUE 15):
                                              410 on ask/tell until the
                                              operator intervenes

Integrity (ISSUE 15): every appended/rewritten line carries a CRC32C
suffix field (``"c":"<hex>"`` over the canonical record bytes — see
``service/integrity.py``); replay classifies each line as ok /
torn-tail / corrupt-mid-file through ``integrity.iter_checked_jsonl``
and the scheduler quarantines per study instead of failing the boot.
Pre-ISSUE-15 journals (no ``c`` field) replay unchanged, pinned
bitwise.  ENOSPC on append/fsync raises the typed, retryable
:class:`JournalFullError` (HTTP 507 + store-full shed).

Ordering and idempotency (the replay argument, DESIGN.md §17): records
append in the order the scheduler applied them, and studies are
independent — a study's proposals depend only on its own ask/tell
history.  Replay therefore walks the journal once, per record:

* ``admit``/``snapshot`` re-create the study (bypassing the admission
  quota — resumed studies are grandfathered; the quota is admission
  control for NEW work, not an excuse to drop journaled state);
* ``ask`` advances the study's seed stream by exactly one draw and
  re-lands any doc the store does not already hold, regenerated through
  the SAME code path that served it (the PR-9 determinism pins make the
  regenerated docs bit-identical — the exactly-once argument the fleet
  uses for duplicate shard publishes);
* ``tell`` applies only if the trial is not already DONE — a duplicate
  (journaled AND settled into the store before the crash) is skipped,
  never double-applied.

fsync is batched per wave: ask records flush+fsync once at the end of
the wave that served them (before any asker unblocks), tell records
before the tell returns.  Compaction (:meth:`StudyJournal.rewrite`)
replaces the file atomically (tmp + ``os.replace``) with one
``snapshot`` record per live study; it runs only when the scheduler has
a store (without one the ask records ARE the trial data) and only at
quiescent points (no wave in flight — a snapshot taken after a pending
ask's seed draw but before its ask record would replay that draw twice).
"""

from __future__ import annotations

import json
import logging
import os
import time

from .. import chaos
from . import integrity
from .integrity import StoreFullError

__all__ = ["StudyJournal", "JournalError", "JournalFullError",
           "JournalCorruptError", "wal_path_for"]

logger = logging.getLogger(__name__)

#: journal file name under a store root (``wal_path_for``)
WAL_BASENAME = "service.wal.jsonl"

#: suffix a quarantined journal segment is renamed under (evidence —
#: never replayed, never GC'd, readable by scrub and post-mortems)
QUARANTINE_SUFFIX = ".quarantined"


class JournalError(OSError):
    """The journal could not be written.  Raised back through the serving
    path so the failed request errors (client retries) instead of the
    scheduler advancing past state the journal never captured."""


class JournalFullError(JournalError, StoreFullError):
    """The journal write failed with ENOSPC (ISSUE 15).  Both a
    :class:`JournalError` (every existing handler keeps working) and a
    :class:`~hyperopt_tpu.exceptions.StoreFullError` (the serving path
    answers a typed, retryable 507 and arms the store-full shed)."""


class JournalCorruptError(JournalError):
    """A compaction refused to run because the chain it would discard
    holds records that fail checksum verification — rewriting would
    launder the corruption into the only surviving copy.  The old chain
    is kept; scrub/resume quarantine the affected studies."""


def wal_path_for(store_root):
    """The default journal location for a scheduler persisting into
    ``store_root`` (the WAL shares the store's durability story)."""
    return os.path.join(str(store_root), WAL_BASENAME)


_METRICS = None


def _metrics():
    """Lazy process-global service registry for the journal's chaos
    sites, so injected wal faults/corruptions land in /metrics (the
    smoke gate's ground truth for '100% of injections detected')."""
    global _METRICS
    if _METRICS is None:
        from ..obs.metrics import get_metrics

        _METRICS = get_metrics("service")
    return _METRICS


def _fsync_dir(path):
    """fsync the DIRECTORY holding ``path``.  ``os.replace`` makes the
    compacted journal visible atomically, but on ext4-ordered (and most
    journaled) mounts the rename itself is only durable once the parent
    directory entry is flushed — a crash right after the replace could
    otherwise resurrect the pre-compaction journal, whose stale records
    would replay draws the snapshot already accounts for.  Best-effort:
    some filesystems refuse O_RDONLY fsync on directories; losing the
    directory flush there degrades to the pre-ISSUE-12 ordering, never
    to an error on the serving path."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class StudyJournal:
    """Append-side + replay-side of the WAL.  Not thread-safe by itself —
    the scheduler already serializes every mutation under its lock, and
    the journal is only touched there."""

    def __init__(self, path, checksum=True):
        self.path = str(path)
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._fh = None
        self._dirty = False
        # checksummed records (ISSUE 15): every appended/rewritten line
        # carries the CRC32C suffix field.  Off only for the bench's
        # overhead baseline and back-compat pins — production journals
        # are always sealed.
        self.checksum = bool(checksum)
        self.appends = 0
        self.syncs = 0
        self.compactions = 0

    # -- append side -------------------------------------------------------

    def _handle(self):
        if self._fh is None:
            self._fh = open(self.path, "ab")
        return self._fh

    def _line(self, rec):
        if self.checksum:
            return (integrity.seal(rec) + "\n").encode("utf-8")
        return (json.dumps(rec, sort_keys=True,
                           separators=(",", ":")) + "\n").encode("utf-8")

    @staticmethod
    def _raise_typed(what, e):
        if integrity.is_enospc(e):
            raise JournalFullError(
                e.errno, f"journal {what} failed, disk full: {e}") from e
        raise JournalError(f"journal {what} failed: {e}") from e

    def append(self, rec):
        """One record onto the journal (buffered — call :meth:`sync` at
        the durability point).  Any OSError surfaces as
        :class:`JournalError` — ENOSPC as the retryable
        :class:`JournalFullError` — so the serving path fails THIS
        request instead of silently losing the record."""
        try:
            chaos.io_point("wal", _metrics())
            # the chaos 'corrupt' site: the write SUCCEEDS but the
            # medium lies — exactly the fault class the checksum
            # exists to catch
            data = chaos.corrupt_bytes("wal", self._line(rec),
                                       _metrics())
            fh = self._handle()
            fh.write(data)
        except OSError as e:
            self._drop_handle()
            self._raise_typed("append", e)
        self._dirty = True
        self.appends += 1

    def sync(self):
        """Flush + fsync everything appended since the last sync (the
        batched per-wave durability point)."""
        if not self._dirty or self._fh is None:
            return
        try:
            self._fh.flush()
            os.fsync(self._fh.fileno())
        except OSError as e:
            self._drop_handle()
            self._raise_typed("fsync", e)
        self._dirty = False
        self.syncs += 1

    def _drop_handle(self):
        fh, self._fh = self._fh, None
        self._dirty = False
        if fh is not None:
            try:
                fh.close()
            except OSError:
                pass

    def close(self):
        try:
            self.sync()
        finally:
            self._drop_handle()

    # -- replay / compaction side -----------------------------------------

    def records(self):
        """Every verified record, in append order, with the checksum
        field stripped.  Torn tails (the crash artifact batched fsync
        allows) are skipped as always; CORRUPT lines are skipped WITH a
        warning — callers that must react per-study (the scheduler's
        quarantine, scrub) read :meth:`checked_records` instead."""
        for chk in self.checked_records():
            if chk.status in (integrity.OK, integrity.UNCHECKED):
                yield chk.rec
            elif chk.status == integrity.CORRUPT:
                logger.warning(
                    "%s:%d: CORRUPT journal record (checksum/framing "
                    "failure mid-file) skipped by an unchecked reader",
                    self.path, chk.lineno)

    def checked_records(self):
        """Every line, classified (:class:`~hyperopt_tpu.service
        .integrity.Checked`): ok / unchecked (pre-ISSUE-15) / corrupt /
        torn.  The scheduler's resume and the scrub tool drive their
        quarantine decisions from this."""
        if not os.path.exists(self.path):
            return
        yield from integrity.iter_checked_jsonl(self.path)

    def size_bytes(self):
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0

    def rewrite(self, records, verify_old=True):
        """Atomically replace the journal with ``records`` (compaction).
        The append handle reopens on the next :meth:`append`, so a
        concurrent-append-after-compact lands in the NEW file.

        Two integrity refusals (ISSUE 15 — compaction must never
        LAUNDER corruption into the only surviving copy):

        * with ``verify_old`` the existing chain is checksum-verified
          first; a corrupt record aborts (:class:`JournalCorruptError`)
          keeping the old chain, so scrub/resume still see the
          evidence and quarantine precisely;
        * the freshly-written snapshot is re-read and re-verified
          before the ``os.replace`` — a write the disk corrupted in
          flight aborts the same way instead of becoming the journal.
        """
        try:
            chaos.io_point("wal", _metrics())
        except OSError as e:
            self._raise_typed("compaction", e)
        if verify_old and self.checksum and os.path.exists(self.path):
            for chk in integrity.iter_checked_jsonl(self.path):
                if chk.status == integrity.CORRUPT:
                    raise JournalCorruptError(
                        f"{self.path}:{chk.lineno}: corrupt record in "
                        "the chain compaction would discard; keeping "
                        "the old chain (quarantine via resume/scrub)")
        self._drop_handle()
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                for rec in records:
                    f.write(self._line(rec))
                f.flush()
                os.fsync(f.fileno())
            if self.checksum:
                for chk in integrity.iter_checked_jsonl(tmp):
                    if chk.status != integrity.OK:
                        raise JournalCorruptError(
                            f"{tmp}:{chk.lineno}: compaction snapshot "
                            "failed re-read verification; keeping the "
                            "old chain")
            os.replace(tmp, self.path)
        except OSError as e:
            try:
                os.remove(tmp)
            except OSError:
                pass
            if isinstance(e, JournalError):
                raise
            self._raise_typed("compaction", e)
        # the rename is durable only once the parent directory entry is
        # too (ISSUE 12 satellite — see _fsync_dir)
        _fsync_dir(self.path)
        self.compactions += 1

    def quarantine_segment(self, reason):
        """Move this journal FILE aside as evidence (ISSUE 15): rename
        to ``<path>.quarantined`` (suffixed with a counter if one
        already exists), append a sealed reason record to the renamed
        file, fsync the directory.  The live path is then free — the
        caller rewrites it from the healthy replayed state (or the
        next append recreates it).  Returns the quarantine path, or
        None when there was nothing to rename."""
        self._drop_handle()
        if not os.path.exists(self.path):
            return None
        qpath = self.path + QUARANTINE_SUFFIX
        n = 1
        while os.path.exists(qpath):
            qpath = f"{self.path}{QUARANTINE_SUFFIX}.{n}"
            n += 1
        try:
            os.replace(self.path, qpath)
            with open(qpath, "ab") as f:
                f.write((integrity.seal({
                    "kind": "quarantine_reason", "reason": str(reason),
                    "path": self.path, "ts": time.time()}) + "\n")
                    .encode("utf-8"))
                f.flush()
                os.fsync(f.fileno())
        except OSError as e:
            logger.warning("could not quarantine journal segment %s: %s",
                           self.path, e)
            return None
        _fsync_dir(self.path)
        logger.warning("journal segment quarantined: %s -> %s (%s)",
                       self.path, qpath, reason)
        return qpath

    # -- record constructors (one place owns the schema) -------------------

    # ``trace`` (ISSUE 11) is the request-trace id that caused the
    # record — pure metadata for the per-study audit timeline.  Replay
    # NEVER reads it (unknown fields were always ignored), so journals
    # written before the field existed — and journals written with
    # tracing disarmed — resume bit-identically (pinned by test).

    @staticmethod
    def admit_rec(study_id, spec, seed, kwargs, trace=None):
        rec = {"kind": "admit", "sid": study_id, "spec": spec,
               "seed": int(seed), "kwargs": dict(kwargs), "ts": time.time()}
        if trace is not None:
            rec["trace"] = str(trace)
        return rec

    @staticmethod
    def ask_rec(study_id, tids, seed, algo, trace=None, req=None):
        rec = {"kind": "ask", "sid": study_id,
               "tids": [int(t) for t in tids], "seed": int(seed),
               "algo": str(algo), "ts": time.time()}
        if trace is not None:
            rec["trace"] = str(trace)
        if req is not None:
            # the client's ask-idempotency token (ISSUE 12): replay
            # rebuilds the served-request map from it so a retried ask
            # answers the same tids across crashes and shard migrations
            rec["req"] = str(req)
        return rec

    @staticmethod
    def tell_rec(study_id, tid, loss, status, trace=None):
        rec = {"kind": "tell", "sid": study_id, "tid": int(tid),
               "loss": None if loss is None else float(loss),
               "status": status, "ts": time.time()}
        if trace is not None:
            rec["trace"] = str(trace)
        return rec

    @staticmethod
    def close_rec(study_id, trace=None):
        rec = {"kind": "close", "sid": study_id, "ts": time.time()}
        if trace is not None:
            rec["trace"] = str(trace)
        return rec

    @staticmethod
    def quarantine_rec(study_id, reason):
        """Durable per-study quarantine marker (ISSUE 15): replay marks
        the study quarantined (410 on ask/tell, listed in ``/studies``)
        without touching any other study — the resume-twice idempotence
        of the corruption path rides on this record."""
        return {"kind": "quarantine", "sid": study_id,
                "reason": str(reason), "ts": time.time()}

    @staticmethod
    def snapshot_rec(study):
        """Compaction record for one study: registry entry + exact RNG
        position (``numpy`` Generator state is a JSON-clean dict of
        bigints) so replay resumes the seed stream mid-flight."""
        rec = {
            "kind": "snapshot", "sid": study.study_id,
            "spec": study.space_spec, "seed": study.seed,
            "kwargs": study.admit_kwargs,
            "rstate": study.rstate.bit_generator.state,
            "n_asked": study.n_asked, "n_told": study.n_told,
            "state": study.state, "ts": time.time(),
        }
        if study.served_reqs:
            # compaction must not break ask idempotency: the retry
            # window spans a drain/migration (pre-field snapshots
            # replay fine — the map just starts empty)
            rec["served"] = dict(study.served_reqs)
        return rec
