"""Ask/tell optimizer service: thousands of concurrent studies on one mesh.

The serving layer over ``tpe.build_suggest_batched`` (ISSUE 9 /
ROADMAP item 1): a :class:`~hyperopt_tpu.service.scheduler.StudyScheduler`
packs live studies into fixed-shape cohort slots and runs ONE batched
fused tell+ask device program per ask wave, and
``hyperopt_tpu.service.server`` puts a stdlib HTTP front end
(``POST /study``, ``POST /ask``, ``POST /tell``, ``GET /studies``) on top
— the surface every later workload (ATPE, multi-objective, ASHA) plugs
into.  ``service/fleet.py`` (ISSUE 12) replicates that server: N
processes over one store root partition the study keyspace into leased
study-shards with per-(shard, epoch) WALs, 307 routing and
bit-identical WAL-replay migration — one logical service that survives
SIGKILLs and rolling restarts with zero lost tells.
``service/compile_plane.py`` (ISSUE 14) takes XLA compilation off the
serving path: cold cohort keys are served at a flagged warming rand
floor while one background thread compiles, and a census-driven kernel
bank pre-warms common keys before the listener opens on restart.
``service/integrity.py`` + ``service/scrub.py`` (ISSUE 15) are the
storage-integrity survival plane: CRC32C-sealed WAL/census/ownership
records, per-study corruption quarantine (410, never a boot failure),
ENOSPC backpressure (507 + Retry-After, compact-and-GC degrade rung)
and an offline scrub/repair tool.
"""

from ..exceptions import StoreFullError
from .client import ServiceClient
from .compile_plane import CompilePlane, SignatureCensus
from .fleet import FleetReplica, ShardNotOwned, ShardUnavailable, shard_of
from .journal import StudyJournal
from .overload import (AdmissionGuard, Deadline, DegradeLadder,
                       OverloadError, StoreFullShed)
from .scheduler import (DrainingError, QuarantinedStudyError,
                        StudyQuotaError, StudyScheduler,
                        UnknownStudyError)
from .spacespec import space_from_spec

__all__ = ["StudyScheduler", "StudyQuotaError", "UnknownStudyError",
           "DrainingError", "QuarantinedStudyError", "StudyJournal",
           "AdmissionGuard", "Deadline",
           "DegradeLadder", "OverloadError", "StoreFullError",
           "StoreFullShed", "ServiceClient",
           "CompilePlane", "SignatureCensus",
           "FleetReplica", "ShardNotOwned", "ShardUnavailable", "shard_of",
           "space_from_spec"]
