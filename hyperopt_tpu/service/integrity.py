"""Storage-integrity plane (ISSUE 15): checksummed self-validating
records, corruption classification, disk-watermark monitoring and
bounded store GC.

Every durability guarantee the serving plane makes — bit-identical
crash-resume (§17), epoch-WAL migration (§19), census pre-warming
(§20) — silently assumed the filesystem under the store root was
healthy: records were parsed with ``json.loads`` and no integrity
check, so a mid-file bit-flip (failing NVMe, NFS cache corruption, a
torn compaction rewrite) was indistinguishable from the benign torn
tail a crash leaves, and a full disk turned the WAL append at the
durability point into an unrecoverable crash loop.  This module is the
shared vocabulary every store surface now speaks:

* **Sealed records** — :func:`seal` serializes a record canonically
  (``sort_keys``, compact separators) and splices a CRC32C suffix field
  ``"c":"<8 hex>"`` computed over the canonical bytes WITHOUT the
  field; :func:`verify_obj` pops ``c``, re-serializes and compares.
  Canonical-JSON round-tripping makes the check writer-independent:
  ``json.loads`` then ``json.dumps(sort_keys, separators)`` reproduces
  the exact bytes for any JSON-clean record (Python floats repr
  shortest-round-trip), so the verifier needs no framing beyond the
  line itself.  Records written before ISSUE 15 simply lack ``c`` and
  classify ``unchecked`` — replayed byte-identically, never rejected.

* **Classification, not parsing** — :func:`iter_checked_jsonl`
  generalizes :func:`~hyperopt_tpu.obs.trace.iter_jsonl`: every line
  classifies as ``ok`` (checksum verified), ``unchecked``
  (pre-ISSUE-15, no ``c``), ``corrupt`` (parseable-with-bad-checksum
  anywhere, or unparseable MID-file) or ``torn`` (unparseable FINAL
  line — the normal crash artifact batched fsync allows, skipped as
  always).  The distinction is the whole point: a torn tail is
  expected and survivable; a corrupt middle means the medium lied and
  the affected study must be quarantined, not silently mis-replayed.

* **ENOSPC as a typed, retryable state** — :func:`is_enospc` maps
  ``ENOSPC``/``EDQUOT`` to
  :class:`~hyperopt_tpu.exceptions.StoreFullError`;
  :class:`DiskWatermark` polls ``statvfs`` (cached, scrape-time +
  per-wave) and publishes ``store.free_bytes`` / ``store.used_frac``
  gauges; :func:`gc_store_root` is the degrade rung's bounded GC:
  settle-superseded doc copies, stale tmp files, expired flight dumps
  and ancestor epoch WALs already compacted by adoption.

The scrub tool (``python -m hyperopt_tpu.service.scrub``) walks a
whole store root through these primitives offline; the journal, fleet
ownership table and census ride :func:`seal`/:func:`verify_obj` on
their write paths.
"""

from __future__ import annotations

import errno
import json
import logging
import os
import re
import time
from collections import namedtuple

from ..exceptions import StoreFullError

__all__ = [
    "OK", "UNCHECKED", "CORRUPT", "TORN",
    "Checked", "StoreFullError",
    "crc32c", "seal", "seal_obj", "verify_obj",
    "iter_checked_jsonl", "salvage_sid", "is_enospc",
    "DiskWatermark", "gc_store_root",
]

logger = logging.getLogger(__name__)

#: line classifications (iter_checked_jsonl)
OK = "ok"                #: checksummed and verified
UNCHECKED = "unchecked"  #: parseable, no ``c`` field (pre-ISSUE-15)
CORRUPT = "corrupt"      #: bad checksum, or unparseable mid-file
TORN = "torn"            #: unparseable FINAL line (crash artifact)

#: one classified JSONL line: ``rec`` is the parsed record with ``c``
#: popped (None when unparseable), ``raw`` the line text
Checked = namedtuple("Checked", ["rec", "status", "lineno", "raw"])

#: the checksum field name — reserved in every sealed record
CHECKSUM_FIELD = "c"


# ---------------------------------------------------------------------------
# CRC32C (Castagnoli) — hardware-friendly polynomial, software table here
# ---------------------------------------------------------------------------

_CRC_TABLE = None
_accel = None  # optional C implementation, resolved once


def _crc_table():
    global _CRC_TABLE
    if _CRC_TABLE is None:
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ 0x82F63B78 if c & 1 else c >> 1
            table.append(c)
        _CRC_TABLE = table
    return _CRC_TABLE


def _resolve_accel():
    """Use a C crc32c if the environment happens to ship one (the wire
    format is identical); fall back to the table loop.  Resolved once —
    never a hard dependency."""
    global _accel
    if _accel is None:
        _accel = False
        for mod in ("google_crc32c", "crc32c"):
            try:
                m = __import__(mod)
                fn = getattr(m, "value", None) or getattr(m, "crc32c", None)
                if fn is not None and fn(b"123456789") == 0xE3069283:
                    _accel = fn
                    break
            except Exception:  # noqa: BLE001 - optional accel only
                continue
    return _accel


def crc32c(data, crc=0):
    """CRC32C (Castagnoli, reflected poly 0x1EDC6F41) of ``data``.
    ``crc32c(b"123456789") == 0xE3069283`` (the RFC 3720 check value,
    pinned by test)."""
    fn = _resolve_accel()
    if fn:
        return fn(bytes(data)) if crc == 0 else _crc_soft(data, crc)
    return _crc_soft(data, crc)


def _crc_soft(data, crc=0):
    table = _crc_table()
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


# ---------------------------------------------------------------------------
# sealed records
# ---------------------------------------------------------------------------


def _canonical(rec):
    return json.dumps(rec, sort_keys=True, separators=(",", ":"))


def seal(rec):
    """One canonical JSONL line (no newline) for ``rec`` with the CRC32C
    suffix field spliced in: ``{...,"c":"<8 hex>"}``.  The checksum
    covers the canonical serialization WITHOUT the field, so a verifier
    pops ``c``, re-serializes and compares — no side framing."""
    if CHECKSUM_FIELD in rec:
        raise ValueError(f"record already carries {CHECKSUM_FIELD!r}: "
                         f"double-sealing would break verification")
    body = _canonical(rec)
    c = format(crc32c(body.encode("utf-8")), "08x")
    if body == "{}":
        return '{"c":"%s"}' % c
    return f'{body[:-1]},"{CHECKSUM_FIELD}":"{c}"}}'


def seal_obj(rec):
    """Dict form of :func:`seal` for single-object JSON files (the fleet
    ownership table): returns a copy of ``rec`` with ``c`` added."""
    body = _canonical(rec)
    out = dict(rec)
    out[CHECKSUM_FIELD] = format(crc32c(body.encode("utf-8")), "08x")
    return out


def verify_obj(rec):
    """Classify one PARSED record: pops ``c`` in place and returns
    :data:`OK` / :data:`UNCHECKED` / :data:`CORRUPT`."""
    c = rec.pop(CHECKSUM_FIELD, None)
    if c is None:
        return UNCHECKED
    try:
        want = int(str(c), 16)
    except ValueError:
        return CORRUPT
    have = crc32c(_canonical(rec).encode("utf-8"))
    return OK if have == want else CORRUPT


def iter_checked_jsonl(path):
    """Stream ``path`` one classified line at a time (:class:`Checked`).

    Classification: a parseable line with a verifying ``c`` is ``ok``;
    parseable without ``c`` is ``unchecked`` (pre-ISSUE-15 back-compat
    — replayed unchanged); parseable with a failing ``c`` is
    ``corrupt`` wherever it sits (a torn write essentially never yields
    complete JSON with a present-but-wrong checksum — that is the
    medium flipping bits); an UNPARSEABLE line is ``torn`` on the final
    line (the crash artifact batched fsync allows) and ``corrupt``
    anywhere else (records are whole lines — a mid-file fragment means
    data was destroyed after it was durable).  Empty lines are skipped
    like :func:`~hyperopt_tpu.obs.trace.iter_jsonl` always did.

    Streams with a ONE-line lag (only the final line needs the
    is-this-the-tail lookahead) — a multi-GB WAL or event stream is
    never materialized wholesale, the contract ``iter_jsonl`` always
    kept."""
    def classify(lineno, line, is_last):
        try:
            rec = json.loads(line)
        except ValueError:
            rec = None
        if not isinstance(rec, dict):
            # unparseable, or a bare scalar/list this plane never wrote
            return Checked(None, TORN if is_last else CORRUPT,
                           lineno, line)
        return Checked(rec, verify_obj(rec), lineno, line)

    with open(path, encoding="utf-8", errors="replace") as f:
        prev = None  # (lineno, stripped line)
        for lineno, raw in enumerate(f, 1):
            line = raw.strip()
            if not line:
                continue
            if prev is not None:
                yield classify(prev[0], prev[1], False)
            prev = (lineno, line)
        if prev is not None:
            yield classify(prev[0], prev[1], True)


_SID_RE = re.compile(r'"sid"\s*:\s*"([^"\\]{1,128})"')


def salvage_sid(raw):
    """Best-effort study-id extraction from a corrupt (possibly
    JSON-broken) line, so a bit-flip that destroys the framing can
    still be attributed to ONE study instead of failing the whole
    resume.  Returns None when nothing salvageable."""
    m = _SID_RE.search(raw or "")
    return m.group(1) if m else None


# ---------------------------------------------------------------------------
# ENOSPC / disk-watermark plane
# ---------------------------------------------------------------------------

_ENOSPC_ERRNOS = {errno.ENOSPC, getattr(errno, "EDQUOT", errno.ENOSPC)}


def is_enospc(exc):
    """True when ``exc`` is the filesystem saying "no space" (ENOSPC,
    or EDQUOT — a quota is just a smaller disk)."""
    return (isinstance(exc, OSError)
            and getattr(exc, "errno", None) in _ENOSPC_ERRNOS)


class DiskWatermark:
    """Cached ``statvfs`` monitor over a store root.

    ``threshold`` arms the low-space decision: a value below 1.0 is a
    minimum FREE FRACTION, a value >= 1.0 a minimum free BYTE count;
    ``None`` disarms the decision (sampling still publishes gauges).
    ``sample()`` is cheap enough for the per-wave hot path: the real
    ``statvfs`` runs at most once per ``poll_sec`` (scrape time forces
    a fresh read with ``force=True``)."""

    def __init__(self, root, threshold=None, poll_sec=1.0,
                 clock=time.monotonic, statvfs=os.statvfs, metrics=None):
        self.root = str(root)
        self.threshold = threshold
        self.poll_sec = float(poll_sec)
        self._clock = clock
        self._statvfs = statvfs
        self.metrics = metrics
        self._last = None       # cached sample dict
        self._last_ts = None

    def sample(self, force=False):
        """The current disk state ``{free_bytes, total_bytes, used_frac,
        free_frac, low}`` — or None when ``statvfs`` itself fails (a
        dead mount is an I/O problem, not a full disk)."""
        now = self._clock()
        if (not force and self._last is not None
                and now - self._last_ts < self.poll_sec):
            return self._last
        try:
            st = self._statvfs(self.root)
        except OSError:
            return self._last
        total = st.f_blocks * st.f_frsize
        free = st.f_bavail * st.f_frsize
        free_frac = (free / total) if total else 1.0
        out = {
            "free_bytes": int(free),
            "total_bytes": int(total),
            "used_frac": 1.0 - free_frac,
            "free_frac": free_frac,
            "low": self._is_low(free, free_frac),
        }
        self._last, self._last_ts = out, now
        if self.metrics is not None:
            self.metrics.gauge("store.free_bytes").set(float(free))
            self.metrics.gauge("store.used_frac").set(1.0 - free_frac)
        return out

    def _is_low(self, free_bytes, free_frac):
        t = self.threshold
        if t is None or t <= 0:
            return False
        return free_frac < t if t < 1.0 else free_bytes < t


# ---------------------------------------------------------------------------
# bounded store GC (the space-pressure degrade rung)
# ---------------------------------------------------------------------------

_EPOCH_RE = re.compile(r"^e(\d+)\..+\.jsonl$")


def _first_record_kind(path):
    try:
        with open(path, encoding="utf-8", errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    return None
                return rec.get("kind") if isinstance(rec, dict) else None
    except OSError:
        return None
    return None


def _rm_sized(path, stats):
    try:
        size = os.path.getsize(path)
        os.remove(path)
    except OSError:
        return
    stats["removed"] += 1
    stats["reclaimed_bytes"] += size


def gc_store_root(root, limit_dirs=None, tmp_max_age=300.0,
                  flight_max_age=7 * 86400.0, metrics=None):
    """Bounded store hygiene under a serving root — the degrade rung the
    disk watermark triggers BEFORE any shed.  Reclaims only what is
    provably redundant:

    * per-study :class:`~hyperopt_tpu.filestore.FileStore` GC
      (settle-superseded ``new``/``running`` copies, precedence-loser
      terminal duplicates, stale ``*.tmp.*``, expired flight dumps) for
      every subdirectory that IS a store (has a ``counter`` file) — up
      to ``limit_dirs`` of them, oldest-modified first;
    * stale ``*.tmp.*`` atomic-write leftovers at the root itself;
    * ancestor epoch WALs under ``fleet/wal/shard*/`` whose NEWEST
      epoch file is snapshot-led (the adoption compaction that makes
      ancestors redundant — a crash between that compaction and the
      ancestor delete leaves exactly this state).

    ``*.quarantined`` files are never touched — they are evidence.
    Returns ``{reclaimed_bytes, removed, dirs_swept}``."""
    from ..filestore import FileStore

    stats = {"reclaimed_bytes": 0, "removed": 0, "dirs_swept": 0}
    root = str(root)
    try:
        entries = sorted(os.listdir(root))
    except OSError:
        return stats
    now = time.time()

    # root-level stale tmp files (atomic-write leftovers of dead writers)
    for fname in entries:
        if ".tmp." not in fname:
            continue
        path = os.path.join(root, fname)
        try:
            if os.path.isfile(path) and now - os.path.getmtime(path) \
                    > tmp_max_age:
                _rm_sized(path, stats)
        except OSError:
            continue

    # per-study store GC, oldest-modified dirs first, bounded
    store_dirs = []
    for fname in entries:
        d = os.path.join(root, fname)
        if os.path.isfile(os.path.join(d, "counter")):
            try:
                store_dirs.append((os.path.getmtime(d), d))
            except OSError:
                continue
    store_dirs.sort()
    if limit_dirs is not None:
        store_dirs = store_dirs[: int(limit_dirs)]
    for _, d in store_dirs:
        try:
            sub = FileStore(d).gc(tmp_max_age=tmp_max_age,
                                  flight_max_age=flight_max_age)
        except OSError:
            continue
        stats["dirs_swept"] += 1
        stats["removed"] += sub["removed"]
        stats["reclaimed_bytes"] += sub["reclaimed_bytes"]

    # ancestor epoch WALs already made redundant by adoption compaction
    wal_root = os.path.join(root, "fleet", "wal")
    if os.path.isdir(wal_root):
        for shard in sorted(os.listdir(wal_root)):
            d = os.path.join(wal_root, shard)
            try:
                names = os.listdir(d)
            except OSError:
                continue
            epochs = sorted(
                (int(m.group(1)), os.path.join(d, n))
                for n in names for m in [_EPOCH_RE.match(n)] if m)
            for fname in names:
                if ".tmp." in fname:
                    path = os.path.join(d, fname)
                    try:
                        if now - os.path.getmtime(path) > tmp_max_age:
                            _rm_sized(path, stats)
                    except OSError:
                        pass
            if len(epochs) < 2:
                continue
            if _first_record_kind(epochs[-1][1]) in ("snapshot",
                                                     "quarantine"):
                for _, path in epochs[:-1]:
                    _rm_sized(path, stats)

    if metrics is not None:
        metrics.counter("store.gc.runs").inc()
        metrics.counter("store.gc.reclaimed_bytes").inc(
            stats["reclaimed_bytes"])
    if stats["removed"]:
        logger.info("store gc: reclaimed %d bytes across %d files "
                    "(%d store dirs swept) under %s",
                    stats["reclaimed_bytes"], stats["removed"],
                    stats["dirs_swept"], root)
    return stats
