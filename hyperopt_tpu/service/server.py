"""HTTP ask/tell front end over the :class:`StudyScheduler`.

Grown out of ``obs/serve.py``'s fail-open stdlib-daemon pattern — the
same ``ThreadingHTTPServer`` + daemon-thread shape, now serving
*proposals* instead of metrics.  Endpoints (all JSON):

* ``POST /study`` — ``{"space": <spec>}`` (``service/spacespec.py``
  schema) or ``{"zoo": "<zoo name>"}``, plus optional ``seed``,
  ``n_startup_jobs``, ``max_trials`` and the ``tpe.suggest`` tuning
  kwargs → ``{"study_id": ...}`` (an opaque ``filestore.new_run_id``).
* ``POST /ask`` — ``{"study_id": ..., "n": 1}`` →
  ``{"trials": [{"tid": ..., "params": {label: value}}, ...]}``.
  Concurrent asks coalesce into one batched cohort tick per wave.
* ``POST /tell`` — ``{"study_id": ..., "tid": ..., "loss": ...}`` (or
  ``"results": [{tid, loss[, status]}, ...]``) → ``{"ok": true}``.
* ``POST /close`` — ``{"study_id": ...}`` frees the study's slot.
* ``GET /studies`` — the study table: per-study status + cohort/slot
  roll-up + cohort-program cache counters.
* ``GET /metrics`` / ``GET /snapshot`` — the obs integration:
  Prometheus exposition of every registry namespace (the ``service.*``
  family rides along) and a JSON snapshot with the study table.

Error mapping is in-band and typed: schema errors answer 400, unknown
studies 404, quota exhaustion and load sheds 429 (+ ``Retry-After``
from the live wave-latency EWMA), draining 503 — all as ``{"ok":
false, "error": ...}`` JSON.  A handler bug answers 500 once per
request and never propagates into the scheduler (the obs/serve.py
contract); every response increments a per-endpoint status-class
counter (``service.http.<endpoint>.<c>xx``) and a 500 records the
exception in the flight ring, so handler failures are observable
instead of vanishing into the fail-open path.

Overload control (ISSUE 10): ``POST /ask`` passes through a bounded
admission queue (``HYPEROPT_TPU_SERVICE_QUEUE``) and a per-request
monotonic deadline (``X-Deadline-Ms`` header, clamped by
``HYPEROPT_TPU_SERVICE_DEADLINE_MS``); past the bound — or when the
deadline cannot cover the predicted wait — the server sheds with 429
instead of queuing unboundedly.  Tells shed only at 4x the ask bound
(they are cheap and preserve client work).

Arming: ``python -m hyperopt_tpu.service.server [--port P]`` (or
``HYPEROPT_TPU_SERVICE=<port>`` with no ``--port``); ``--port 0`` binds
an ephemeral port and ``--announce`` prints ``SERVICE_URL <url>`` for
harnesses (``scripts/service_smoke.py``).  SIGTERM drains gracefully:
stop admitting, finish in-flight waves, compact + close the WAL, exit
0.
"""

from __future__ import annotations

import json
import logging
import threading
import time

from ..obs.serve import prometheus_text, split_hostport
from .overload import AdmissionGuard, Deadline, OverloadError
from .scheduler import (DrainingError, DuplicateTellError, StudyQuotaError,
                        StudyScheduler, UnknownStudyError)
from .spacespec import SpaceSpecError, space_from_spec

__all__ = ["ServiceHTTPServer", "main"]

logger = logging.getLogger(__name__)

_STUDY_KWARGS = ("n_startup_jobs", "max_trials", "prior_weight",
                 "n_EI_candidates", "gamma", "linear_forgetting",
                 "ei_select", "ei_tau", "prior_eps")


class _RequestError(Exception):
    """Typed in-band failure: (HTTP status, message)."""

    def __init__(self, status, message):
        super().__init__(message)
        self.status = int(status)


class ServiceHTTPServer:
    """Daemon-thread ask/tell server over one scheduler (see module
    docstring).  Fail-open lifecycle matches ``obs/serve.py``:
    ``start()`` warns and returns False on a bind failure instead of
    raising, ``stop()`` is idempotent."""

    def __init__(self, port, scheduler=None, host=None, store_root=None,
                 guard=None):
        from .._env import parse_service_deadline_ms

        try:
            if host is None:
                host, port = split_hostport(port)
            self.port = int(port)
        except (TypeError, ValueError):
            self.port = None  # start() warns and fails open
        self.host = host or "127.0.0.1"
        self.scheduler = scheduler if scheduler is not None else (
            StudyScheduler(store_root=store_root, wave_window=0.005))
        self.guard = (guard if guard is not None
                      else AdmissionGuard(metrics=self.scheduler.metrics))
        if self.scheduler.overload is None:
            # the scheduler feeds the guard its wave latencies — that
            # EWMA is what sizes every Retry-After hint
            self.scheduler.overload = self.guard
        self.default_deadline_ms = parse_service_deadline_ms()
        self._httpd = None
        self._thread = None
        self._stopped = False

    # -- request handling --------------------------------------------------

    def handle(self, method, path, body, headers=None):
        """Route one request; returns ``(status, payload dict)``.  Pure
        (no socket I/O) so tests can drive it directly.  ``headers`` is
        a lower-cased mapping (the deadline header rides in it); a 429/
        503 payload carries ``retry_after`` seconds, which the HTTP
        layer also emits as a ``Retry-After`` header."""
        status, payload = self._handle(method, path, body, headers or {})
        self._count_response(method, path, status)
        return status, payload

    @staticmethod
    def _endpoint_label(method, path):
        """Metric-friendly endpoint label: known routes by name, the
        rest pooled (an attacker probing random paths must not mint
        unbounded metric families)."""
        known = ("/study", "/ask", "/tell", "/close", "/studies",
                 "/metrics", "/snapshot", "/")
        if path in known:
            return path.strip("/") or "root"
        return "other"

    def _count_response(self, method, path, status):
        ep = self._endpoint_label(method, path)
        cls = int(status) // 100
        self.scheduler.metrics.counter(
            f"service.http.{ep}.{cls}xx").inc()

    def _record_failure(self, method, path, exc):
        """A handler exception became a 500: record it in the flight
        ring (it used to vanish into the fail-open path — invisible to
        every post-mortem)."""
        try:
            from ..obs.flight import get_flight

            get_flight().record({
                "kind": "service_error", "ts": time.time(),
                "method": method, "path": path,
                "error": f"{type(exc).__name__}: {exc}"})
        except Exception:  # noqa: BLE001 - forensics must never cascade
            pass

    def _handle(self, method, path, body, headers):
        sched = self.scheduler
        try:
            if method == "GET":
                if path == "/studies":
                    return 200, sched.studies_status()
                if path == "/snapshot":
                    return 200, self.snapshot_dict()
                if path == "/":
                    return 200, {
                        "ok": True,
                        "endpoints": ["POST /study", "POST /ask",
                                      "POST /tell", "POST /close",
                                      "GET /studies", "GET /metrics",
                                      "GET /snapshot"]}
                raise _RequestError(404, f"no such endpoint: {path}")
            if method != "POST":
                raise _RequestError(405, f"{method} not supported")
            if path == "/study":
                return 200, self._create_study(body)
            if path == "/ask":
                study_id = self._required(body, "study_id")
                n = int(body.get("n", 1))
                deadline = Deadline.from_request(
                    headers.get("x-deadline-ms"), self.default_deadline_ms)
                token = self.guard.admit_ask(deadline)
                try:
                    trials = sched.ask(study_id, n, deadline=deadline)
                finally:
                    self.guard.release(token)
                out = {"ok": True, "study_id": study_id,
                       "trials": [{k: t[k] for k in
                                   ("tid", "params", "degraded", "algo")
                                   if k in t}
                                  for t in trials]}
                if any(t.get("degraded") for t in trials):
                    out["degraded"] = True
                return 200, out
            if path == "/tell":
                study_id = self._required(body, "study_id")
                token = self.guard.admit_tell()
                try:
                    results = body.get("results")
                    batch = results is not None
                    if not batch:
                        results = [{"tid": self._required(body, "tid"),
                                    "loss": body.get("loss"),
                                    "status": body.get("status")}]
                    told = dups = 0
                    for r in results:
                        if not isinstance(r, dict) or r.get("tid") is None:
                            raise _RequestError(
                                400, f"each result needs a 'tid': {r!r}")
                        try:
                            sched.tell(study_id, r["tid"],
                                       loss=r.get("loss"),
                                       status=r.get("status"))
                            told += 1
                        except DuplicateTellError:
                            # a retried BATCH must not strand its untold
                            # tail behind one already-settled tid — skip
                            # and report; a single-tid duplicate still
                            # answers 409 so the client learns the
                            # conflict
                            if not batch:
                                raise
                            dups += 1
                finally:
                    self.guard.release(token)
                return 200, {"ok": True, "study_id": study_id,
                             "told": told, "duplicates": dups}
            if path == "/close":
                study_id = self._required(body, "study_id")
                sched.close_study(study_id)
                return 200, {"ok": True, "study_id": study_id}
            raise _RequestError(404, f"no such endpoint: {path}")
        except _RequestError as e:
            return e.status, {"ok": False, "error": str(e)}
        except UnknownStudyError as e:
            return 404, {"ok": False, "error": str(e)}
        except DuplicateTellError as e:
            # 409, not 429: "already told" is permanent — a client
            # retrying a lost tell response must not back off forever
            return 409, {"ok": False, "error": str(e)}
        except DrainingError as e:
            # 503: the process is going away; retry against the restart
            return 503, {"ok": False, "error": str(e), "retry_after": 1.0}
        except OverloadError as e:
            # load shed (queue full / deadline unservable / expired):
            # the retry_after hint is measured from live wave latency
            return 429, {"ok": False, "error": str(e),
                         "retry_after": e.retry_after}
        except StudyQuotaError as e:
            return 429, {"ok": False, "error": str(e)}
        # ValueError/TypeError here are request-shape problems (bad n,
        # non-numeric loss, schema coercions); internal KeyError-class
        # bugs fall through to the 500 handler so server-side alerting
        # sees them instead of the client eating a bogus 400
        except (SpaceSpecError, ValueError, TypeError) as e:
            return 400, {"ok": False,
                         "error": f"{type(e).__name__}: {e}"}
        except Exception as e:  # noqa: BLE001 - fail-open contract
            logger.warning("service: %s %s failed: %s", method, path, e)
            self._record_failure(method, path, e)
            return 500, {"ok": False, "error": f"{type(e).__name__}: {e}"}

    @staticmethod
    def _required(body, key):
        v = body.get(key)
        if v is None:
            raise _RequestError(400, f"missing required field {key!r}")
        return v

    def _create_study(self, body):
        if "space" in body:
            space = space_from_spec(body["space"])
            space_spec = {"space": body["space"]}
        elif "zoo" in body:
            from ..zoo import ZOO

            rec = ZOO.get(str(body["zoo"]))
            if rec is None:
                raise _RequestError(
                    400, f"unknown zoo domain {body['zoo']!r} "
                         f"(one of {sorted(ZOO)})")
            space = rec.space
            space_spec = {"zoo": str(body["zoo"])}
        else:
            raise _RequestError(400, "POST /study needs 'space' or 'zoo'")
        kwargs = {k: body[k] for k in _STUDY_KWARGS if k in body}
        # the wire schema IS the WAL registry entry: every HTTP-created
        # study is crash-resumable
        study_id = self.scheduler.create_study(
            space, seed=int(body.get("seed", 0)), space_spec=space_spec,
            **kwargs)
        return {"ok": True, "study_id": study_id}

    def snapshot_dict(self):
        """``/snapshot``: the service metrics namespace plus the study
        table — the obs-plane view of the serving layer."""
        out = {"ts": time.time(), "endpoint": "snapshot"}
        out["sections"] = {
            "service": self.scheduler.metrics.snapshot()["metrics"]}
        status = self.scheduler.studies_status()
        out["studies"] = status["studies"]
        out["cohorts"] = status["cohorts"]
        out["slot_utilization"] = status["slot_utilization"]
        out["cohort_cache"] = status["cohort_cache"]
        return out

    # -- lifecycle ---------------------------------------------------------

    @property
    def url(self):
        if self._httpd is None:
            return None
        return f"http://{self.host}:{self._httpd.server_address[1]}"

    def start(self):
        """Bind + serve on a daemon thread; False (after one warning) on
        any bind failure."""
        import http.server

        if self.port is None:
            logger.warning("service: unparseable port/host value; "
                           "ask/tell serving disabled")
            return False
        handler = _make_handler(self)
        try:
            self._httpd = http.server.ThreadingHTTPServer(
                (self.host, self.port), handler)
        except (OSError, OverflowError, ValueError) as e:
            logger.warning("service: cannot bind %s:%s (%s); ask/tell "
                           "serving disabled", self.host, self.port, e)
            self._httpd = None
            return False
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.25},
            name="hyperopt-service-http", daemon=True)
        self._thread.start()
        logger.info("ask/tell service listening on %s", self.url)
        return True

    def drain(self, timeout=30.0):
        """Graceful shutdown (the SIGTERM path): stop admitting (new
        studies and asks answer 503/``DrainingError`` immediately, tells
        keep landing), wait for in-flight waves to finish, compact +
        close the WAL, then stop serving.  Returns True when the
        scheduler quiesced within ``timeout``."""
        quiesced = self.scheduler.drain(timeout=timeout)
        self.stop()
        return quiesced

    def stop(self):
        if self._stopped:
            return
        self._stopped = True
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            try:
                httpd.shutdown()
                httpd.server_close()
            except Exception:
                pass


def _make_handler(server):
    import http.server

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            logger.debug("service http: " + fmt, *args)

        def _answer(self, status, payload, content_type="application/json"):
            data = (payload if isinstance(payload, bytes)
                    else json.dumps(payload, default=str,
                                    sort_keys=True).encode())
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            if (status in (429, 503) and isinstance(payload, dict)
                    and payload.get("retry_after") is not None):
                # RFC 7231 delta-seconds is an INTEGER — a fractional
                # header is discarded by standard clients/proxies.  The
                # wire header rounds up; the JSON payload keeps the
                # precise float for service/client.py
                import math

                self.send_header(
                    "Retry-After",
                    str(max(1, math.ceil(float(payload["retry_after"])))))
            self.end_headers()
            self.wfile.write(data)

        def _dispatch(self, method):
            path = self.path.partition("?")[0]
            try:
                if method == "GET" and path == "/metrics":
                    server._count_response(method, path, 200)
                    self._answer(
                        200, prometheus_text().encode(),
                        "text/plain; version=0.0.4; charset=utf-8")
                    return
                body = {}
                if method == "POST":
                    length = int(self.headers.get("Content-Length") or 0)
                    raw = self.rfile.read(length) if length else b"{}"
                    try:
                        body = json.loads(raw or b"{}")
                    except ValueError:
                        self._answer(400, {"ok": False,
                                           "error": "body is not JSON"})
                        return
                    if not isinstance(body, dict):
                        self._answer(400, {"ok": False,
                                           "error": "body must be a JSON "
                                                    "object"})
                        return
                headers = {k.lower(): v for k, v in self.headers.items()}
                status, payload = server.handle(method, path, body,
                                                headers=headers)
                self._answer(status, payload)
            except (BrokenPipeError, ConnectionResetError):
                pass  # client went away mid-write
            except Exception as e:  # noqa: BLE001 - never kill the server
                logger.warning("service http: %s %s failed: %s",
                               method, path, e)
                try:
                    self.send_error(500)
                except Exception:
                    pass

        def do_GET(self):  # noqa: N802 (stdlib handler contract)
            self._dispatch("GET")

        def do_POST(self):  # noqa: N802
            self._dispatch("POST")

    return Handler


def main(argv=None):
    import argparse

    from .._env import parse_service

    p = argparse.ArgumentParser(
        prog="python -m hyperopt_tpu.service.server",
        description="Serve ask/tell hyperparameter optimization over HTTP "
                    "(thousands of concurrent studies batched onto one "
                    "device mesh).")
    p.add_argument("--port", default=None,
                   help="bind port or host:port (0 = ephemeral; default: "
                        "$HYPEROPT_TPU_SERVICE)")
    p.add_argument("--store", default=None,
                   help="FileStore root: persist each study's trials under "
                        "<store>/<study_id>")
    p.add_argument("--max-studies", type=int, default=None,
                   help="admission quota (default: "
                        "$HYPEROPT_TPU_SERVICE_MAX_STUDIES or 4096)")
    p.add_argument("--max-pending", type=int, default=None,
                   help="per-study asked-but-untold quota (default: "
                        "$HYPEROPT_TPU_SERVICE_MAX_PENDING or 64)")
    p.add_argument("--idle-sec", type=float, default=None,
                   help="evict a study's cohort slot after this much "
                        "inactivity (default: "
                        "$HYPEROPT_TPU_SERVICE_IDLE_SEC or 600)")
    p.add_argument("--wal", default=None,
                   help="write-ahead journal: 'auto' (default — under "
                        "--store when given), 'off', or an explicit path "
                        "(default: $HYPEROPT_TPU_SERVICE_WAL)")
    p.add_argument("--announce", action="store_true",
                   help="print 'SERVICE_URL <url>' once bound (harness "
                        "handshake)")
    args = p.parse_args(argv)

    port = args.port if args.port is not None else parse_service()
    if port is None:
        p.error("no port: pass --port or set HYPEROPT_TPU_SERVICE")
    wal = None  # env-resolved
    if args.wal is not None:
        # the SAME token sets as _env.parse_service_wal — '--wal true'
        # must not create a journal file literally named 'true'
        raw = args.wal.strip().lower()
        if raw in ("auto", "", "1", "on", "true", "yes"):
            wal = None
        elif raw in ("off", "0", "false", "no"):
            wal = False
        else:
            wal = args.wal
    sched = StudyScheduler(max_studies=args.max_studies,
                           max_pending=args.max_pending,
                           idle_sec=args.idle_sec,
                           store_root=args.store,
                           wal=wal,
                           wave_window=0.005)
    server = ServiceHTTPServer(port, scheduler=sched)
    if not server.start():
        return 1
    if args.announce:
        print(f"SERVICE_URL {server.url}", flush=True)

    # graceful drain on SIGTERM: stop admitting, finish in-flight waves,
    # compact + close the WAL, exit 0 — a supervised restart (or spot
    # preemption with notice) must not look like a crash
    import signal

    stop = threading.Event()
    prev = signal.signal(signal.SIGTERM, lambda _s, _f: stop.set())
    try:
        while not stop.is_set():
            stop.wait(0.5)
    except KeyboardInterrupt:
        pass
    finally:
        signal.signal(signal.SIGTERM, prev)
        quiesced = server.drain()
        logger.info("service: drained (quiesced=%s); exiting", quiesced)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
